"""GCP TPU-VM node provider (reference:
`python/ray/autoscaler/_private/gcp/node_provider.py` — the TPU branch
of the GCP provider — and the `v2` TPU REST surface it drives).

Implements the `NodeProvider` contract against the Cloud TPU API
(`tpu.googleapis.com/v2`): nodes are TPU VMs tagged with cluster
labels; worker nodes boot a startup script that joins the head's
controller.  The HTTP transport is injectable so the provider (and the
autoscaler above it) is fully exercisable against a mock — the same
split the reference gets from googleapiclient's mockable discovery
layer.

Zero-egress environments: nothing here talks to the network unless a
real transport is used.
"""

from __future__ import annotations

import json
import time
import uuid
from typing import Any, Callable, Dict, List, Optional

from ray_tpu.autoscaler.node_provider import NodeProvider

API_ROOT = "https://tpu.googleapis.com/v2"

# states that count as alive (reference: the provider's non-terminated
# filter over instance status)
_LIVE_STATES = ("CREATING", "READY", "STARTING", "REPAIRING")

Transport = Callable[[str, str, Optional[dict]], dict]


def default_transport(method: str, url: str, body: Optional[dict]) -> dict:
    """urllib-based transport; auth via the VM metadata token (running
    on GCP) — for laptops, plug in a transport that shells out to
    `gcloud auth print-access-token`."""
    import urllib.request

    tok_req = urllib.request.Request(
        "http://metadata.google.internal/computeMetadata/v1/instance/"
        "service-accounts/default/token",
        headers={"Metadata-Flavor": "Google"},
    )
    with urllib.request.urlopen(tok_req, timeout=5) as r:
        token = json.loads(r.read())["access_token"]
    req = urllib.request.Request(
        url,
        method=method,
        data=json.dumps(body).encode() if body is not None else None,
        headers={
            "Authorization": f"Bearer {token}",
            "Content-Type": "application/json",
        },
    )
    with urllib.request.urlopen(req, timeout=30) as r:
        payload = r.read()
    return json.loads(payload) if payload else {}


def chips_for_accelerator_type(accelerator_type: str) -> int:
    """Per-HOST chip count for a slice type (the resources one node
    daemon advertises)."""
    from ray_tpu.core.accelerators import num_hosts_in_slice

    gen, _, count = accelerator_type.partition("-")
    total = int(count)
    if gen in ("v2", "v3", "v4"):
        total //= 2  # those report cores; 2 cores per chip
    return max(1, total // num_hosts_in_slice(accelerator_type))


class GcpTpuNodeProvider(NodeProvider):
    """Creates/terminates TPU VMs labeled as members of one cluster."""

    def __init__(
        self,
        project: str,
        zone: str,
        cluster_name: str,
        *,
        accelerator_type: str = "v5e-8",
        runtime_version: str = "tpu-ubuntu2204-base",
        startup_script: str = "",
        network: Optional[str] = None,
        transport: Optional[Transport] = None,
    ):
        self.project = project
        self.zone = zone
        self.cluster_name = cluster_name
        self.accelerator_type = accelerator_type
        self.runtime_version = runtime_version
        self.startup_script = startup_script
        self.network = network
        self._transport = transport or default_transport
        self._parent = f"projects/{project}/locations/{zone}"
        self._node_states: Dict[str, str] = {}  # id -> last-seen state

    # -- REST helpers --------------------------------------------------
    def _url(self, path: str) -> str:
        return f"{API_ROOT}/{path}"

    def _node_body(self, node_config: Dict[str, Any]) -> Dict[str, Any]:
        body: Dict[str, Any] = {
            "acceleratorType": node_config.get(
                "accelerator_type", self.accelerator_type
            ),
            "runtimeVersion": node_config.get(
                "runtime_version", self.runtime_version
            ),
            "labels": {
                "rt-cluster": self.cluster_name,
                "rt-node-type": node_config.get("node_type", "worker"),
                # only the autoscaler-generated keys (values are safe
                # lowercase [a-z0-9-]) ride the TPU API labels — GCP
                # rejects arbitrary user label keys/values, and the busy
                # fold reads labels from noded registration (fed by the
                # rt-labels metadata below), not from here
                **{k: str(v)
                   for k, v in node_config.get("labels", {}).items()
                   if k in ("rt-launch", "tpu-slice")},
            },
            "metadata": {
                "startup-script": node_config.get(
                    "startup_script", self.startup_script
                ),
                # the default worker_startup_script reads this off the
                # metadata server and hands it to noded via --labels so
                # runtime registration carries the same identity
                "rt-labels": json.dumps(node_config.get("labels", {})),
            },
        }
        if self.network:
            body["networkConfig"] = {"network": self.network}
        return body

    # -- NodeProvider contract -----------------------------------------
    def create_node(self, node_config: Dict[str, Any], count: int = 1) -> List[str]:
        ids = []
        for _ in range(count):
            node_id = f"{self.cluster_name}-{uuid.uuid4().hex[:8]}"
            self._transport(
                "POST",
                self._url(f"{self._parent}/nodes?nodeId={node_id}"),
                self._node_body(node_config),
            )
            ids.append(node_id)
        return ids

    def create_slice(self, node_config: Dict[str, Any], hosts: int) -> List[str]:
        """Atomic multi-host scale-up: one Cloud TPU node whose
        accelerator_type spans all `hosts` hosts — the API allocates the
        whole ICI-connected slice or fails, so no rollback choreography
        is needed (reference analog: pod-level `TPU-{pod}-head` gang
        resource, `_private/accelerators/tpu.py:381`)."""
        from ray_tpu.core.accelerators import num_hosts_in_slice

        cfg = dict(node_config)
        cfg.setdefault("accelerator_type", self.accelerator_type)
        actual = num_hosts_in_slice(cfg["accelerator_type"])
        if actual != hosts:
            # a mismatch would book phantom capacity: the instance
            # table records `hosts` hosts but the slice delivers
            # `actual` — gang demand absorbs into capacity that never
            # arrives and the PG pends forever
            raise ValueError(
                f"accelerator_type {cfg['accelerator_type']!r} spans "
                f"{actual} host(s) but the node type requests "
                f"hosts_per_slice={hosts}; align the type's "
                "provider_config.accelerator_type with hosts_per_slice"
            )
        return self.create_node(cfg, 1)

    def terminate_node(self, provider_id: str):
        self._transport(
            "DELETE", self._url(f"{self._parent}/nodes/{provider_id}"), None
        )

    def _list(self) -> List[Dict[str, Any]]:
        reply = self._transport(
            "GET", self._url(f"{self._parent}/nodes"), None
        )
        out = []
        for n in reply.get("nodes", []):
            if n.get("labels", {}).get("rt-cluster") != self.cluster_name:
                continue
            out.append(n)
        return out

    def non_terminated_nodes(self) -> List[str]:
        return [n["id"] for n in self.list_cluster_nodes()]

    def node_is_ready(self, provider_id: str) -> bool:
        # states cached by the list_cluster_nodes() the reconcile tick
        # just made — no extra API call per node
        return self._node_states.get(provider_id) == "READY"

    def node_ip(self, provider_id: str) -> Optional[str]:
        """Reachable IP of a node (external accessConfig when present,
        else the internal endpoint) — what `rt attach/exec` ssh to."""
        for n in self._list():
            if n["name"].rsplit("/", 1)[-1] != provider_id:
                continue
            for ep in n.get("networkEndpoints", []):
                ac = ep.get("accessConfig") or {}
                if ac.get("externalIp"):
                    return ac["externalIp"]
            for ep in n.get("networkEndpoints", []):
                if ep.get("ipAddress"):
                    return ep["ipAddress"]
        return None

    def list_cluster_nodes(self) -> List[Dict[str, Any]]:
        """Live cluster members from ONE list call: id, type label, and
        per-host resources (avoids the 1+N listing pattern a per-node
        `node_resources` loop would produce)."""
        out = []
        states: Dict[str, str] = {}
        for n in self._list():
            states[n["name"].rsplit("/", 1)[-1]] = n.get("state", "")
            if n.get("state") not in _LIVE_STATES:
                continue
            at = n.get("acceleratorType", self.accelerator_type)
            out.append({
                "id": n["name"].rsplit("/", 1)[-1],
                "node_type": n.get("labels", {}).get("rt-node-type",
                                                     "worker"),
                "resources": {
                    "TPU": float(chips_for_accelerator_type(at))
                },
            })
        self._node_states = states
        return out

    def node_resources(self, provider_id: str) -> Dict[str, float]:
        for n in self.list_cluster_nodes():
            if n["id"] == provider_id:
                return dict(n["resources"])
        raise KeyError(provider_id)


def worker_startup_script(controller_host: str, controller_port: int,
                          *, num_workers: int = 0,
                          pip_package: str = "ray_tpu") -> str:
    """Startup script a TPU-VM worker runs to join the cluster: the
    reference's equivalent is the cluster YAML's worker_start_ray_
    commands rendered into the instance."""
    nw = f" --num-workers {num_workers}" if num_workers else ""
    return "\n".join([
        "#!/bin/bash",
        "set -e",
        f"python3 -m pip install -q {pip_package} || true",
        "mkdir -p /tmp/ray_tpu/node",
        # node identity labels (rt-launch, tpu-slice) stamped by the
        # autoscaler into instance metadata; forwarding them to noded
        # lets the busy fold and STRICT_PACK placement see this node
        # -f: a 404 (attribute absent) must exit non-zero so the '{}'
        # fallback engages instead of capturing the error body
        "RT_LABELS=$(curl -sf -H 'Metadata-Flavor: Google' "
        "'http://metadata.google.internal/computeMetadata/v1/instance/"
        "attributes/rt-labels' || echo '{}')",
        '[ -n "$RT_LABELS" ] || RT_LABELS=\'{}\'',
        # bind all interfaces + advertise the VM's routable IP: peers
        # on OTHER hosts dial the registered address for object
        # transfer / node routing — loopback would point them at
        # themselves
        "export RT_BIND_HOST=0.0.0.0",
        "nohup python3 -m ray_tpu.core.noded "
        "--session-dir /tmp/ray_tpu/node "
        f"--controller {controller_host}:{controller_port}{nw} "
        '--labels "$RT_LABELS" '
        ">> /tmp/ray_tpu/node/noded.out 2>&1 &",
    ])


def head_startup_script(controller_port: int = 7777, *,
                        num_workers: int = 0,
                        pip_package: str = "ray_tpu") -> str:
    """Bootstrap a TPU-VM HEAD node: start the head daemon (controller
    + noded) bound on all interfaces at a pinned controller port so
    worker VMs can join (reference analog: the cluster YAML's
    head_start_ray_commands)."""
    nw = f" --num-workers {num_workers}" if num_workers else ""
    return "\n".join([
        "#!/bin/bash",
        "set -e",
        f"python3 -m pip install -q {pip_package} || true",
        "mkdir -p /tmp/ray_tpu/node",
        # bind all interfaces + pin the controller port: worker VMs
        # join via the head's internal IP
        "export RT_BIND_HOST=0.0.0.0",
        f"export RT_CONTROLLER_PORT={controller_port}",
        "nohup python3 -m ray_tpu.core.noded "
        "--session-dir /tmp/ray_tpu/node "
        f"--head{nw} "
        ">> /tmp/ray_tpu/node/noded.out 2>&1 &",
    ])
