"""Autoscaler v2: instance-manager architecture with atomic TPU-slice
scale-up.

Reference: `python/ray/autoscaler/v2/autoscaler.py:42` +
`v2/instance_manager/` + `v2/scheduler.py` — a declarative pipeline:

1. an **instance table** tracks every managed machine through an
   explicit lifecycle state machine (versioned updates, invalid
   transitions rejected);
2. a pure **scheduler** maps (pending demand, pending gang demand,
   current instances, node-type config) -> launch/terminate decisions —
   no side effects, unit-testable in isolation;
3. a **reconciler** executes decisions against the NodeProvider and
   folds provider/cluster reality back into the table.

TPU-first inversion (SURVEY §7): the unit of scale-up for gang demand is
an **ICI-connected slice**, not a host.  A multi-host slice is
provisioned as ONE unit — either every host launches and registers
within the ready timeout, or the whole slice is rolled back (the
reference approximates this with the `TPU-{pod}-head` resource hack,
`_private/accelerators/tpu.py:381`; GCP can allocate a slice atomically
as a single multi-host TPU VM, `GcpTpuNodeProvider.create_slice`).
Scale-down is also slice-granular: a slice is terminated only when ALL
its hosts sit idle past the timeout.
"""

from __future__ import annotations

import time
import uuid
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu.autoscaler.node_provider import NodeProvider
from ray_tpu.core.task_spec import fits as _fits

# instance lifecycle (reference: `instance_manager/common.py`
# InstanceStatus — collapsed to the states this runtime distinguishes)
QUEUED = "QUEUED"            # decided, not yet requested from the provider
REQUESTED = "REQUESTED"      # provider create issued
RUNNING = "RUNNING"          # runtime node registered with the controller
TERMINATING = "TERMINATING"  # provider terminate issued
TERMINATED = "TERMINATED"    # gone (kept briefly for observability)

_TRANSITIONS = {
    QUEUED: {REQUESTED, TERMINATED},
    REQUESTED: {RUNNING, TERMINATING, TERMINATED},
    RUNNING: {TERMINATING, TERMINATED},
    TERMINATING: {TERMINATED},
    TERMINATED: set(),
}


@dataclass
class Instance:
    instance_id: str
    node_type: str
    status: str = QUEUED
    provider_id: Optional[str] = None
    runtime_node_id: Optional[str] = None  # controller's node id
    slice_id: Optional[str] = None  # set for every host of a gang slice
    # label stamped into node_config at launch; runtime nodes booted by
    # the provider carry it back through noded registration, letting
    # busy state be folded onto instances for providers that cannot map
    # provider ids to runtime node ids
    launch_id: Optional[str] = None
    # hosts this instance represents: 1 for per-host providers; N when
    # the provider allocates a whole N-host slice as ONE provider node
    # (GCP multi-host TPU VM)
    hosts: int = 1
    requested_at: float = 0.0
    last_busy_at: float = field(default_factory=time.time)
    version: int = 0


class InstanceManager:
    """The versioned instance table (reference:
    `instance_manager/instance_manager.py` — UpdateInstanceManagerState
    validates transitions and bumps a global version)."""

    def __init__(self):
        self._instances: Dict[str, Instance] = {}
        self.version = 0

    def add(self, inst: Instance):
        self._instances[inst.instance_id] = inst
        self.version += 1

    def update_status(self, instance_id: str, status: str):
        inst = self._instances[instance_id]
        if status not in _TRANSITIONS[inst.status]:
            raise ValueError(
                f"invalid transition {inst.status} -> {status} for "
                f"{instance_id}"
            )
        inst.status = status
        inst.version = self.version = self.version + 1

    def instances(self, *statuses: str) -> List[Instance]:
        if not statuses:
            return list(self._instances.values())
        return [i for i in self._instances.values() if i.status in statuses]

    def get(self, instance_id: str) -> Instance:
        return self._instances[instance_id]

    def prune_terminated(self, keep_s: float = 300.0):
        now = time.time()
        for iid, inst in list(self._instances.items()):
            if inst.status == TERMINATED and now - inst.last_busy_at > keep_s:
                del self._instances[iid]

    def slice_members(self, slice_id: str) -> List[Instance]:
        return [
            i for i in self._instances.values() if i.slice_id == slice_id
        ]


@dataclass
class NodeTypeConfigV2:
    """One launchable shape.  `hosts_per_slice > 1` makes it a
    multi-host TPU slice type: always provisioned and released whole."""

    num_cpus: float = 4
    resources: Dict[str, float] = field(default_factory=dict)
    num_workers: int = 2
    hosts_per_slice: int = 1
    max_slices: int = 8
    # provider-specific payload merged into the node config (e.g. the
    # GCP accelerator_type for the whole slice)
    provider_config: Dict[str, Any] = field(default_factory=dict)

    def host_provides(self) -> Dict[str, float]:
        return {"CPU": self.num_cpus, **self.resources}


@dataclass
class AutoscalerV2Config:
    node_types: Dict[str, NodeTypeConfigV2] = field(default_factory=dict)
    max_hosts: int = 16
    idle_timeout_s: float = 30.0
    # a REQUESTED slice whose hosts have not all registered by then is
    # rolled back whole
    # generous default: promotion is gated on REAL readiness (GCP state
    # READY / GKE pod Running), and cloud provisioning routinely takes
    # minutes (TPU-VM CREATE 2-5 min, GKE TPU node-pool scale-up up to
    # ~10) — a tighter timeout would reap+relaunch healthy boots in an
    # endless churn loop
    slice_ready_timeout_s: float = 900.0


@dataclass
class LaunchDecision:
    node_type: str
    hosts: int  # == hosts_per_slice of the type
    reason: str = ""


@dataclass
class SchedulingDecision:
    launches: List[LaunchDecision] = field(default_factory=list)
    terminations: List[str] = field(default_factory=list)  # instance ids


class ResourceDemandScheduler:
    """Pure decision function (reference: `v2/scheduler.py`
    ResourceDemandScheduler.schedule): no provider calls, no clock
    mutation — everything it needs rides in as arguments."""

    def __init__(self, config: AutoscalerV2Config):
        self.config = config

    def schedule(
        self,
        demands: List[Dict[str, float]],
        gangs: List[Dict[str, Any]],
        im: InstanceManager,
        now: float,
    ) -> SchedulingDecision:
        out = SchedulingDecision()
        live = im.instances(QUEUED, REQUESTED, RUNNING)
        hosts_up = sum(max(1, i.hosts) for i in live)
        slice_counts: Dict[str, int] = {}
        for inst in live:
            slice_counts[inst.node_type] = (
                slice_counts.get(inst.node_type, 0)
                + (1 if inst.slice_id is None else 0)
            )
        # count whole slices per type (a slice contributes once)
        seen_slices = set()
        for inst in live:
            if inst.slice_id is not None and inst.slice_id not in seen_slices:
                seen_slices.add(inst.slice_id)
                slice_counts[inst.node_type] = (
                    slice_counts.get(inst.node_type, 0) + 1
                )

        # capacity still inbound (QUEUED/REQUESTED) absorbs demand so a
        # slow-booting slice is not double-launched.  One entry per HOST
        # the instance represents (a GCP slice is one provider node for
        # N hosts — Instance.hosts carries the weight).
        spare: List[Dict[str, float]] = []
        for inst in im.instances(QUEUED, REQUESTED):
            cfg = self.config.node_types.get(inst.node_type)
            if cfg is not None:
                for _ in range(max(1, inst.hosts)):
                    spare.append(cfg.host_provides())

        def _pack(bundles: List[Dict[str, float]],
                  caps: List[Dict[str, float]]) -> bool:
            """All-or-nothing first-fit-decreasing bin-pack of bundles
            into per-host capacities; commits into `caps` on success."""
            trial = [dict(cap) for cap in caps]
            for need in sorted(bundles, key=lambda b: -sum(b.values())):
                hit = None
                for cap in trial:
                    if _fits(need, cap):
                        for k, v in need.items():
                            cap[k] = cap.get(k, 0.0) - v
                        hit = cap
                        break
                if hit is None:
                    return False
            for cap, t in zip(caps, trial):
                cap.clear()
                cap.update(t)
            return True

        def absorb_bundles(bundles: List[Dict[str, float]]) -> bool:
            """A gang absorbs into inbound capacity whole or not at all
            — per-bundle packing is what lets a multi-host gang match a
            multi-host inbound slice."""
            return _pack(bundles, spare)

        def absorb(need: Dict[str, float]) -> bool:
            return absorb_bundles([need])

        planned_hosts = 0

        def try_launch(tname: str, reason: str) -> Optional[List[Dict[str, float]]]:
            """Plan one slice launch; returns the new slice's per-host
            spare capacities (for the caller to consume) or None."""
            nonlocal planned_hosts
            cfg = self.config.node_types[tname]
            if slice_counts.get(tname, 0) >= cfg.max_slices:
                return None
            if (hosts_up + planned_hosts + cfg.hosts_per_slice
                    > self.config.max_hosts):
                return None
            out.launches.append(LaunchDecision(
                node_type=tname, hosts=cfg.hosts_per_slice, reason=reason
            ))
            slice_counts[tname] = slice_counts.get(tname, 0) + 1
            planned_hosts += cfg.hosts_per_slice
            new_caps = [cfg.host_provides()
                        for _ in range(cfg.hosts_per_slice)]
            spare.extend(new_caps)
            return new_caps

        # 1. gang demand first: whole pending placement groups -> whole
        # slices.  STRICT_PACK bundles must land in ONE ICI domain, so
        # the chosen type's slice must fit the entire bundle set.
        for gang in gangs:
            bundles = [dict(b) for b in gang.get("bundles", [])]
            if not bundles:
                continue
            if absorb_bundles(bundles):
                continue
            for tname, cfg in self.config.node_types.items():
                # real feasibility: the bundles must PACK into one
                # slice's hosts (an aggregate-capacity check admits
                # gangs no host assignment can satisfy, launching
                # slices forever)
                if not _pack(bundles, [cfg.host_provides()
                                       for _ in range(cfg.hosts_per_slice)]):
                    continue
                new_caps = try_launch(tname, f"gang:{gang.get('pg_id', '?')}")
                if new_caps is not None:
                    # consume from exactly the slice just planned for
                    # this gang — packability was verified above
                    _pack(bundles, new_caps)
                    break

        # 2. per-task demand
        for demand in demands:
            if absorb(demand):
                continue
            for tname, cfg in self.config.node_types.items():
                if _fits(demand, cfg.host_provides()):
                    new_caps = try_launch(tname, "demand")
                    if new_caps is not None:
                        _pack([demand], new_caps)
                        break

        # 3. slice-granular idle scale-down: only when no demand is
        # pending, and only slices whose EVERY host idled past the
        # timeout (single-host instances are slices of one)
        if not demands and not gangs:
            by_slice: Dict[str, List[Instance]] = {}
            for inst in im.instances(RUNNING):
                key = inst.slice_id or inst.instance_id
                by_slice.setdefault(key, []).append(inst)
            for members in by_slice.values():
                if all(
                    now - m.last_busy_at > self.config.idle_timeout_s
                    for m in members
                ):
                    out.terminations.extend(m.instance_id for m in members)
        return out


class AutoscalerV2:
    """The reconcile loop (reference: `v2/autoscaler.py:42` — each
    update(): sync state, schedule, execute)."""

    def __init__(self, provider: NodeProvider, config: AutoscalerV2Config,
                 cluster_state_fn=None):
        self.provider = provider
        self.config = config
        self.im = InstanceManager()
        self.scheduler = ResourceDemandScheduler(config)
        self._cluster_state_fn = cluster_state_fn or self._default_state

    @staticmethod
    def _default_state() -> Dict[str, Any]:
        from ray_tpu.core.runtime import get_runtime

        return get_runtime().controller_call("get_autoscaler_state")

    # -- one reconcile pass -------------------------------------------
    def update(self):
        state = self._cluster_state()
        now = time.time()
        self._sync_provider(state, now)
        decision = self.scheduler.schedule(
            state.get("pending_demands", []),
            state.get("pending_gangs", []),
            self.im,
            now,
        )
        for launch in decision.launches:
            self._launch_slice(launch, now)
        self._reap_stuck_slices(now)
        self._terminate(decision.terminations)
        self.im.prune_terminated()

    def _cluster_state(self) -> Dict[str, Any]:
        return self._cluster_state_fn()

    def _sync_provider(self, state: Dict[str, Any], now: float):
        """Fold provider + controller reality into the table."""
        live_provider = set(self.provider.non_terminated_nodes())
        alive_nodes = {
            n["node_id"]: n for n in state.get("nodes", []) if n["alive"]
        }
        rt_id = getattr(self.provider, "runtime_node_id", None)
        # providers without an id mapping fold busy state via the
        # rt-launch label each booted node registered with; a busy
        # worker that carries NO launch label (e.g. a TPU-VM bootstrap
        # that predates labels) conservatively refreshes every cloud
        # instance — slower scale-down beats terminating a busy slice
        busy_launches: set = set()
        unlabeled_busy = False
        if rt_id is None:
            for n in alive_nodes.values():
                if not n.get("busy") or n.get("is_head"):
                    continue
                lid = (n.get("labels") or {}).get("rt-launch")
                if lid:
                    busy_launches.add(lid)
                else:
                    unlabeled_busy = True
        for inst in self.im.instances(REQUESTED, RUNNING, TERMINATING):
            if inst.provider_id not in live_provider:
                self.im.update_status(inst.instance_id, TERMINATED)
                continue
            if rt_id is not None and inst.runtime_node_id is None:
                try:
                    inst.runtime_node_id = rt_id(inst.provider_id)
                except KeyError:
                    pass
            node = alive_nodes.get(inst.runtime_node_id)
            if inst.status == REQUESTED and node is not None:
                self.im.update_status(inst.instance_id, RUNNING)
            elif inst.status == REQUESTED and rt_id is None:
                # provider cannot map its ids to runtime nodes (cloud
                # slices boot daemons via startup script): REAL readiness
                # (GCP state READY / GKE pod phase Running) is the
                # promotion signal — a merely-listed Pending pod/VM must
                # stay REQUESTED so it keeps absorbing its gang as
                # inbound capacity and stays reapable at the ready
                # timeout instead of triggering a duplicate slice launch
                # every reconcile tick
                if self.provider.node_is_ready(inst.provider_id):
                    self.im.update_status(inst.instance_id, RUNNING)
            if node is not None and node.get("busy"):
                inst.last_busy_at = now
            elif rt_id is None and (
                inst.launch_id in busy_launches or unlabeled_busy
            ):
                inst.last_busy_at = now
        # demand pending means nothing should look idle (matches v1)
        if state.get("pending_demands") or state.get("pending_gangs"):
            for inst in self.im.instances(RUNNING):
                inst.last_busy_at = now

    def _launch_slice(self, launch: LaunchDecision, now: float):
        """All-or-nothing: `create_slice` either yields every host or
        the partial set is rolled back (provider default already
        guarantees this for per-host providers)."""
        cfg = self.config.node_types[launch.node_type]
        slice_id = (
            f"slice-{uuid.uuid4().hex[:8]}" if launch.hosts > 1 else None
        )
        launch_id = slice_id or f"launch-{uuid.uuid4().hex[:8]}"
        node_config = {
            "num_cpus": cfg.num_cpus,
            "resources": dict(cfg.resources),
            "num_workers": cfg.num_workers,
            **cfg.provider_config,
        }
        # the launch label rides node_config -> provider -> noded
        # registration so _sync_provider can fold busy state back onto
        # these instances even without a provider id mapping
        node_config["labels"] = {
            **node_config.get("labels", {}), "rt-launch": launch_id,
        }
        if slice_id is not None:
            # every host of the slice shares one ICI-domain label so
            # STRICT_PACK placement sees them as a gang target
            node_config["labels"]["tpu-slice"] = slice_id
        try:
            pids = self.provider.create_slice(node_config, launch.hosts)
        except Exception:
            import traceback

            traceback.print_exc()
            return
        # a provider may allocate the whole slice as ONE provider node
        # (GCP multi-host TPU VM): weight each instance by the hosts it
        # represents so capacity accounting stays exact
        hosts_each = max(1, launch.hosts // max(1, len(pids)))
        for pid in pids:
            inst = Instance(
                instance_id=f"i-{uuid.uuid4().hex[:8]}",
                node_type=launch.node_type,
                status=QUEUED,
                provider_id=pid,
                slice_id=slice_id,
                launch_id=launch_id,
                hosts=hosts_each,
                requested_at=now,
                last_busy_at=now,
            )
            self.im.add(inst)
            self.im.update_status(inst.instance_id, REQUESTED)

    def _reap_stuck_slices(self, now: float):
        """A slice partially registered past the ready timeout is torn
        down WHOLE — half a slice can never serve its gang demand.
        Non-slice nodes stuck REQUESTED (a Pending pod that never
        schedules) age out the same way, singly: without this they'd
        absorb their demand as inbound capacity forever."""
        by_slice: Dict[str, List[Instance]] = {}
        for inst in self.im.instances(REQUESTED, RUNNING):
            if inst.slice_id is not None:
                by_slice.setdefault(inst.slice_id, []).append(inst)
            elif (inst.status == REQUESTED
                  and now - inst.requested_at
                  > self.config.slice_ready_timeout_s):
                self._terminate([inst.instance_id])
        for members in by_slice.values():
            waiting = [m for m in members if m.status == REQUESTED]
            if not waiting:
                continue
            oldest = min(m.requested_at for m in members)
            if now - oldest > self.config.slice_ready_timeout_s:
                self._terminate([m.instance_id for m in members])

    def _terminate(self, instance_ids: List[str]):
        for iid in instance_ids:
            inst = self.im.get(iid)
            if inst.status in (TERMINATING, TERMINATED):
                continue
            try:
                if inst.provider_id is not None:
                    self.provider.terminate_node(inst.provider_id)
                self.im.update_status(iid, TERMINATING)
            except Exception:
                import traceback

                traceback.print_exc()

    def run(self, interval_s: float = 2.0, stop_event=None):
        while stop_event is None or not stop_event.is_set():
            try:
                self.update()
            except Exception:
                import traceback

                traceback.print_exc()
            time.sleep(interval_s)
