"""Command runners: how the launcher reaches cluster nodes.

Reference: `python/ray/autoscaler/_private/command_runner.py`
(`SSHCommandRunner`, `DockerCommandRunner`) — the seam `ray attach` /
`ray exec` / file sync run through.  The runner is injectable so the
whole attach/exec flow is testable against a mock, and alternative
transports (gcloud tpu-vm ssh, kubectl exec) slot in without touching
the command layer.
"""

from __future__ import annotations

import subprocess
from typing import Any, Dict, List, Optional, Tuple


class CommandRunner:
    """One node's command channel."""

    def run(self, cmd: str, *, timeout: Optional[float] = None,
            ) -> Tuple[int, str]:
        """Run `cmd` on the node; returns (returncode, combined output)."""
        raise NotImplementedError

    def run_interactive(self, cmd: str = "bash") -> int:
        """Attach an interactive session (inherits this process's tty);
        returns the exit code."""
        raise NotImplementedError

    def remote_shell_command(self, cmd: str = "") -> List[str]:
        """The argv a user could run by hand to reach the node (printed
        by `rt attach` so the session is reproducible without the CLI)."""
        raise NotImplementedError


class SSHCommandRunner(CommandRunner):
    """Plain ssh (reference: `command_runner.py` SSHCommandRunner).

    auth fields come from the cluster YAML's `auth:` section:
    ssh_user, ssh_private_key (optional), ssh_options (list).
    """

    def __init__(self, ip: str, *, ssh_user: str = "ubuntu",
                 ssh_private_key: Optional[str] = None,
                 ssh_options: Optional[List[str]] = None):
        self.ip = ip
        self.user = ssh_user
        self.key = ssh_private_key
        self.options = list(ssh_options or (
            "-o", "StrictHostKeyChecking=no",
            "-o", "ConnectTimeout=10",
        ))

    def _base(self) -> List[str]:
        argv = ["ssh", *self.options]
        if self.key:
            argv += ["-i", self.key]
        argv.append(f"{self.user}@{self.ip}")
        return argv

    def remote_shell_command(self, cmd: str = "") -> List[str]:
        argv = self._base()
        if cmd:
            argv.append(cmd)
        return argv

    def run(self, cmd: str, *, timeout: Optional[float] = None):
        proc = subprocess.run(
            self.remote_shell_command(cmd),
            capture_output=True, text=True, timeout=timeout,
        )
        return proc.returncode, proc.stdout + proc.stderr

    def run_interactive(self, cmd: str = "bash") -> int:
        argv = self._base()
        argv += ["-t", cmd]
        return subprocess.call(argv)


class DockerCommandRunner(SSHCommandRunner):
    """ssh + `docker exec` into a named container (reference:
    `command_runner.py` DockerCommandRunner): commands run INSIDE the
    container the cluster processes live in."""

    def __init__(self, ip: str, *, container: str, **ssh_kwargs):
        super().__init__(ip, **ssh_kwargs)
        self.container = container

    def _wrap(self, cmd: str, interactive: bool = False) -> str:
        import shlex

        parts = ["docker", "exec"]
        if interactive:
            parts.append("-it")
        parts += [self.container, "/bin/bash", "-lc", shlex.quote(cmd)]
        return " ".join(parts)

    def run(self, cmd: str, *, timeout: Optional[float] = None):
        return super().run(self._wrap(cmd), timeout=timeout)

    def run_interactive(self, cmd: str = "bash") -> int:
        return super().run_interactive(self._wrap(cmd, interactive=True))


def runner_for(cfg: Dict[str, Any], ip: str) -> CommandRunner:
    """Build the configured runner for one node ip from the cluster
    YAML (`auth:` + optional `docker:` sections)."""
    auth = cfg.get("auth", {})
    kwargs = {
        "ssh_user": auth.get("ssh_user", "ubuntu"),
        "ssh_private_key": auth.get("ssh_private_key"),
    }
    if auth.get("ssh_options"):
        kwargs["ssh_options"] = list(auth["ssh_options"])
    docker = cfg.get("docker", {})
    if docker.get("container_name"):
        return DockerCommandRunner(
            ip, container=docker["container_name"], **kwargs
        )
    return SSHCommandRunner(ip, **kwargs)
