"""StandardAutoscaler: the reconcile loop.

Reference: `autoscaler/_private/autoscaler.py` `StandardAutoscaler.update()`
(`:172,374`) — each update: read cluster state (nodes + pending resource
demand, here from the controller's autoscaler-state endpoint, the
equivalent of `gcs_autoscaler_state_manager.h`), bin-pack unmet demand
onto configured node types and launch what is missing
(`resource_demand_scheduler.py`), and terminate nodes idle past the
timeout, respecting min/max worker counts.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from ray_tpu.autoscaler.node_provider import NodeProvider
from ray_tpu.core.runtime import get_runtime
from ray_tpu.core.task_spec import fits as _fits


@dataclass
class NodeTypeConfig:
    num_cpus: float = 4
    resources: Dict[str, float] = field(default_factory=dict)
    num_workers: int = 2
    max_count: int = 8

    def provides(self) -> Dict[str, float]:
        return {"CPU": self.num_cpus, **self.resources}


@dataclass
class AutoscalerConfig:
    node_types: Dict[str, NodeTypeConfig] = field(default_factory=dict)
    min_workers: int = 0
    max_workers: int = 8
    idle_timeout_s: float = 30.0


class StandardAutoscaler:
    ABSORB_MAX_S = 60.0  # safety valve: a launch absorbs matching demand
    # until demand clears, but never longer than this (stuck demand that
    # genuinely needs more nodes gets another chance)

    def __init__(self, provider: NodeProvider, config: AutoscalerConfig):
        self.provider = provider
        self.config = config
        # provider_id -> (node_type, last time it was needed)
        self._managed: Dict[str, List] = {}
        self._recent_launches: List = []  # (ts, provides dict)

    # -- state ---------------------------------------------------------
    def _cluster_state(self) -> Dict[str, Any]:
        return get_runtime().controller_call("get_autoscaler_state")

    def _launch(self, type_name: str, count: int = 1):
        cfg = self.config.node_types[type_name]
        ids = self.provider.create_node(
            {
                "num_cpus": cfg.num_cpus,
                "resources": cfg.resources,
                "num_workers": cfg.num_workers,
            },
            count,
        )
        now = time.time()
        for pid in ids:
            self._managed[pid] = [type_name, now]

    def num_managed(self) -> int:
        return len([
            p for p in self._managed if p in self.provider.non_terminated_nodes()
        ])

    # -- the loop body -------------------------------------------------
    def update(self):
        """One reconcile pass (call periodically)."""
        state = self._cluster_state()
        live = set(self.provider.non_terminated_nodes())
        self._managed = {
            p: v for p, v in self._managed.items() if p in live
        }
        now = time.time()

        # 1. scale up for unmet demand: demand is pending because no
        # node fits it.  Launched nodes absorb demand via bin-packing —
        # each recent launch's capacity is consumed by the demands it
        # can serve, and only the remainder triggers new launches.  The
        # demand signature stays "pending" in controller state until the
        # work is actually scheduled, so launches keep absorbing until
        # the demand list clears (not a fixed cooldown, which double-
        # launches whenever node startup + scheduling outlasts it).
        demands: List[Dict[str, float]] = state["pending_demands"]
        if not demands:
            self._recent_launches = []
        else:
            self._recent_launches = [
                (ts, prov) for ts, prov in self._recent_launches
                if now - ts < self.ABSORB_MAX_S
            ]
        counts: Dict[str, int] = {}
        for p, (tname, _) in self._managed.items():
            counts[tname] = counts.get(tname, 0) + 1
        # remaining capacity of launches still absorbing demand
        spare: List[Dict[str, float]] = [
            dict(prov) for _, prov in self._recent_launches
        ]
        for demand in demands:
            absorbed = False
            for cap in spare:
                if _fits(demand, cap):
                    for k, v in demand.items():
                        cap[k] = cap.get(k, 0.0) - v
                    absorbed = True
                    break
            if absorbed:
                continue
            if self.num_managed() >= self.config.max_workers:
                break
            for tname, tcfg in self.config.node_types.items():
                if not _fits(demand, tcfg.provides()):
                    continue
                if counts.get(tname, 0) >= tcfg.max_count:
                    continue
                self._launch(tname)
                self._recent_launches.append((now, tcfg.provides()))
                cap = tcfg.provides()
                for k, v in demand.items():
                    cap[k] = cap.get(k, 0.0) - v
                spare.append(cap)
                counts[tname] = counts.get(tname, 0) + 1
                break
        if demands:
            for v in self._managed.values():
                v[1] = now  # demand exists: nothing is idle

        # a managed node reported busy (running tasks/actors or a
        # non-empty queue) is not idle, demand or no demand
        busy_ids = {
            n["node_id"] for n in state["nodes"] if n.get("busy")
        }
        rt_id = getattr(self.provider, "runtime_node_id", None)
        if rt_id is not None:
            for pid, v in self._managed.items():
                try:
                    if rt_id(pid) in busy_ids:
                        v[1] = now
                except KeyError:
                    pass

        # 2. min_workers floor
        while self.num_managed() < self.config.min_workers:
            tname = next(iter(self.config.node_types))
            self._launch(tname)

        # 3. scale down idle managed nodes past the timeout
        if not demands:
            for pid, (tname, last_needed) in list(self._managed.items()):
                if self.num_managed() <= self.config.min_workers:
                    break
                if now - last_needed > self.config.idle_timeout_s:
                    self.provider.terminate_node(pid)
                    del self._managed[pid]

    def run(self, interval_s: float = 2.0, stop_event=None):
        """Loop forever (the head-node monitor process shape,
        reference: `_private/monitor.py`)."""
        while stop_event is None or not stop_event.is_set():
            try:
                self.update()
            except Exception:
                import traceback

                traceback.print_exc()
            time.sleep(interval_s)
