"""Kubernetes/GKE node provider: cluster nodes as pods.

Reference: `python/ray/autoscaler/_private/kuberay/node_provider.py` —
the k8s-native provider where scale-up creates pods (there via the
KubeRay operator's scale request; here directly against the Kubernetes
API) and node identity is the pod name.  GKE TPU specifics follow the
documented pod shape: `google.com/tpu` resource limits plus the
`cloud.google.com/gke-tpu-accelerator` / `gke-tpu-topology` node
selectors; a multi-host slice maps to `hosts` pods sharing a
`tpu-slice` label so STRICT_PACK placement sees one ICI domain.

The HTTP transport is injectable (same seam as `gcp.py`): in-cluster
it reads the service-account token; tests drive the provider against a
recorded transport with zero egress.
"""

from __future__ import annotations

import json
import uuid
from typing import Any, Callable, Dict, List, Optional

from ray_tpu.autoscaler.node_provider import NodeProvider

Transport = Callable[[str, str, Optional[dict]], dict]

_SA = "/var/run/secrets/kubernetes.io/serviceaccount"


def default_transport(method: str, url: str, body: Optional[dict]) -> dict:
    """In-cluster transport: k8s API over the pod's service account."""
    import ssl
    import urllib.request

    with open(f"{_SA}/token") as f:
        token = f.read()
    ctx = ssl.create_default_context(cafile=f"{_SA}/ca.crt")
    req = urllib.request.Request(
        url,
        method=method,
        data=json.dumps(body).encode() if body is not None else None,
        headers={
            "Authorization": f"Bearer {token}",
            "Content-Type": "application/json",
        },
    )
    with urllib.request.urlopen(req, timeout=30, context=ctx) as r:
        payload = r.read()
    return json.loads(payload) if payload else {}


class GkeNodeProvider(NodeProvider):
    """Creates/terminates worker pods labeled as members of one
    cluster."""

    def __init__(
        self,
        cluster_name: str,
        *,
        namespace: str = "default",
        image: str = "python:3.11-slim",
        api_server: str = "https://kubernetes.default.svc",
        controller_addr: Optional[tuple] = None,
        tpu_accelerator: Optional[str] = None,  # e.g. "tpu-v5-lite-podslice"
        tpu_topology: Optional[str] = None,     # e.g. "2x4"
        transport: Optional[Transport] = None,
    ):
        self.cluster_name = cluster_name
        self.namespace = namespace
        self.image = image
        self.api = api_server.rstrip("/")
        self.controller_addr = controller_addr
        self.tpu_accelerator = tpu_accelerator
        self.tpu_topology = tpu_topology
        self._transport = transport or default_transport
        self._pod_phases: Dict[str, str] = {}  # pod name -> last phase

    # -- pod construction ---------------------------------------------
    def _pods_url(self, name: str = "") -> str:
        base = f"{self.api}/api/v1/namespaces/{self.namespace}/pods"
        return f"{base}/{name}" if name else base

    def _pod_body(self, name: str, node_config: Dict[str, Any]) -> dict:
        resources = dict(node_config.get("resources", {}))
        num_cpus = node_config.get("num_cpus", 4)
        labels = {
            "rt-cluster": self.cluster_name,
            "rt-node-type": node_config.get("node_type", "worker"),
            **{f"rt-{k}": str(v)
               for k, v in node_config.get("labels", {}).items()},
        }
        limits: Dict[str, Any] = {"cpu": str(num_cpus)}
        tpus = resources.get("TPU")
        if tpus:
            limits["google.com/tpu"] = str(int(tpus))
        args = ["-m", "ray_tpu.core.noded",
                "--session-dir", "/tmp/ray_tpu/node",
                "--num-cpus", str(num_cpus)]
        if self.controller_addr:
            args += ["--controller",
                     f"{self.controller_addr[0]}:{self.controller_addr[1]}"]
        if node_config.get("num_workers"):
            args += ["--num-workers", str(node_config["num_workers"])]
        if node_config.get("labels"):
            args += ["--labels", json.dumps(node_config["labels"])]
        spec: Dict[str, Any] = {
            "restartPolicy": "Never",
            "containers": [{
                "name": "noded",
                "image": self.image,
                "command": ["python"],
                "args": args,
                "resources": {"limits": limits},
            }],
        }
        selector: Dict[str, str] = {}
        if tpus and self.tpu_accelerator:
            selector["cloud.google.com/gke-tpu-accelerator"] = (
                self.tpu_accelerator
            )
        if tpus and self.tpu_topology:
            selector["cloud.google.com/gke-tpu-topology"] = self.tpu_topology
        if selector:
            spec["nodeSelector"] = selector
        return {
            "apiVersion": "v1",
            "kind": "Pod",
            "metadata": {"name": name, "labels": labels},
            "spec": spec,
        }

    # -- NodeProvider contract ----------------------------------------
    def create_node(self, node_config: Dict[str, Any], count: int = 1) -> List[str]:
        out = []
        for _ in range(count):
            name = f"rt-{self.cluster_name}-{uuid.uuid4().hex[:8]}"
            self._transport(
                "POST", self._pods_url(), self._pod_body(name, node_config)
            )
            out.append(name)
        return out

    def terminate_node(self, provider_id: str):
        self._transport("DELETE", self._pods_url(provider_id), None)

    def non_terminated_nodes(self) -> List[str]:
        reply = self._transport(
            "GET",
            self._pods_url()
            + f"?labelSelector=rt-cluster%3D{self.cluster_name}",
            None,
        )
        out = []
        phases: Dict[str, str] = {}
        for item in reply.get("items", []):
            phase = item.get("status", {}).get("phase", "Pending")
            phases[item["metadata"]["name"]] = phase
            if phase in ("Pending", "Running"):
                out.append(item["metadata"]["name"])
        self._pod_phases = phases
        return out

    def node_is_ready(self, provider_id: str) -> bool:
        # phases cached by the non_terminated_nodes() call the reconcile
        # tick just made — a Pending pod is NOT ready, so the autoscaler
        # keeps it REQUESTED (spare inbound capacity + reapable)
        return self._pod_phases.get(provider_id) == "Running"

    def node_resources(self, provider_id: str) -> Dict[str, float]:
        reply = self._transport("GET", self._pods_url(provider_id), None)
        limits = (
            reply.get("spec", {}).get("containers", [{}])[0]
            .get("resources", {}).get("limits", {})
        )
        out: Dict[str, float] = {}
        if "cpu" in limits:
            out["CPU"] = float(str(limits["cpu"]).rstrip("m")) / (
                1000.0 if str(limits["cpu"]).endswith("m") else 1.0
            )
        if "google.com/tpu" in limits:
            out["TPU"] = float(limits["google.com/tpu"])
        return out
