"""Autoscaler: demand-driven node lifecycle.

Reference: `python/ray/autoscaler/` — v2 architecture
(`v2/autoscaler.py:42`: declarative reconcile from GCS autoscaler
state) with the v1 `StandardAutoscaler.update()` loop shape
(`_private/autoscaler.py:172,374`) and a `FakeMultiNodeProvider`-style
local provider (`_private/fake_multi_node/node_provider.py:236`) as the
test backend.
"""

from ray_tpu.autoscaler.autoscaler import AutoscalerConfig, StandardAutoscaler
from ray_tpu.autoscaler.gke import GkeNodeProvider
from ray_tpu.autoscaler.node_provider import LocalNodeProvider, NodeProvider
from ray_tpu.autoscaler.v2 import (
    AutoscalerV2,
    AutoscalerV2Config,
    InstanceManager,
    NodeTypeConfigV2,
    ResourceDemandScheduler,
)

__all__ = [
    "AutoscalerConfig",
    "AutoscalerV2",
    "AutoscalerV2Config",
    "GkeNodeProvider",
    "InstanceManager",
    "LocalNodeProvider",
    "NodeProvider",
    "NodeTypeConfigV2",
    "ResourceDemandScheduler",
    "StandardAutoscaler",
]
