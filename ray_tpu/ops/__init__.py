"""TPU kernels and fused ops (Pallas where it wins, XLA elsewhere)."""

from ray_tpu.ops.attention import flash_attention
from ray_tpu.ops.xent import fused_cross_entropy
from ray_tpu.ops.xent_pallas import pallas_cross_entropy

__all__ = [
    "flash_attention",
    "fused_cross_entropy",
    "pallas_cross_entropy",
]
