"""TPU kernels and fused ops (Pallas where it wins, XLA elsewhere)."""

from ray_tpu.ops.attention import flash_attention

__all__ = ["flash_attention"]
