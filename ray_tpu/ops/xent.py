"""Fused chunked softmax cross-entropy against a tied embedding matrix.

The naive LM loss path materializes the full logits tensor — for GPT-2
124M at batch 32 / seq 1024 that is a [32768, 50257] f32 array (6.6 GB)
written to and re-read from HBM three times (forward, softmax backward,
dW matmul).  On TPU that HBM traffic, not FLOPs, dominates the lm-head
cost.  (Reference counterpart: torch `F.cross_entropy` over
materialized logits in its GPT-2 benchmarks, e.g.
ray/release/air_tests/air_benchmarks/workloads — fused here instead,
which the reference never does.)

This op walks the [N, E] hidden states in row chunks under `lax.scan`:

- forward: per chunk, logits = x_c @ W^T (bf16 on the MXU, f32
  accumulation), reduce to logsumexp + target logit, keep ONLY the
  per-row lse (N floats) as residual.
- backward: recompute the chunk's logits, form
  dlogits = softmax - onehot(targets) in-register, and immediately
  contract to dx_c and a running dW accumulator.  The [chunk, V] block
  never leaves VMEM-scale working set; peak extra HBM is one f32
  [chunk, V] scratch instead of 3x [N, V].

Cost: one extra lm-head matmul (the backward recompute) ≈ +2.5% model
FLOPs for GPT-2 124M, bought back several times over in step time.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax


def _pick_chunk(n_rows: int, requested: int) -> int:
    c = min(requested, n_rows)
    while n_rows % c:
        c -= 1
    return c


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def fused_cross_entropy(x, w, targets, chunk: int = 2048):
    """Mean softmax cross-entropy of rows of `x` against classes of `w`.

    x: [N, E] activations (any float dtype; matmuls run in x.dtype),
    w: [V, E] class embedding matrix (f32 master ok — cast inside),
    targets: [N] int32.  Returns scalar f32 mean loss.
    """
    loss, _ = _xent_fwd_impl(x, w, targets, chunk)
    return loss


def _xent_fwd_impl(x, w, targets, chunk):
    N, E = x.shape
    C = _pick_chunk(N, chunk)
    wc = w.astype(x.dtype)
    xs = x.reshape(N // C, C, E)
    ts = targets.reshape(N // C, C)

    def body(total, inp):
        x_c, t_c = inp
        logits = jnp.dot(x_c, wc.T, preferred_element_type=jnp.float32)
        m = jnp.max(logits, axis=-1)
        lse = m + jnp.log(jnp.sum(jnp.exp(logits - m[:, None]), axis=-1))
        tgt = jnp.take_along_axis(logits, t_c[:, None], axis=1)[:, 0]
        return total + jnp.sum(lse - tgt), lse

    total, lses = lax.scan(body, jnp.zeros((), jnp.float32), (xs, ts))
    return total / N, lses


def _xent_fwd(x, w, targets, chunk):
    loss, lses = _xent_fwd_impl(x, w, targets, chunk)
    return loss, (x, w, targets, lses)


def _xent_bwd(chunk, res, g):
    x, w, targets, lses = res
    N, E = x.shape
    C = _pick_chunk(N, chunk)
    wc = w.astype(x.dtype)
    xs = x.reshape(N // C, C, E)
    ts = targets.reshape(N // C, C)
    scale = g / N
    rows = jnp.arange(C)

    def body(dw, inp):
        x_c, t_c, lse_c = inp
        logits = jnp.dot(x_c, wc.T, preferred_element_type=jnp.float32)
        p = jnp.exp(logits - lse_c[:, None])
        p = p.at[rows, t_c].add(-1.0)
        dl = (p * scale).astype(x.dtype)
        dx_c = jnp.dot(dl, wc, preferred_element_type=jnp.float32)
        dw = dw + jnp.dot(dl.T, x_c, preferred_element_type=jnp.float32)
        return dw, dx_c.astype(x.dtype)

    dw, dxs = lax.scan(
        body, jnp.zeros(w.shape, jnp.float32), (xs, ts, lses)
    )
    dt = np.zeros(targets.shape, dtype=jax.dtypes.float0)
    return dxs.reshape(N, E), dw.astype(w.dtype), dt


fused_cross_entropy.defvjp(_xent_fwd, _xent_bwd)
