"""Paged flash-decode attention as Pallas TPU kernels.

The serve engine's per-chip decode lever: decode attention that reads
the paged KV pool THROUGH the block tables instead of gathering every
sequence's blocks into a dense view and scattering them back each
chunk (vLLM's PagedAttention, Kwon et al. SOSP 2023, fused with the
split-KV walk of Flash-Decoding, Dao et al. 2023).  This is the
opposite regime from the MXU-bound lm-head where Pallas measurably
lost (PERF.md round 5): decode attention is memory-bound over the KV
pool, and the gather path pays two extra full passes over the live KV
per chunk (pool -> dense copy, dense -> pool scatter) plus pow-2
padding on the gather width — pure HBM bandwidth the kernel never
spends.

Two kernels, both taking the pool `[L, num_blocks, block_size, KV,
hd]` whole with the LAYER INDEX as a scalar-prefetch argument, so the
engine's per-layer scan never slices (= copies) the pool:

- `paged_kv_append`: writes one new KV row per sequence into its tail
  block, in place (`input_output_aliases`) — the grid touches ONE
  block per row, replacing the chunk stepper's whole-view scatter.
- `paged_decode_attention`: grid `(B, W)`; block tables and per-row
  positions ride in SMEM (`PrefetchScalarGridSpec`), each grid step
  DMAs pool block `tables[b, w]` and folds it into an online softmax
  (running max / sum / f32 accumulator in VMEM scratch) — the
  split-KV combine, one sequential axis per row.

Numerics mirror `llama.decode_step_vec`'s attention exactly in form
(q.k^T with f32 accumulation, -1e30 mask, softmax weights cast to the
compute dtype for the value matmul, f32 value accumulation); the
reduction is blockwise-online rather than dense, so logits agree to
float rounding and greedy argmax is preserved (pinned by
`tests/test_paged_attention.py`).

Int8 KV rides the same kernels: pools carry int8 payload plus a
per-row, per-kv-head f32 scale sidecar `[L, num_blocks, block_size,
KV]` stored blockwise beside the pool; dequantization is fused inside
the attention kernel (int8 payload is all that crosses HBM) and the
append kernel writes the quantized row + its scale.

On CPU the kernels run in interpret mode (`interpret=None` resolves
via `jax.default_backend()`); `ray_tpu.testing.pallas_kernel_support
("paged")` probes the environment and tier-1 kernel tests skip-guard
on it.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from ray_tpu.ops.pallas_compat import compiler_params as _compiler_params

_NEG_INF = -1e30


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


# ----------------------------------------------------------------------
# int8 helpers (shared with the engine's gather fallback + weight quant)
# ----------------------------------------------------------------------
def quantize_int8(x: jax.Array, axis: int = -1) -> Tuple[jax.Array, jax.Array]:
    """Symmetric per-slice int8 quantization along `axis` in f32 math:
    scale = max|x| / 127 (so the max element maps to exactly ±127 and a
    dequant->requant round trip is IDEMPOTENT — stored KV never drifts
    when the gather fallback rewrites untouched rows), zero slices get
    scale 0 and payload 0.  Returns (q int8, scale f32 with `axis`
    removed)."""
    xf = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(xf), axis=axis, keepdims=True)
    scale = amax / 127.0
    q = jnp.round(xf / jnp.where(scale == 0.0, 1.0, scale))
    q = jnp.clip(q, -127.0, 127.0).astype(jnp.int8)
    return q, jnp.squeeze(scale, axis=axis)


def dequantize_int8(q: jax.Array, scale: jax.Array, dtype,
                    axis: int = -1) -> jax.Array:
    """Inverse of `quantize_int8`: f32 multiply, then cast to `dtype`."""
    return (q.astype(jnp.float32)
            * jnp.expand_dims(scale, axis)).astype(dtype)


# ----------------------------------------------------------------------
# append kernel: one KV row into each sequence's tail block, in place
# ----------------------------------------------------------------------
@functools.lru_cache(maxsize=32)
def _build_append(L, NB, BS, KV, HD, B, W, pool_dtype, new_dtype,
                  quantized, interpret):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    view = W * BS  # positions the W-wide table can address

    def pool_map(b, layer_ref, tables_ref, pos_ref):
        # tail block of row b; clamped so an overshooting finished row
        # (pos past its own allocation) indexes table PADDING (the
        # scratch block) instead of reading out of bounds
        w = jnp.minimum(pos_ref[b] // BS, W - 1)
        return (layer_ref[0], tables_ref[b, w], 0, 0, 0)

    def scale_map(b, layer_ref, tables_ref, pos_ref):
        w = jnp.minimum(pos_ref[b] // BS, W - 1)
        return (layer_ref[0], tables_ref[b, w], 0, 0)

    def row_map(b, *_refs):
        return (b, 0, 0)

    def srow_map(b, *_refs):
        return (b, 0)

    if quantized:
        def kernel(layer_ref, tables_ref, pos_ref, kp_ref, vp_ref,
                   ks_ref, vs_ref, kn_ref, vn_ref, kns_ref, vns_ref,
                   kp_out, vp_out, ks_out, vs_out):
            b = pl.program_id(0)
            p_b = pos_ref[b]
            off = p_b % BS
            # copy-through: the out block is staged whole, so rows the
            # kernel doesn't write must be re-written from the input
            kp_out[...] = kp_ref[...]
            vp_out[...] = vp_ref[...]
            ks_out[...] = ks_ref[...]
            vs_out[...] = vs_ref[...]

            @pl.when(p_b < view)
            def _write():  # matches the gather path's masked select:
                # a position past the table's reach writes nothing
                kp_out[pl.ds(off, 1)] = kn_ref[...].reshape(1, KV, HD)
                vp_out[pl.ds(off, 1)] = vn_ref[...].reshape(1, KV, HD)
                ks_out[pl.ds(off, 1)] = kns_ref[...].reshape(1, KV)
                vs_out[pl.ds(off, 1)] = vns_ref[...].reshape(1, KV)

        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=3,
            grid=(B,),
            in_specs=[
                pl.BlockSpec((None, None, BS, KV, HD), pool_map),
                pl.BlockSpec((None, None, BS, KV, HD), pool_map),
                pl.BlockSpec((None, None, BS, KV), scale_map),
                pl.BlockSpec((None, None, BS, KV), scale_map),
                pl.BlockSpec((None, KV, HD), row_map),
                pl.BlockSpec((None, KV, HD), row_map),
                pl.BlockSpec((None, KV), srow_map),
                pl.BlockSpec((None, KV), srow_map),
            ],
            out_specs=[
                pl.BlockSpec((None, None, BS, KV, HD), pool_map),
                pl.BlockSpec((None, None, BS, KV, HD), pool_map),
                pl.BlockSpec((None, None, BS, KV), scale_map),
                pl.BlockSpec((None, None, BS, KV), scale_map),
            ],
        )
        out_shape = [
            jax.ShapeDtypeStruct((L, NB, BS, KV, HD), pool_dtype),
            jax.ShapeDtypeStruct((L, NB, BS, KV, HD), pool_dtype),
            jax.ShapeDtypeStruct((L, NB, BS, KV), jnp.float32),
            jax.ShapeDtypeStruct((L, NB, BS, KV), jnp.float32),
        ]
        # operand indices are FLATTENED and include the 3 scalar-
        # prefetch args (megablox gmm convention)
        aliases = {3: 0, 4: 1, 5: 2, 6: 3}
    else:
        def kernel(layer_ref, tables_ref, pos_ref, kp_ref, vp_ref,
                   kn_ref, vn_ref, kp_out, vp_out):
            b = pl.program_id(0)
            p_b = pos_ref[b]
            off = p_b % BS
            kp_out[...] = kp_ref[...]
            vp_out[...] = vp_ref[...]

            @pl.when(p_b < view)
            def _write():
                kp_out[pl.ds(off, 1)] = kn_ref[...].reshape(1, KV, HD)
                vp_out[pl.ds(off, 1)] = vn_ref[...].reshape(1, KV, HD)

        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=3,
            grid=(B,),
            in_specs=[
                pl.BlockSpec((None, None, BS, KV, HD), pool_map),
                pl.BlockSpec((None, None, BS, KV, HD), pool_map),
                pl.BlockSpec((None, KV, HD), row_map),
                pl.BlockSpec((None, KV, HD), row_map),
            ],
            out_specs=[
                pl.BlockSpec((None, None, BS, KV, HD), pool_map),
                pl.BlockSpec((None, None, BS, KV, HD), pool_map),
            ],
        )
        out_shape = [
            jax.ShapeDtypeStruct((L, NB, BS, KV, HD), pool_dtype),
            jax.ShapeDtypeStruct((L, NB, BS, KV, HD), pool_dtype),
        ]
        aliases = {3: 0, 4: 1}

    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=out_shape,
        input_output_aliases=aliases,
        compiler_params=_compiler_params(
            # two idle rows can share the scratch tail block: the grid
            # must stay sequential so their copy-through writes don't race
            dimension_semantics=("arbitrary",),
        ),
        interpret=interpret,
    )


def paged_kv_append(k_pool, v_pool, k_new, v_new, tables, pos, layer, *,
                    k_scale=None, v_scale=None, k_new_scale=None,
                    v_new_scale=None, interpret: Optional[bool] = None):
    """Write each row's new KV into its tail pool block, in place.

    k_pool/v_pool [L, NB, BS, KV, hd]; k_new/v_new [B, KV, hd] (pool
    dtype); tables [B, W] int32; pos [B] int32 (the position being
    written); layer: scalar int32 (traced OK).  With the int8 sidecar
    (`k_scale`/`v_scale` [L, NB, BS, KV] f32 + per-row `k_new_scale`/
    `v_new_scale` [B, KV]) returns (k_pool, v_pool, k_scale, v_scale),
    else (k_pool, v_pool)."""
    L, NB, BS, KV, HD = k_pool.shape
    B, W = tables.shape
    quantized = k_scale is not None
    if interpret is None:
        interpret = _interpret()
    fn = _build_append(L, NB, BS, KV, HD, B, W,
                       jnp.dtype(k_pool.dtype).name,
                       jnp.dtype(k_new.dtype).name, quantized,
                       bool(interpret))
    layer = jnp.asarray(layer, jnp.int32).reshape(1)
    if quantized:
        return tuple(fn(layer, tables, pos, k_pool, v_pool, k_scale,
                        v_scale, k_new, v_new, k_new_scale, v_new_scale))
    return tuple(fn(layer, tables, pos, k_pool, v_pool, k_new, v_new))


# ----------------------------------------------------------------------
# decode attention kernel: split-KV walk over the block table
# ----------------------------------------------------------------------
@functools.lru_cache(maxsize=32)
def _build_attention(L, NB, BS, KV, HD, B, W, H, pool_dtype, q_dtype,
                     quantized, interpret):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    group = H // KV
    scale = HD ** -0.5
    q_dt = jnp.dtype(q_dtype)

    def pool_map(b, w, layer_ref, tables_ref, pos_ref):
        return (layer_ref[0], tables_ref[b, w], 0, 0, 0)

    def scale_map(b, w, layer_ref, tables_ref, pos_ref):
        return (layer_ref[0], tables_ref[b, w], 0, 0)

    def q_map(b, w, *_refs):
        return (b, 0, 0)

    def kernel(layer_ref, tables_ref, pos_ref, q_ref, k_ref, v_ref,
               *rest):
        if quantized:
            ks_ref, vs_ref, o_ref, m_ref, l_ref, acc_ref = rest
        else:
            o_ref, m_ref, l_ref, acc_ref = rest
        b = pl.program_id(0)
        w = pl.program_id(1)
        n_w = pl.num_programs(1)
        p_b = pos_ref[b]

        @pl.when(w == 0)
        def _init():
            m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
            l_ref[...] = jnp.zeros_like(l_ref)
            acc_ref[...] = jnp.zeros_like(acc_ref)

        @pl.when(w * BS <= p_b)
        def _compute():
            cols = w * BS + jax.lax.broadcasted_iota(
                jnp.int32, (group, BS), 1
            )
            valid = cols <= p_b
            # unrolled kv-head loop: 2-D MXU dots only (batched
            # dot_general does not lower on TPU Pallas); KV is small
            for h in range(KV):
                g0 = h * group
                if quantized:
                    kh = (k_ref[:, h, :].astype(jnp.float32)
                          * ks_ref[:, h][:, None]).astype(q_dt)
                    vh = (v_ref[:, h, :].astype(jnp.float32)
                          * vs_ref[:, h][:, None]).astype(q_dt)
                else:
                    kh = k_ref[:, h, :]
                    vh = v_ref[:, h, :]
                s = jax.lax.dot_general(
                    q_ref[g0:g0 + group, :], kh,
                    (((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32,
                ) * scale
                s = jnp.where(valid, s, _NEG_INF)
                m = m_ref[g0:g0 + group]
                m_new = jnp.maximum(m, jnp.max(s, axis=-1))
                corr = jnp.exp(m - m_new)
                p = jnp.where(valid, jnp.exp(s - m_new[:, None]), 0.0)
                m_ref[g0:g0 + group] = m_new
                l_ref[g0:g0 + group] = (
                    l_ref[g0:g0 + group] * corr + jnp.sum(p, axis=-1)
                )
                # softmax weights cast to the compute dtype for the
                # value matmul, f32 accumulation — decode_step_vec form
                acc_ref[g0:g0 + group, :] = (
                    acc_ref[g0:g0 + group, :] * corr[:, None]
                    + jax.lax.dot_general(
                        p.astype(q_dt), vh,
                        (((1,), (0,)), ((), ())),
                        preferred_element_type=jnp.float32,
                    )
                )

        @pl.when(w == n_w - 1)
        def _finalize():
            l = l_ref[...]
            safe_l = jnp.where(l == 0.0, 1.0, l)
            o_ref[...] = (acc_ref[...] / safe_l[:, None]).astype(
                o_ref.dtype
            )

    in_specs = [
        pl.BlockSpec((None, H, HD), q_map),
        pl.BlockSpec((None, None, BS, KV, HD), pool_map),
        pl.BlockSpec((None, None, BS, KV, HD), pool_map),
    ]
    if quantized:
        in_specs += [
            pl.BlockSpec((None, None, BS, KV), scale_map),
            pl.BlockSpec((None, None, BS, KV), scale_map),
        ]

    return pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=3,
            grid=(B, W),
            in_specs=in_specs,
            out_specs=pl.BlockSpec((None, H, HD), q_map),
            scratch_shapes=[
                pltpu.VMEM((H,), jnp.float32),
                pltpu.VMEM((H,), jnp.float32),
                pltpu.VMEM((H, HD), jnp.float32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((B, H, HD), q_dt),
        compiler_params=_compiler_params(
            # rows are independent; the block walk carries the online
            # softmax scratch and must stay sequential
            dimension_semantics=("parallel", "arbitrary"),
        ),
        interpret=interpret,
    )


def paged_decode_attention(q, k_pool, v_pool, tables, pos, layer, *,
                           k_scale=None, v_scale=None,
                           interpret: Optional[bool] = None):
    """One step of decode attention straight off the paged pool.

    q [B, H, hd] (post-RoPE, current positions); k_pool/v_pool
    [L, NB, BS, KV, hd]; tables [B, W] int32 block tables (pad with the
    scratch block); pos [B] int32 per-row positions — attention covers
    columns 0..pos[b] inclusive, so the current row must already be
    written (`paged_kv_append` first).  `layer` scalar int32 selects
    the pool layer.  GQA: query head h attends through kv head
    h // (H // KV).  Returns o [B, H, hd] in q's dtype."""
    L, NB, BS, KV, HD = k_pool.shape
    B, W = tables.shape
    H = q.shape[1]
    quantized = k_scale is not None
    if interpret is None:
        interpret = _interpret()
    fn = _build_attention(L, NB, BS, KV, HD, B, W, H,
                          jnp.dtype(k_pool.dtype).name,
                          jnp.dtype(q.dtype).name, quantized,
                          bool(interpret))
    layer = jnp.asarray(layer, jnp.int32).reshape(1)
    if quantized:
        return fn(layer, tables, pos, q, k_pool, v_pool, k_scale, v_scale)
    return fn(layer, tables, pos, q, k_pool, v_pool)
