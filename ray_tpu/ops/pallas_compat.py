"""Pallas API-surface compatibility shared by the TPU kernels."""

from __future__ import annotations


def compiler_params(**kwargs):
    """Pallas TPU compiler params across the API rename (the class is
    `CompilerParams` in newer JAX, `TPUCompilerParams` through 0.4.x)."""
    from jax.experimental.pallas import tpu as pltpu

    cls = getattr(pltpu, "CompilerParams", None)
    if cls is None:
        cls = pltpu.TPUCompilerParams
    return cls(**kwargs)
