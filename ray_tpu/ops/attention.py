"""Flash attention as a Pallas TPU kernel.

Reference has no TPU kernels (its hot ops ride CUDA/cuDNN through
torch); this is the TPU-native equivalent of its fused-attention path.
Design per /opt/skills/guides/pallas_guide.md: q blocks stream from
VMEM, the kv sequence is walked block-by-block with an online softmax
(running max / sum / accumulator in f32), so the [Tq, Tk] score matrix
never materializes in HBM — the memory shape that unlocks long context
on one chip.

`flash_attention` is a drop-in for `plain_attention` ([B, T, H, D]
layout) with a custom VJP whose backward recomputes attention with
standard XLA ops (flash-forward + recompute-backward: the standard
memory/compute trade, same totals as remat).  On CPU (tests) the kernel
runs in interpreter mode when small, else falls back to the XLA path.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

from ray_tpu.parallel.ring_attention import plain_attention

_NEG_INF = -1e30


def _flash_fwd_pallas(q, k, v, *, causal: bool, scale: float,
                      block_q: int, block_k: int, interpret: bool):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    BH, T, D = q.shape  # batch*heads folded
    n_q = T // block_q
    n_k = T // block_k

    def kernel(q_ref, k_ref, v_ref, o_ref, m_ref, l_ref, acc_ref):
        qi = pl.program_id(1)
        kb = pl.program_id(2)

        @pl.when(kb == 0)
        def _init():
            m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
            l_ref[...] = jnp.zeros_like(l_ref)
            acc_ref[...] = jnp.zeros_like(acc_ref)

        qb = q_ref[...].astype(jnp.float32) * scale  # [block_q, D]
        kblk = k_ref[...].astype(jnp.float32)  # [block_k, D]
        vblk = v_ref[...].astype(jnp.float32)
        s = qb @ kblk.T  # [block_q, block_k]
        if causal:
            rows = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            )
            cols = kb * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
            s = jnp.where(rows >= cols, s, _NEG_INF)
        m = m_ref[...]
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        corr = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new[:, None])
        m_ref[...] = m_new
        l_ref[...] = l_ref[...] * corr + jnp.sum(p, axis=-1)
        acc_ref[...] = acc_ref[...] * corr[:, None] + p @ vblk

        @pl.when(kb == n_k - 1)
        def _finalize():
            o_ref[...] = (
                acc_ref[...] / l_ref[...][:, None]
            ).astype(o_ref.dtype)

    # The kv walk is the INNERMOST grid dim: TPU grids iterate
    # sequentially, so the VMEM scratch accumulators persist across kv
    # steps of one q block.  Only one [block_k, D] K/V tile is resident
    # per step — long sequences never exceed VMEM.
    return pl.pallas_call(
        kernel,
        grid=(BH, n_q, n_k),
        in_specs=[
            pl.BlockSpec((None, block_q, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((None, block_k, D), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((None, block_k, D), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=pl.BlockSpec((None, block_q, D), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((BH, T, D), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((block_q,), jnp.float32),
            pltpu.VMEM((block_q,), jnp.float32),
            pltpu.VMEM((block_q, D), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v)


def _supported(T: int, D: int, block_q: int, block_k: int) -> bool:
    return (
        T % block_q == 0
        and T % block_k == 0
        and D % 8 == 0
        and T >= block_q
    )


@functools.partial(
    jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6)
)
def flash_attention(q, k, v, causal: bool = True,
                    block_q: int = 128, block_k: int = 128,
                    force_pallas: Optional[bool] = None):
    """q/k/v [B, T, H, D] -> [B, T, H, D]."""
    return _flash_forward(q, k, v, causal, block_q, block_k, force_pallas)


def _flash_forward(q, k, v, causal, block_q, block_k, force_pallas):
    B, T, H, D = q.shape
    on_tpu = jax.default_backend() == "tpu"
    use_pallas = force_pallas if force_pallas is not None else on_tpu
    block_q = min(block_q, T)
    block_k = min(block_k, T)
    if not use_pallas or not _supported(T, D, block_q, block_k):
        return plain_attention(q, k, v, causal=causal)
    scale = 1.0 / (D ** 0.5)
    fold = lambda x: x.transpose(0, 2, 1, 3).reshape(B * H, T, D)
    out = _flash_fwd_pallas(
        fold(q), fold(k), fold(v), causal=causal, scale=scale,
        block_q=block_q, block_k=block_k, interpret=not on_tpu,
    )
    return out.reshape(B, H, T, D).transpose(0, 2, 1, 3)


def _fwd(q, k, v, causal, block_q, block_k, force_pallas):
    out = _flash_forward(q, k, v, causal, block_q, block_k, force_pallas)
    return out, (q, k, v)


def _bwd(causal, block_q, block_k, force_pallas, res, g):
    q, k, v = res
    # recompute-backward: differentiate the XLA attention (bitwise-equal
    # math in f32; the flash forward only changed the summation order)
    _, vjp = jax.vjp(lambda q, k, v: plain_attention(q, k, v, causal=causal),
                     q, k, v)
    return vjp(g)


flash_attention.defvjp(_fwd, _bwd)
