"""Flash attention as Pallas TPU kernels, forward AND backward.

Reference has no TPU kernels (its hot ops ride CUDA/cuDNN through
torch); this is the TPU-native equivalent of its fused-attention path.
Design per /opt/skills/guides/pallas_guide.md: q blocks stay resident in
VMEM while the kv sequence streams block-by-block through an online
softmax (running max / sum / accumulator in f32), so the [Tq, Tk] score
matrix never materializes in HBM — the memory shape that unlocks long
context on one chip.

What makes it *beat* dense XLA attention at seq ~1k (the round-1 kernel
lost to it):
- matmuls run on the MXU in bf16 with f32 accumulation
  (`preferred_element_type`) — the old kernel upcast q/k/v to f32
  first, quartering MXU throughput;
- causal block skipping: fully-masked [block_q, block_k] tiles skip
  their matmuls entirely (~half the quadratic FLOPs at equal block
  counts), where the dense path computes-then-masks;
- a real Pallas backward (dq kernel + dk/dv kernel, FlashAttention-2
  style with the per-row logsumexp saved from forward) instead of
  recomputing dense attention with XLA ops — same block skipping, no
  [T, T] HBM tensor in the backward either;
- `dimension_semantics`: batch*heads and q blocks are parallel grid
  axes, the kv walk is the sole sequential axis;
- single-tile FUSED backward when block_q == block_k == T (the bench
  shapes): dq/dk/dv come out of one kernel per (batch, head) that
  computes s, p, dp, ds once and delta=rowsum(do*out) in-kernel — the
  split kernel pair pays 7 matmuls + 2 exps + an XLA delta pass for
  the same math (measured +6% end-to-end GPT-2 step on v5e).

On CPU (tests) the kernels run in interpreter mode when small, else
fall back to the XLA path (`plain_attention`).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

from ray_tpu.ops.pallas_compat import compiler_params as _compiler_params
from ray_tpu.parallel.ring_attention import plain_attention

_NEG_INF = -1e30


def _dot_f32(a, b, trans_b=False):
    """MXU matmul: any-dtype in, f32 accumulate/out."""
    dims = (((1,), (1 if trans_b else 0,)), ((), ()))
    return jax.lax.dot_general(a, b, dims, preferred_element_type=jnp.float32)


def _causal_mask(s, qi, kb, block_q, block_k):
    rows = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
    cols = kb * block_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
    return jnp.where(rows >= cols, s, _NEG_INF)


def _build_fwd(causal, scale, block_q, block_k, n_k, interpret, dtype):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    def kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, m_ref, l_ref, acc_ref):
        qi = pl.program_id(1)
        kb = pl.program_id(2)

        @pl.when(kb == 0)
        def _init():
            m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
            l_ref[...] = jnp.zeros_like(l_ref)
            acc_ref[...] = jnp.zeros_like(acc_ref)

        def compute():
            qb = q_ref[...]  # [block_q, D] compute dtype
            s = _dot_f32(qb, k_ref[...], trans_b=True) * scale
            if causal:
                s = _causal_mask(s, qi, kb, block_q, block_k)
            m = m_ref[...]
            m_new = jnp.maximum(m, jnp.max(s, axis=-1))
            corr = jnp.exp(m - m_new)
            p = jnp.exp(s - m_new[:, None])
            m_ref[...] = m_new
            l_ref[...] = l_ref[...] * corr + jnp.sum(p, axis=-1)
            acc_ref[...] = acc_ref[...] * corr[:, None] + _dot_f32(
                p.astype(dtype), v_ref[...]
            )

        if causal:
            # skip tiles strictly above the diagonal (fully masked)
            @pl.when(kb * block_k <= qi * block_q + block_q - 1)
            def _():
                compute()
        else:
            compute()

        @pl.when(kb == n_k - 1)
        def _finalize():
            l = l_ref[...]
            # fully-masked rows (can't happen causally, but keep the
            # kernel total): lse=-inf, out=0
            safe_l = jnp.where(l == 0.0, 1.0, l)
            o_ref[...] = (acc_ref[...] / safe_l[:, None]).astype(o_ref.dtype)
            # lse rides a trailing singleton lane dim: TPU block specs
            # need the last two dims (8, 128)-divisible or array-equal
            lse_ref[...] = (m_ref[...] + jnp.log(safe_l))[:, None]

    def call(q, k, v):
        BH, T, D = q.shape
        n_q = T // block_q
        grid = (BH, n_q, n_k)
        return pl.pallas_call(
            kernel,
            grid=grid,
            in_specs=[
                pl.BlockSpec((None, block_q, D), lambda b, i, j: (b, i, 0)),
                pl.BlockSpec((None, block_k, D), lambda b, i, j: (b, j, 0)),
                pl.BlockSpec((None, block_k, D), lambda b, i, j: (b, j, 0)),
            ],
            out_specs=[
                pl.BlockSpec((None, block_q, D), lambda b, i, j: (b, i, 0)),
                pl.BlockSpec((None, block_q, 1), lambda b, i, j: (b, i, 0)),
            ],
            out_shape=[
                jax.ShapeDtypeStruct((BH, T, D), q.dtype),
                jax.ShapeDtypeStruct((BH, T, 1), jnp.float32),
            ],
            scratch_shapes=[
                pltpu.VMEM((block_q,), jnp.float32),
                pltpu.VMEM((block_q,), jnp.float32),
                pltpu.VMEM((block_q, D), jnp.float32),
            ],
            compiler_params=_compiler_params(
                dimension_semantics=("parallel", "parallel", "arbitrary"),
            ),
            interpret=interpret,
        )(q, k, v)

    return call


def _build_bwd_dq(causal, scale, block_q, block_k, n_k, interpret, dtype):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    def kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, dlt_ref, dq_ref, acc_ref):
        qi = pl.program_id(1)
        kb = pl.program_id(2)

        @pl.when(kb == 0)
        def _init():
            acc_ref[...] = jnp.zeros_like(acc_ref)

        def compute():
            qb = q_ref[...]
            s = _dot_f32(qb, k_ref[...], trans_b=True) * scale
            if causal:
                s = _causal_mask(s, qi, kb, block_q, block_k)
            p = jnp.exp(s - lse_ref[...])  # [bq,bk] - [bq,1] broadcast
            dp = _dot_f32(do_ref[...], v_ref[...], trans_b=True)
            ds = p * (dp - dlt_ref[...]) * scale
            acc_ref[...] += _dot_f32(ds.astype(dtype), k_ref[...])

        if causal:
            @pl.when(kb * block_k <= qi * block_q + block_q - 1)
            def _():
                compute()
        else:
            compute()

        @pl.when(kb == n_k - 1)
        def _fin():
            dq_ref[...] = acc_ref[...].astype(dq_ref.dtype)

    def call(q, k, v, do, lse, delta):
        BH, T, D = q.shape
        n_q = T // block_q
        return pl.pallas_call(
            kernel,
            grid=(BH, n_q, n_k),
            in_specs=[
                pl.BlockSpec((None, block_q, D), lambda b, i, j: (b, i, 0)),
                pl.BlockSpec((None, block_k, D), lambda b, i, j: (b, j, 0)),
                pl.BlockSpec((None, block_k, D), lambda b, i, j: (b, j, 0)),
                pl.BlockSpec((None, block_q, D), lambda b, i, j: (b, i, 0)),
                pl.BlockSpec((None, block_q, 1), lambda b, i, j: (b, i, 0)),
                pl.BlockSpec((None, block_q, 1), lambda b, i, j: (b, i, 0)),
            ],
            out_specs=pl.BlockSpec((None, block_q, D), lambda b, i, j: (b, i, 0)),
            out_shape=jax.ShapeDtypeStruct((BH, T, D), q.dtype),
            scratch_shapes=[pltpu.VMEM((block_q, D), jnp.float32)],
            compiler_params=_compiler_params(
                dimension_semantics=("parallel", "parallel", "arbitrary"),
            ),
            interpret=interpret,
        )(q, k, v, do, lse, delta)

    return call


def _build_bwd_fused(causal, scale, T, interpret, dtype):
    """Single-tile backward for the whole-sequence block case
    (block_q == block_k == T): with a (BH,) grid there is no
    cross-block accumulation, so dq/dk/dv come out of ONE kernel that
    computes s, p=exp(s-lse), dp, ds exactly once — the split
    dq/dkdv pair recomputes all four per kernel (7 matmuls + 2 exps vs
    5 matmuls + 1 exp here) and re-reads q/k/v/do twice from HBM."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    def kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, out_ref,
               dq_ref, dk_ref, dv_ref):
        qb = q_ref[...]
        kb = k_ref[...]
        dob = do_ref[...]
        # delta = rowsum(do * out) computed here instead of a separate
        # XLA pass that would re-read both [BH, T, D] tensors from HBM
        delta = jnp.sum(
            dob.astype(jnp.float32) * out_ref[...].astype(jnp.float32),
            axis=-1, keepdims=True,
        )
        s = _dot_f32(qb, kb, trans_b=True) * scale
        if causal:
            s = _causal_mask(s, 0, 0, T, T)
        p = jnp.exp(s - lse_ref[...])
        pc = p.astype(dtype)
        dv_ref[...] = _dot_f32(pc.T, dob).astype(dv_ref.dtype)
        dp = _dot_f32(dob, v_ref[...], trans_b=True)
        ds = (p * (dp - delta) * scale).astype(dtype)
        dq_ref[...] = _dot_f32(ds, kb).astype(dq_ref.dtype)
        dk_ref[...] = _dot_f32(ds.T, qb).astype(dk_ref.dtype)

    def call(q, k, v, do, lse, out):
        BH, T_, D = q.shape
        spec = pl.BlockSpec((None, T_, D), lambda b: (b, 0, 0))
        vec = pl.BlockSpec((None, T_, 1), lambda b: (b, 0, 0))
        return pl.pallas_call(
            kernel,
            grid=(BH,),
            in_specs=[spec, spec, spec, spec, vec, spec],
            out_specs=[spec, spec, spec],
            out_shape=[jax.ShapeDtypeStruct((BH, T_, D), q.dtype)] * 3,
            compiler_params=_compiler_params(
                dimension_semantics=("parallel",),
            ),
            interpret=interpret,
        )(q, k, v, do, lse, out)

    return call


def _build_bwd_dkv(causal, scale, block_q, block_k, n_q, interpret, dtype):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    def kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, dlt_ref,
               dk_ref, dv_ref, dk_acc, dv_acc):
        kb = pl.program_id(1)
        qi = pl.program_id(2)

        @pl.when(qi == 0)
        def _init():
            dk_acc[...] = jnp.zeros_like(dk_acc)
            dv_acc[...] = jnp.zeros_like(dv_acc)

        def compute():
            qb = q_ref[...]
            s = _dot_f32(qb, k_ref[...], trans_b=True) * scale
            if causal:
                s = _causal_mask(s, qi, kb, block_q, block_k)
            p = jnp.exp(s - lse_ref[...])  # [bq,bk] - [bq,1] broadcast
            pT = p.astype(dtype).T  # [bk, bq]
            dv_acc[...] += _dot_f32(pT, do_ref[...])
            dp = _dot_f32(do_ref[...], v_ref[...], trans_b=True)
            ds = p * (dp - dlt_ref[...]) * scale
            dk_acc[...] += _dot_f32(ds.astype(dtype).T, qb)

        if causal:
            # q blocks entirely above the diagonal see this kv block
            # fully masked: skip
            @pl.when(qi * block_q + block_q - 1 >= kb * block_k)
            def _():
                compute()
        else:
            compute()

        @pl.when(qi == n_q - 1)
        def _fin():
            dk_ref[...] = dk_acc[...].astype(dk_ref.dtype)
            dv_ref[...] = dv_acc[...].astype(dv_ref.dtype)

    def call(q, k, v, do, lse, delta):
        BH, T, D = q.shape
        n_k = T // block_k
        return pl.pallas_call(
            kernel,
            grid=(BH, n_k, n_q),
            in_specs=[
                pl.BlockSpec((None, block_q, D), lambda b, j, i: (b, i, 0)),
                pl.BlockSpec((None, block_k, D), lambda b, j, i: (b, j, 0)),
                pl.BlockSpec((None, block_k, D), lambda b, j, i: (b, j, 0)),
                pl.BlockSpec((None, block_q, D), lambda b, j, i: (b, i, 0)),
                pl.BlockSpec((None, block_q, 1), lambda b, j, i: (b, i, 0)),
                pl.BlockSpec((None, block_q, 1), lambda b, j, i: (b, i, 0)),
            ],
            out_specs=[
                pl.BlockSpec((None, block_k, D), lambda b, j, i: (b, j, 0)),
                pl.BlockSpec((None, block_k, D), lambda b, j, i: (b, j, 0)),
            ],
            out_shape=[
                jax.ShapeDtypeStruct((BH, T, D), q.dtype),
                jax.ShapeDtypeStruct((BH, T, D), q.dtype),
            ],
            scratch_shapes=[
                pltpu.VMEM((block_k, D), jnp.float32),
                pltpu.VMEM((block_k, D), jnp.float32),
            ],
            compiler_params=_compiler_params(
                dimension_semantics=("parallel", "parallel", "arbitrary"),
            ),
            interpret=interpret,
        )(q, k, v, do, lse, delta)

    return call


def _supported(T: int, D: int, block_q: int, block_k: int) -> bool:
    return (
        T % block_q == 0
        and T % block_k == 0
        and D % 8 == 0
        and T >= block_q
    )


def _fold(x):
    B, T, H, D = x.shape
    return x.transpose(0, 2, 1, 3).reshape(B * H, T, D)


def _unfold(x, B, H):
    BH, T, D = x.shape
    return x.reshape(B, H, T, D).transpose(0, 2, 1, 3)


@functools.partial(
    jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6)
)
def flash_attention(q, k, v, causal: bool = True,
                    block_q: int = 1024, block_k: int = 1024,
                    force_pallas: Optional[bool] = None):
    """q/k/v [B, T, H, D] -> [B, T, H, D]."""
    out, _ = _fwd(q, k, v, causal, block_q, block_k, force_pallas)
    return out


def _use_pallas(q, block_q, block_k, force_pallas):
    B, T, H, D = q.shape
    on_tpu = jax.default_backend() == "tpu"
    use = force_pallas if force_pallas is not None else on_tpu
    return (use and _supported(T, D, min(block_q, T), min(block_k, T)),
            on_tpu)


def _fwd(q, k, v, causal, block_q, block_k, force_pallas):
    B, T, H, D = q.shape
    use_pallas, on_tpu = _use_pallas(q, block_q, block_k, force_pallas)
    if not use_pallas:
        return plain_attention(q, k, v, causal=causal), (q, k, v, None, None)
    block_q = min(block_q, T)
    block_k = min(block_k, T)
    scale = 1.0 / (D ** 0.5)
    n_k = T // block_k
    fwd = _build_fwd(causal, scale, block_q, block_k, n_k,
                     not on_tpu, q.dtype)
    out, lse = fwd(_fold(q), _fold(k), _fold(v))
    return _unfold(out, B, H), (q, k, v, _unfold_lse(lse, B, H), out)


def _unfold_lse(lse, B, H):
    # [B*H, T] -> kept folded; tagged via tuple to avoid reshuffling
    return lse


def _bwd(causal, block_q, block_k, force_pallas, res, g):
    q, k, v, lse, out_folded = res
    if lse is None:
        # fallback path: differentiate the XLA attention
        _, vjp = jax.vjp(
            lambda q, k, v: plain_attention(q, k, v, causal=causal), q, k, v
        )
        return vjp(g)
    B, T, H, D = q.shape
    on_tpu = jax.default_backend() == "tpu"
    block_q = min(block_q, T)
    block_k = min(block_k, T)
    scale = 1.0 / (D ** 0.5)
    n_q = T // block_q
    n_k = T // block_k
    qf, kf, vf, dof = _fold(q), _fold(k), _fold(v), _fold(g)
    if block_q == T and block_k == T:
        fused = _build_bwd_fused(causal, scale, T, not on_tpu, q.dtype)
        dq, dk, dv = fused(qf, kf, vf, dof, lse, out_folded)
        return _unfold(dq, B, H), _unfold(dk, B, H), _unfold(dv, B, H)
    delta = jnp.sum(
        dof.astype(jnp.float32) * out_folded.astype(jnp.float32),
        axis=-1, keepdims=True,
    )  # [BH, T, 1], matching lse's singleton lane dim
    dq_call = _build_bwd_dq(causal, scale, block_q, block_k, n_k,
                            not on_tpu, q.dtype)
    dkv_call = _build_bwd_dkv(causal, scale, block_q, block_k, n_q,
                              not on_tpu, q.dtype)
    dq = dq_call(qf, kf, vf, dof, lse, delta)
    dk, dv = dkv_call(qf, kf, vf, dof, lse, delta)
    return _unfold(dq, B, H), _unfold(dk, B, H), _unfold(dv, B, H)


flash_attention.defvjp(_fwd, _bwd)
