"""Pallas fused lm-head + softmax cross-entropy (blockwise vocab,
online logsumexp).

The loss and its gradients are computed without EVER materializing the
[N, V] logits in HBM: the forward walks vocab blocks with an online
(max, sumexp) carry held in VMEM scratch; the backward recomputes each
logits block and contracts it immediately — dx accumulates in a VMEM
[block_n, E] scratch across the vocab-minor grid, dW in a VMEM
[block_v, E] scratch across the rows-minor grid, so neither gradient
pays per-block HBM accumulator round trips (the weakness of the
`lax.scan` row-chunk formulation in `ops/xent.py`, whose dW
accumulator travels through HBM every chunk).

When to use which (measured on v5e-1, PERF.md round 5):
- logits FIT in HBM (the 124M bench: [35840, 50257] bf16 = 3.6 GB):
  the stock lse-form loss is best — XLA stores bf16 logits once and
  skips the backward recompute; the lm-head is MXU-bound there, so
  trading HBM for recompute FLOPs LOSES.
- logits DO NOT fit (long sequences / big vocab): the recompute is
  forced on every formulation, and this kernel's VMEM-resident
  accumulators + double-buffered DMA beat the scan fallback.

Reference counterpart: torch `F.cross_entropy` over materialized
logits (the reference never fuses this); design per
/opt/skills/guides/pallas_guide.md.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp

from ray_tpu.ops.pallas_compat import compiler_params as _compiler_params

_NEG = -1e30


def _pad_to(n: int, m: int) -> int:
    return ((n + m - 1) // m) * m


def _interpret() -> bool:
    # CPU (tests) runs the kernels in interpreter mode, same switch as
    # ops/attention.py
    return jax.default_backend() != "tpu"


def _fwd_kernel(x_ref, w_ref, tg_ref, lse_ref, tgt_ref,
                m_scr, l_scr, t_scr, *, v_actual: int, block_v: int):
    import jax.lax as lax
    from jax.experimental import pallas as pl

    vb = pl.program_id(1)

    @pl.when(vb == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, _NEG)
        l_scr[...] = jnp.zeros_like(l_scr)
        t_scr[...] = jnp.zeros_like(t_scr)

    s = lax.dot_general(
        x_ref[...], w_ref[...], (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # [block_n, block_v]
    cols = vb * block_v + lax.broadcasted_iota(jnp.int32, s.shape, 1)
    s = jnp.where(cols < v_actual, s, _NEG)
    t_scr[...] += jnp.sum(
        jnp.where(cols == tg_ref[...], s, 0.0), axis=1, keepdims=True
    )
    m_new = jnp.maximum(m_scr[...], jnp.max(s, axis=1, keepdims=True))
    l_scr[...] = (
        l_scr[...] * jnp.exp(m_scr[...] - m_new)
        + jnp.sum(jnp.exp(s - m_new), axis=1, keepdims=True)
    )
    m_scr[...] = m_new

    @pl.when(vb == pl.num_programs(1) - 1)
    def _fin():
        lse_ref[...] = m_scr[...] + jnp.log(l_scr[...])
        tgt_ref[...] = t_scr[...]


def _dx_kernel(x_ref, w_ref, tg_ref, lse_ref, dx_ref, acc_scr,
               *, v_actual: int, block_v: int):
    import jax.lax as lax
    from jax.experimental import pallas as pl

    vb = pl.program_id(1)

    @pl.when(vb == 0)
    def _init():
        acc_scr[...] = jnp.zeros_like(acc_scr)

    s = lax.dot_general(
        x_ref[...], w_ref[...], (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    cols = vb * block_v + lax.broadcasted_iota(jnp.int32, s.shape, 1)
    p = jnp.where(cols < v_actual, jnp.exp(s - lse_ref[...]), 0.0)
    dl = p - jnp.where(cols == tg_ref[...], 1.0, 0.0)
    acc_scr[...] += lax.dot_general(
        dl.astype(x_ref.dtype), w_ref[...], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # [block_n, E]

    @pl.when(vb == pl.num_programs(1) - 1)
    def _fin():
        dx_ref[...] = acc_scr[...]


def _dw_kernel(w_ref, x_ref, tg_ref, lse_ref, dw_ref, acc_scr,
               *, v_actual: int, n_actual: int, block_v: int,
               block_n: int):
    import jax.lax as lax
    from jax.experimental import pallas as pl

    vb = pl.program_id(0)
    nb = pl.program_id(1)

    @pl.when(nb == 0)
    def _init():
        acc_scr[...] = jnp.zeros_like(acc_scr)

    s = lax.dot_general(
        x_ref[...], w_ref[...], (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # [block_n, block_v]
    cols = vb * block_v + lax.broadcasted_iota(jnp.int32, s.shape, 1)
    rows = nb * block_n + lax.broadcasted_iota(jnp.int32, s.shape, 0)
    p = jnp.where(cols < v_actual, jnp.exp(s - lse_ref[...]), 0.0)
    dl = p - jnp.where(cols == tg_ref[...], 1.0, 0.0)
    dl = jnp.where(rows < n_actual, dl, 0.0)  # padded rows contribute 0
    acc_scr[...] += lax.dot_general(
        dl.astype(x_ref.dtype), x_ref[...], (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # [block_v, E]

    @pl.when(nb == pl.num_programs(1) - 1)
    def _fin():
        dw_ref[...] = acc_scr[...]


def _prep(x, w, targets, block_n, block_v):
    N, E = x.shape
    V = w.shape[0]
    Np, Vp = _pad_to(N, block_n), _pad_to(V, block_v)
    xc = x
    tg = targets
    if Np != N:
        xc = jnp.pad(x, ((0, Np - N), (0, 0)))
        tg = jnp.pad(targets, (0, Np - N), constant_values=-1)
    wc = w.astype(x.dtype)
    if Vp != V:
        wc = jnp.pad(wc, ((0, Vp - V), (0, 0)))
    return xc, wc, tg.reshape(-1, 1), N, V, Np, Vp


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def pallas_cross_entropy(x, w, targets, block_n: int = 512,
                         block_v: int = 512):
    """Mean softmax cross-entropy of rows of `x` against classes of
    `w`, never materializing [N, V] logits in HBM.

    x: [N, E] (bf16/f32), w: [V, E] (f32 master ok), targets: [N]
    int32.  Returns scalar f32 mean loss.  Gradients flow to x and w.
    Default blocks fit double-buffered VMEM for f32 inputs at E<=1024;
    block_v=1024 is ~96 KB over the 16 MB scoped-vmem limit with f32
    blocks (and measured no faster with bf16 ones).
    """
    loss, _ = _fwd(x, w, targets, block_n, block_v)
    return loss


def _lse_tgt(x, w, targets, block_n, block_v):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    xc, wc, tg2, N, V, Np, Vp = _prep(x, w, targets, block_n, block_v)
    E = x.shape[1]
    grid = (Np // block_n, Vp // block_v)
    lse, tgt = pl.pallas_call(
        functools.partial(_fwd_kernel, v_actual=V, block_v=block_v),
        grid=grid,
        in_specs=[
            pl.BlockSpec((block_n, E), lambda n, v: (n, 0)),
            pl.BlockSpec((block_v, E), lambda n, v: (v, 0)),
            pl.BlockSpec((block_n, 1), lambda n, v: (n, 0)),
        ],
        out_specs=[
            pl.BlockSpec((block_n, 1), lambda n, v: (n, 0)),
            pl.BlockSpec((block_n, 1), lambda n, v: (n, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((Np, 1), jnp.float32),
            jax.ShapeDtypeStruct((Np, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_n, 1), jnp.float32),
            pltpu.VMEM((block_n, 1), jnp.float32),
            pltpu.VMEM((block_n, 1), jnp.float32),
        ],
        compiler_params=_compiler_params(
            dimension_semantics=("arbitrary", "arbitrary"),
        ),
        interpret=_interpret(),
    )(xc, wc, tg2)
    return lse, tgt, (xc, wc, tg2, N, V, Np, Vp)


def _fwd(x, w, targets, block_n, block_v):
    lse, tgt, (xc, wc, tg2, N, V, Np, Vp) = _lse_tgt(
        x, w, targets, block_n, block_v
    )
    loss = jnp.mean(lse[:N, 0] - tgt[:N, 0])
    return loss, (x, w, targets, lse)


def _bwd(block_n, block_v, res, g):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    x, w, targets, lse = res
    xc, wc, tg2, N, V, Np, Vp = _prep(x, w, targets, block_n, block_v)
    E = x.shape[1]
    scale = (g / N).astype(jnp.float32)

    dx = pl.pallas_call(
        functools.partial(_dx_kernel, v_actual=V, block_v=block_v),
        grid=(Np // block_n, Vp // block_v),
        in_specs=[
            pl.BlockSpec((block_n, E), lambda n, v: (n, 0)),
            pl.BlockSpec((block_v, E), lambda n, v: (v, 0)),
            pl.BlockSpec((block_n, 1), lambda n, v: (n, 0)),
            pl.BlockSpec((block_n, 1), lambda n, v: (n, 0)),
        ],
        out_specs=pl.BlockSpec((block_n, E), lambda n, v: (n, 0)),
        out_shape=jax.ShapeDtypeStruct((Np, E), jnp.float32),
        scratch_shapes=[pltpu.VMEM((block_n, E), jnp.float32)],
        compiler_params=_compiler_params(
            dimension_semantics=("arbitrary", "arbitrary"),
        ),
        interpret=_interpret(),
    )(xc, wc, tg2, lse)

    dw = pl.pallas_call(
        functools.partial(_dw_kernel, v_actual=V, n_actual=N,
                          block_v=block_v, block_n=block_n),
        grid=(Vp // block_v, Np // block_n),
        in_specs=[
            pl.BlockSpec((block_v, E), lambda v, n: (v, 0)),
            pl.BlockSpec((block_n, E), lambda v, n: (n, 0)),
            pl.BlockSpec((block_n, 1), lambda v, n: (n, 0)),
            pl.BlockSpec((block_n, 1), lambda v, n: (n, 0)),
        ],
        out_specs=pl.BlockSpec((block_v, E), lambda v, n: (v, 0)),
        out_shape=jax.ShapeDtypeStruct((Vp, E), jnp.float32),
        scratch_shapes=[pltpu.VMEM((block_v, E), jnp.float32)],
        compiler_params=_compiler_params(
            dimension_semantics=("arbitrary", "arbitrary"),
        ),
        interpret=_interpret(),
    )(wc, xc, tg2, lse)

    dx = (dx[:N] * scale).astype(x.dtype)
    dw = (dw[:V] * scale).astype(w.dtype)
    return dx, dw, None


pallas_cross_entropy.defvjp(_fwd, _bwd)


def reference_cross_entropy(x, w, targets) -> jax.Array:
    """Materializing lse-form loss (the testing oracle)."""
    logits = (x @ w.astype(x.dtype).T).astype(jnp.float32)
    lse = jax.scipy.special.logsumexp(logits, axis=-1)
    t = jnp.take_along_axis(logits, targets[:, None], axis=-1)[:, 0]
    return jnp.mean(lse - t)
