"""Interprocedural concurrency rules (RT009–RT013).

The RT001–RT008 rules are intraprocedural: they judge one function (or
one file) at a time.  The bug classes the last six PRs' review passes
hand-caught are NOT visible at that granularity — a sync helper that
blocks is fine until an `async def` three call-edges away starts
calling it; a deadline timer is armed in one method and (not) cancelled
in another; a metric name drifts from the catalog in a different file.
These checks therefore run over a *project call graph*: every linted
module's functions, indexed by dotted qualified name, with call edges
resolved through import aliases and `self.`-method dispatch.

Resolution is deliberately conservative (a call that cannot be
statically resolved simply creates no edge), so every finding is backed
by a concrete chain the message spells out.  The graph is built once
per lint run and shared by all five rules.

Rules:
  RT009 blocking-reachable-from-async — RT001 across function
        boundaries: an `async def` calls a sync function whose
        transitive sync call closure hits a known-blocking call.
  RT010 resource-lifecycle — acquire without release along any path:
        discarded `call_later` handles (the PR-1 un-cancelled deadline
        timer), `start_span` without `finish_span`, `placement_group`
        results that leak, `store.create` without seal/abort, and
        `chan_write_acquire` without a seal in the same function (the
        PR-15 wedged-ring shape).
  RT011 cross-loop-misuse — loop-bound primitives touched from the
        wrong context: `call_soon` from a plain (possibly foreign-
        thread) sync function instead of `call_soon_threadsafe` /
        `rpc.call_on_conn_loop`, and asyncio primitives constructed at
        module/class scope where several loops can bind them.
  RT012 unawaited-coroutine — a call that resolves to an `async def`
        used as a bare statement or truth-tested (always-true), so it
        never runs (the PR-6 class).
  RT013 catalog-drift — literal metric names at instrumentation sites
        and in grafana panel expressions must exist in
        `metrics/metric_defs.py`'s CATALOG (and catalog entries must be
        referenced somewhere); `Config` knobs must appear in the docs/
        knob tables.
"""

from __future__ import annotations

import ast
import glob
import os
import re
from dataclasses import dataclass
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from ray_tpu.lint.checks import _last_segment, blocking_label
from ray_tpu.lint.framework import (
    Check,
    Finding,
    ModuleInfo,
    _suppressions,
    register,
    shallow_walk,
)


# ----------------------------------------------------------------------
# the shared project call graph
# ----------------------------------------------------------------------
@dataclass
class FuncDef:
    qname: str  # dotted module path + qualified name inside the module
    mod: ModuleInfo
    node: ast.AST  # FunctionDef | AsyncFunctionDef
    cls_qual: Optional[str]  # dotted qname of the enclosing class
    is_async: bool


class ProjectIndex:
    """Function table + call resolution over one lint run's modules.

    Built once and shared: `of(mods)` caches on the identity of the
    module list `lint_paths` hands every check in `visit_project`."""

    _cache: Tuple[Optional[int], Optional["ProjectIndex"]] = (None, None)

    def __init__(self, mods: Sequence[ModuleInfo]):
        self.mods = list(mods)
        self.funcs: List[FuncDef] = []
        self.by_qname: Dict[str, FuncDef] = {}
        self._parents: Dict[str, Dict[ast.AST, ast.AST]] = {}
        for mod in mods:
            self._collect(mod)

    @classmethod
    def of(cls, mods: Sequence[ModuleInfo]) -> "ProjectIndex":
        key, cached = cls._cache
        if cached is not None and key == id(mods) and cached.mods == list(mods):
            return cached
        built = cls(mods)
        cls._cache = (id(mods), built)
        return built

    # -- construction --------------------------------------------------
    def _collect(self, mod: ModuleInfo) -> None:
        def walk(node: ast.AST, quals: List[str], cls_qual: Optional[str]):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.ClassDef):
                    walk(child, quals + [child.name],
                         f"{mod.dotted}." + ".".join(quals + [child.name]))
                elif isinstance(
                    child, (ast.FunctionDef, ast.AsyncFunctionDef)
                ):
                    q = f"{mod.dotted}." + ".".join(quals + [child.name])
                    fd = FuncDef(
                        qname=q,
                        mod=mod,
                        node=child,
                        cls_qual=cls_qual,
                        is_async=isinstance(child, ast.AsyncFunctionDef),
                    )
                    self.funcs.append(fd)
                    # latest definition wins (overloads are rare enough
                    # that the ambiguity isn't worth tracking)
                    self.by_qname[q] = fd
                    walk(child, quals + [child.name], cls_qual)
                else:
                    walk(child, quals, cls_qual)

        walk(mod.tree, [], None)

    def parents(self, mod: ModuleInfo) -> Dict[ast.AST, ast.AST]:
        p = self._parents.get(mod.path)
        if p is None:
            p = {}
            for node in ast.walk(mod.tree):
                for child in ast.iter_child_nodes(node):
                    p[child] = node
            self._parents[mod.path] = p
        return p

    # -- call resolution -----------------------------------------------
    def resolve(self, call: ast.Call, f: FuncDef) -> Optional[FuncDef]:
        """The FuncDef a call statically resolves to, or None.  Only
        unambiguous shapes resolve: local nested defs, module-level
        names, `self./cls.` methods of the enclosing class, and
        imported names whose canonical dotted path names a linted
        function."""
        fn = call.func
        mod = f.mod
        if isinstance(fn, ast.Name):
            name = fn.id
            # nested def in the enclosing function
            hit = self.by_qname.get(f"{f.qname}.{name}")
            if hit is not None:
                return hit
            # module-level function (or a method of the same class for
            # code inside a class body)
            hit = self.by_qname.get(f"{mod.dotted}.{name}")
            if hit is not None:
                return hit
            origin = mod.aliases.get(name)
            if origin:
                return self.by_qname.get(origin)
            return None
        if isinstance(fn, ast.Attribute):
            base = fn.value
            if (
                isinstance(base, ast.Name)
                and base.id in ("self", "cls")
                and f.cls_qual
            ):
                return self.by_qname.get(f"{f.cls_qual}.{fn.attr}")
            cn = mod.canonical(fn)
            if cn:
                return self.by_qname.get(cn)
        return None


# ----------------------------------------------------------------------
@register
class BlockingReachableFromAsync(Check):
    """RT009: RT001 generalized across function boundaries.  An
    `async def` that calls a sync function whose (transitive, sync-only)
    call closure contains a blocking call stalls its event loop exactly
    like the direct case — it's just invisible to single-function
    review.  Traversal crosses SYNC edges only: blocking inside another
    `async def` is that function's own RT001."""

    rule = "RT009"
    name = "blocking-reachable-from-async"
    description = (
        "sync function containing a blocking call (time.sleep, "
        "subprocess, sync IO) transitively called from `async def` — "
        "the whole chain stalls the event loop"
    )

    _MAX_DEPTH = 24

    def visit_project(self, mods: Sequence[ModuleInfo]) -> Iterable[Finding]:
        idx = ProjectIndex.of(mods)
        # direct blocking site per sync function.  A `# rtlint:
        # disable=RT009` ON THE BLOCKING LINE exempts that site for
        # every async caller — the rationale lives once, at the true
        # source, instead of repeating at each of N call sites (the
        # standard suppression at the reported call-site line also
        # works, per finding).
        sup_cache: Dict[str, Tuple[Dict[int, Set[str]], Set[str]]] = {}

        def exempt(mod: ModuleInfo, line: int) -> bool:
            per_line, per_file = sup_cache.setdefault(
                mod.path, _suppressions(mod.source)
            )
            rules = per_file | per_line.get(line, set())
            return "*" in rules or self.rule in rules

        direct: Dict[str, Tuple[str, int]] = {}
        for f in idx.funcs:
            if f.is_async:
                continue
            for sub in shallow_walk(f.node.body):
                if isinstance(sub, ast.Call):
                    label = blocking_label(sub, f.mod)
                    if label and not exempt(f.mod, sub.lineno):
                        direct[f.qname] = (label, sub.lineno)
                        break

        # memoized chain to the nearest blocking call, sync edges only:
        # qname -> [qname, ..., terminal-with-direct-blocking] or None
        memo: Dict[str, Optional[List[str]]] = {}

        def chain_of(q: str, depth: int) -> Optional[List[str]]:
            if q in memo:
                return memo[q]
            memo[q] = None  # cycle guard: a cycle alone never blocks
            if q in direct:
                memo[q] = [q]
                return memo[q]
            if depth >= self._MAX_DEPTH:
                return None
            f = idx.by_qname[q]
            for sub in shallow_walk(f.node.body):
                if not isinstance(sub, ast.Call):
                    continue
                callee = idx.resolve(sub, f)
                if callee is None or callee.is_async:
                    continue
                tail = chain_of(callee.qname, depth + 1)
                if tail is not None:
                    memo[q] = [q] + tail
                    return memo[q]
            return None

        for f in idx.funcs:
            if not f.is_async:
                continue
            for sub in shallow_walk(f.node.body):
                if not isinstance(sub, ast.Call):
                    continue
                callee = idx.resolve(sub, f)
                if callee is None or callee.is_async:
                    continue
                chain = chain_of(callee.qname, 0)
                if chain is None:
                    continue
                label, line = direct[chain[-1]]
                term = idx.by_qname[chain[-1]]
                hops = " -> ".join(
                    q.rsplit(".", 1)[-1] for q in chain[:4]
                ) + (" -> ..." if len(chain) > 4 else "")
                yield Finding(
                    self.rule,
                    f.mod.path,
                    sub.lineno,
                    sub.col_offset,
                    f"`async def {f.node.name}` reaches blocking "
                    f"{label} through sync call chain {hops} "
                    f"({term.mod.path}:{line}) — the whole chain runs "
                    "on the event loop; run_in_executor the entry call "
                    "or make the chain async",
                )


# ----------------------------------------------------------------------
# RT010 resource lifecycle
# ----------------------------------------------------------------------
# acquire shapes.  token: how the resource is named afterwards —
#   "result"  the call's return value (timer handle, span record, PG)
#   "arg0"    the call's first positional arg (store.create's key)
#   None      no token; the release must simply appear in the function
_LIFECYCLES = [
    {
        "key": "timer",
        "attr": {"call_later", "call_at"},
        "token": "result",
        "release_methods": {"cancel"},
        "release_calls": set(),
        "what": "timer handle",
        "fix": "keep the handle and cancel() it on every completion "
               "path (an uncancelled watchdog fires into torn-down "
               "state)",
    },
    {
        "key": "span",
        "attr": {"start_span"},
        "token": "result",
        "release_methods": set(),
        "release_calls": {"finish_span"},
        "what": "trace span",
        "fix": "finish_span(span) on every exit (an unfinished span "
               "never exports and leaks its buffer entry)",
    },
    {
        "key": "pg",
        "attr": {"placement_group"},
        "token": "result",
        "release_methods": set(),
        "release_calls": {"remove_placement_group"},
        "what": "placement group",
        "fix": "remove_placement_group(pg) when done (a CREATED PG "
               "pins its bundles forever)",
    },
    {
        "key": "store-create",
        "attr": {"create"},
        "recv_contains": "store",
        "token": "arg0",
        "release_methods": set(),
        # seal publishes, abort/delete reclaim — any of them resolves
        # the ACQUIRED state
        "release_calls": {"seal", "abort", "delete"},
        "what": "created-but-unsealed store object",
        "fix": "seal() it (or abort() on the failure path) before "
               "returning — an unsealed create pins arena and wedges "
               "readers",
    },
]


@register
class ResourceLifecycle(Check):
    """RT010: acquire/release pairing.  Flags the two shapes that are
    provably leaks without path-sensitive analysis: (a) the acquire's
    token is DISCARDED on the spot (`loop.call_later(...)` as a bare
    statement — nobody can ever cancel it), and (b) the token is bound
    to a local that is never released NOR escapes the function (never
    returned/stored/passed on) — it dies unreleased on every path.
    Tokens that escape are some other scope's responsibility; releases
    anywhere in the function (including under `finally`) count."""

    rule = "RT010"
    name = "resource-lifecycle"
    description = (
        "acquired resource never released on any path: discarded "
        "call_later handle, start_span without finish_span, leaked "
        "placement group, store.create without seal/abort, "
        "chan_write_acquire without seal"
    )

    def visit_module(self, mod: ModuleInfo) -> Iterable[Finding]:
        for node in ast.walk(mod.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield from self._check_function(node, mod)

    # -- per-function --------------------------------------------------
    def _check_function(self, fn, mod: ModuleInfo) -> Iterable[Finding]:
        body = list(shallow_walk(fn.body))
        calls = [n for n in body if isinstance(n, ast.Call)]
        parents: Dict[ast.AST, ast.AST] = {}
        stack = list(fn.body)
        while stack:
            n = stack.pop()
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda)):
                continue
            for c in ast.iter_child_nodes(n):
                parents[c] = n
                stack.append(c)

        # ring slots: acquire and seal are separate native calls; a
        # function that acquires but can't seal wedges the ring
        acquires = [c for c in calls
                    if self._attr_name(c) == "chan_write_acquire"]
        if acquires and not any(
            self._attr_name(c) == "chan_write_seal" for c in calls
        ):
            a = acquires[0]
            yield Finding(
                self.rule, mod.path, a.lineno, a.col_offset,
                "chan_write_acquire without chan_write_seal in "
                f"`{fn.name}` — an acquired-but-unsealed slot wedges "
                "the ring for every later writer; seal (payload or "
                "overflow marker) on every path",
            )

        for call in calls:
            spec = self._match_acquire(call, mod)
            if spec is None:
                continue
            parent = parents.get(call)
            if spec["token"] == "result":
                if isinstance(parent, ast.Expr):
                    yield Finding(
                        self.rule, mod.path, call.lineno, call.col_offset,
                        f"{spec['what']} from "
                        f"{self._label(call)}() is discarded — {spec['fix']}",
                    )
                    continue
                token = self._assigned_name(parent, call)
                if token is None:
                    continue  # escapes immediately (arg, attr, return)
                if not self._released_or_escapes(
                    body, call, token, spec
                ):
                    yield Finding(
                        self.rule, mod.path, call.lineno, call.col_offset,
                        f"{spec['what']} `{token}` is never released "
                        f"and never leaves `{fn.name}` — {spec['fix']}",
                    )
            elif spec["token"] == "arg0":
                if not call.args or not isinstance(call.args[0], ast.Name):
                    continue
                token = call.args[0].id
                released = any(
                    self._attr_name(c) in spec["release_calls"]
                    and any(
                        isinstance(a, ast.Name) and a.id == token
                        for a in c.args
                    )
                    for c in calls
                )
                escapes = self._name_escapes(body, call, token,
                                             spec["release_calls"])
                if not released and not escapes:
                    yield Finding(
                        self.rule, mod.path, call.lineno, call.col_offset,
                        f"{spec['what']} keyed `{token}` in "
                        f"`{fn.name}` — {spec['fix']}",
                    )

    # -- matchers ------------------------------------------------------
    @staticmethod
    def _attr_name(call: ast.Call) -> str:
        return _last_segment(call.func)

    @staticmethod
    def _label(call: ast.Call) -> str:
        return _last_segment(call.func) or "<call>"

    def _match_acquire(self, call: ast.Call, mod: ModuleInfo):
        name = self._attr_name(call)
        for spec in _LIFECYCLES:
            if name not in spec["attr"]:
                continue
            recv_needs = spec.get("recv_contains")
            if recv_needs:
                if not isinstance(call.func, ast.Attribute):
                    continue
                recv = _last_segment(call.func.value).lower()
                if recv_needs not in recv:
                    continue
            elif spec["key"] == "pg":
                # bare-name or imported call only (a `.placement_group`
                # attribute on some object is not the util constructor)
                cn = mod.canonical(call.func)
                if not (cn == "placement_group"
                        or cn.endswith(".placement_group")):
                    continue
            elif spec["key"] == "timer":
                # call_later/call_at live on event loops; require an
                # attribute call so dict.get-style names can't trip it
                if not isinstance(call.func, ast.Attribute):
                    continue
            return spec
        return None

    @staticmethod
    def _assigned_name(parent, call: ast.Call) -> Optional[str]:
        if (
            isinstance(parent, ast.Assign)
            and parent.value is call
            and len(parent.targets) == 1
            and isinstance(parent.targets[0], ast.Name)
        ):
            return parent.targets[0].id
        if (
            isinstance(parent, ast.AnnAssign)
            and parent.value is call
            and isinstance(parent.target, ast.Name)
        ):
            return parent.target.id
        return None

    def _released_or_escapes(self, body, acquire, token, spec) -> bool:
        """True when the token is released in this function OR any
        other use reaches it (returned, passed on, stored) — only a
        token that provably dies untouched is a finding."""
        for n in body:
            if isinstance(n, ast.Call):
                # token.cancel()-style release
                if (
                    isinstance(n.func, ast.Attribute)
                    and isinstance(n.func.value, ast.Name)
                    and n.func.value.id == token
                    and n.func.attr in spec["release_methods"]
                ):
                    return True
                # finish_span(token)-style release
                if self._attr_name(n) in spec["release_calls"] and any(
                    isinstance(a, ast.Name) and a.id == token
                    for a in n.args
                ):
                    return True
        return self._name_escapes(body, acquire, token,
                                  spec["release_calls"],
                                  spec["release_methods"])

    @staticmethod
    def _name_escapes(body, acquire, token, release_calls,
                      release_methods=frozenset()) -> bool:
        """Any Load use of `token` beyond the acquire itself and the
        recognized release shapes — conservative: an escaping token is
        assumed released elsewhere."""
        for n in body:
            if not (isinstance(n, ast.Name) and n.id == token
                    and isinstance(n.ctx, ast.Load)):
                continue
            if any(n is a for a in getattr(acquire, "args", ())):
                continue  # the arg0 position inside the acquire
            return True
        return False


# ----------------------------------------------------------------------
@register
class CrossLoopMisuse(Check):
    """RT011: loop-bound primitives touched from the wrong context.
    `loop.call_soon` from a plain sync function runs on whatever thread
    the caller happens to be — from a foreign thread it enqueues
    without waking the selector, so the callback sits until unrelated
    traffic arrives (the PR-7 hang).  asyncio primitives constructed at
    module/class scope bind to whichever loop first touches them, then
    explode (or silently never wake) when another loop follows."""

    rule = "RT011"
    name = "cross-loop-misuse"
    description = (
        "loop.call_soon from a sync (possibly foreign-thread) function "
        "— use call_soon_threadsafe / rpc.call_on_conn_loop; asyncio "
        "Event/Condition/Queue/Lock constructed at module or class "
        "scope binds to the first loop that touches it"
    )

    _PRIMS = {
        "asyncio.Event",
        "asyncio.Condition",
        "asyncio.Queue",
        "asyncio.Lock",
        "asyncio.Semaphore",
        "asyncio.BoundedSemaphore",
    }
    _SAME_THREAD_LOOP = {"asyncio.get_event_loop",
                         "asyncio.get_running_loop"}

    def visit_module(self, mod: ModuleInfo) -> Iterable[Finding]:
        yield from self._module_scope_prims(mod)
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.FunctionDef):  # sync only
                yield from self._call_soon_in_sync(node, mod)

    def _module_scope_prims(self, mod: ModuleInfo) -> Iterable[Finding]:
        def scan(body) -> Iterable[Finding]:
            for stmt in body:
                if isinstance(stmt, ast.ClassDef):
                    yield from scan(stmt.body)
                    continue
                if not isinstance(stmt, (ast.Assign, ast.AnnAssign)):
                    continue
                value = stmt.value
                if not isinstance(value, ast.Call):
                    continue
                cn = mod.canonical(value.func)
                if cn in self._PRIMS:
                    yield Finding(
                        self.rule, mod.path, value.lineno,
                        value.col_offset,
                        f"{cn}() at module/class scope binds to the "
                        "first event loop that touches it — construct "
                        "it inside the owning loop's context (e.g. in "
                        "the coroutine / loop-thread init)",
                    )

        yield from scan(mod.tree.body)

    def _call_soon_in_sync(self, fn: ast.FunctionDef,
                           mod: ModuleInfo) -> Iterable[Finding]:
        # receivers proven same-thread: `loop = asyncio.get_event_loop()`
        local_loops: Set[str] = set()
        for sub in shallow_walk(fn.body):
            if (
                isinstance(sub, ast.Assign)
                and isinstance(sub.value, ast.Call)
                and mod.canonical(sub.value.func) in self._SAME_THREAD_LOOP
            ):
                local_loops.update(
                    t.id for t in sub.targets if isinstance(t, ast.Name)
                )
        for sub in shallow_walk(fn.body):
            if not (
                isinstance(sub, ast.Call)
                and isinstance(sub.func, ast.Attribute)
                and sub.func.attr == "call_soon"
            ):
                continue
            recv = sub.func.value
            if isinstance(recv, ast.Name) and recv.id in local_loops:
                continue
            # only flag receivers that are clearly event loops: a bare
            # `loop`-ish name or an attribute chain ending `.loop`/`._loop`
            last = _last_segment(recv).lower()
            if "loop" not in last:
                continue
            yield Finding(
                self.rule, mod.path, sub.lineno, sub.col_offset,
                f"`{last}.call_soon(...)` inside sync `{fn.name}` — "
                "from a foreign thread this never wakes the selector "
                "(callback sits until unrelated traffic); use "
                "call_soon_threadsafe, or suppress with a rationale if "
                "this function provably runs on that loop",
            )


# ----------------------------------------------------------------------
@register
class UnawaitedCoroutine(Check):
    """RT012: a call whose callee statically resolves to `async def`,
    used where the coroutine object itself is the bug: as a bare
    statement (never runs — the PR-6 class) or in a truth test (a
    coroutine object is always truthy, so the branch is constant AND
    it never runs)."""

    rule = "RT012"
    name = "unawaited-coroutine"
    description = (
        "call resolving to `async def` used as a bare statement or "
        "truth-tested — the coroutine never runs; await it or hand it "
        "to ensure_future/create_task"
    )

    def visit_project(self, mods: Sequence[ModuleInfo]) -> Iterable[Finding]:
        idx = ProjectIndex.of(mods)
        for f in idx.funcs:
            parents = idx.parents(f.mod)
            for sub in shallow_walk(f.node.body):
                if not isinstance(sub, ast.Call):
                    continue
                callee = idx.resolve(sub, f)
                if callee is None or not callee.is_async:
                    continue
                how = self._misused(sub, parents)
                if how is None:
                    continue
                yield Finding(
                    self.rule, f.mod.path, sub.lineno, sub.col_offset,
                    f"coroutine `{callee.node.name}()` (async def at "
                    f"{callee.mod.path}:{callee.node.lineno}) {how} — "
                    "it never executes; await it or wrap in "
                    "asyncio.ensure_future/create_task",
                )

    @staticmethod
    def _misused(call: ast.Call, parents) -> Optional[str]:
        p = parents.get(call)
        if isinstance(p, ast.Expr):
            return "called as a bare statement"
        if isinstance(p, ast.BoolOp) and call in p.values:
            return "used as a boolean operand (always truthy)"
        if isinstance(p, ast.UnaryOp) and isinstance(p.op, ast.Not):
            return "negated (a coroutine object is always truthy)"
        if isinstance(p, (ast.If, ast.While)) and p.test is call:
            return "used as a branch condition (always truthy)"
        if isinstance(p, ast.IfExp) and p.test is call:
            return "used as a conditional-expression test (always truthy)"
        if isinstance(p, ast.Assert) and p.test is call:
            return "asserted (always passes without running)"
        if isinstance(p, ast.comprehension) and call in p.ifs:
            return "used as a comprehension filter (always truthy)"
        return None


# ----------------------------------------------------------------------
@register
class CatalogDrift(Check):
    """RT013: single-source-of-truth catalogs must not drift.  Metric
    names: every literal passed to the `metric_defs` record helpers and
    every `rt_*` token in a grafana panel expression must exist in
    `metrics/metric_defs.py`'s CATALOG (grafana's own `_gauge`
    definitions count), and every CATALOG entry must be referenced
    SOMEWHERE (a renamed metric leaves a dead catalog row and a silent
    dashboard hole).  Config knobs: every `Config` field's `RT_*` env
    var must appear in the docs/ knob tables."""

    rule = "RT013"
    name = "catalog-drift"
    description = (
        "metric name not in metric_defs.CATALOG (or catalog entry "
        "referenced nowhere), grafana panel referencing an unknown "
        "metric, or Config knob missing from the docs/ knob tables"
    )

    _HELPERS = {
        "ray_tpu.metrics.metric_defs.inc",
        "ray_tpu.metrics.metric_defs.observe",
        "ray_tpu.metrics.metric_defs.set_gauge",
        "ray_tpu.metrics.metric_defs.metric",
    }
    _TOKEN_RE = re.compile(r"\brt_[a-z0-9_]+")
    _SERIES_SUFFIXES = ("_bucket", "_sum", "_count")

    def __init__(self) -> None:
        self._catalog: Dict[str, int] = {}  # name -> def line
        self._catalog_path: Optional[str] = None
        self._uses: List[Tuple[str, int, int, str]] = []  # helper sites
        self._grafana: List[Tuple[str, int, int, str]] = []  # panel tokens
        self._grafana_local: Set[str] = set()
        self._all_literals: Set[str] = set()  # rt_* tokens repo-wide
        self._config_fields: List[Tuple[str, int, str]] = []
        self._config_path: Optional[str] = None
        self._n_runtime_mods = 0

    # -- collection ----------------------------------------------------
    def visit_module(self, mod: ModuleInfo) -> Iterable[Finding]:
        if mod.path.endswith("metrics/metric_defs.py"):
            self._collect_catalog(mod)
            return ()
        if "ray_tpu/" in f"/{mod.path}":
            self._n_runtime_mods += 1
            self._all_literals.update(self._TOKEN_RE.findall(mod.source))
        if mod.path.endswith("dashboard/grafana.py"):
            self._collect_grafana(mod)
        if mod.path.endswith("core/config.py"):
            self._collect_config(mod)
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            if mod.canonical(node.func) in self._HELPERS and node.args:
                a0 = node.args[0]
                if isinstance(a0, ast.Constant) and isinstance(a0.value, str):
                    self._uses.append(
                        (mod.path, a0.lineno, a0.col_offset, a0.value)
                    )
        return ()

    def _collect_catalog(self, mod: ModuleInfo) -> None:
        self._catalog_path = mod.path
        for node in mod.tree.body:
            targets = []
            if isinstance(node, ast.Assign):
                targets, value = node.targets, node.value
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                targets, value = [node.target], node.value
            else:
                continue
            if not any(
                isinstance(t, ast.Name) and t.id == "CATALOG"
                for t in targets
            ):
                continue
            if isinstance(value, ast.Dict):
                for k in value.keys:
                    if isinstance(k, ast.Constant) and isinstance(
                        k.value, str
                    ):
                        self._catalog[k.value] = k.lineno

    def _collect_grafana(self, mod: ModuleInfo) -> None:
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Call) and node.args:
                if _last_segment(node.func) in ("_gauge", "Gauge",
                                                "Counter", "Histogram"):
                    a0 = node.args[0]
                    if isinstance(a0, ast.Constant) and isinstance(
                        a0.value, str
                    ):
                        self._grafana_local.add(a0.value)
            if isinstance(node, ast.Constant) and isinstance(
                node.value, str
            ):
                for tok in self._TOKEN_RE.findall(node.value):
                    self._grafana.append(
                        (mod.path, node.lineno, node.col_offset, tok)
                    )

    def _collect_config(self, mod: ModuleInfo) -> None:
        self._config_path = mod.path
        for node in mod.tree.body:
            if not (isinstance(node, ast.ClassDef) and node.name == "Config"):
                continue
            for stmt in node.body:
                if isinstance(stmt, ast.AnnAssign) and isinstance(
                    stmt.target, ast.Name
                ):
                    name = stmt.target.id
                    self._config_fields.append(
                        (name, stmt.lineno, f"RT_{name.upper()}")
                    )

    # -- judgement -----------------------------------------------------
    def finalize(self) -> Iterable[Finding]:
        if self._catalog_path is not None:
            yield from self._judge_metrics()
        if self._config_path is not None:
            yield from self._judge_knobs()

    def _judge_metrics(self) -> Iterable[Finding]:
        known = set(self._catalog)
        for path, line, col, name in self._uses:
            if name not in known:
                yield Finding(
                    self.rule, path, line, col,
                    f"metric name {name!r} is not in "
                    "metric_defs.CATALOG — the whole point is that "
                    "core metric names exist in one table; add the "
                    "row or fix the name",
                )
        local = known | self._grafana_local
        for path, line, col, tok in self._grafana:
            base = tok
            for suf in self._SERIES_SUFFIXES:
                if base.endswith(suf) and base.removesuffix(suf) in local:
                    base = base.removesuffix(suf)
                    break
            if base not in local:
                yield Finding(
                    self.rule, path, line, col,
                    f"grafana panel references {tok!r} which matches "
                    "no metric_defs.CATALOG entry nor a dashboard-"
                    "local gauge — the panel would render empty "
                    "forever",
                )
        # reverse direction: a catalog row nothing references is dead
        # weight (usually the leftover of a rename).  Only meaningful
        # when the run actually linted the runtime tree.
        if self._n_runtime_mods >= 1:
            referenced = self._all_literals | {
                t for _, _, _, t in self._grafana
            }
            for name, line in sorted(self._catalog.items()):
                if name not in referenced:
                    yield Finding(
                        self.rule, self._catalog_path, line, 0,
                        f"CATALOG entry {name!r} is referenced "
                        "nowhere in the linted tree — dead catalog "
                        "row (rename leftover?); drop it or wire the "
                        "instrumentation",
                    )

    def _judge_knobs(self) -> Iterable[Finding]:
        docs_dir = os.path.join(self.root, "docs")
        if not os.path.isdir(docs_dir):
            return
        corpus = []
        for p in sorted(glob.glob(os.path.join(docs_dir, "*.md"))):
            try:
                with open(p, "r", encoding="utf-8") as fh:
                    corpus.append(fh.read())
            except OSError:
                continue
        docs = "\n".join(corpus)
        for name, line, env in self._config_fields:
            if env not in docs:
                yield Finding(
                    self.rule, self._config_path, line, 0,
                    f"Config knob `{name}` ({env}) appears in no "
                    "docs/ knob table — every env-overridable tunable "
                    "must be documented (docs/configuration.md is the "
                    "full table)",
                )
