"""Minimal ECMAScript tokenizer for typo-class syntax gating.

The dashboard SPA (`dashboard/app.html`) ships as inline `<script>`
blocks that no tier-1 test ever executes — a stray brace or an
unterminated template literal would only surface as a blank dashboard
in production (VERDICT Weak #7).  This is NOT a parser: it tokenizes
far enough to catch the breakage class a typo produces —

- unbalanced / mismatched brackets `()[]{}`
- unterminated string, template literal, regex, or block comment

while understanding the constructs that defeat naive bracket counting:
comments, strings with escapes, template literals with nested `${}`
expressions, and regex literals (disambiguated from division by the
preceding token, the standard lexer heuristic).

`check_js(src)` returns a list of "line N: message" error strings
(empty when clean).
"""

from __future__ import annotations

from typing import List, Optional, Tuple

_OPEN = {"(": ")", "[": "]", "{": "}"}
_CLOSE = {")": "(", "]": "[", "}": "{"}

# a `/` after one of these tokens starts a REGEX, not division
_REGEX_PRECEDERS = {
    "return", "typeof", "instanceof", "in", "of", "new", "case", "do",
    "else", "throw", "delete", "void", "yield", "await",
}

_PUNCT_CHARS = set("+-*/%=<>!&|^~?:;,.")


def _is_ident_char(c: str) -> bool:
    return c.isalnum() or c in "_$"


def check_js(src: str) -> List[str]:
    errors: List[str] = []
    # bracket stack entries: (char, line); template stack tracks the
    # ${ } nesting of template literals
    brackets: List[Tuple[str, int]] = []
    # mode stack: "tpl" = inside a template literal body; an entry is
    # pushed on `${` and the matching `}` returns to template mode
    tpl_stack: List[int] = []  # line where each open template began
    i = 0
    line = 1
    n = len(src)
    last_tok: Optional[str] = None  # last significant token (or kind)
    in_template = False

    def err(li: int, msg: str) -> None:
        errors.append(f"line {li}: {msg}")

    while i < n:
        c = src[i]
        if c == "\n":
            line += 1
            i += 1
            continue

        # ---- inside a template literal body -------------------------
        if in_template:
            start_line = tpl_stack[-1]
            while i < n:
                c = src[i]
                if c == "\n":
                    line += 1
                    i += 1
                elif c == "\\":
                    i += 2
                elif c == "`":
                    tpl_stack.pop()
                    in_template = False
                    last_tok = "string"
                    i += 1
                    break
                elif c == "$" and i + 1 < n and src[i + 1] == "{":
                    brackets.append(("${", line))
                    in_template = False  # tokenize the expression
                    last_tok = None
                    i += 2
                    break
                else:
                    i += 1
            else:
                err(start_line, "unterminated template literal")
                return errors
            continue

        if c in " \t\r":
            i += 1
            continue

        # ---- comments -----------------------------------------------
        if c == "/" and i + 1 < n and src[i + 1] == "/":
            while i < n and src[i] != "\n":
                i += 1
            continue
        if c == "/" and i + 1 < n and src[i + 1] == "*":
            start = line
            i += 2
            while i < n and not (src[i] == "*" and i + 1 < n
                                 and src[i + 1] == "/"):
                if src[i] == "\n":
                    line += 1
                i += 1
            if i >= n:
                err(start, "unterminated block comment")
                return errors
            i += 2
            continue

        # ---- strings ------------------------------------------------
        if c in "'\"":
            quote = c
            start = line
            i += 1
            while i < n:
                if src[i] == "\\":
                    if i + 1 < n and src[i + 1] == "\n":
                        line += 1  # legal line continuation
                    i += 2
                    continue
                if src[i] == "\n":
                    err(start, f"unterminated {quote} string")
                    line += 1
                    i += 1
                    break
                if src[i] == quote:
                    i += 1
                    break
                i += 1
            else:
                err(start, f"unterminated {quote} string")
                return errors
            last_tok = "string"
            continue

        # ---- template literal open ----------------------------------
        if c == "`":
            tpl_stack.append(line)
            in_template = True
            i += 1
            continue

        # ---- regex vs division --------------------------------------
        if c == "/":
            regex_ok = (
                last_tok is None
                or last_tok in _REGEX_PRECEDERS
                or last_tok in ("operator", "open")
            )
            if regex_ok:
                start = line
                i += 1
                in_class = False
                closed = False
                while i < n:
                    ch = src[i]
                    if ch == "\\":
                        i += 2
                        continue
                    if ch == "\n":
                        break  # regex literals cannot span lines
                    if ch == "[":
                        in_class = True
                    elif ch == "]":
                        in_class = False
                    elif ch == "/" and not in_class:
                        closed = True
                        i += 1
                        while i < n and _is_ident_char(src[i]):
                            i += 1  # flags
                        break
                    i += 1
                if not closed:
                    err(start, "unterminated regex literal")
                    return errors
                last_tok = "string"
                continue
            # division operator
            last_tok = "operator"
            i += 1
            continue

        # ---- brackets -----------------------------------------------
        if c in _OPEN:
            brackets.append((c, line))
            last_tok = "open"
            i += 1
            continue
        if c in _CLOSE:
            if not brackets:
                err(line, f"unmatched '{c}'")
                return errors
            opener, oline = brackets.pop()
            if c == "}" and opener == "${":
                in_template = True  # back into the template body
                i += 1
                continue
            if opener == "${":
                err(line, f"mismatched '{c}' closing template expression "
                          f"opened on line {oline}")
                return errors
            if opener != _CLOSE[c]:
                err(line, f"mismatched '{c}' (opened with '{opener}' on "
                          f"line {oline})")
                return errors
            last_tok = ")" if c == ")" else "value"
            i += 1
            continue

        # ---- identifiers / keywords ---------------------------------
        if _is_ident_char(c) and not c.isdigit():
            j = i
            while j < n and _is_ident_char(src[j]):
                j += 1
            word = src[i:j]
            last_tok = word if word in _REGEX_PRECEDERS else "value"
            i = j
            continue

        # ---- numbers ------------------------------------------------
        if c.isdigit():
            j = i
            while j < n and (_is_ident_char(src[j]) or src[j] == "."):
                j += 1
            last_tok = "value"
            i = j
            continue

        # ---- operators / punctuation --------------------------------
        if c in _PUNCT_CHARS:
            last_tok = "operator"
            i += 1
            continue

        # anything else (unicode, stray chars): treat as value
        last_tok = "value"
        i += 1

    if in_template and tpl_stack:
        err(tpl_stack[-1], "unterminated template literal")
    for opener, oline in brackets:
        err(oline, f"unclosed '{opener}'")
    return errors


def extract_scripts(html: str) -> List[Tuple[int, str]]:
    """-> [(start_line, script_source)] for every inline <script>
    block (src= scripts have no inline body worth checking)."""
    import re

    out: List[Tuple[int, str]] = []
    for m in re.finditer(
        r"<script(?![^>]*\bsrc\s*=)[^>]*>(.*?)</script>",
        html,
        re.DOTALL | re.IGNORECASE,
    ):
        start_line = html.count("\n", 0, m.start(1)) + 1
        out.append((start_line, m.group(1)))
    return out
