"""The rtlint rules (RT001–RT008): this repo's real invariants.

Each rule's *why* is documented in `docs/lint.md`; the short version
rides in each class docstring.  All name matching is import-gated
through `ModuleInfo.canonical` so a local variable named `time` cannot
trip a stdlib-name rule.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from ray_tpu.lint.framework import (
    Check,
    Finding,
    ModuleInfo,
    register,
    shallow_walk,
)


def _last_segment(node: ast.AST) -> str:
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return ""


def _is_lockish(expr: ast.AST, mod: ModuleInfo) -> bool:
    """A `with` item that looks like a sync mutex: a threading lock
    constructed inline, or a name/attribute whose last segment contains
    'lock' or 'mutex' (the repo's naming convention: _lock, _spill_lock,
    _build_lock...)."""
    if isinstance(expr, ast.Call):
        return mod.canonical(expr.func) in {
            "threading.Lock",
            "threading.RLock",
            "threading.Semaphore",
            "threading.BoundedSemaphore",
            "threading.Condition",
        }
    last = _last_segment(expr).lower()
    return "lock" in last or "mutex" in last


def _numeric_constant(node: ast.AST) -> bool:
    return isinstance(node, ast.Constant) and isinstance(
        node.value, (int, float)
    )


# ----------------------------------------------------------------------
_BLOCKING_CALLS = {
    "time.sleep",
    "subprocess.run",
    "subprocess.call",
    "subprocess.check_call",
    "subprocess.check_output",
    "subprocess.getoutput",
    "os.system",
    "os.popen",
    "os.waitpid",
    "socket.create_connection",
    "urllib.request.urlopen",
    "requests.get",
    "requests.post",
    "requests.request",
}
# unambiguous blocking method names, matched without receiver type
_BLOCKING_METHODS = {"read_text", "write_text", "read_bytes", "write_bytes"}


def blocking_label(call: ast.Call, mod: ModuleInfo) -> Optional[str]:
    """Label of a known-blocking call, or None.  Shared by RT001
    (direct blocking in `async def`) and RT009 (blocking reachable
    from `async def` through the call graph)."""
    cn = mod.canonical(call.func)
    if cn in _BLOCKING_CALLS:
        return f"{cn}()"
    if cn == "open" and "open" not in mod.aliases:
        return "open()"
    if isinstance(call.func, ast.Attribute):
        if call.func.attr in _BLOCKING_METHODS:
            return f".{call.func.attr}()"
        # chained `...submit(...).result()` / run_coroutine_threadsafe
        if call.func.attr == "result" and isinstance(
            call.func.value, ast.Call
        ):
            inner = call.func.value.func
            if _last_segment(inner) in (
                "submit",
                "run_coroutine_threadsafe",
            ):
                return f"{_last_segment(inner)}(...).result()"
    return None


@register
class BlockingInAsync(Check):
    """RT001: a blocking call on an event-loop path stalls every task
    multiplexed on that loop — one daemon's `time.sleep(0.05)` freezes
    all of its RPC handling for 50ms."""

    rule = "RT001"
    name = "blocking-call-in-async"
    description = (
        "blocking call (time.sleep, subprocess.*, sync file/socket IO, "
        "Future.result) inside `async def` — use asyncio.sleep / "
        "run_in_executor"
    )

    def visit_module(self, mod: ModuleInfo) -> Iterable[Finding]:
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.AsyncFunctionDef):
                continue
            for sub in shallow_walk(node.body):
                if not isinstance(sub, ast.Call):
                    continue
                label = blocking_label(sub, mod)
                if label:
                    yield Finding(
                        self.rule,
                        mod.path,
                        sub.lineno,
                        sub.col_offset,
                        f"blocking call {label} inside `async def "
                        f"{node.name}` stalls the event loop — await "
                        f"the async equivalent or run_in_executor",
                    )


# ----------------------------------------------------------------------
@register
class LockAcrossAwait(Check):
    """RT002: a threading lock held across an `await` parks the lock
    for the whole suspension — any OTHER coroutine or pool thread
    touching it deadlocks the loop (the classic asyncio/threading
    hybrid hang; asyncio.Lock + `async with` is the loop-safe shape)."""

    rule = "RT002"
    name = "lock-held-across-await"
    description = (
        "sync `with <lock>:` body contains `await` — the lock is held "
        "across suspension; use asyncio.Lock or restructure"
    )

    def visit_module(self, mod: ModuleInfo) -> Iterable[Finding]:
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.With):
                continue
            if not any(
                _is_lockish(i.context_expr, mod) for i in node.items
            ):
                continue
            for sub in shallow_walk(node.body):
                if isinstance(sub, ast.Await):
                    yield Finding(
                        self.rule,
                        mod.path,
                        node.lineno,
                        node.col_offset,
                        "threading lock held across `await` (line "
                        f"{sub.lineno}) — suspension parks the lock; "
                        "use asyncio.Lock or drop it before awaiting",
                    )
                    break


# ----------------------------------------------------------------------
@register
class LockOrderCycle(Check):
    """RT003: the static race detector.  Collects every syntactic
    nested acquisition `with A: ... with B:` into a cross-module lock
    graph; a cycle in that graph is a latent ABBA deadlock, and a
    self-edge is a non-reentrant re-acquisition."""

    rule = "RT003"
    name = "lock-order-cycle"
    description = (
        "inconsistent lock-acquisition order across the codebase "
        "(cycle in the cross-module lock graph) — latent ABBA deadlock"
    )

    def __init__(self) -> None:
        # (outer_id, inner_id) -> every acquisition site; one finding
        # per site, so an inline suppression at one site cannot hide
        # the same cycle elsewhere
        self._edges: Dict[
            Tuple[str, str], List[Tuple[str, int, int]]
        ] = {}

    def visit_module(self, mod: ModuleInfo) -> Iterable[Finding]:
        stem = mod.path.rsplit("/", 1)[-1].removesuffix(".py")
        module_names = {
            t.id
            for n in mod.tree.body
            if isinstance(n, ast.Assign)
            for t in n.targets
            if isinstance(t, ast.Name)
        }

        def lock_id(expr: ast.AST, cls: str, fn: str) -> Optional[str]:
            if isinstance(expr, ast.Call):
                return None  # inline construction: no shared identity
            if isinstance(expr, ast.Attribute):
                base = expr.value
                if isinstance(base, ast.Name) and base.id in ("self", "cls"):
                    return f"{stem}.{cls or fn}.{expr.attr}"
                # `locks.a_lock` resolves through the import alias map
                # so every importer agrees on one global identity
                if isinstance(base, ast.Name) and base.id in mod.aliases:
                    return mod.canonical(expr)
                head = _last_segment(base)
                return f"{stem}.{head}.{expr.attr}" if head else None
            if isinstance(expr, ast.Name):
                if expr.id in mod.aliases:  # from x import a_lock
                    return mod.aliases[expr.id]
                if expr.id in module_names:
                    return f"{stem}.{expr.id}"
                return f"{stem}.{fn}.{expr.id}"
            return None

        def walk(node: ast.AST, held: List[str], cls: str, fn: str) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.ClassDef):
                    walk(child, [], child.name, fn)
                elif isinstance(
                    child, (ast.FunctionDef, ast.AsyncFunctionDef)
                ):
                    # fresh call context: held locks don't flow into a
                    # nested def (it runs later, possibly elsewhere)
                    walk(child, [], cls, child.name)
                elif isinstance(child, ast.With):
                    acquired = []
                    for item in child.items:
                        if _is_lockish(item.context_expr, mod):
                            lid = lock_id(item.context_expr, cls, fn)
                            if lid:
                                if held or acquired:
                                    outer = (held + acquired)[-1]
                                    self._edges.setdefault(
                                        (outer, lid), []
                                    ).append((
                                        mod.path,
                                        item.context_expr.lineno,
                                        item.context_expr.col_offset,
                                    ))
                                acquired.append(lid)
                    walk(child, held + acquired, cls, fn)
                else:
                    walk(child, held, cls, fn)

        walk(mod.tree, [], "", "<module>")
        return ()

    def finalize(self) -> Iterable[Finding]:
        graph: Dict[str, Set[str]] = {}
        for a, b in self._edges:
            graph.setdefault(a, set()).add(b)
            graph.setdefault(b, set())
        scc_of = _tarjan(graph)
        sizes: Dict[int, int] = {}
        for comp in scc_of.values():
            sizes[comp] = sizes.get(comp, 0) + 1
        for (a, b), sites in sorted(self._edges.items()):
            cyclic = a == b or (
                scc_of[a] == scc_of[b] and sizes[scc_of[a]] > 1
            )
            if not cyclic:
                continue
            why = (
                f"`{a}` re-acquired while already held"
                if a == b
                else f"`{a}` -> `{b}` is also acquired in the "
                f"reverse order elsewhere"
            )
            for path, line, col in sorted(set(sites)):
                yield Finding(
                    self.rule,
                    path,
                    line,
                    col,
                    f"lock-order cycle: {why} — pick one global order "
                    "or merge the locks",
                )


def _tarjan(graph: Dict[str, Set[str]]) -> Dict[str, int]:
    """Iterative Tarjan SCC; -> node -> component id."""
    index: Dict[str, int] = {}
    low: Dict[str, int] = {}
    on_stack: Set[str] = set()
    stack: List[str] = []
    comp: Dict[str, int] = {}
    counter = [0]
    ncomp = [0]

    for root in sorted(graph):
        if root in index:
            continue
        work: List[Tuple[str, int]] = [(root, 0)]
        while work:
            node, pi = work[-1]
            if pi == 0:
                index[node] = low[node] = counter[0]
                counter[0] += 1
                stack.append(node)
                on_stack.add(node)
            advanced = False
            succs = sorted(graph[node])
            for i in range(pi, len(succs)):
                s = succs[i]
                if s not in index:
                    work[-1] = (node, i + 1)
                    work.append((s, 0))
                    advanced = True
                    break
                if s in on_stack:
                    low[node] = min(low[node], index[s])
            if advanced:
                continue
            work.pop()
            if low[node] == index[node]:
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    comp[w] = ncomp[0]
                    if w == node:
                        break
                ncomp[0] += 1
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])
    return comp


# ----------------------------------------------------------------------
@register
class PickleOutsideSerialization(Check):
    """RT004: the no-pickle wire invariant (`core/wire.py`): `decode`
    never unpickles, and the only module allowed to deserialize
    payload bytes is `core/serialization.py` — a `pickle.loads` in a
    daemon turns any wire peer into remote code execution."""

    rule = "RT004"
    name = "pickle-outside-serialization"
    description = (
        "pickle.load/loads/Unpickler outside core/serialization.py — "
        "route through ray_tpu.core.serialization (no-pickle wire "
        "invariant)"
    )

    _BANNED = {"pickle.loads", "pickle.load", "pickle.Unpickler"}

    def visit_module(self, mod: ModuleInfo) -> Iterable[Finding]:
        if mod.path.endswith("ray_tpu/core/serialization.py"):
            return
        # runtime code only: tests pickle on purpose, to *verify* the
        # invariant (test_wire's smuggled-frame probe)
        if "ray_tpu/" not in f"/{mod.path}":
            return
        for node in ast.walk(mod.tree):
            if isinstance(node, (ast.Attribute, ast.Name)):
                cn = mod.canonical(node)
                if cn in self._BANNED and not isinstance(
                    getattr(node, "ctx", None), (ast.Store, ast.Del)
                ):
                    yield Finding(
                        self.rule,
                        mod.path,
                        node.lineno,
                        node.col_offset,
                        f"{cn} outside core/serialization.py — use "
                        "ray_tpu.core.serialization.loads (or a wire "
                        "schema) so unpickling stays at one audited "
                        "chokepoint",
                    )


# ----------------------------------------------------------------------
@register
class SwallowedException(Check):
    """RT005: `except: pass` and friends turned real faults into
    silence 213 times before this linter existed.  A broad handler
    must log (debug is enough — context for the next incident) or
    re-raise; narrowing the exception type is the other legal fix."""

    rule = "RT005"
    name = "swallowed-exception"
    description = (
        "broad `except`/`except Exception` whose body neither logs "
        "nor re-raises — narrow the type or log at debug with context"
    )

    _LOG_HEADS = {"logging", "warnings", "traceback"}

    def visit_module(self, mod: ModuleInfo) -> Iterable[Finding]:
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if not self._broad(node.type, mod):
                continue
            if self._handled(node.body, mod):
                continue
            caught = (
                "bare except"
                if node.type is None
                else f"except {_last_segment(node.type) or 'Exception'}"
            )
            yield Finding(
                self.rule,
                mod.path,
                node.lineno,
                node.col_offset,
                f"{caught} swallows the exception silently — log it "
                "(logger.debug with context) or narrow the type",
            )

    def _broad(self, t: Optional[ast.AST], mod: ModuleInfo) -> bool:
        if t is None:
            return True
        if isinstance(t, ast.Tuple):
            return any(self._broad(e, mod) for e in t.elts)
        return _last_segment(t) in ("Exception", "BaseException")

    def _handled(self, body: List[ast.stmt], mod: ModuleInfo) -> bool:
        for sub in shallow_walk(body):
            if isinstance(sub, ast.Raise):
                return True
            if isinstance(sub, ast.Call):
                fn = sub.func
                cn = mod.canonical(fn)
                if cn.partition(".")[0] in self._LOG_HEADS:
                    return True
                if cn == "print":
                    return True
                if isinstance(fn, ast.Attribute):
                    recv = _last_segment(fn.value).lower()
                    if "log" in recv:  # logger.debug, self._logger.x
                        return True
                    if fn.attr in ("print_exc", "print_stack", "exception"):
                        return True
        return False


# ----------------------------------------------------------------------
@register
class RawRetryLoop(Check):
    """RT006: PR-3's fault-tolerance contracts.  (a) A retry loop that
    sleeps a constant re-synchronizes retry storms — pacing must come
    from core/retry.backoff_delay_s (+ RetryBudget).  (b) A ContextVar
    `.set()` whose token is discarded can never `reset()`: on a shared
    event loop the ambient deadline leaks into the next task."""

    rule = "RT006"
    name = "raw-retry-or-deadline-drop"
    description = (
        "retry loop pacing with a constant sleep instead of "
        "core/retry.py backoff/budget, or ContextVar.set() dropping "
        "the reset token (ambient-deadline leak)"
    )

    _SLEEPS = {"time.sleep", "asyncio.sleep"}

    def __init__(self) -> None:
        # two-phase cross-module state: ContextVars DEFINED anywhere,
        # by canonical dotted name, and `.set()`-token-drop sites on
        # IMPORTED names, resolved against that registry in finalize()
        # (catches `from core.runtime import _ambient_deadline;
        # _ambient_deadline.set(...)` in an rpc helper)
        self._defined: Set[str] = set()
        self._import_drops: List[Tuple[str, str, int, int, str]] = []

    def visit_module(self, mod: ModuleInfo) -> Iterable[Finding]:
        if mod.path.endswith("ray_tpu/core/retry.py"):
            return
        yield from self._retry_loops(mod)
        yield from self._token_drops(mod)

    def finalize(self) -> Iterable[Finding]:
        for canonical, path, line, col, var in self._import_drops:
            if canonical in self._defined:
                yield Finding(
                    self.rule, path, line, col,
                    self._drop_message(var),
                )

    @staticmethod
    def _drop_message(var: str) -> str:
        return (
            f"{var}.set(...) discards the reset token — the ambient "
            "value leaks across tasks sharing this context; keep the "
            "token and reset() in a finally (suppress inline only if "
            "overwrite-by-design)"
        )

    def _retry_loops(self, mod: ModuleInfo) -> Iterable[Finding]:
        seen: Set[int] = set()
        for node in ast.walk(mod.tree):
            if not isinstance(node, (ast.While, ast.For)):
                continue
            body = list(shallow_walk(node.body))
            if not any(isinstance(s, ast.ExceptHandler) for s in body):
                continue
            for sub in body:
                if (
                    isinstance(sub, ast.Call)
                    and mod.canonical(sub.func) in self._SLEEPS
                    and sub.args
                    and _numeric_constant(sub.args[0])
                    and sub.lineno not in seen
                ):
                    seen.add(sub.lineno)
                    yield Finding(
                        self.rule,
                        mod.path,
                        sub.lineno,
                        sub.col_offset,
                        "retry loop sleeps a constant "
                        f"({sub.args[0].value!r}) — constant pacing "
                        "synchronizes retry storms; use core/retry."
                        "backoff_delay_s and spend a RetryBudget token",
                    )

    def _token_drops(self, mod: ModuleInfo) -> Iterable[Finding]:
        ctxvars: Set[str] = set()
        for node in mod.tree.body:
            targets: List[ast.expr] = []
            value: Optional[ast.expr] = None
            if isinstance(node, ast.Assign):
                targets, value = node.targets, node.value
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                targets, value = [node.target], node.value
            if (
                value is not None
                and isinstance(value, ast.Call)
                and mod.canonical(value.func)
                in ("contextvars.ContextVar", "ContextVar")
            ):
                ctxvars.update(
                    t.id for t in targets if isinstance(t, ast.Name)
                )
        modname = mod.path.removesuffix(".py").replace("/", ".")
        self._defined.update(f"{modname}.{n}" for n in ctxvars)
        for node in ast.walk(mod.tree):
            if not (
                isinstance(node, ast.Expr)
                and isinstance(node.value, ast.Call)
                and isinstance(node.value.func, ast.Attribute)
                and node.value.func.attr == "set"
                and isinstance(node.value.func.value, ast.Name)
            ):
                continue
            var = node.value.func.value.id
            if var in ctxvars:
                yield Finding(
                    self.rule,
                    mod.path,
                    node.lineno,
                    node.col_offset,
                    self._drop_message(var),
                )
            elif var in mod.aliases:
                # imported name: judged in finalize() once every
                # module's ContextVar definitions are known
                self._import_drops.append((
                    mod.aliases[var], mod.path,
                    node.lineno, node.col_offset, var,
                ))


# ----------------------------------------------------------------------
@register
class HostEffectInJit(Check):
    """RT007: `jax.jit`/`shard_map` trace Python once and replay XLA —
    a print/np.random/wall-clock call inside runs at trace time only
    (silently wrong on step 2), and reusing a donated buffer after the
    call reads freed device memory."""

    rule = "RT007"
    name = "host-effect-in-jit"
    description = (
        "host side effect (print, np.random, wall-clock) inside a "
        "jitted/shard_map function, or a donated buffer used after "
        "donation"
    )

    _JIT_DECOS = {
        "jax.jit",
        "jit",
        "eqx.filter_jit",
        "equinox.filter_jit",
        "pjit",
        "jax.pjit",
        "shard_map",
        "jax.experimental.shard_map.shard_map",
    }
    _HOST_CALLS = {
        "time.time",
        "time.perf_counter",
        "time.monotonic",
        "time.time_ns",
        "datetime.datetime.now",
        "datetime.datetime.utcnow",
        "os.urandom",
        "uuid.uuid4",
    }

    def visit_module(self, mod: ModuleInfo) -> Iterable[Finding]:
        jitted, donated = self._collect_jitted(mod)
        for fn in jitted:
            yield from self._host_effects(fn, mod)
        yield from self._donated_reuse(mod, donated)

    # -- which functions are traced -----------------------------------
    def _collect_jitted(
        self, mod: ModuleInfo
    ) -> Tuple[List[ast.AST], Dict[str, Set[int]]]:
        by_name = {
            n.name: n
            for n in ast.walk(mod.tree)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        }
        jitted: List[ast.AST] = []
        donated: Dict[str, Set[int]] = {}  # jitted-callable name -> argnums
        for n in by_name.values():
            if any(self._is_jit(d, mod) for d in n.decorator_list):
                jitted.append(n)
        for node in ast.walk(mod.tree):
            if not (
                isinstance(node, ast.Call) and self._is_jit_name(node.func, mod)
            ):
                continue
            if node.args and isinstance(node.args[0], ast.Name):
                target = by_name.get(node.args[0].id)
                if target is not None and target not in jitted:
                    jitted.append(target)
        # donated: g = jax.jit(f, donate_argnums=(0,)) — map g -> {0}
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Assign):
                continue
            v = node.value
            if isinstance(v, ast.Call) and self._is_jit_name(v.func, mod):
                nums = self._donate_argnums(v)
                if nums:
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            donated[t.id] = nums
        return jitted, donated

    def _is_jit(self, deco: ast.AST, mod: ModuleInfo) -> bool:
        if self._is_jit_name(deco, mod):
            return True
        if isinstance(deco, ast.Call):
            if self._is_jit_name(deco.func, mod):
                return True
            # @partial(jax.jit, static_argnums=...)
            if mod.canonical(deco.func) in ("functools.partial", "partial"):
                return bool(
                    deco.args and self._is_jit_name(deco.args[0], mod)
                )
        return False

    def _is_jit_name(self, node: ast.AST, mod: ModuleInfo) -> bool:
        return mod.canonical(node) in self._JIT_DECOS

    @staticmethod
    def _donate_argnums(call: ast.Call) -> Optional[Set[int]]:
        for kw in call.keywords:
            if kw.arg in ("donate_argnums", "donate_argnames"):
                v = kw.value
                if isinstance(v, ast.Constant) and isinstance(v.value, int):
                    return {v.value}
                if isinstance(v, (ast.Tuple, ast.List)):
                    return {
                        e.value
                        for e in v.elts
                        if isinstance(e, ast.Constant)
                        and isinstance(e.value, int)
                    }
                return set()
        return None

    # -- rule bodies ---------------------------------------------------
    def _host_effects(self, fn: ast.AST, mod: ModuleInfo) -> Iterable[Finding]:
        for sub in shallow_walk(fn.body):
            if not isinstance(sub, ast.Call):
                continue
            cn = mod.canonical(sub.func)
            label = None
            if cn in self._HOST_CALLS or cn == "print":
                label = cn
            elif cn.startswith("numpy.random.") or cn.startswith("random."):
                label = cn
            if label:
                yield Finding(
                    self.rule,
                    mod.path,
                    sub.lineno,
                    sub.col_offset,
                    f"host side effect {label}() inside jitted "
                    f"`{fn.name}` runs at trace time only — hoist it "
                    "out or thread a jax.random key / host callback",
                )

    def _donated_reuse(
        self, mod: ModuleInfo, donated: Dict[str, Set[int]]
    ) -> Iterable[Finding]:
        if not donated:
            return
        for scope in ast.walk(mod.tree):
            if not isinstance(scope, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            # donated arg name -> donation line
            burns: Dict[str, int] = {}
            for sub in shallow_walk(scope.body):
                if (
                    isinstance(sub, ast.Call)
                    and isinstance(sub.func, ast.Name)
                    and sub.func.id in donated
                ):
                    rebound: Set[str] = set()
                    # `x = g(x)` rebinding makes later `x` the NEW buffer
                    parent = None
                    for st in ast.walk(scope):
                        if (
                            isinstance(st, ast.Assign)
                            and st.value is sub
                        ):
                            parent = st
                    if parent is not None:
                        rebound = {
                            t.id
                            for t in parent.targets
                            if isinstance(t, ast.Name)
                        }
                    for idx in donated[sub.func.id]:
                        if idx < len(sub.args) and isinstance(
                            sub.args[idx], ast.Name
                        ):
                            name = sub.args[idx].id
                            if name not in rebound:
                                burns.setdefault(name, sub.lineno)
            if not burns:
                continue
            for sub in shallow_walk(scope.body):
                if (
                    isinstance(sub, ast.Name)
                    and isinstance(sub.ctx, ast.Load)
                    and sub.id in burns
                    and sub.lineno > burns[sub.id]
                ):
                    yield Finding(
                        self.rule,
                        mod.path,
                        sub.lineno,
                        sub.col_offset,
                        f"`{sub.id}` used after being donated to a "
                        f"jitted call (line {burns[sub.id]}) — donated "
                        "buffers are freed; use the call's result",
                    )
                    burns.pop(sub.id)
                    if not burns:
                        break


# ----------------------------------------------------------------------
@register
class UnseededRandomInTests(Check):
    """RT008: an unseeded RNG in a test is a flake generator — the
    chaos suites learned this in PR 3 (every RNG seeded for
    determinism); this pins it for all of tests/."""

    rule = "RT008"
    name = "unseeded-random-in-tests"
    description = (
        "module-level random/np.random use in tests/ without a seed "
        "anywhere in the file — seed it or use random.Random(seed)"
    )

    _RANDOM_FNS = {
        "random",
        "randint",
        "randrange",
        "choice",
        "choices",
        "shuffle",
        "sample",
        "uniform",
        "gauss",
        "random_sample",
        "rand",
        "randn",
        "permutation",
        "normal",
        "bytes",
    }

    def visit_module(self, mod: ModuleInfo) -> Iterable[Finding]:
        if "tests" not in mod.path.split("/"):
            return
        if self._file_seeds(mod):
            return
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            cn = mod.canonical(node.func)
            head, _, fn = cn.rpartition(".")
            if head in ("random", "numpy.random") and fn in self._RANDOM_FNS:
                yield Finding(
                    self.rule,
                    mod.path,
                    node.lineno,
                    node.col_offset,
                    f"unseeded {cn}() in a test file — flake "
                    "generator; call random.seed / np.random.seed or "
                    "use an explicitly seeded Random/default_rng",
                )
            elif cn in ("numpy.random.default_rng", "random.Random") and (
                not node.args
                or (
                    isinstance(node.args[0], ast.Constant)
                    and node.args[0].value is None
                )
            ):
                yield Finding(
                    self.rule,
                    mod.path,
                    node.lineno,
                    node.col_offset,
                    f"{cn}() without a seed in a test file — pass an "
                    "explicit seed for determinism",
                )

    @staticmethod
    def _file_seeds(mod: ModuleInfo) -> bool:
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            cn = mod.canonical(node.func)
            if cn in ("random.seed", "numpy.random.seed"):
                return True
            if cn in ("numpy.random.default_rng", "random.Random"):
                if node.args and not (
                    isinstance(node.args[0], ast.Constant)
                    and node.args[0].value is None
                ):
                    return True
        return False
