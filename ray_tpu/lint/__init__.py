"""rtlint: AST-based invariant checks for the ray_tpu runtime.

The C++ shm store is guarded by TSAN/ASAN/UBSAN (`shm/run_sanitizers.sh`,
reference practice per SURVEY §5.2), but the Python runtime has no
equivalent: its concurrency, wire-safety, and fault-tolerance contracts
(no pickle on the control path, deadline propagation, breaker-fed RPC,
jittered retries) were enforced only by reviewer memory.  rtlint encodes
them as small AST checks so tier-1 fails when they rot.

Usage:
    python -m ray_tpu.lint [paths...]          # check against baseline
    python -m ray_tpu.lint --write-baseline    # regenerate the baseline

Findings on the checked-in `lint_baseline.json` are grandfathered by
(path, rule) count: CI fails only on NEW violations, and a grandfathered
count can only shrink.  Inline suppression:

    do_thing()  # rtlint: disable=RT001
    # rtlint: disable-file=RT004   (anywhere in the file: whole file)

Rule catalog lives in `docs/lint.md`; the checks themselves are in
`ray_tpu/lint/checks.py`.
"""

from ray_tpu.lint.framework import (  # noqa: F401
    Finding,
    compare_to_baseline,
    default_baseline_path,
    lint_paths,
    load_baseline,
    render_baseline,
    rule_catalog,
)
