"""Check framework: file walking, import-alias resolution, inline
suppressions, the baseline protocol, and the check registry.

A check subclasses `Check` and yields `Finding`s from `visit_module`
(per file) and/or `finalize` (after all files — program-wide checks
like the lock-order graph use this).  Checks never see suppressed
lines: suppression and sorting are applied by `lint_paths`.
"""

from __future__ import annotations

import ast
import io
import json
import os
import re
import time
import tokenize
from dataclasses import dataclass
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

_REPO_ROOT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

_SKIP_DIRS = {"__pycache__", ".git", "node_modules", ".eggs", "build"}


# ----------------------------------------------------------------------
# findings
# ----------------------------------------------------------------------
@dataclass(frozen=True)
class Finding:
    rule: str
    path: str  # posix, relative to the lint root
    line: int
    col: int
    message: str

    @property
    def key(self) -> str:
        """Baseline identity: line numbers churn, (path, rule) counts
        don't — a grandfathered count can only shrink."""
        return f"{self.path}::{self.rule}"

    def __str__(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: {self.rule} {self.message}"


# ----------------------------------------------------------------------
# per-module context handed to checks
# ----------------------------------------------------------------------
class ModuleInfo:
    def __init__(self, path: str, source: str, tree: ast.Module):
        self.path = path  # posix relative path
        self.source = source
        self.tree = tree
        self.aliases = _import_aliases(tree)
        # dotted import path of this module ("ray_tpu/core/retry.py" ->
        # "ray_tpu.core.retry"); the interprocedural pass keys its
        # project-wide function table on it
        self.dotted = (
            path.removesuffix(".py").removesuffix("/__init__")
            .replace("/", ".")
        )

    def canonical(self, node: ast.AST) -> str:
        """Dotted name of a Name/Attribute expr with the first segment
        resolved through this module's import aliases; '' when the
        expression has no static dotted form (subscripts, calls, ...).

        Matching is import-gated: `time.sleep` only canonicalizes to
        the stdlib name if the module actually imported `time`, so a
        local variable that happens to be called `time` cannot trip a
        rule."""
        dotted = _dotted(node)
        if not dotted:
            return ""
        head, _, rest = dotted.partition(".")
        mapped = self.aliases.get(head)
        if mapped is None:
            return dotted
        return f"{mapped}.{rest}" if rest else mapped

def _dotted(node: ast.AST) -> str:
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        base = _dotted(node.value)
        return f"{base}.{node.attr}" if base else ""
    return ""


def _import_aliases(tree: ast.Module) -> Dict[str, str]:
    """local name -> canonical dotted origin, for every import in the
    file (any depth — function-local imports are idiomatic here)."""
    out: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.asname:
                    out[a.asname] = a.name
                else:
                    # `import a.b` binds `a`
                    out[a.name.split(".")[0]] = a.name.split(".")[0]
        elif isinstance(node, ast.ImportFrom):
            if node.level:  # relative import: no stable canonical form
                continue
            mod = node.module or ""
            for a in node.names:
                if a.name == "*":
                    continue
                out[a.asname or a.name] = f"{mod}.{a.name}" if mod else a.name
    return out


def shallow_walk(body: Sequence[ast.AST]) -> Iterable[ast.AST]:
    """Walk statements/expressions without crossing into nested
    function definitions or lambdas (their bodies execute in a
    different context — possibly an executor thread), but descending
    into everything else."""
    stack: List[ast.AST] = list(body)
    while stack:
        node = stack.pop()
        yield node
        if isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
        ):
            continue  # yielded, but its body belongs to another context
        stack.extend(ast.iter_child_nodes(node))


# ----------------------------------------------------------------------
# check registry
# ----------------------------------------------------------------------
class Check:
    rule: str = "RT000"
    name: str = ""
    description: str = ""
    #: lint root (absolute path); set by `lint_paths` before any visit
    #: so checks that consult non-Python project files (docs/ knob
    #: tables, the baseline) resolve them against the tree under lint.
    root: str = ""

    def visit_module(self, mod: ModuleInfo) -> Iterable[Finding]:
        return ()

    def visit_project(self, mods: Sequence[ModuleInfo]) -> Iterable[Finding]:
        """Called once after every module's `visit_module`, with ALL
        parsed modules — the interprocedural checks (call graph,
        catalog drift) do their whole-program reasoning here."""
        return ()

    def finalize(self) -> Iterable[Finding]:
        return ()


_REGISTRY: List[type] = []


def register(cls: type) -> type:
    _REGISTRY.append(cls)
    return cls


def rule_catalog() -> List[Tuple[str, str, str]]:
    _load_checks()
    return sorted(
        (c.rule, c.name, c.description.strip()) for c in _REGISTRY
    )


def _load_checks() -> None:
    if not _REGISTRY:
        from ray_tpu.lint import checks  # noqa: F401  (registers on import)
        from ray_tpu.lint import concurrency  # noqa: F401  (RT009-RT013)


# ----------------------------------------------------------------------
# suppressions
# ----------------------------------------------------------------------
_SUPPRESS_RE = re.compile(
    r"rtlint:\s*(disable(?:-file)?)\s*(?:=\s*([A-Z0-9,\s]+))?"
)


def _suppressions(source: str) -> Tuple[Dict[int, Set[str]], Set[str]]:
    """-> ({line: rules-or-{'*'}}, file-wide rules-or-{'*'}).

    Comments are located with tokenize so strings that merely contain
    'rtlint:' can't suppress anything; on tokenize failure (the file
    already gets an RT000 parse finding) nothing is suppressed."""
    per_line: Dict[int, Set[str]] = {}
    per_file: Set[str] = set()
    try:
        toks = list(tokenize.generate_tokens(io.StringIO(source).readline))
    except (tokenize.TokenError, IndentationError, SyntaxError):
        return per_line, per_file
    for tok in toks:
        if tok.type != tokenize.COMMENT:
            continue
        m = _SUPPRESS_RE.search(tok.string)
        if not m:
            continue
        kind, rules_s = m.group(1), m.group(2)
        rules = (
            {r.strip() for r in rules_s.split(",") if r.strip()}
            if rules_s
            else {"*"}
        )
        if kind == "disable-file":
            per_file |= rules
        else:
            per_line.setdefault(tok.start[0], set()).update(rules)
    return per_line, per_file


def _suppressed(
    f: Finding, per_line: Dict[int, Set[str]], per_file: Set[str]
) -> bool:
    for rules in (per_file, per_line.get(f.line, set())):
        if "*" in rules or f.rule in rules:
            return True
    return False


# ----------------------------------------------------------------------
# runner
# ----------------------------------------------------------------------
def iter_py_files(paths: Sequence[str]) -> Iterable[str]:
    for p in paths:
        if os.path.isfile(p):
            if p.endswith(".py"):
                yield p
        elif os.path.isdir(p):
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = sorted(
                    d for d in dirnames if d not in _SKIP_DIRS
                )
                for fn in sorted(filenames):
                    if fn.endswith(".py"):
                        yield os.path.join(dirpath, fn)


def lint_paths(
    paths: Sequence[str],
    *,
    select: Optional[Set[str]] = None,
    root: Optional[str] = None,
    stats: Optional[Dict[str, Dict[str, float]]] = None,
) -> List[Finding]:
    """Run every registered check over `paths`; findings come back
    suppression-filtered and sorted.  `root` anchors the relative paths
    findings carry (default: the repo root).  Passing a dict as `stats`
    fills it with per-rule accounting: {rule: {"findings": n,
    "seconds": wall}} plus a "_total" row (the `--stats` CLI view and
    the tier-1 interprocedural-pass time budget read it)."""
    _load_checks()
    root = os.path.abspath(root or _REPO_ROOT)
    checks = [cls() for cls in _REGISTRY]
    if select:
        checks = [c for c in checks if c.rule in select]
    t_start = time.perf_counter()
    spent: Dict[str, float] = {c.rule: 0.0 for c in checks}
    for check in checks:
        check.root = root
    raw: List[Finding] = []
    sup: Dict[str, Tuple[Dict[int, Set[str]], Set[str]]] = {}
    mods: List[ModuleInfo] = []
    for abspath in iter_py_files([os.path.abspath(p) for p in paths]):
        rel = os.path.relpath(abspath, root).replace(os.sep, "/")
        if rel.startswith("../"):  # outside the root: keep it readable
            rel = abspath.replace(os.sep, "/")
        try:
            with open(abspath, "r", encoding="utf-8") as fh:
                source = fh.read()
            tree = ast.parse(source, filename=abspath)
        except (SyntaxError, ValueError, UnicodeDecodeError) as e:
            line = getattr(e, "lineno", 1) or 1
            raw.append(Finding("RT000", rel, line, 0, f"parse error: {e}"))
            continue
        sup[rel] = _suppressions(source)
        mod = ModuleInfo(rel, source, tree)
        mods.append(mod)
        for check in checks:
            t0 = time.perf_counter()
            raw.extend(check.visit_module(mod))
            spent[check.rule] += time.perf_counter() - t0
    for check in checks:
        t0 = time.perf_counter()
        raw.extend(check.visit_project(mods))
        raw.extend(check.finalize())
        spent[check.rule] += time.perf_counter() - t0
    out = [
        f
        for f in raw
        if f.path not in sup or not _suppressed(f, *sup[f.path])
    ]
    out = sorted(set(out), key=lambda f: (f.path, f.line, f.col, f.rule))
    if stats is not None:
        per_rule: Dict[str, int] = {}
        for f in out:
            per_rule[f.rule] = per_rule.get(f.rule, 0) + 1
        for check in checks:
            stats[check.rule] = {
                "findings": float(per_rule.get(check.rule, 0)),
                "seconds": spent[check.rule],
            }
        stats["_total"] = {
            "findings": float(len(out)),
            "seconds": time.perf_counter() - t_start,
        }
    return out


# ----------------------------------------------------------------------
# baseline protocol
# ----------------------------------------------------------------------
def default_baseline_path() -> str:
    return os.path.join(_REPO_ROOT, "lint_baseline.json")


def load_baseline(path: str) -> Dict[str, int]:
    with open(path, "r", encoding="utf-8") as fh:
        doc = json.load(fh)
    return {k: int(v) for k, v in doc.get("counts", {}).items()}


def render_baseline(findings: Sequence[Finding]) -> str:
    counts: Dict[str, int] = {}
    for f in findings:
        if f.rule == "RT000":
            # a parse error can never be grandfathered: an unparseable
            # file receives zero invariant checking, so baselining it
            # would make tier-1 pass on a file the linter cannot read
            continue
        counts[f.key] = counts.get(f.key, 0) + 1
    doc = {
        "_comment": (
            "Grandfathered rtlint findings, keyed by 'path::rule' with "
            "counts. Regenerate (only ever shrinking it) with: "
            "python -m ray_tpu.lint --write-baseline"
        ),
        "version": 1,
        "counts": {k: counts[k] for k in sorted(counts)},
    }
    return json.dumps(doc, indent=2) + "\n"


def compare_to_baseline(
    findings: Sequence[Finding], baseline: Dict[str, int]
) -> Tuple[List[Finding], Dict[str, Tuple[int, int]]]:
    """-> (new_findings, shrunk).

    A (path, rule) bucket that grew past its grandfathered count
    surfaces ALL its findings (line churn makes 'which one is new'
    unknowable); `shrunk` maps keys whose live count dropped below the
    baseline (current, baselined) so callers can prompt a regen."""
    by_key: Dict[str, List[Finding]] = {}
    for f in findings:
        by_key.setdefault(f.key, []).append(f)
    new: List[Finding] = []
    shrunk: Dict[str, Tuple[int, int]] = {}
    for key, fs in by_key.items():
        allowed = baseline.get(key, 0)
        if len(fs) > allowed:
            new.extend(fs)
        elif len(fs) < allowed:
            shrunk[key] = (len(fs), allowed)
    for key, allowed in baseline.items():
        if allowed and key not in by_key:
            shrunk[key] = (0, allowed)
    return sorted(new, key=lambda f: (f.path, f.line, f.rule)), shrunk
