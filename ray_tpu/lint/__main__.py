"""CLI: `python -m ray_tpu.lint [paths...]`.

Exit codes: 0 clean (vs baseline), 1 new findings (or parse errors),
2 usage error.  `--write-baseline` regenerates `lint_baseline.json`
from the current findings (review the diff: it should only shrink).
"""

from __future__ import annotations

import argparse
import os
import sys

from ray_tpu.lint.framework import (
    _REPO_ROOT,
    compare_to_baseline,
    default_baseline_path,
    lint_paths,
    load_baseline,
    render_baseline,
    rule_catalog,
)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m ray_tpu.lint",
        description="rtlint: ray_tpu invariant checks (see docs/lint.md)",
    )
    ap.add_argument(
        "paths",
        nargs="*",
        help="files/directories to lint (default: ray_tpu tests)",
    )
    ap.add_argument("--baseline", default=None, help="baseline json path")
    ap.add_argument(
        "--no-baseline",
        action="store_true",
        help="report every finding; exit 1 if any",
    )
    ap.add_argument(
        "--write-baseline",
        action="store_true",
        help="regenerate the baseline from current findings",
    )
    ap.add_argument(
        "--select", default=None, help="comma-separated rule ids to run"
    )
    ap.add_argument(
        "--list-rules", action="store_true", help="print the rule catalog"
    )
    ap.add_argument(
        "--stats",
        action="store_true",
        help="print per-rule finding counts and wall time",
    )
    args = ap.parse_args(argv)

    if args.list_rules:
        for rule, name, desc in rule_catalog():
            print(f"{rule}  {name}\n       {desc}")
        return 0

    paths = args.paths or [
        os.path.join(_REPO_ROOT, "ray_tpu"),
        os.path.join(_REPO_ROOT, "tests"),
    ]
    for p in paths:
        if not os.path.exists(p):
            print(f"rtlint: no such path: {p}", file=sys.stderr)
            return 2
    select = (
        {s.strip() for s in args.select.split(",") if s.strip()}
        if args.select
        else None
    )
    stats: dict = {}
    findings = lint_paths(paths, select=select, stats=stats)
    if args.stats:
        total = stats.pop("_total", {"findings": 0.0, "seconds": 0.0})
        print(f"{'rule':<8}{'findings':>10}{'seconds':>10}")
        for rule in sorted(stats):
            row = stats[rule]
            print(
                f"{rule:<8}{int(row['findings']):>10}"
                f"{row['seconds']:>10.3f}"
            )
        print(
            f"{'total':<8}{int(total['findings']):>10}"
            f"{total['seconds']:>10.3f}"
        )

    baseline_path = args.baseline or default_baseline_path()
    if args.write_baseline:
        with open(baseline_path, "w", encoding="utf-8") as fh:
            fh.write(render_baseline(findings))
        print(
            f"rtlint: wrote {len(findings)} grandfathered finding(s) "
            f"to {baseline_path}"
        )
        return 0

    if args.no_baseline or not os.path.exists(baseline_path):
        for f in findings:
            print(f)
        print(f"rtlint: {len(findings)} finding(s)")
        return 1 if findings else 0

    baseline = load_baseline(baseline_path)
    new, shrunk = compare_to_baseline(findings, baseline)
    for f in new:
        print(f)
    if shrunk:
        keys = ", ".join(sorted(shrunk))
        print(
            f"rtlint: note: {len(shrunk)} baseline bucket(s) shrank "
            f"({keys}) — run --write-baseline to lock in the progress"
        )
    grandfathered = len(findings) - len(new)
    if new:
        print(
            f"rtlint: {len(new)} NEW finding(s) "
            f"({grandfathered} grandfathered in baseline)"
        )
        return 1
    print(f"rtlint: clean ({grandfathered} grandfathered finding(s))")
    return 0


if __name__ == "__main__":
    try:
        sys.exit(main())
    except BrokenPipeError:  # e.g. `... --list-rules | head`
        os.dup2(os.open(os.devnull, os.O_WRONLY), sys.stdout.fileno())
        sys.exit(0)
