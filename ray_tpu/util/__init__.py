"""ray_tpu.util — public utilities (reference: `ray.util`)."""

from ray_tpu.util.actor_pool import ActorPool
from ray_tpu.util.placement_group import (
    multislice_placement_groups,
    PlacementGroup,
    placement_group,
    placement_group_table,
    remove_placement_group,
)
from ray_tpu.util.queue import Queue

__all__ = [
    "ActorPool",
    "PlacementGroup",
    "Queue",
    "multislice_placement_groups",
    "placement_group",
    "placement_group_table",
    "remove_placement_group",
]
