"""User-facing scheduling strategies.

Reference: `python/ray/util/scheduling_strategies.py` —
`PlacementGroupSchedulingStrategy`, `NodeAffinitySchedulingStrategy`,
and the "SPREAD"/"DEFAULT" string strategies accepted by
`.options(scheduling_strategy=...)`.  These are thin declarative
objects converted to the internal `SchedulingStrategy` at submission
(`core/task_spec.py`).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

from ray_tpu.core.task_spec import SchedulingStrategy as _Internal


def pg_id_bytes(pg) -> bytes:
    """Normalize a placement-group argument (PlacementGroup object, id
    object, or raw bytes) to its binary id — the one extraction both
    the `placement_group=` option path and the strategy objects use."""
    if isinstance(pg, bytes):
        return pg
    pid = getattr(pg, "id", None)
    if isinstance(pid, bytes):
        return pid
    return pid.binary()


@dataclass
class PlacementGroupSchedulingStrategy:
    """Run on a reserved bundle of a placement group (reference:
    `scheduling_strategies.py` PlacementGroupSchedulingStrategy)."""

    placement_group: object
    placement_group_bundle_index: int = -1
    placement_group_capture_child_tasks: bool = False

    def _to_internal(self) -> _Internal:
        return _Internal(
            kind="placement_group",
            pg_id=pg_id_bytes(self.placement_group),
            pg_bundle_index=self.placement_group_bundle_index,
            pg_capture_child_tasks=self.placement_group_capture_child_tasks,
        )


@dataclass
class NodeAffinitySchedulingStrategy:
    """Pin to a node by id; `soft=True` allows fallback if the node is
    gone (reference: NodeAffinitySchedulingStrategy)."""

    node_id: str
    soft: bool = False

    def _to_internal(self) -> _Internal:
        return _Internal(kind="node_affinity", node_id=self.node_id,
                         soft=self.soft)


def to_internal(strategy) -> Optional[_Internal]:
    """Normalize any accepted `scheduling_strategy=` value."""
    if strategy is None:
        return None
    if isinstance(strategy, _Internal):
        return strategy
    if isinstance(strategy, str):
        s = strategy.upper()
        if s == "DEFAULT":
            return _Internal()
        if s == "SPREAD":
            return _Internal(kind="spread")
        return _Internal(kind=strategy)
    if hasattr(strategy, "_to_internal"):
        return strategy._to_internal()
    raise TypeError(f"unsupported scheduling_strategy: {strategy!r}")
