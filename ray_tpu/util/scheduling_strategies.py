"""User-facing scheduling strategies.

Reference: `python/ray/util/scheduling_strategies.py` —
`PlacementGroupSchedulingStrategy`, `NodeAffinitySchedulingStrategy`,
and the "SPREAD"/"DEFAULT" string strategies accepted by
`.options(scheduling_strategy=...)`.  These are thin declarative
objects converted to the internal `SchedulingStrategy` at submission
(`core/task_spec.py`).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

from ray_tpu.core.task_spec import SchedulingStrategy as _Internal


def pg_id_bytes(pg) -> bytes:
    """Normalize a placement-group argument (PlacementGroup object, id
    object, or raw bytes) to its binary id — the one extraction both
    the `placement_group=` option path and the strategy objects use."""
    if isinstance(pg, bytes):
        return pg
    pid = getattr(pg, "id", None)
    if isinstance(pid, bytes):
        return pid
    return pid.binary()


@dataclass
class PlacementGroupSchedulingStrategy:
    """Run on a reserved bundle of a placement group (reference:
    `scheduling_strategies.py` PlacementGroupSchedulingStrategy)."""

    placement_group: object
    placement_group_bundle_index: int = -1
    placement_group_capture_child_tasks: bool = False

    def _to_internal(self) -> _Internal:
        return _Internal(
            kind="placement_group",
            pg_id=pg_id_bytes(self.placement_group),
            pg_bundle_index=self.placement_group_bundle_index,
            pg_capture_child_tasks=self.placement_group_capture_child_tasks,
        )


class In:
    """Label value must be one of the given values."""

    def __init__(self, *values):
        _check_values(values, "In")
        self.values = list(values)

    _op = "in"


class NotIn:
    """Label value must not be any of the given values."""

    def __init__(self, *values):
        _check_values(values, "NotIn")
        self.values = list(values)

    _op = "not_in"


class Exists:
    """Label key must be present on the node."""

    values: list = []
    _op = "exists"


class DoesNotExist:
    """Label key must be absent from the node."""

    values: list = []
    _op = "does_not_exist"


def _check_values(values, op_name: str):
    if not values:
        raise ValueError(f"{op_name}() requires at least one value")
    for v in values:
        if not isinstance(v, str):
            raise ValueError(
                f"{op_name}() values must be str, got {type(v).__name__}"
            )


def _expressions(mapping, param: str):
    """{"key": In("a", "b"), ...} -> [(key, op, values), ...] for the
    internal strategy (reference: `_convert_map_to_expressions`,
    `scheduling_strategies.py:159`)."""
    if mapping is None:
        return []
    if not isinstance(mapping, dict):
        raise ValueError(
            f"The {param} parameter must be a dict of label matchers"
        )
    out = []
    for key, matcher in mapping.items():
        if not isinstance(key, str):
            raise ValueError(f"label keys must be str, got {key!r}")
        if not isinstance(matcher, (In, NotIn, Exists, DoesNotExist)):
            raise ValueError(
                f"value for {key!r} must be In/NotIn/Exists/DoesNotExist, "
                f"got {type(matcher).__name__}"
            )
        out.append((key, matcher._op, list(matcher.values)))
    return out


class NodeLabelSchedulingStrategy:
    """Label-based node selection (reference:
    `util/scheduling_strategies.py:135`): `hard` expressions must all
    match the target node's labels; among hard-feasible nodes, ones
    matching `soft` are preferred.

    scheduling_strategy=NodeLabelSchedulingStrategy(
        {"tpu-slice": Exists()}, soft={"region": In("us-central2")})
    """

    def __init__(self, hard, *, soft=None):
        self.hard = _expressions(hard, "hard")
        self.soft = _expressions(soft, "soft")
        if not (self.hard or self.soft):
            raise ValueError(
                "NodeLabelSchedulingStrategy requires at least one of "
                "`hard` or `soft` to be non-empty"
            )

    def _to_internal(self) -> _Internal:
        return _Internal(
            kind="node_labels",
            label_hard=self.hard,
            label_soft=self.soft,
        )


@dataclass
class NodeAffinitySchedulingStrategy:
    """Pin to a node by id; `soft=True` allows fallback if the node is
    gone (reference: NodeAffinitySchedulingStrategy)."""

    node_id: str
    soft: bool = False

    def _to_internal(self) -> _Internal:
        return _Internal(kind="node_affinity", node_id=self.node_id,
                         soft=self.soft)


def to_internal(strategy) -> Optional[_Internal]:
    """Normalize any accepted `scheduling_strategy=` value."""
    if strategy is None:
        return None
    if isinstance(strategy, _Internal):
        return strategy
    if isinstance(strategy, str):
        s = strategy.upper()
        if s == "DEFAULT":
            return _Internal()
        if s == "SPREAD":
            return _Internal(kind="spread")
        return _Internal(kind=strategy)
    if hasattr(strategy, "_to_internal"):
        return strategy._to_internal()
    raise TypeError(f"unsupported scheduling_strategy: {strategy!r}")
