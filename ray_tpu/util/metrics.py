"""Application metrics: Counter / Gauge / Histogram.

Reference: `python/ray/util/metrics.py` — the user-facing metric types
(also used internally by the libraries), collected in a per-process
registry and exported in Prometheus text exposition format (the
reference exports via the per-node metrics agent; here `export_text()`
serves the same scrape format directly).
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Sequence, Tuple

_registry_lock = threading.Lock()
_registry: List["Metric"] = []


def _label_key(labels: Optional[Dict[str, str]]) -> Tuple:
    return tuple(sorted((labels or {}).items()))


class Metric:
    def __init__(self, name: str, description: str = "",
                 tag_keys: Sequence[str] = ()):
        self.name = name
        self.description = description
        self.tag_keys = tuple(tag_keys)
        self._default_tags: Dict[str, str] = {}
        self._lock = threading.Lock()
        with _registry_lock:
            _registry.append(self)

    def set_default_tags(self, tags: Dict[str, str]):
        self._default_tags = dict(tags)
        return self

    def _merge(self, tags: Optional[Dict[str, str]]) -> Dict[str, str]:
        merged = dict(self._default_tags)
        merged.update(tags or {})
        return merged

    def _samples(self) -> List[Tuple[Dict[str, str], float]]:
        raise NotImplementedError

    def _type(self) -> str:
        raise NotImplementedError


class Counter(Metric):
    def __init__(self, name, description="", tag_keys=()):
        super().__init__(name, description, tag_keys)
        self._values: Dict[Tuple, float] = {}

    def inc(self, value: float = 1.0, tags: Optional[Dict[str, str]] = None):
        if value < 0:
            raise ValueError("counters only increase")
        key = _label_key(self._merge(tags))
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + value

    def _samples(self):
        with self._lock:
            return [(dict(k), v) for k, v in self._values.items()]

    def _type(self):
        return "counter"


class Gauge(Metric):
    def __init__(self, name, description="", tag_keys=()):
        super().__init__(name, description, tag_keys)
        self._values: Dict[Tuple, float] = {}

    def set(self, value: float, tags: Optional[Dict[str, str]] = None):
        with self._lock:
            self._values[_label_key(self._merge(tags))] = float(value)

    def clear(self):
        """Drop all tagged series — refresh-style exporters that
        recompute the full tag set each pass call this first so
        vanished tag values (a deleted app, a drained state) stop
        exporting stale samples."""
        with self._lock:
            self._values.clear()

    def _samples(self):
        with self._lock:
            return [(dict(k), v) for k, v in self._values.items()]

    def _type(self):
        return "gauge"


class Histogram(Metric):
    def __init__(self, name, description="", boundaries: Sequence[float] = (),
                 tag_keys=()):
        super().__init__(name, description, tag_keys)
        self.boundaries = sorted(boundaries) or [0.1, 1, 10, 100]
        self._counts: Dict[Tuple, List[int]] = {}
        self._sums: Dict[Tuple, float] = {}

    def observe(self, value: float, tags: Optional[Dict[str, str]] = None):
        key = _label_key(self._merge(tags))
        with self._lock:
            counts = self._counts.setdefault(
                key, [0] * (len(self.boundaries) + 1)
            )
            self._sums[key] = self._sums.get(key, 0.0) + value
            for i, b in enumerate(self.boundaries):
                if value <= b:
                    counts[i] += 1
                    return
            counts[-1] += 1

    def _samples(self):
        out = []
        with self._lock:
            for key, counts in self._counts.items():
                labels = dict(key)
                cum = 0
                for b, c in zip(self.boundaries, counts):
                    cum += c
                    out.append(({**labels, "le": str(b)}, float(cum)))
                cum += counts[-1]
                out.append(({**labels, "le": "+Inf"}, float(cum)))
                out.append(({**labels, "__count__": "1"}, float(cum)))
                out.append(({**labels, "__sum__": "1"}, self._sums[key]))
        return out

    def _type(self):
        return "histogram"


def export_text() -> str:
    """Prometheus text exposition of every registered metric."""
    lines: List[str] = []
    with _registry_lock:
        metrics = list(_registry)
    for m in metrics:
        if m.description:
            lines.append(f"# HELP {m.name} {m.description}")
        lines.append(f"# TYPE {m.name} {m._type()}")
        for labels, value in m._samples():
            if "__sum__" in labels:
                labels = {k: v for k, v in labels.items() if k != "__sum__"}
                name = f"{m.name}_sum"
            elif "__count__" in labels:
                labels = {k: v for k, v in labels.items() if k != "__count__"}
                name = f"{m.name}_count"
            elif "le" in labels:
                name = f"{m.name}_bucket"
            else:
                name = m.name
            if labels:
                inner = ",".join(f'{k}="{v}"' for k, v in sorted(labels.items()))
                lines.append(f"{name}{{{inner}}} {value}")
            else:
                lines.append(f"{name} {value}")
    return "\n".join(lines) + "\n"
