"""Application metrics: Counter / Gauge / Histogram.

Reference: `python/ray/util/metrics.py` — the user-facing metric types.
The implementation moved to :mod:`ray_tpu.metrics.registry` when the
unified observability plane landed (central catalog in
`ray_tpu/metrics/metric_defs.py`, cluster-wide collection in
`ray_tpu/metrics/exporter.py`); this module stays as the stable
user-facing import path, matching the reference's layout.
"""

from __future__ import annotations

from ray_tpu.metrics.registry import (  # noqa: F401
    Counter,
    Gauge,
    Histogram,
    Metric,
    export_text,
    render_exposition,
    snapshot,
)

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "Metric",
    "export_text",
    "render_exposition",
    "snapshot",
]
