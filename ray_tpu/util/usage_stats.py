"""Usage stats: opt-in cluster usage reporting.

Reference: `python/ray/_private/usage/usage_lib.py` — collects
coarse-grained cluster facts (version, cluster size, which libraries
were touched) and reports them once per interval, controllable via env
var.  Differences here, deliberate: reporting is **opt-in**
(`RT_USAGE_STATS_ENABLED=1`; the reference is opt-out), and the report
sink is a local JSON file plus an injectable transport — nothing ever
leaves the machine unless an operator plugs in a real transport.
"""

from __future__ import annotations

import json
import os
import platform
import time
from typing import Any, Callable, Dict, List, Optional

_ENV = "RT_USAGE_STATS_ENABLED"
_library_usages: set = set()


def usage_stats_enabled() -> bool:
    return os.environ.get(_ENV, "0").lower() in ("1", "true", "yes")


def record_library_usage(name: str) -> None:
    """Called by library entry points (serve.start, Tuner.fit, ...);
    a no-op set insert when reporting is disabled."""
    _library_usages.add(name)


def _collect(extra: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
    try:
        from ray_tpu.core.runtime import get_runtime

        rt = get_runtime()
        nodes = rt.controller_call("get_nodes") if rt is not None else []
    except Exception:
        nodes = []
    total = {}
    for n in nodes or []:
        for k, v in (n.get("resources") or {}).items():
            total[k] = total.get(k, 0.0) + v
    report = {
        "schema_version": 1,
        "timestamp": time.time(),
        "python_version": platform.python_version(),
        "os": platform.system().lower(),
        "num_nodes": len(nodes or []),
        "total_resources": total,
        "libraries_used": sorted(_library_usages),
    }
    if extra:
        report.update(extra)
    return report


def report_usage(transport: Optional[Callable[[Dict[str, Any]], None]] = None,
                 session_dir: Optional[str] = None) -> Optional[Dict[str, Any]]:
    """Collect + deliver one report; returns it (None when disabled).
    `transport(report)` is the egress seam — absent, the report only
    lands in `<session_dir>/usage_stats.json`."""
    if not usage_stats_enabled():
        return None
    report = _collect()
    sdir = session_dir or os.environ.get("RT_TMPDIR", "/tmp/ray_tpu")
    try:
        os.makedirs(sdir, exist_ok=True)
        with open(os.path.join(sdir, "usage_stats.json"), "w") as f:
            json.dump(report, f, indent=2)
    except OSError:
        pass
    if transport is not None:
        try:
            transport(report)
        except Exception:
            pass  # usage stats must never break anything
    return report
