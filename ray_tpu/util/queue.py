"""Distributed FIFO queue backed by an actor.

Reference: `python/ray/util/queue.py` — same surface (put/get with
block/timeout, put_nowait/get_nowait, qsize/empty/full), implemented on
an async actor so blocked getters don't pin worker threads.
"""

from __future__ import annotations

import asyncio
from typing import Any, List, Optional

import ray_tpu as rt


class Empty(Exception):
    pass


class Full(Exception):
    pass


class _QueueActor:
    def __init__(self, maxsize: int = 0):
        self._q: asyncio.Queue = asyncio.Queue(maxsize)

    async def put(self, item, timeout: Optional[float] = None) -> bool:
        if timeout is None:
            await self._q.put(item)
            return True
        try:
            await asyncio.wait_for(self._q.put(item), timeout)
            return True
        except asyncio.TimeoutError:
            return False

    async def get(self, timeout: Optional[float] = None):
        if timeout is None:
            return (True, await self._q.get())
        try:
            return (True, await asyncio.wait_for(self._q.get(), timeout))
        except asyncio.TimeoutError:
            return (False, None)

    def put_nowait(self, item) -> bool:
        try:
            self._q.put_nowait(item)
            return True
        except asyncio.QueueFull:
            return False

    def get_nowait(self):
        try:
            return (True, self._q.get_nowait())
        except asyncio.QueueEmpty:
            return (False, None)

    def qsize(self) -> int:
        return self._q.qsize()

    def empty(self) -> bool:
        return self._q.empty()

    def full(self) -> bool:
        return self._q.full()

    def put_batch_nowait(self, items: List[Any]) -> bool:
        """All-or-nothing insert (capacity checked before any put)."""
        maxsize = self._q.maxsize
        if maxsize > 0 and self._q.qsize() + len(items) > maxsize:
            return False
        for it in items:
            self._q.put_nowait(it)
        return True

    def get_batch_nowait(self, n: int):
        """All-or-nothing removal of n items."""
        if self._q.qsize() < n:
            return (False, None)
        return (True, [self._q.get_nowait() for _ in range(n)])


class Queue:
    def __init__(self, maxsize: int = 0, *, actor_options: Optional[dict] = None):
        opts = dict(actor_options or {})
        opts.setdefault("num_cpus", 0)
        opts.setdefault("max_concurrency", 64)
        self.actor = rt.remote(_QueueActor).options(**opts).remote(maxsize)

    def put(self, item: Any, block: bool = True, timeout: Optional[float] = None):
        if not block:
            if not rt.get(self.actor.put_nowait.remote(item)):
                raise Full("queue is full")
            return
        ok = rt.get(self.actor.put.remote(item, timeout))
        if not ok:
            raise Full(f"put timed out after {timeout}s")

    def get(self, block: bool = True, timeout: Optional[float] = None) -> Any:
        if not block:
            ok, item = rt.get(self.actor.get_nowait.remote())
            if not ok:
                raise Empty("queue is empty")
            return item
        ok, item = rt.get(self.actor.get.remote(timeout))
        if not ok:
            raise Empty(f"get timed out after {timeout}s")
        return item

    def put_nowait(self, item: Any):
        self.put(item, block=False)

    def get_nowait(self) -> Any:
        return self.get(block=False)

    def put_nowait_batch(self, items: List[Any]):
        """Atomic: raises Full without inserting anything if the batch
        does not fit (reference: `util/queue.py` put_nowait_batch)."""
        if not rt.get(self.actor.put_batch_nowait.remote(list(items))):
            raise Full(f"batch of {len(items)} does not fit")

    def get_nowait_batch(self, num_items: int) -> List[Any]:
        """Atomic: raises Empty without consuming anything if fewer than
        num_items are queued."""
        ok, items = rt.get(self.actor.get_batch_nowait.remote(num_items))
        if not ok:
            raise Empty(f"fewer than {num_items} items queued")
        return items

    def qsize(self) -> int:
        return rt.get(self.actor.qsize.remote())

    def size(self) -> int:
        return self.qsize()

    def empty(self) -> bool:
        return rt.get(self.actor.empty.remote())

    def full(self) -> bool:
        return rt.get(self.actor.full.remote())

    def shutdown(self):
        try:
            rt.kill(self.actor)
        except Exception:
            pass
