"""joblib backend over the actor runtime.

Reference: `python/ray/util/joblib/` — `register_ray()` registers a
joblib parallel backend whose pool workers are actors, so
scikit-learn-style `Parallel(n_jobs=...)` code fans out over the
cluster:

    from ray_tpu.util.joblib import register_ray
    import joblib
    register_ray()
    with joblib.parallel_backend("ray"):
        results = joblib.Parallel()(joblib.delayed(f)(i) for i in ...)
"""

from __future__ import annotations


def register_ray():
    from joblib.parallel import register_parallel_backend

    from ray_tpu.util.joblib.ray_backend import RayTpuBackend

    register_parallel_backend("ray", RayTpuBackend)


__all__ = ["register_ray"]
