"""The joblib ParallelBackend implementation.

Reference: `python/ray/util/joblib/ray_backend.py` RayBackend — extends
joblib's MultiprocessingBackend but builds the pool from
`ray_tpu.util.multiprocessing.Pool`, so every batch runs as an actor
task and `n_jobs=-1` means "all cluster CPUs", not local cores.
"""

from __future__ import annotations

from joblib._parallel_backends import MultiprocessingBackend

import ray_tpu as rt
from ray_tpu.util.multiprocessing import Pool


class RayTpuBackend(MultiprocessingBackend):
    supports_timeout = True

    def effective_n_jobs(self, n_jobs):
        if n_jobs == 0:
            raise ValueError("n_jobs == 0 in Parallel has no meaning")
        if not rt.is_started():
            rt.init()
        cluster_cpus = max(1, int(rt.cluster_resources().get("CPU", 1)))
        if n_jobs is None:
            return 1
        if n_jobs < 0:
            return max(cluster_cpus + 1 + n_jobs, 1)
        return n_jobs

    def configure(self, n_jobs=1, parallel=None, prefer=None, require=None,
                  **kwargs):
        n_jobs = self.effective_n_jobs(n_jobs)
        self.parallel = parallel
        self._pool = Pool(processes=n_jobs)
        return n_jobs
