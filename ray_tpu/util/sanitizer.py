"""Runtime concurrency sanitizer (`RT_SANITIZE=1`).

The static rtlint pass (RT009–RT013) proves what it can from source;
this module catches the rest at runtime, the way the reference leans on
TSan plus its declared lock discipline in `src/ray/common/`.  Three
detectors, all recording TYPED reports into one process-local list so a
test can assert exactly what went wrong:

* **Lock order.**  The runtime's declared partial order, written down
  here once instead of living in PR descriptions::

      rank  0  ray_tpu.serve.api._state_lock      (outermost: held
               across rt.get() during deployment rollout)
      rank 10  Runtime._state_lock                (RLock; owner state)
      rank 20  OwnerShard.lock                    (never before 10)
      rank 30  leaf locks (rpc outbox, channel/ring internals) —
               never held while taking anything else

  :func:`wrap_lock` proxies a lock and records per-thread acquisition
  stacks; acquiring a lock whose rank is LOWER than one already held
  (on the same thread, different object) is a
  :class:`LockOrderViolation`.  Reentrant RLock acquires are fine.

* **Loop health.**  While enabled, every asyncio callback in the
  process is timed (one patched ``Handle._run``); a callback holding
  its loop longer than ``Config.sanitize_loop_lag_ms`` becomes a
  :class:`LoopLagViolation` naming the callable — the runtime symptom
  of everything RT001/RT009 exists to prevent.

* **Leaks.**  :func:`audit_leaks` sweeps at end of test: non-cancelled
  timers on loops registered via :func:`register_loop` (the PR-1
  un-cancelled deadline-timer class), coroutines garbage-collected
  without ever being awaited (PR-6), store objects CREATED but never
  sealed/aborted and ring slots ACQUIRED but never sealed (PR-15,
  reported through the :func:`note_acquire`/:func:`note_release` hooks
  the shm layer calls), and placement groups still CREATED at audit
  time (PR-9).

Everything is no-op-cheap when disabled: the wrappers check one module
flag.  `tests/conftest.py` enables this for tests carrying the
``sanitize`` marker and asserts a clean report at teardown (see
docs/lint.md, "Running sanitized").
"""

from __future__ import annotations

import asyncio.events
import gc
import os
import threading
import time
import traceback
import warnings
import weakref
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

# the declared partial order (see module docstring)
SERVE_STATE_LOCK = 0
RUNTIME_STATE_LOCK = 10
SHARD_LOCK = 20
LEAF_LOCK = 30


# ----------------------------------------------------------------------
# typed reports
# ----------------------------------------------------------------------
@dataclass
class LockOrderViolation:
    acquiring: str
    acquiring_rank: int
    held: str
    held_rank: int
    thread: str
    stack: str = field(repr=False, default="")

    def __str__(self) -> str:
        return (
            f"lock-order inversion on {self.thread}: acquiring "
            f"{self.acquiring!r} (rank {self.acquiring_rank}) while "
            f"holding {self.held!r} (rank {self.held_rank})"
        )


@dataclass
class LoopLagViolation:
    callback: str
    lag_ms: float
    threshold_ms: float

    def __str__(self) -> str:
        return (
            f"event-loop callback held its loop {self.lag_ms:.0f}ms "
            f"(threshold {self.threshold_ms:.0f}ms): {self.callback}"
        )


@dataclass
class LeakReport:
    kind: str  # pending-timer | unawaited-coroutine | store-create |
    #            ring-slot | placement-group
    detail: str

    def __str__(self) -> str:
        return f"leak[{self.kind}]: {self.detail}"


# ----------------------------------------------------------------------
# state
# ----------------------------------------------------------------------
_enabled = os.environ.get("RT_SANITIZE", "") in ("1", "true", "True")
_lag_threshold_ms = 0.0
_report_lock = threading.Lock()  # plain on purpose: never sanitized
_violations: List[Any] = []
_held = threading.local()  # .stack: per-thread list of SanitizedLock
_loops: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()
# (kind, key) -> description of the still-pending acquire
_pending: Dict[Tuple[str, str], str] = {}
_orig_handle_run = None
# "coroutine ... was never awaited" messages trapped while enabled —
# CPython emits the warning the moment the refcount hits zero, which
# is mid-test, long before the audit's own capture window
_unawaited: List[str] = []
_prev_showwarning = None


def enabled() -> bool:
    return _enabled


def set_enabled(on: bool) -> None:
    """Flip the sanitizer for THIS process; mirrors RT_SANITIZE so
    children spawned after the flip inherit it, and (un)installs the
    loop-lag watchdog."""
    global _enabled, _lag_threshold_ms
    _enabled = bool(on)
    if on:
        os.environ["RT_SANITIZE"] = "1"
        _lag_threshold_ms = _resolve_lag_threshold_ms()
        _install_watchdog()
        _install_warning_trap()
    else:
        os.environ.pop("RT_SANITIZE", None)
        _uninstall_watchdog()
        _uninstall_warning_trap()


def _resolve_lag_threshold_ms() -> float:
    env = os.environ.get("RT_SANITIZE_LOOP_LAG_MS")
    if env:
        try:
            return float(env)
        except ValueError:
            pass
    try:
        from ray_tpu.core.config import get_config

        return float(get_config().sanitize_loop_lag_ms)
    # fall back to the documented default: this runs at enable time,
    # possibly mid-bootstrap before the config package imports — the
    # sanitizer must arm regardless
    except Exception:  # rtlint: disable=RT005
        return 500.0


def violations() -> List[Any]:
    with _report_lock:
        return list(_violations)


def reset() -> None:
    """Clear recorded violations and pending-acquire bookkeeping
    (start-of-test)."""
    with _report_lock:
        _violations.clear()
    _pending.clear()
    _unawaited.clear()


def _record(v: Any) -> None:
    with _report_lock:
        _violations.append(v)


# ----------------------------------------------------------------------
# lock-order discipline
# ----------------------------------------------------------------------
def _stack() -> List["SanitizedLock"]:
    s = getattr(_held, "stack", None)
    if s is None:
        s = _held.stack = []
    return s


class SanitizedLock:
    """Proxy recording per-thread acquisition order.  Delegates every
    unknown attribute to the wrapped lock, so RLock reentrancy and
    Condition integration keep working; the order check reports but
    never refuses the acquire (a sanitizer must not deadlock the code
    under test)."""

    def __init__(self, lock: Any, name: str, rank: int):
        self._lock = lock
        self.name = name
        self.rank = rank

    def acquire(self, *args, **kwargs) -> bool:
        if _enabled:
            self._check_order()
        got = self._lock.acquire(*args, **kwargs)
        if got and _enabled:
            _stack().append(self)
        return got

    def release(self) -> None:
        if _enabled:
            s = _stack()
            for i in range(len(s) - 1, -1, -1):
                if s[i] is self:
                    del s[i]
                    break
        self._lock.release()

    def __enter__(self) -> "SanitizedLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def _check_order(self) -> None:
        worst: Optional[SanitizedLock] = None
        for h in _stack():
            if h._lock is self._lock:
                return  # RLock reentry on the same object: always fine
            if worst is None or h.rank > worst.rank:
                worst = h
        if worst is not None and self.rank < worst.rank:
            _record(
                LockOrderViolation(
                    acquiring=self.name,
                    acquiring_rank=self.rank,
                    held=worst.name,
                    held_rank=worst.rank,
                    thread=threading.current_thread().name,
                    stack="".join(traceback.format_stack(limit=12)),
                )
            )

    def __getattr__(self, item: str) -> Any:
        return getattr(self._lock, item)

    def __repr__(self) -> str:
        return f"<SanitizedLock {self.name!r} rank={self.rank}>"


def wrap_lock(lock: Any, name: str, rank: int) -> SanitizedLock:
    """Wrap unconditionally (the declared-order sites call this at
    construction, which may precede enablement); disabled-mode cost is
    one flag test per acquire/release."""
    return SanitizedLock(lock, name, rank)


# ----------------------------------------------------------------------
# loop-lag watchdog
# ----------------------------------------------------------------------
def _install_watchdog() -> None:
    global _orig_handle_run
    if _orig_handle_run is not None:
        return
    _orig_handle_run = asyncio.events.Handle._run

    def _timed_run(handle):
        t0 = time.monotonic()
        try:
            return _orig_handle_run(handle)
        finally:
            if _enabled and _lag_threshold_ms > 0:
                lag_ms = (time.monotonic() - t0) * 1000.0
                if lag_ms >= _lag_threshold_ms:
                    cb = getattr(handle, "_callback", None)
                    _record(
                        LoopLagViolation(
                            callback=repr(cb),
                            lag_ms=lag_ms,
                            threshold_ms=_lag_threshold_ms,
                        )
                    )

    asyncio.events.Handle._run = _timed_run


def _uninstall_watchdog() -> None:
    global _orig_handle_run
    if _orig_handle_run is not None:
        asyncio.events.Handle._run = _orig_handle_run
        _orig_handle_run = None


def _install_warning_trap() -> None:
    global _prev_showwarning
    if _prev_showwarning is not None:
        return
    _prev_showwarning = warnings.showwarning

    def _trap(message, category, filename, lineno, file=None, line=None):
        if category is RuntimeWarning and "was never awaited" in str(
            message
        ):
            _unawaited.append(f"{str(message)} ({filename}:{lineno})")
        return _prev_showwarning(
            message, category, filename, lineno, file, line
        )

    warnings.showwarning = _trap


def _uninstall_warning_trap() -> None:
    global _prev_showwarning
    if _prev_showwarning is not None:
        warnings.showwarning = _prev_showwarning
        _prev_showwarning = None


def register_loop(
    loop: asyncio.AbstractEventLoop, name: str, audit_timers: bool = True
) -> None:
    """Register a loop with the sanitizer.  ``audit_timers=True`` opts
    it into the end-of-test pending-timer audit; infrastructure loops
    (the runtime io loop, owner shards) register with ``False`` because
    lease-keepalive and deadline timers are LEGITIMATELY armed between
    tests on a module-scoped cluster — their discipline is that
    shutdown cancels them, which the probe tests assert on dedicated
    loops instead."""
    try:
        _loops[loop] = (name, bool(audit_timers))
    except TypeError:  # non-weakrefable test double
        pass


# ----------------------------------------------------------------------
# acquire/release leak notes (shm store + channel rings)
# ----------------------------------------------------------------------
def note_acquire(kind: str, key: str, detail: str = "") -> None:
    if _enabled:
        _pending[(kind, key)] = detail or key


def note_release(kind: str, key: str) -> None:
    if _enabled:
        _pending.pop((kind, key), None)


# ----------------------------------------------------------------------
# end-of-test audits
# ----------------------------------------------------------------------
def audit_leaks() -> List[LeakReport]:
    out: List[LeakReport] = []
    # coroutines collected without ever being awaited surface as
    # RuntimeWarning at finalization — force the sweep and capture
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        gc.collect()
    for w in caught:
        msg = str(w.message)
        if "was never awaited" in msg:
            out.append(LeakReport("unawaited-coroutine", msg))
    # ...plus the ones the persistent trap caught mid-test (refcount-
    # zero coroutines finalize immediately, not at this gc pass)
    for msg in _unawaited:
        out.append(LeakReport("unawaited-coroutine", msg))
    _unawaited.clear()
    # armed timers that nobody will ever cancel (closed loops dropped
    # their callbacks; only live loops can still misfire)
    for loop, (name, audit_timers) in list(_loops.items()):
        if not audit_timers or loop.is_closed():
            continue
        for th in list(getattr(loop, "_scheduled", ())):
            if not getattr(th, "_cancelled", False):
                out.append(
                    LeakReport(
                        "pending-timer",
                        f"loop {name!r}: "
                        f"{getattr(th, '_callback', th)!r}",
                    )
                )
    # created-unsealed store objects / acquired-unsealed ring slots
    for (kind, key), detail in sorted(_pending.items()):
        out.append(LeakReport(kind, detail))
    # placement groups still CREATED (pinning bundles) when the test
    # ends; only meaningful while a runtime is up
    try:
        from ray_tpu.core import runtime as _runtime_mod

        rt = getattr(_runtime_mod, "_runtime", None)
        if rt is not None:
            from ray_tpu.util.placement_group import placement_group_table

            for row in placement_group_table() or []:
                if row.get("state") == "CREATED":
                    out.append(
                        LeakReport(
                            "placement-group",
                            f"pg {row.get('pg_id', '?')} still CREATED "
                            f"(bundles {row.get('bundles')})",
                        )
                    )
    # no live control plane to ask (runtime down or mid-shutdown) —
    # nothing to audit; the other detectors above already reported
    except Exception:  # rtlint: disable=RT005
        pass
    return out


# a process born with RT_SANITIZE=1 (workers under a sanitized test,
# `RT_SANITIZE=1 pytest ...`) arms the watchdog immediately — enabled()
# alone would track locks but never time callbacks
if _enabled:
    set_enabled(True)


def check_clean() -> None:
    """Raise AssertionError listing every violation and leak (the
    `sanitize` marker's teardown assertion)."""
    probs = [str(v) for v in violations()] + [
        str(r) for r in audit_leaks()
    ]
    if probs:
        raise AssertionError(
            "sanitizer found %d problem(s):\n  %s"
            % (len(probs), "\n  ".join(probs))
        )
