"""multiprocessing.Pool shim over the actor runtime.

Reference: `python/ray/util/multiprocessing/pool.py` — a drop-in
`multiprocessing.Pool` whose worker processes are actors, so pools span
the cluster instead of one host.  Same surface: apply/apply_async,
map/map_async, imap/imap_unordered, starmap, close/terminate/join,
context manager.
"""

from __future__ import annotations

import itertools
import threading
import time
from multiprocessing import TimeoutError
from typing import Any, Callable, Iterable, List, Optional

import ray_tpu as rt

_DEFAULT_CHUNK_TARGET = 4  # chunks per worker for map, like the reference


class _PoolWorker:
    """One pool process: runs an optional initializer then executes
    function chunks."""

    def __init__(self, initializer=None, initargs=()):
        if initializer is not None:
            initializer(*initargs)

    def run_chunk(self, func, chunk, star):
        if star:
            return [func(*item) for item in chunk]
        return [func(item) for item in chunk]


class AsyncResult:
    """Reference: multiprocessing.pool.AsyncResult semantics."""

    def __init__(self, refs: List, single: bool, callback=None,
                 error_callback=None):
        self._refs = refs
        self._single = single
        self._value = None
        self._error = None
        self._done = threading.Event()
        t = threading.Thread(target=self._collect,
                             args=(callback, error_callback), daemon=True)
        t.start()

    def _collect(self, callback, error_callback):
        try:
            chunks = rt.get(self._refs)
            out = list(itertools.chain.from_iterable(chunks))
            self._value = out[0] if self._single else out
            if callback is not None:
                callback(self._value)
        except Exception as e:  # noqa: BLE001 - user exception boundary
            self._error = e
            if error_callback is not None:
                error_callback(e)
        finally:
            self._done.set()

    def ready(self) -> bool:
        return self._done.is_set()

    def successful(self) -> bool:
        if not self.ready():
            raise ValueError("result is not ready")
        return self._error is None

    def wait(self, timeout: Optional[float] = None):
        self._done.wait(timeout)

    def get(self, timeout: Optional[float] = None):
        if not self._done.wait(timeout):
            raise TimeoutError("result not ready within timeout")
        if self._error is not None:
            raise self._error
        return self._value


class Pool:
    def __init__(self, processes: Optional[int] = None, initializer=None,
                 initargs=(), maxtasksperchild=None, context=None,
                 ray_remote_args: Optional[dict] = None):
        if not rt.is_started():
            rt.init()
        if processes is None:
            processes = max(1, int(rt.cluster_resources().get("CPU", 1)))
        if processes < 1:
            raise ValueError("processes must be >= 1")
        self._processes = processes
        remote_args = {"num_cpus": 1, **(ray_remote_args or {})}
        worker_cls = rt.remote(**remote_args)(_PoolWorker)
        self._workers = [
            worker_cls.remote(initializer, tuple(initargs))
            for _ in range(processes)
        ]
        self._rr = itertools.count()
        self._closed = False
        self._outstanding: List[AsyncResult] = []

    # -- submission helpers -------------------------------------------
    def _next_worker(self):
        return self._workers[next(self._rr) % self._processes]

    def _check_running(self):
        if self._closed:
            raise ValueError("Pool not running")

    def _chunks(self, iterable: Iterable, chunksize: Optional[int]):
        items = list(iterable)
        if chunksize is None:
            chunksize = max(
                1, len(items) // (self._processes * _DEFAULT_CHUNK_TARGET)
            )
        return [
            items[i:i + chunksize] for i in range(0, len(items), chunksize)
        ], chunksize

    def _submit_chunks(self, func, chunks, star=False):
        return [
            self._next_worker().run_chunk.remote(func, chunk, star)
            for chunk in chunks
        ]

    # -- apply --------------------------------------------------------
    def apply(self, func: Callable, args=(), kwds=None):
        return self.apply_async(func, args, kwds).get()

    def apply_async(self, func: Callable, args=(), kwds=None, callback=None,
                    error_callback=None) -> AsyncResult:
        self._check_running()
        kwds = kwds or {}
        f = (lambda a: func(*a, **kwds)) if kwds else (lambda a: func(*a))
        ref = self._next_worker().run_chunk.remote(f, [tuple(args)], False)
        return self._track(AsyncResult([ref], single=True, callback=callback,
                                       error_callback=error_callback))

    # -- map ----------------------------------------------------------
    def map(self, func: Callable, iterable: Iterable, chunksize=None):
        return self.map_async(func, iterable, chunksize).get()

    def map_async(self, func, iterable, chunksize=None, callback=None,
                  error_callback=None) -> AsyncResult:
        self._check_running()
        chunks, _ = self._chunks(iterable, chunksize)
        refs = self._submit_chunks(func, chunks)
        return self._track(AsyncResult(refs, single=False, callback=callback,
                                       error_callback=error_callback))

    def starmap(self, func: Callable, iterable: Iterable, chunksize=None):
        return self.starmap_async(func, iterable, chunksize).get()

    def starmap_async(self, func, iterable, chunksize=None, callback=None,
                      error_callback=None) -> AsyncResult:
        self._check_running()
        chunks, _ = self._chunks(iterable, chunksize)
        refs = self._submit_chunks(func, chunks, star=True)
        return self._track(AsyncResult(refs, single=False, callback=callback,
                                       error_callback=error_callback))

    def imap(self, func, iterable, chunksize: Optional[int] = 1):
        """Ordered lazy iteration; chunks resolve as they finish."""
        self._check_running()
        chunks, _ = self._chunks(iterable, chunksize)
        refs = self._submit_chunks(func, chunks)
        for ref in refs:
            for item in rt.get(ref):
                yield item

    def imap_unordered(self, func, iterable, chunksize: Optional[int] = 1):
        """Unordered: whichever chunk finishes first yields first."""
        self._check_running()
        chunks, _ = self._chunks(iterable, chunksize)
        refs = self._submit_chunks(func, chunks)
        pending = list(refs)
        while pending:
            done, pending = rt.wait(pending, num_returns=1)
            for ref in done:
                for item in rt.get(ref):
                    yield item

    # -- lifecycle ----------------------------------------------------
    def _track(self, r: AsyncResult) -> AsyncResult:
        self._outstanding = [o for o in self._outstanding if not o.ready()]
        self._outstanding.append(r)
        return r

    def close(self):
        self._closed = True

    def terminate(self):
        self._closed = True
        for w in self._workers:
            rt.kill(w)
        self._workers = []
        self._outstanding = []

    def join(self):
        """Blocks until all submitted work has finished (the
        multiprocessing contract: close() then join() drains the pool)."""
        if not self._closed:
            raise ValueError("Pool is still running")
        for r in self._outstanding:
            r.wait()
        self._outstanding = []

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.terminate()
