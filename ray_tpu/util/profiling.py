"""On-demand worker profiling: CPU flamegraphs + heap profiles.

Reference: `dashboard/modules/reporter/profile_manager.py:78` — py-spy
CPU flamegraphs and memray heap profiles per worker.  Neither tool is
a dependency here: the CPU profiler is a native wall-clock sampler
over `sys._current_frames()` emitting standard FOLDED stacks (the
flamegraph.pl / speedscope input format), and the heap profiler rides
stdlib `tracemalloc` for allocations during a window.
"""

from __future__ import annotations

import sys
import threading
import time
import traceback
from typing import Dict, List


def sample_flamegraph(duration_s: float = 5.0, hz: float = 99.0,
                      top: int = 0) -> str:
    """Sample every thread's stack for `duration_s` at `hz` and return
    folded-stack text: one line per unique stack,
    `func (file:line);...;leaf N` — paste into speedscope or
    flamegraph.pl.  Wall-clock sampling (like py-spy's default): a
    thread blocked in IO shows where it waits."""
    me = threading.get_ident()
    counts: Dict[str, int] = {}
    interval = 1.0 / max(hz, 1.0)
    deadline = time.monotonic() + duration_s
    while time.monotonic() < deadline:
        for tid, frame in sys._current_frames().items():
            if tid == me:
                continue
            parts: List[str] = []
            f = frame
            while f is not None:
                code = f.f_code
                parts.append(
                    f"{code.co_name} ({code.co_filename.rsplit('/', 1)[-1]}"
                    f":{f.f_lineno})"
                )
                f = f.f_back
            stack = ";".join(reversed(parts))
            counts[stack] = counts.get(stack, 0) + 1
        time.sleep(interval)
    lines = sorted(counts.items(), key=lambda kv: -kv[1])
    if top:
        lines = lines[:top]
    return "\n".join(f"{stack} {n}" for stack, n in lines)


_memory_profile_lock = threading.Lock()


def memory_profile(duration_s: float = 5.0, top: int = 30) -> str:
    """Allocations made during a `duration_s` window, grouped by
    allocation site (stdlib tracemalloc; the memray-analog tier).
    Returns one line per site: `size_kb count file:line <- caller`.
    Serialized process-wide: tracemalloc tracing is global state, and
    one window's stop() must not kill another's."""
    import tracemalloc

    with _memory_profile_lock:
        started_here = not tracemalloc.is_tracing()
        if started_here:
            tracemalloc.start(8)  # frames per allocation site
        before = tracemalloc.take_snapshot()
        time.sleep(duration_s)
        after = tracemalloc.take_snapshot()
        if started_here:
            tracemalloc.stop()
    # positives FIRST, then slice: compare_to sorts by |size_diff|, so
    # slicing first would let big frees crowd out allocation sites
    stats = [s for s in after.compare_to(before, "traceback")
             if s.size_diff > 0]
    out: List[str] = []

    def _frame_str(frame) -> str:
        return f"{frame.filename.rsplit('/', 1)[-1]}:{frame.lineno}"

    for s in stats[:top]:
        frames = list(s.traceback)  # oldest -> newest
        site = _frame_str(frames[-1]) if frames else "?"
        caller = _frame_str(frames[-2]) if len(frames) >= 2 else ""
        out.append(
            f"{s.size_diff / 1024:.1f}kB x{s.count_diff} {site}"
            + (f" <- {caller}" if caller else "")
        )
    return "\n".join(out) or "(no net allocations in window)"


def dump_all_stacks() -> str:
    """One-shot all-thread stack dump (the original /api/profile
    behavior)."""
    out = []
    names = {t.ident: t.name for t in threading.enumerate()}
    for tid, frame in sys._current_frames().items():
        out.append(
            f"--- thread {names.get(tid, '?')} ({tid}) ---\n"
            + "".join(traceback.format_stack(frame))
        )
    return "\n".join(out)
