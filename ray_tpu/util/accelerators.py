"""User-facing TPU helpers (reference: `python/ray/util/accelerators/tpu.py`).

Call these from inside tasks/actors to discover the slice the current
node belongs to and fan work out across its member hosts, e.g.::

    @rt.remote(resources={"TPU-v5e-16-head": 1})
    def coordinator():
        name = rt.util.accelerators.get_current_pod_name()
        n = rt.util.accelerators.get_current_pod_worker_count()
        fn = per_host_fn.options(resources={"TPU": 4, name: 1})
        return rt.get([fn.remote() for _ in range(n)])
"""

from __future__ import annotations

import os
from typing import List, Optional

from ray_tpu.core import accelerators as _core


def get_current_pod_name() -> Optional[str]:
    """Name of the TPU pod/slice this node belongs to (also registered
    as a 1.0 custom resource on every member host)."""
    return _core.get_tpu_name()


def get_current_pod_worker_count() -> Optional[int]:
    """Number of member hosts in this node's slice, derived from the
    `v{gen}-{chips}` slice type."""
    st = _core.get_slice_type()
    return _core.num_hosts_in_slice(st) if st else None


def get_num_tpu_chips_on_node() -> int:
    """Locally attached chip count (0 off-TPU)."""
    return _core.detect_num_chips()


def get_current_process_visible_chip_ids() -> Optional[List[str]]:
    """Chip ids this worker process was granted at lease time, or None
    when unrestricted (whole host visible)."""
    raw = os.environ.get(_core.VISIBLE_CHIPS_ENV)
    if raw is None:
        return None
    return [c for c in raw.split(",") if c]
