"""Minimal asyncio HTTP/1.1 server helpers (dependency-free).

Shared by observability endpoints (dashboard) and anything else serving
HTTP off the runtime's io loop.  The serve proxy keeps its own copy of
this logic tuned for its routing path.
"""

from __future__ import annotations

import asyncio
import json
from typing import Any, Awaitable, Callable, Dict, Optional, Tuple

from ray_tpu.serve.request import Request

_MAX_BODY = 64 * 1024 * 1024

_REASONS = {200: "OK", 404: "Not Found", 500: "Internal Server Error"}

Handler = Callable[[Request], Awaitable[Tuple[int, str, bytes]]]


async def read_request(reader: asyncio.StreamReader) -> Optional[Request]:
    try:
        line = await reader.readline()
    except (ConnectionResetError, asyncio.LimitOverrunError):
        return None
    if not line or line in (b"\r\n", b"\n"):
        return None
    parts = line.decode("latin1").strip().split()
    if len(parts) < 2:
        return None
    headers: Dict[str, str] = {}
    while True:
        line = await reader.readline()
        if not line or line in (b"\r\n", b"\n"):
            break
        if b":" in line:
            k, v = line.decode("latin1").split(":", 1)
            headers[k.strip().lower()] = v.strip()
    length = int(headers.get("content-length", "0") or "0")
    if length > _MAX_BODY:
        return None
    body = await reader.readexactly(length) if length else b""
    return Request(parts[0], parts[1], headers, body)


async def write_response(writer: asyncio.StreamWriter, status: int,
                         ctype: str, body: bytes, keep_alive: bool = True):
    head = (
        f"HTTP/1.1 {status} {_REASONS.get(status, 'Status')}\r\n"
        f"Content-Type: {ctype}\r\n"
        f"Content-Length: {len(body)}\r\n"
        f"Connection: {'keep-alive' if keep_alive else 'close'}\r\n\r\n"
    )
    writer.write(head.encode() + body)
    await writer.drain()


def json_response(value: Any, status: int = 200) -> Tuple[int, str, bytes]:
    return status, "application/json", json.dumps(value, default=str).encode()


async def serve_http(host: str, port: int, handler: Handler):
    """Start an asyncio HTTP server; returns (server, bound_port)."""

    async def _conn(reader, writer):
        try:
            while True:
                req = await read_request(reader)
                if req is None:
                    break
                keep = req.headers.get("connection", "keep-alive") != "close"
                try:
                    status, ctype, body = await handler(req)
                except Exception as e:  # noqa: BLE001 — HTTP boundary
                    import traceback

                    status, ctype = 500, "text/plain"
                    body = f"{e}\n{traceback.format_exc()}".encode()
                await write_response(writer, status, ctype, body, keep)
                if not keep:
                    break
        except (ConnectionResetError, asyncio.IncompleteReadError):
            pass
        finally:
            try:
                writer.close()
                await writer.wait_closed()
            except Exception:
                pass

    server = await asyncio.start_server(_conn, host, port)
    return server, server.sockets[0].getsockname()[1]
