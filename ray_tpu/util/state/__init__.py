"""State API: programmatic cluster introspection.

Reference: `python/ray/util/state/api.py` (`StateApiClient:110`,
`list_tasks:1008`) — list/summarize tasks, actors, nodes, placement
groups, jobs; data aggregated by the controller (the GCS-task-manager
equivalent fed by every runtime's task-event buffer).
"""

from __future__ import annotations

import json
import time
from collections import Counter
from typing import Any, Dict, List, Optional

from ray_tpu.core.runtime import get_runtime


def list_tasks(name: Optional[str] = None, state: Optional[str] = None,
               limit: int = 1000) -> List[Dict[str, Any]]:
    """Latest task state transitions (newest last)."""
    return get_runtime().controller_call(
        "list_task_events", {"name": name, "state": state, "limit": limit}
    )


def list_actors() -> List[Dict[str, Any]]:
    return get_runtime().controller_call("list_actors")


def list_nodes() -> List[Dict[str, Any]]:
    return get_runtime().controller_call("get_nodes")


def list_placement_groups() -> List[Dict[str, Any]]:
    return get_runtime().controller_call("list_placement_groups")


def list_jobs() -> List[Dict[str, Any]]:
    rt = get_runtime()
    out = rt.controller_call("list_jobs")
    return out if out is not None else []


def list_workers() -> List[Dict[str, Any]]:
    """Pool workers across alive nodes (reference: `ray list workers`):
    id, pid, kind, hosted actor, idleness, node."""
    rt = get_runtime()
    # fast path: the per-node reporter pushes worker inventories to the
    # controller every second — one RPC, no per-node fan-out (reference:
    # reporter agents feeding the state aggregator)
    try:
        snap = rt.controller_call("get_worker_snapshot", timeout=10)
        if snap is not None:
            return snap
    except Exception:
        pass
    out: List[Dict[str, Any]] = []
    for n in rt.controller_call("get_nodes") or []:
        if not n.get("alive"):
            continue
        try:
            # bounded: a node that blackholes connections must cost one
            # timeout, not a kernel TCP connect stall per dead node
            ws = rt.noded_call(
                "route_node",
                {"node_id": n["node_id"], "method": "list_workers"},
                timeout=15,
            )
        except Exception:
            ws = None  # node died between listing and the call
        out.extend(ws or [])
    return out


_STATE_RANK = {"SUBMITTED": 0, "RUNNING": 1, "FINISHED": 2, "FAILED": 2}


def summarize_tasks() -> Dict[str, int]:
    """state -> count over the retained event window (the latest event
    per task wins, mirroring `ray summary tasks`).  Events from
    different processes land in the ring in arbitrary order, so 'latest'
    is decided by timestamp with terminal states breaking ties."""
    latest: Dict[str, tuple] = {}
    for ev in list_tasks(limit=50_000):
        tid = ev.get("task_id")
        if not tid:
            continue
        key = (ev["ts"], _STATE_RANK.get(ev["state"], 0))
        if tid not in latest or key >= latest[tid][0]:
            latest[tid] = (key, ev["state"])
    return dict(Counter(state for _, state in latest.values()))


def cluster_status() -> Dict[str, Any]:
    """`ray status`-shaped summary."""
    nodes = list_nodes()
    actors = list_actors()
    state = get_runtime().controller_call("get_autoscaler_state")
    return {
        "nodes_alive": sum(1 for n in nodes if n["alive"]),
        "nodes_dead": sum(1 for n in nodes if not n["alive"]),
        "total_resources": _sum_resources(nodes),
        "actors_alive": sum(1 for a in actors if a["state"] == "ALIVE"),
        "pending_demands": state["pending_demands"],
        "task_summary": summarize_tasks(),
    }


def _sum_resources(nodes) -> Dict[str, float]:
    total: Dict[str, float] = {}
    for n in nodes:
        if n["alive"]:
            for k, v in n["resources"].items():
                total[k] = total.get(k, 0.0) + v
    return total


def timeline(filename: Optional[str] = None,
             trace_id: Optional[str] = None) -> List[Dict[str, Any]]:
    """Chrome-tracing events from the task event log MERGED with the
    cluster-collected trace spans (reference: `ray.timeline()` —
    `_private/state.py:948` chrome_tracing_dump, plus the otel span
    view the reference splits across tools).  One builder feeds this
    and `GET /api/timeline` (`dashboard/timeline.py`), so the two
    surfaces can never drift.  Load the output in chrome://tracing or
    Perfetto; `trace_id` narrows the span set to one request's
    lineage."""
    from ray_tpu.dashboard.timeline import build_chrome_trace

    data = get_runtime().controller_call(
        "timeline_data", {"trace_id": trace_id}
    ) or {}
    doc = build_chrome_trace(
        data.get("events", []),
        data.get("spans", []),
        events_truncated=data.get("events_truncated", False),
        spans_truncated=data.get("spans_truncated", False),
    )
    trace = doc["traceEvents"]
    if filename:
        with open(filename, "w") as f:
            json.dump(trace, f)
    return trace


__all__ = [
    "cluster_status",
    "list_actors",
    "list_jobs",
    "list_nodes",
    "list_placement_groups",
    "list_tasks",
    "list_workers",
    "summarize_tasks",
    "timeline",
]


def memory_summary() -> List[Dict[str, Any]]:
    """Per-node object-memory tables (reference: `ray memory` —
    `python/ray/_private/internal_api.py:34` + `scripts.py:1955`):
    every runtime's reference table (kind, counts, size, residence,
    opt-in creation callsite via RT_RECORD_REF_CREATION_SITES=1) plus
    each daemon's store occupancy and spilled primaries.  This is the
    tool that answers "what is pinning my object store"."""
    rt = get_runtime()
    out = []
    for n in rt.controller_call("get_nodes") or []:
        if not n.get("alive"):
            continue
        try:
            t = rt.noded_call(
                "route_node",
                {"node_id": n["node_id"], "method": "memory_table"},
                timeout=20,
            )
        except Exception:
            continue  # node died between listing and the call
        if t:
            out.append(t)
    return out


def list_objects(kind: Optional[str] = None,
                 min_size: int = 0) -> List[Dict[str, Any]]:
    """Flattened object-reference rows across the cluster (reference:
    `ray list objects`).  One row per (process, object) hold; filter by
    `kind` (owned/borrowed/pending) or minimum value size."""
    rows: List[Dict[str, Any]] = []
    for node in memory_summary():
        for proc in node.get("processes", []):
            for r in proc.get("refs", []):
                if kind and r["kind"] != kind:
                    continue
                if min_size and (r.get("size") or 0) < min_size:
                    continue
                rows.append({
                    **r,
                    "process": proc.get("mode"),
                    "pid": proc.get("pid"),
                    "node_id_host": node.get("node_id"),
                })
    return rows


def list_cluster_events(severity: Optional[str] = None,
                        event_type: Optional[str] = None,
                        limit: int = 200) -> List[Dict[str, Any]]:
    """Structured cluster event log (reference: `ray list
    cluster-events` over `dashboard/modules/event/`)."""
    return get_runtime().controller_call(
        "list_cluster_events",
        {"severity": severity, "event_type": event_type, "limit": limit},
    )


def watch_cluster_events(timeout: Optional[float] = None):
    """Generator of live cluster events via the controller's pubsub
    channel (reference: the GCS event pubsub feeding dashboard
    watchers).  Yields until `timeout` passes with no event."""
    import queue as _q

    sub = get_runtime().subscribe("cluster_events")
    try:
        while True:
            try:
                yield sub.next_message(timeout=timeout)
            except _q.Empty:
                return
    finally:
        sub.close()
