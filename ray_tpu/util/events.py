"""Structured cluster event log.

Reference: `src/ray/util/event.h` (`RAY_EVENT` — structured events with
severity/label/source/custom fields, written to per-process
`event_*.log` JSON-lines files and surfaced by
`dashboard/modules/event/`).  Here: every process can emit events
through :func:`report_event`; they land in a JSON-lines file under the
session dir AND in the controller's in-memory ring, which the dashboard
(`/api/cluster_events`) and the state CLI read cluster-wide.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Dict, List, Optional

# severities (reference: `event.h` EventSeverity)
DEBUG = "DEBUG"
INFO = "INFO"
WARNING = "WARNING"
ERROR = "ERROR"
FATAL = "FATAL"

_SEVERITIES = (DEBUG, INFO, WARNING, ERROR, FATAL)

_lock = threading.Lock()
_log_path: Optional[str] = None


def configure_event_log(session_dir: str):
    """Point the local JSON-lines sink at a session directory (one
    `events.jsonl` per process tree, like the reference's per-source
    event files)."""
    global _log_path
    with _lock:
        _log_path = os.path.join(session_dir, "events.jsonl")


def make_event(event_type: str, message: str, *, severity: str = INFO,
               source: str = "", **custom_fields: Any) -> Dict[str, Any]:
    if severity not in _SEVERITIES:
        raise ValueError(f"unknown severity {severity!r}")
    return {
        "timestamp": time.time(),
        "severity": severity,
        "event_type": event_type,
        "source": source or f"pid-{os.getpid()}",
        "message": message,
        "custom_fields": custom_fields,
    }


def _write_local(ev: Dict[str, Any]):
    with _lock:
        path = _log_path
    if path is None:
        return
    try:
        with open(path, "a") as f:
            f.write(json.dumps(ev) + "\n")
    except OSError:
        pass


def report_event(event_type: str, message: str, *, severity: str = INFO,
                 source: str = "", **custom_fields: Any) -> Dict[str, Any]:
    """Emit a structured event: local JSON-lines sink + the controller
    ring (best-effort — events must never take a process down)."""
    ev = make_event(event_type, message, severity=severity, source=source,
                    **custom_fields)
    _write_local(ev)
    try:
        from ray_tpu.core.runtime import get_runtime

        rt = get_runtime()
        if rt is not None:
            rt.controller_call("report_cluster_event", {"event": ev})
    except Exception:
        pass
    return ev


def read_local_events(session_dir: str) -> List[Dict[str, Any]]:
    path = os.path.join(session_dir, "events.jsonl")
    out = []
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if line:
                    out.append(json.loads(line))
    except OSError:
        pass
    return out
