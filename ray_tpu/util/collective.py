"""ray_tpu.util.collective — the reference's `ray.util.collective`
surface (`util/collective/collective.py:120,151,258-615`), re-exported
from the parallel layer where the implementation lives (SURVEY §5.8:
in-program `jax.lax` collectives are the TPU fast path; the host tier
rides the framework's own object plane instead of NCCL/Gloo).
"""

from ray_tpu.parallel.collectives import (
    CollectiveGroup,
    allgather,
    allreduce,
    barrier,
    broadcast,
    destroy_collective_group,
    get_group,
    init_collective_group,
    recv,
    reducescatter,
    send,
)

__all__ = [
    "CollectiveGroup",
    "allgather",
    "allreduce",
    "barrier",
    "broadcast",
    "destroy_collective_group",
    "get_group",
    "init_collective_group",
    "recv",
    "reducescatter",
    "send",
]
