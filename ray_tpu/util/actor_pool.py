"""ActorPool: multiplex work over a fixed set of actors.

Reference: `python/ray/util/actor_pool.py` — same surface
(map/map_unordered/submit/get_next/get_next_unordered/has_next).
"""

from __future__ import annotations

from typing import Any, Callable, Iterable, Iterator, List, TypeVar

import ray_tpu as rt

V = TypeVar("V")


class ActorPool:
    def __init__(self, actors: List[Any]):
        self._idle = list(actors)
        self._future_to_actor: dict = {}
        self._index_to_future: dict = {}
        self._next_task_index = 0
        self._next_return_index = 0
        self._pending_submits: List = []

    def submit(self, fn: Callable, value: Any):
        """fn(actor, value) -> ObjectRef (reference: ActorPool.submit)."""
        if self._idle:
            actor = self._idle.pop()
            ref = fn(actor, value)
            self._future_to_actor[ref] = (self._next_task_index, actor)
            self._index_to_future[self._next_task_index] = ref
            self._next_task_index += 1
        else:
            self._pending_submits.append((fn, value))

    def has_next(self) -> bool:
        return bool(self._future_to_actor) or bool(self._pending_submits)

    def _return_actor(self, actor):
        self._idle.append(actor)
        if self._pending_submits:
            self.submit(*self._pending_submits.pop(0))

    def get_next(self, timeout: float = None) -> Any:
        """Next result in submission order.  On timeout the future stays
        queued and the actor stays busy, so a retry sees the same task
        (reference: `actor_pool.py` keeps state on TimeoutError)."""
        if self._next_return_index not in self._index_to_future:
            raise StopIteration("no pending results")
        ref = self._index_to_future[self._next_return_index]
        if timeout is not None:
            ready, _ = rt.wait([ref], num_returns=1, timeout=timeout)
            if not ready:
                raise TimeoutError("get_next timed out")
        idx, actor = self._future_to_actor.pop(ref)
        del self._index_to_future[self._next_return_index]
        self._next_return_index += 1
        try:
            return rt.get(ref)
        finally:
            self._return_actor(actor)

    def get_next_unordered(self, timeout: float = None) -> Any:
        """Next result in completion order."""
        if not self._future_to_actor:
            raise StopIteration("no pending results")
        ready, _ = rt.wait(
            list(self._future_to_actor), num_returns=1, timeout=timeout
        )
        if not ready:
            raise TimeoutError("get_next_unordered timed out")
        ref = ready[0]
        idx, actor = self._future_to_actor.pop(ref)
        self._index_to_future.pop(idx, None)
        try:
            return rt.get(ref)
        finally:
            self._return_actor(actor)

    def map(self, fn: Callable, values: Iterable[V]) -> Iterator[Any]:
        for v in values:
            self.submit(fn, v)
        while self.has_next():
            yield self.get_next()

    def map_unordered(self, fn: Callable, values: Iterable[V]) -> Iterator[Any]:
        for v in values:
            self.submit(fn, v)
        while self.has_next():
            yield self.get_next_unordered()

    def has_free(self) -> bool:
        return bool(self._idle)

    def pop_idle(self):
        return self._idle.pop() if self._idle else None

    def push(self, actor):
        self._return_actor(actor)
