"""ActorPool: multiplex work over a fixed set of actors.

Reference: `python/ray/util/actor_pool.py` — same public surface
(map/map_unordered/submit/get_next/get_next_unordered/has_next), own
bookkeeping: results are tracked by submission sequence number with a
single in-flight table keyed by ref.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Iterable, Iterator, List, Tuple, TypeVar

import ray_tpu as rt

V = TypeVar("V")


class ActorPool:
    def __init__(self, actors: List[Any]):
        self._idle = list(actors)
        # ref -> (submission seq, actor) for every in-flight task
        self._inflight: Dict[Any, Tuple[int, Any]] = {}
        # submission seq -> ref, drained in order by get_next
        self._by_seq: Dict[int, Any] = {}
        self._submit_seq = 0
        self._deliver_seq = 0
        self._backlog: List[Tuple[Callable, Any]] = []

    def submit(self, fn: Callable, value: Any):
        """fn(actor, value) -> ObjectRef (reference: ActorPool.submit)."""
        if self._idle:
            actor = self._idle.pop()
            ref = fn(actor, value)
            self._inflight[ref] = (self._submit_seq, actor)
            self._by_seq[self._submit_seq] = ref
            self._submit_seq += 1
        else:
            self._backlog.append((fn, value))

    def has_next(self) -> bool:
        return bool(self._inflight) or bool(self._backlog)

    def _release(self, actor):
        self._idle.append(actor)
        if self._backlog:
            self.submit(*self._backlog.pop(0))

    def get_next(self, timeout: float = None) -> Any:
        """Next result in submission order.  On timeout the future stays
        queued and the actor stays busy, so a retry sees the same task
        (reference: `actor_pool.py` keeps state on TimeoutError)."""
        if self._deliver_seq not in self._by_seq:
            raise StopIteration("no pending results")
        ref = self._by_seq[self._deliver_seq]
        if timeout is not None:
            ready, _ = rt.wait([ref], num_returns=1, timeout=timeout)
            if not ready:
                raise TimeoutError("get_next timed out")
        _, actor = self._inflight.pop(ref)
        del self._by_seq[self._deliver_seq]
        self._deliver_seq += 1
        try:
            return rt.get(ref)
        finally:
            self._release(actor)

    def get_next_unordered(self, timeout: float = None) -> Any:
        """Next result in completion order."""
        if not self._inflight:
            raise StopIteration("no pending results")
        ready, _ = rt.wait(
            list(self._inflight), num_returns=1, timeout=timeout
        )
        if not ready:
            raise TimeoutError("get_next_unordered timed out")
        ref = ready[0]
        seq, actor = self._inflight.pop(ref)
        self._by_seq.pop(seq, None)
        try:
            return rt.get(ref)
        finally:
            self._release(actor)

    def map(self, fn: Callable, values: Iterable[V]) -> Iterator[Any]:
        for v in values:
            self.submit(fn, v)
        while self.has_next():
            yield self.get_next()

    def map_unordered(self, fn: Callable, values: Iterable[V]) -> Iterator[Any]:
        for v in values:
            self.submit(fn, v)
        while self.has_next():
            yield self.get_next_unordered()

    def has_free(self) -> bool:
        return bool(self._idle)

    def pop_idle(self):
        return self._idle.pop() if self._idle else None

    def push(self, actor):
        self._release(actor)
