"""Placement groups: gang reservations of resource bundles.

Reference surface: `ray.util.placement_group` (`python/ray/util/
placement_group.py`), backed here by the controller's
PlacementGroupManager (`ray_tpu/core/placement.py`) the way the
reference's is backed by the GCS placement-group manager
(`gcs_placement_group_manager.h`).

TPU-native: strategies include the reference's PACK / SPREAD /
STRICT_PACK / STRICT_SPREAD, where STRICT_PACK is the idiom for "give
me an ICI-connected set of chips on one host/slice".
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional

def get_runtime():
    # deferred: core modules import ray_tpu.util (sanitizer wrappers),
    # and this package's __init__ pulls us in — a module-level runtime
    # import would close the cycle before Runtime exists
    from ray_tpu.core.runtime import get_runtime as _get

    return _get()

VALID_STRATEGIES = ("PACK", "SPREAD", "STRICT_PACK", "STRICT_SPREAD")


class PlacementGroup:
    def __init__(self, pg_id: bytes, bundles: List[Dict[str, float]],
                 strategy: str = "PACK"):
        self.id = pg_id
        self.bundle_specs = bundles
        self.strategy = strategy

    def ready(self, timeout: Optional[float] = None) -> bool:
        """Block until all bundles are reserved (the reference returns an
        ObjectRef to wait on; blocking + timeout covers the same uses)."""
        reply = get_runtime().controller_call(
            "pg_wait_ready", {"pg_id": self.id, "timeout": timeout}
        )
        return bool(reply.get("ok"))

    def wait(self, timeout_seconds: float = 30.0) -> bool:
        return self.ready(timeout=timeout_seconds)

    @property
    def bundle_count(self) -> int:
        return len(self.bundle_specs)

    def bundle_node(self, bundle_index: int) -> Optional[str]:
        reply = get_runtime().controller_call(
            "pg_node_for_bundle", {"pg_id": self.id, "bundle_index": bundle_index}
        )
        return reply.get("node_id") if isinstance(reply, dict) else None

    def __reduce__(self):
        return (PlacementGroup, (self.id, self.bundle_specs, self.strategy))

    def __repr__(self):
        return (
            f"PlacementGroup(id={self.id.hex()[:12]}, "
            f"bundles={len(self.bundle_specs)}, strategy={self.strategy})"
        )


def placement_group(
    bundles: List[Dict[str, float]],
    strategy: str = "PACK",
    name: str = "",
    lifetime: Optional[str] = None,
) -> PlacementGroup:
    if strategy not in VALID_STRATEGIES:
        raise ValueError(f"strategy must be one of {VALID_STRATEGIES}")
    if not bundles:
        raise ValueError("bundles must be non-empty")
    for b in bundles:
        if not isinstance(b, dict) or not b:
            raise ValueError(f"each bundle must be a non-empty dict, got {b!r}")
    pg_id = os.urandom(14)
    get_runtime().controller_call(
        "create_placement_group",
        {"pg_id": pg_id, "bundles": bundles, "strategy": strategy, "name": name},
    )
    return PlacementGroup(pg_id, bundles, strategy)


def multislice_placement_groups(
    n_slices: int,
    bundles_per_slice: int,
    resources_per_bundle: Dict[str, float],
    head_resource: Optional[str] = None,
    timeout: Optional[float] = 120.0,
) -> List[PlacementGroup]:
    """The runtime counterpart of ``MeshSpec(slices=N)``: one
    STRICT_PACK placement group per ICI slice, so each slice's worker
    gang lands wholly inside one `tpu-slice` label domain and the
    compiler mesh and runtime placement agree (SURVEY §7: "compiler
    mesh vs runtime PGs must agree").

    `head_resource` (e.g. the per-slice ``TPU-v5e-16-head`` gang
    resource that `accelerators.py` publishes on worker 0 of each
    slice — reference analog `_private/accelerators/tpu.py:381`) is
    charged once per group to pin distinct groups to DISTINCT slices;
    without it two groups may pack into one large slice.

    All-or-nothing: if any group fails to reserve before the shared
    `timeout` deadline — or anything raises mid-way — every group is
    removed before this returns/raises.  Reservation itself is
    sequential (the same per-PG two-phase commit the reference's GCS
    uses), so two callers racing for the same slices can each hold a
    partial gang until the deadline; stagger concurrent multislice
    jobs or front them with a queue.
    """
    if n_slices < 1 or bundles_per_slice < 1:
        raise ValueError("n_slices and bundles_per_slice must be >= 1")
    import time as _time

    deadline = None if timeout is None else _time.monotonic() + timeout
    pgs: List[PlacementGroup] = []
    try:
        for _ in range(n_slices):
            bundles = [
                dict(resources_per_bundle) for _ in range(bundles_per_slice)
            ]
            if head_resource:
                bundles[0][head_resource] = bundles[0].get(head_resource, 0) + 1
            pgs.append(placement_group(bundles, strategy="STRICT_PACK"))
        for pg in pgs:
            remaining = (
                None if deadline is None
                else max(0.0, deadline - _time.monotonic())
            )
            if not pg.ready(timeout=remaining):
                from ray_tpu import exceptions as exc

                raise exc.RayTpuError(
                    f"could not reserve {n_slices} x {bundles_per_slice} "
                    f"slice-aligned bundles {resources_per_bundle}"
                )
    except BaseException:
        for pg in pgs:
            try:
                remove_placement_group(pg)
            except Exception:
                pass
        raise
    return pgs


def remove_placement_group(pg: PlacementGroup) -> None:
    get_runtime().controller_call("remove_placement_group", {"pg_id": pg.id})


def placement_group_table() -> List[Dict]:
    return get_runtime().controller_call("list_placement_groups")
