"""Placement groups: gang reservations of resource bundles.

Reference surface: `ray.util.placement_group` (`python/ray/util/
placement_group.py`), backed here by the controller's
PlacementGroupManager (`ray_tpu/core/placement.py`) the way the
reference's is backed by the GCS placement-group manager
(`gcs_placement_group_manager.h`).

TPU-native: strategies include the reference's PACK / SPREAD /
STRICT_PACK / STRICT_SPREAD, where STRICT_PACK is the idiom for "give
me an ICI-connected set of chips on one host/slice".
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional

from ray_tpu.core.runtime import get_runtime

VALID_STRATEGIES = ("PACK", "SPREAD", "STRICT_PACK", "STRICT_SPREAD")


class PlacementGroup:
    def __init__(self, pg_id: bytes, bundles: List[Dict[str, float]],
                 strategy: str = "PACK"):
        self.id = pg_id
        self.bundle_specs = bundles
        self.strategy = strategy

    def ready(self, timeout: Optional[float] = None) -> bool:
        """Block until all bundles are reserved (the reference returns an
        ObjectRef to wait on; blocking + timeout covers the same uses)."""
        reply = get_runtime().controller_call(
            "pg_wait_ready", {"pg_id": self.id, "timeout": timeout}
        )
        return bool(reply.get("ok"))

    def wait(self, timeout_seconds: float = 30.0) -> bool:
        return self.ready(timeout=timeout_seconds)

    @property
    def bundle_count(self) -> int:
        return len(self.bundle_specs)

    def bundle_node(self, bundle_index: int) -> Optional[str]:
        reply = get_runtime().controller_call(
            "pg_node_for_bundle", {"pg_id": self.id, "bundle_index": bundle_index}
        )
        return reply.get("node_id") if isinstance(reply, dict) else None

    def __reduce__(self):
        return (PlacementGroup, (self.id, self.bundle_specs, self.strategy))

    def __repr__(self):
        return (
            f"PlacementGroup(id={self.id.hex()[:12]}, "
            f"bundles={len(self.bundle_specs)}, strategy={self.strategy})"
        )


def placement_group(
    bundles: List[Dict[str, float]],
    strategy: str = "PACK",
    name: str = "",
    lifetime: Optional[str] = None,
) -> PlacementGroup:
    if strategy not in VALID_STRATEGIES:
        raise ValueError(f"strategy must be one of {VALID_STRATEGIES}")
    if not bundles:
        raise ValueError("bundles must be non-empty")
    for b in bundles:
        if not isinstance(b, dict) or not b:
            raise ValueError(f"each bundle must be a non-empty dict, got {b!r}")
    pg_id = os.urandom(14)
    get_runtime().controller_call(
        "create_placement_group",
        {"pg_id": pg_id, "bundles": bundles, "strategy": strategy, "name": name},
    )
    return PlacementGroup(pg_id, bundles, strategy)


def remove_placement_group(pg: PlacementGroup) -> None:
    get_runtime().controller_call("remove_placement_group", {"pg_id": pg.id})


def placement_group_table() -> List[Dict]:
    return get_runtime().controller_call("list_placement_groups")
