"""Distributed tracing: span capture with cross-task context propagation
and batched cluster-wide collection.

Reference: `python/ray/util/tracing/tracing_helper.py` — opt-in
OpenTelemetry tracing where remote calls and task execution are wrapped
in spans and the trace context rides the task metadata
(`_DictPropagator:165`).  The same design here without the otel
dependency: spans are plain dicts, the context propagates inside
`TaskSpec.trace_ctx` (tasks, actor calls, serve handle hops, shuffle
map→reduce lineage all ride it), and finished spans batch-export to the
driver's controller — one frame per process per flush period, riding
the task-event flush that already runs (`core/runtime.py`) or the node
daemon's obs loop (`core/noded.py`).  The controller keeps a bounded
ring keyed by trace id that `/api/timeline` merges with task events
into one whole-run Chrome trace (`dashboard/timeline.py`).

Usage:
    from ray_tpu.util import tracing
    tracing.enable()           # in the driver, before rt.init
    with tracing.span("my-phase"):
        ... rt.remote work ...
    spans = tracing.get_spans()       # this process's ring
    # cluster-collected spans: rt.timeline() / GET /api/timeline

Overhead knobs (all off/neutral by default — tracing itself defaults
OFF):
    RT_TRACING_ENABLED=1     master switch (propagates to children)
    RT_TRACE_SAMPLE=0.1      head-sample: fraction of NEW traces kept.
                             Decided once at the root; a sampled-out
                             root propagates its NEGATIVE decision
                             (ambient + over the wire), so no
                             descendant re-rolls into orphan fragments
                             and the whole lineage does zero span work
"""

from __future__ import annotations

import contextvars
import os
import random
import threading
import time
import uuid
from collections import deque
from typing import Any, Callable, Dict, List, Optional

_ENV_FLAG = "RT_TRACING_ENABLED"
_ENV_SAMPLE = "RT_TRACE_SAMPLE"

# finished spans awaiting batch export to the controller; bounded so a
# span storm between flushes degrades to counted drops, never to
# unbounded memory
EXPORT_BUFFER = 20_000

_lock = threading.Lock()
_spans: deque = deque(maxlen=10_000)
_export_queue: deque = deque()
_export_dropped = 0
_exporter: Optional[Callable[[Dict[str, Any]], None]] = None
# sampling rng: per-process, seeded from entropy; RT_TRACE_SEED pins it
# for deterministic tests
_sample_rng = random.Random(
    int(os.environ["RT_TRACE_SEED"]) if os.environ.get("RT_TRACE_SEED")
    else None
)
# contextvar, NOT threading.local: async actor tasks interleave on one
# event-loop thread and must each carry their own active span
_ctx_var: contextvars.ContextVar = contextvars.ContextVar(
    "rt_trace_ctx", default=None
)


def enable():
    """Turn tracing on for this process AND propagate the flag to child
    processes (workers inherit env through the daemon spawn chain)."""
    os.environ[_ENV_FLAG] = "1"


def disable():
    os.environ.pop(_ENV_FLAG, None)


def is_enabled() -> bool:
    return os.environ.get(_ENV_FLAG, "") == "1"


def sample_rate() -> float:
    try:
        return min(1.0, max(0.0, float(os.environ.get(_ENV_SAMPLE, "1"))))
    except ValueError:
        return 1.0


def _sampled() -> bool:
    rate = sample_rate()
    if rate >= 1.0:
        return True
    return _sample_rng.random() < rate


# The NEGATIVE sampling decision, made once at a trace's root and then
# propagated exactly like a real context — through the ambient
# contextvar AND over the wire in `TaskSpec.trace_ctx` — so no
# descendant (nested submit, worker execution, daemon hop) ever
# re-rolls sampling into an orphan fragment trace.  Falsy trace_id ==
# "this lineage does no span work".
NOT_SAMPLED: Dict[str, str] = {"trace_id": "", "span_id": ""}


def _is_not_sampled(ctx: Optional[Dict[str, str]]) -> bool:
    return ctx is not None and not ctx.get("trace_id")


def set_span_exporter(fn: Optional[Callable[[Dict[str, Any]], None]]):
    """Every finished span is passed to fn (e.g. an OTLP exporter);
    None restores the in-process ring only."""
    global _exporter
    _exporter = fn


def get_spans() -> List[Dict[str, Any]]:
    with _lock:
        return list(_spans)


def clear_spans():
    with _lock:
        _spans.clear()
        _export_queue.clear()


def drain_export() -> List[Dict[str, Any]]:
    """Pop every span queued for cluster collection (called by the
    periodic obs flush; one batched frame per period).  Drops since the
    last drain surface as `rt_trace_spans_dropped_total`."""
    global _export_dropped
    with _lock:
        out = list(_export_queue)
        _export_queue.clear()
        dropped, _export_dropped = _export_dropped, 0
    if dropped:
        from ray_tpu.metrics import metric_defs as _md

        # unconditional: a drop is the signal that sampling/flush
        # cadence needs tuning — it must not itself be sampled away
        _md.metric("rt_trace_spans_dropped_total").inc(dropped)
    return out


def _new_id() -> str:
    return uuid.uuid4().hex[:16]


def current_context() -> Optional[Dict[str, str]]:
    """The active span's (trace_id, span_id) — the parent for anything
    submitted from here."""
    return _ctx_var.get()


def _record(span: Dict[str, Any]):
    global _export_dropped
    with _lock:
        _spans.append(span)
        if len(_export_queue) < EXPORT_BUFFER:
            _export_queue.append(span)
        else:
            _export_dropped += 1
    if _exporter is not None:
        try:
            _exporter(span)
        except Exception:
            pass


def make_submit_ctx(task_name: str) -> Optional[Dict[str, str]]:
    """Called at task submission: returns the trace context to embed in
    the spec, recording a zero-duration 'submit' span.  A NEW root is
    head-sampled (RT_TRACE_SAMPLE); a propagated parent is always kept
    — sampling is decided once per trace, at its root."""
    if not is_enabled():
        return None
    parent = current_context()
    if _is_not_sampled(parent):
        return dict(NOT_SAMPLED)  # propagate the decision, no span
    if parent is None and not _sampled():
        return dict(NOT_SAMPLED)
    trace_id = parent["trace_id"] if parent else _new_id()
    span_id = _new_id()
    now = time.time()
    _record({
        "name": f"submit:{task_name}",
        "trace_id": trace_id,
        "span_id": span_id,
        "parent_id": parent["span_id"] if parent else None,
        "start": now,
        "end": now,
        "kind": "PRODUCER",
    })
    return {"trace_id": trace_id, "span_id": span_id}


def record_instant(name: str, trace_ctx: Optional[Dict[str, str]],
                   kind: str = "INTERNAL", **attrs):
    """Zero-duration span parented to `trace_ctx` — how owner-side
    retry attempts and daemon-side scheduling hops appear in a trace
    without wrapping any execution."""
    if trace_ctx is None or not trace_ctx.get("trace_id") \
            or not is_enabled():
        return
    now = time.time()
    span = {
        "name": name,
        "trace_id": trace_ctx["trace_id"],
        "span_id": _new_id(),
        "parent_id": trace_ctx.get("span_id"),
        "start": now,
        "end": now,
        "kind": kind,
    }
    if attrs:
        span["attrs"] = attrs
    _record(span)


class span:
    """Context manager for a driver-side (or any in-process) span:
    everything submitted inside is parented under it, so a multi-stage
    operation (a shuffle's map→reduce lineage, a user phase) shares one
    trace id end to end."""

    def __init__(self, name: str, kind: str = "INTERNAL"):
        self._name = name
        self._kind = kind
        self._span: Optional[Dict[str, Any]] = None
        self._token = None

    def __enter__(self):
        if not is_enabled():
            return self
        parent = current_context()
        if _is_not_sampled(parent):
            return self  # decision already made upstream
        if parent is None and not _sampled():
            # make the negative decision ambient so everything inside
            # this block (and everything it submits) skips uniformly
            self._token = _ctx_var.set(dict(NOT_SAMPLED))
            return self
        trace_id = parent["trace_id"] if parent else _new_id()
        span_id = _new_id()
        self._span = {
            "name": self._name,
            "trace_id": trace_id,
            "span_id": span_id,
            "parent_id": parent["span_id"] if parent else None,
            "start": time.time(),
            "kind": self._kind,
        }
        self._token = _ctx_var.set(
            {"trace_id": trace_id, "span_id": span_id}
        )
        return self

    def __exit__(self, exc_type, exc, tb):
        if self._span is not None:
            self._span["end"] = time.time()
            if exc_type is not None:
                self._span["error"] = exc_type.__name__
            _record(self._span)
        if self._token is not None:
            _ctx_var.reset(self._token)
            self._token = None
        return False


# -- explicit-context helpers (generator-shaped drivers) ---------------
# A `with span(...)` around a generator body would leak the ambient
# contextvar into the CALLER between yields (contextvars do not revert
# at generator suspension).  Drivers shaped like that (the shuffle
# exchange) open a span explicitly and scope the ambient context only
# around each submission batch.
def start_span(name: str, kind: str = "INTERNAL") -> Optional[Dict[str, Any]]:
    """Open a span WITHOUT touching the ambient context; parent is the
    caller's current context.  Finish with `finish_span`; pass
    `ctx_of(span)` to `use_context` around submissions that should nest
    under it.  None when tracing is off; when the root is sampled out
    it returns the NOT_SAMPLED record, whose ctx_of() propagates the
    negative decision to every submission scoped under it."""
    if not is_enabled():
        return None
    parent = current_context()
    if _is_not_sampled(parent):
        return dict(NOT_SAMPLED)
    if parent is None and not _sampled():
        return dict(NOT_SAMPLED)
    trace_id = parent["trace_id"] if parent else _new_id()
    return {
        "name": name,
        "trace_id": trace_id,
        "span_id": _new_id(),
        "parent_id": parent["span_id"] if parent else None,
        "start": time.time(),
        "kind": kind,
    }


def ctx_of(span_rec: Optional[Dict[str, Any]]) -> Optional[Dict[str, str]]:
    if span_rec is None:
        return None
    return {"trace_id": span_rec["trace_id"], "span_id": span_rec["span_id"]}


def finish_span(span_rec: Optional[Dict[str, Any]],
                error: Optional[str] = None):
    if span_rec is None or not span_rec.get("trace_id"):
        return  # tracing off, or a NOT_SAMPLED marker — nothing opened
    span_rec["end"] = time.time()
    if error:
        span_rec["error"] = error
    _record(span_rec)


def new_id() -> str:
    """Public id maker for out-of-band span builders (the serve request
    ledger constructs its span tree lazily and only commits it at
    terminal time via `record_spans`)."""
    return _new_id()


def record_spans(spans: List[Dict[str, Any]]):
    """Commit a batch of pre-built span dicts to the ring + export
    queue, bypassing head sampling.  This is the tail-capture hook: the
    serve ledger buffers a request's phase spans locally and calls this
    only when the request turns out to matter (slowest-K% latency, or
    shed/rejected) — even when the head-sampling roll at the root said
    drop.  No-op when tracing is off."""
    if not is_enabled():
        return
    for s in spans:
        if s.get("trace_id"):
            _record(s)


class use_context:
    """Temporarily install `ctx` as the ambient trace context (set +
    reset in the same frame — safe inside generator bodies).  None is
    a no-op, so call sites need no tracing-enabled branches."""

    def __init__(self, ctx: Optional[Dict[str, str]]):
        self._ctx = ctx
        self._token = None

    def __enter__(self):
        if self._ctx is not None:
            self._token = _ctx_var.set(dict(self._ctx))
        return self

    def __exit__(self, exc_type, exc, tb):
        if self._token is not None:
            _ctx_var.reset(self._token)
            self._token = None
        return False


class execution_span:
    """Context manager wrapping task execution on the worker; nested
    submits from inside pick up this span as their parent."""

    def __init__(self, task_name: str, trace_ctx: Optional[Dict[str, str]]):
        self._name = task_name
        self._ctx = trace_ctx
        self._token = None
        self._span: Optional[Dict[str, Any]] = None

    def __enter__(self):
        if self._ctx is None:
            return self
        if not self._ctx.get("trace_id"):
            # NOT_SAMPLED lineage arriving over the wire: record
            # nothing, but keep the negative decision ambient so
            # nested submits from this task skip too (never re-roll)
            self._token = _ctx_var.set(dict(NOT_SAMPLED))
            return self
        span_id = _new_id()
        self._span = {
            "name": f"run:{self._name}",
            "trace_id": self._ctx["trace_id"],
            "span_id": span_id,
            "parent_id": self._ctx["span_id"],
            "start": time.time(),
            "kind": "CONSUMER",
        }
        self._token = _ctx_var.set(
            {"trace_id": self._ctx["trace_id"], "span_id": span_id}
        )
        return self

    def __exit__(self, exc_type, exc, tb):
        if self._span is not None:
            self._span["end"] = time.time()
            if exc_type is not None:
                self._span["error"] = exc_type.__name__
            _record(self._span)
        if self._token is not None:
            _ctx_var.reset(self._token)
            self._token = None
        return False
