"""Distributed tracing: span capture with cross-task context propagation.

Reference: `python/ray/util/tracing/tracing_helper.py` — opt-in
OpenTelemetry tracing where remote calls and task execution are wrapped
in spans and the trace context rides the task metadata
(`_DictPropagator:165`).  The same design here without the otel
dependency: spans are plain dicts, the context propagates inside
`TaskSpec.trace_ctx`, and a pluggable exporter receives finished spans
(wire an OTLP exporter there when the package exists; the default
keeps an in-process ring readable via `get_spans`).

Usage:
    from ray_tpu.util import tracing
    tracing.enable()           # in the driver, before submitting
    ... rt.remote work ...
    spans = tracing.get_spans()   # every process exports its own spans
"""

from __future__ import annotations

import contextvars
import os
import threading
import time
import uuid
from collections import deque
from typing import Any, Callable, Dict, List, Optional

_ENV_FLAG = "RT_TRACING_ENABLED"

_lock = threading.Lock()
_spans: deque = deque(maxlen=10_000)
_exporter: Optional[Callable[[Dict[str, Any]], None]] = None
# contextvar, NOT threading.local: async actor tasks interleave on one
# event-loop thread and must each carry their own active span
_ctx_var: contextvars.ContextVar = contextvars.ContextVar(
    "rt_trace_ctx", default=None
)


def enable():
    """Turn tracing on for this process AND propagate the flag to child
    processes (workers inherit env through the daemon spawn chain)."""
    os.environ[_ENV_FLAG] = "1"


def disable():
    os.environ.pop(_ENV_FLAG, None)


def is_enabled() -> bool:
    return os.environ.get(_ENV_FLAG, "") == "1"


def set_span_exporter(fn: Optional[Callable[[Dict[str, Any]], None]]):
    """Every finished span is passed to fn (e.g. an OTLP exporter);
    None restores the in-process ring only."""
    global _exporter
    _exporter = fn


def get_spans() -> List[Dict[str, Any]]:
    with _lock:
        return list(_spans)


def clear_spans():
    with _lock:
        _spans.clear()


def _new_id() -> str:
    return uuid.uuid4().hex[:16]


def current_context() -> Optional[Dict[str, str]]:
    """The active span's (trace_id, span_id) — the parent for anything
    submitted from here."""
    return _ctx_var.get()


def _record(span: Dict[str, Any]):
    with _lock:
        _spans.append(span)
    if _exporter is not None:
        try:
            _exporter(span)
        except Exception:
            pass


def make_submit_ctx(task_name: str) -> Optional[Dict[str, str]]:
    """Called at task submission: returns the trace context to embed in
    the spec, recording a zero-duration 'submit' span."""
    if not is_enabled():
        return None
    parent = current_context()
    trace_id = parent["trace_id"] if parent else _new_id()
    span_id = _new_id()
    now = time.time()
    _record({
        "name": f"submit:{task_name}",
        "trace_id": trace_id,
        "span_id": span_id,
        "parent_id": parent["span_id"] if parent else None,
        "start": now,
        "end": now,
        "kind": "PRODUCER",
    })
    return {"trace_id": trace_id, "span_id": span_id}


class execution_span:
    """Context manager wrapping task execution on the worker; nested
    submits from inside pick up this span as their parent."""

    def __init__(self, task_name: str, trace_ctx: Optional[Dict[str, str]]):
        self._name = task_name
        self._ctx = trace_ctx
        self._prev = None
        self._span: Optional[Dict[str, Any]] = None

    def __enter__(self):
        if self._ctx is None:
            return self
        span_id = _new_id()
        self._span = {
            "name": f"run:{self._name}",
            "trace_id": self._ctx["trace_id"],
            "span_id": span_id,
            "parent_id": self._ctx["span_id"],
            "start": time.time(),
            "kind": "CONSUMER",
        }
        self._token = _ctx_var.set(
            {"trace_id": self._ctx["trace_id"], "span_id": span_id}
        )
        return self

    def __exit__(self, exc_type, exc, tb):
        if self._span is not None:
            self._span["end"] = time.time()
            if exc_type is not None:
                self._span["error"] = exc_type.__name__
            _record(self._span)
            _ctx_var.reset(self._token)
        return False
