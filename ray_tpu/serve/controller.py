"""ServeController: the serve control plane, as one named actor.

Reference: `python/ray/serve/_private/controller.py` (`ServeController:86`,
`deploy_application:722`) + `application_state.py:119` +
`deployment_state.py`: a reconcile loop drives each deployment's replica
set toward its target (create/kill replica actors, replace unhealthy
ones), autoscaling adjusts targets from replica-reported metrics, and
routers poll versioned routing tables (reference pushes them via
`long_poll.py`; polling is the same contract with simpler failure modes).

The controller's methods are synchronous on purpose: sync actor methods
execute on the worker's thread pool where blocking `rt.get/wait` calls
are safe, while the reconcile loop runs on a dedicated daemon thread.
"""

from __future__ import annotations

import logging
import threading
import time
import traceback
from typing import Any, Dict, List, Optional

import ray_tpu as rt
from ray_tpu.serve.config import DeploymentConfig
from ray_tpu.serve.replica import Replica

logger = logging.getLogger(__name__)

CONTROLLER_NAME = "SERVE_CONTROLLER"
CONTROLLER_NAMESPACE = "serve"

# replica lifecycle states (reference: deployment_state.py ReplicaState)
STARTING = "STARTING"
RUNNING = "RUNNING"


def _replica_depth(r: "_ReplicaState") -> float:
    """One queue-depth signal for routing AND the status panel —
    delegates to the shared backlog definition in serve/autoscaling.py
    so routing and SLO-autoscaling pressure always agree on it."""
    from ray_tpu.serve.autoscaling import replica_depth

    return replica_depth(r.metrics)


def _overload_summary(ds: "_DeploymentState",
                      router_rejected: float = 0.0) -> Dict[str, float]:
    """Deployment-level overload counters for /api/serve's serve panel:
    rejections (router assignment-queue cap — delta-folded from router
    pushes, since those requests never reach a replica — plus replica
    cap and engine queue cap) and deadline sheds, summed over live
    replicas' piggybacked metrics.  Advisory — replica restarts reset
    their counters."""
    rejected = float(router_rejected or 0.0)
    shed = 0.0
    for r in ds.replicas.values():
        m = r.metrics
        us = m.get("user_stats") or {}
        for src, key in ((m, "rejected"), (us, "rejected_total")):
            try:
                rejected += float(src.get(key, 0) or 0)
            except (TypeError, ValueError):
                pass
        try:
            shed += float(us.get("shed_total", 0) or 0)
        except (TypeError, ValueError):
            pass
    return {"rejected_total": rejected, "shed_total": shed}


class _ReplicaState:
    def __init__(self, replica_id: str, handle, max_ongoing: int):
        self.replica_id = replica_id
        self.handle = handle
        self.max_ongoing = max_ongoing
        self.state = STARTING
        self.health_ref = None
        self.health_sent = 0.0
        # latest metrics piggybacked on the health-check reply
        # (requests total, queue depth, latency histogram)
        self.metrics: Dict[str, Any] = {}


class _DeploymentState:
    """Reconciler state for one deployment (reference:
    `deployment_state.py` DeploymentState)."""

    def __init__(self, app_name: str, name: str, callable_def, init_args,
                 init_kwargs, config: DeploymentConfig, resources: Dict[str, float]):
        import uuid as _uuid

        # identity of THIS deploy of this deployment name: request
        # counters are keyed by it so a surviving client router's
        # lifetime-cumulative stats can never credit a redeployed app
        # with the previous incarnation's traffic
        self.incarnation = _uuid.uuid4().hex[:12]
        self.app_name = app_name
        self.name = name
        self.callable_def = callable_def
        self.init_args = init_args
        self.init_kwargs = init_kwargs
        self.config = config
        self.resources = resources or {}
        self.target_replicas = config.initial_replicas()
        self.replicas: Dict[str, _ReplicaState] = {}
        self.version = 0
        self.next_replica_idx = 0
        self.last_scale_change = 0.0
        # autoscaler window: (ts, total_ongoing) for the legacy policy,
        # (ts, load_ratio) for the SLO policy
        self.samples: list = []
        self.deleted = False
        ac = config.autoscaling_config
        if ac is not None and ac.has_slo():
            from ray_tpu.serve.autoscaling import AutoscalingPolicy

            self.policy = AutoscalingPolicy(ac)
        else:
            self.policy = None
        # SLO burn-rate tracker (serve/slo.py): folds the replicas'
        # ledger counter blocks (the health piggyback's "slo" key)
        # into a deployment-cumulative series behind rt.slo_status()
        # and /api/slo.  getattr: configs rehydrated from pre-SLO
        # checkpoints have no slo_config attribute.
        sc = getattr(config, "slo_config", None)
        if sc is not None and sc.has_any():
            from ray_tpu.serve.slo import BurnRateTracker

            self.slo_tracker = BurnRateTracker()
        else:
            self.slo_tracker = None

    def routing_table(self) -> Dict[str, Any]:
        running = [r for r in self.replicas.values() if r.state == RUNNING]
        return {
            "version": self.version,
            "incarnation": self.incarnation,
            "replicas": {
                r.replica_id: (r.handle, r.max_ongoing) for r in running
            },
            # per-replica queue-depth signal, refreshed on the health
            # cadence: a deployment exposing stats() (the LLM engine's
            # queued+active count) reports real backlog; others fall
            # back to the in-flight count.  Routers fold this into
            # their pow-2 choice so N engine replicas share load by
            # actual queue depth, not just each router's local view.
            "depths": {r.replica_id: _replica_depth(r) for r in running},
            # admission-control contract: routers bound their
            # assignment wait pool at this (-1 = unbounded) and reject
            # the overflow with BackPressureError instead of letting
            # every waiter burn its full assignment timeout
            "max_queued": self.config.max_queued_requests,
        }


STATE_KV_KEY = "serve:controller:state"


class ServeController:
    def __init__(self):
        self._lock = threading.RLock()
        self._apps: Dict[str, Dict[str, _DeploymentState]] = {}
        self._ingress: Dict[str, str] = {}  # app name -> ingress deployment
        # app name -> ingress callable is a generator (HTTP responses
        # stream chunked instead of buffering)
        self._ingress_streaming: Dict[str, bool] = {}
        self._routes: Dict[str, str] = {}  # route prefix -> app name
        # pushed handle metrics: (app, dep) -> router_id -> (ts, {rid: n})
        self._handle_metrics: Dict[tuple, Dict[str, tuple]] = {}
        # cumulative request stats: per-router last report + per-
        # deployment monotonic totals (delta-folded)
        self._router_stats: Dict[tuple, Dict[str, Dict[str, float]]] = {}
        self._deployment_stats: Dict[tuple, Dict[str, float]] = {}
        # per-node proxy fleet (reference: one ProxyActor per node,
        # `serve/_private/proxy.py:1140`): node_id -> (handle, addr)
        self._http_options: Optional[tuple] = None  # (host, port)
        self._proxies: Dict[str, tuple] = {}
        # serializes fleet reconciles (ensure_proxies on the actor
        # thread vs the dedicated reconcile thread) — an unlocked
        # read-copy-writeback would double-create named proxies
        self._proxy_lock = threading.Lock()
        self._stop = threading.Event()
        self._recover()
        self._thread = threading.Thread(
            target=self._control_loop, daemon=True, name="serve-controller"
        )
        self._thread.start()
        # proxy reconcile runs on its OWN thread: its health probes are
        # blocking RPCs (a wedged proxy costs seconds), and the replica
        # reconcile/autoscale loop must not stall behind them
        self._proxy_thread = threading.Thread(
            target=self._proxy_loop, daemon=True,
            name="serve-proxy-reconcile",
        )
        self._proxy_thread.start()

    # -- fault tolerance ----------------------------------------------
    # Reference: the controller checkpoints every state change to the
    # GCS KV and rehydrates on restart (`serve/_private/controller.py:
    # 81-91` + `application_state.py` recovering from checkpoints).
    # Replica actors are NAMED, survive a controller crash (no
    # owner-kill in this runtime), and get re-adopted by name.
    def _snapshot_bytes(self) -> bytes:
        import cloudpickle

        with self._lock:
            apps = {}
            for app_name, deployments in self._apps.items():
                apps[app_name] = [
                    {
                        "name": ds.name,
                        "callable_def": ds.callable_def,
                        "init_args": tuple(ds.init_args),
                        "init_kwargs": dict(ds.init_kwargs),
                        "config": ds.config,
                        "resources": dict(ds.resources),
                        "target_replicas": ds.target_replicas,
                        "version": ds.version,
                        "incarnation": ds.incarnation,
                        "next_replica_idx": ds.next_replica_idx,
                        "replica_ids": list(ds.replicas),
                    }
                    for ds in deployments.values()
                ]
            state = {
                "apps": apps,
                "http_options": self._http_options,
                "ingress": dict(self._ingress),
                "ingress_streaming": dict(self._ingress_streaming),
                "routes": dict(self._routes),
                # monotonic request totals survive a controller crash:
                # a reset would make Prometheus rate() see a counter
                # reset + a spurious re-report spike
                "deployment_stats": {
                    f"{k[0]}::{k[1]}": dict(v)
                    for k, v in self._deployment_stats.items()
                },
                "router_stats": {
                    f"{k[0]}::{k[1]}": dict(v)
                    for k, v in self._router_stats.items()
                },
            }
        return cloudpickle.dumps(state)

    def _checkpoint(self):
        from ray_tpu.core.runtime import get_runtime

        try:
            get_runtime().kv_put(STATE_KV_KEY, self._snapshot_bytes())
        except Exception:
            traceback.print_exc()

    def _recover(self):
        from ray_tpu.core import serialization
        from ray_tpu.core.runtime import get_runtime

        try:
            blob = get_runtime().kv_get(STATE_KV_KEY)
        except Exception as e:
            logger.debug("FT snapshot unavailable (%s); cold start", e)
            return
        if not blob:
            return
        try:
            # checkpoint blobs only ever come from this controller, and
            # decode routes through the audited unpickle chokepoint
            state = serialization.loads(blob)
        except Exception:
            traceback.print_exc()
            return
        import ray_tpu as rt

        try:
            with self._lock:
                for app_name, dep_list in state.get("apps", {}).items():
                    deployments: Dict[str, _DeploymentState] = {}
                    for d in dep_list:
                        ds = _DeploymentState(
                            app_name, d["name"], d["callable_def"],
                            d["init_args"], d["init_kwargs"], d["config"],
                            d["resources"],
                        )
                        ds.target_replicas = d["target_replicas"]
                        # keep the saved version: routers holding it keep
                        # their cached replica set through the re-adoption
                        # window (re-adopted replicas are STARTING, so a
                        # bumped version would hand routers an EMPTY
                        # table); the STARTING->RUNNING promotion bumps
                        # the version and triggers the refetch
                        ds.version = d["version"]
                        ds.incarnation = d.get(
                            "incarnation", ds.incarnation
                        )
                        ds.next_replica_idx = d["next_replica_idx"]
                        for rid in d["replica_ids"]:
                            try:
                                handle = rt.get_actor(
                                    f"SERVE_REPLICA::{rid}",
                                    CONTROLLER_NAMESPACE,
                                )
                            except Exception as e:
                                logger.debug("replica %s not resolvable "
                                             "(%s); reconcile replaces it",
                                             rid, e)
                                continue
                            ds.replicas[rid] = _ReplicaState(
                                rid, handle, ds.config.max_ongoing_requests
                            )
                        deployments[d["name"]] = ds
                    self._apps[app_name] = deployments
                self._ingress = dict(state.get("ingress", {}))
                opts = state.get("http_options")
                self._http_options = tuple(opts) if opts else None
                self._ingress_streaming = dict(
                    state.get("ingress_streaming", {})
                )
                self._routes = dict(state.get("routes", {}))
                for attr, key in (("_deployment_stats", "deployment_stats"),
                                  ("_router_stats", "router_stats")):
                    loaded = {}
                    for flat, v in state.get(key, {}).items():
                        app, _, dep = flat.partition("::")
                        loaded[(app, dep)] = v
                    setattr(self, attr, loaded)
        except Exception:
            # a poisoned/old-schema snapshot must not crash-loop the
            # controller through its (effectively infinite) restarts:
            # start empty instead
            traceback.print_exc()
            with self._lock:
                self._apps.clear()
                self._ingress.clear()
                self._ingress_streaming.clear()
                self._routes.clear()

    # -- deploy API ---------------------------------------------------
    def deploy_application(self, app_config: Dict[str, Any]) -> bool:
        """app_config: {name, route_prefix, ingress, deployments: [
        {name, callable_def, init_args, init_kwargs, config, resources}]}
        (reference: `controller.py:722` deploy_application)."""
        app_name = app_config["name"]
        with self._lock:
            deployments: Dict[str, _DeploymentState] = {}
            old = self._apps.get(app_name, {})
            stale: List[_ReplicaState] = []
            for d in app_config["deployments"]:
                ds = _DeploymentState(
                    app_name, d["name"], d["callable_def"],
                    d.get("init_args", ()), d.get("init_kwargs", {}),
                    d.get("config") or DeploymentConfig(), d.get("resources"),
                )
                prev = old.pop(d["name"], None)
                if prev is not None:
                    # rolling redeploy: old replicas are torn down and a
                    # fresh set started at a bumped table version
                    prev.deleted = True
                    stale.extend(prev.replicas.values())
                    prev.replicas = {}
                    ds.version = prev.version + 1
                    ds.next_replica_idx = prev.next_replica_idx
                    # request counters belong to the PREVIOUS incarnation:
                    # drop its totals and per-router prev entries so the
                    # new incarnation's first delta-fold starts from zero
                    self._router_stats.pop((app_name, d["name"]), None)
                    self._deployment_stats.pop((app_name, d["name"]), None)
                deployments[d["name"]] = ds
            for prev in old.values():  # deployments dropped by the update
                prev.deleted = True
                stale.extend(prev.replicas.values())
                prev.replicas = {}
            self._apps[app_name] = deployments
            for key in [k for k in self._handle_metrics
                        if k[0] == app_name and k[1] not in deployments]:
                del self._handle_metrics[key]
            for store in (self._router_stats, self._deployment_stats):
                for key in [k for k in store
                            if k[0] == app_name and k[1] not in deployments]:
                    del store[key]
            self._ingress[app_name] = app_config.get(
                "ingress", app_config["deployments"][-1]["name"]
            )
            self._ingress_streaming[app_name] = bool(
                app_config.get("ingress_streaming", False)
            )
            route = app_config.get("route_prefix") or f"/{app_name}"
            self._routes = {
                k: v for k, v in self._routes.items() if v != app_name
            }
            self._routes[route] = app_name
        for r in stale:
            self._stop_replica(r, timeout_s=5.0)
        self._reconcile_once()
        self._checkpoint()
        for name, ds in deployments.items():
            self._notify_routes(app_name, name, ds.version)
        return True

    def delete_application(self, app_name: str) -> bool:
        with self._lock:
            deployments = self._apps.pop(app_name, {})
            self._ingress.pop(app_name, None)
            self._ingress_streaming.pop(app_name, None)
            self._routes = {k: v for k, v in self._routes.items() if v != app_name}
            for key in [k for k in self._handle_metrics if k[0] == app_name]:
                del self._handle_metrics[key]
            for store in (self._router_stats, self._deployment_stats):
                for key in [k for k in store if k[0] == app_name]:
                    del store[key]
            victims: List[tuple] = []
            for ds in deployments.values():
                ds.deleted = True  # reconcile snapshots may still hold ds
                victims.extend(
                    (r, ds.config.graceful_shutdown_timeout_s)
                    for r in ds.replicas.values()
                )
                ds.replicas = {}
        for r, timeout_s in victims:
            self._stop_replica(r, timeout_s=timeout_s)
        self._checkpoint()
        for name in list(deployments):
            self._notify_routes(app_name, name, -1, deleted=True)
        return True

    def shutdown(self) -> bool:
        self._stop.set()
        for app in list(self._apps):
            self.delete_application(app)
        with self._lock:
            proxies = list(self._proxies.values())
            self._proxies = {}
            self._http_options = None
        for handle, _addr in proxies:
            try:
                rt.kill(handle)
            except Exception as e:
                logger.debug("killing proxy during shutdown: %s", e)
        self._checkpoint()
        return True

    # -- routing ------------------------------------------------------
    def get_routing_table(self, app_name: str, deployment_name: str,
                          router_id: Optional[str] = None,
                          handle_metrics: Optional[Dict[str, int]] = None,
                          handle_stats: Optional[Dict[str, float]] = None):
        """Routers poll this; they piggyback their per-replica in-flight
        counts and cumulative request stats (reference: handles PUSH
        metrics to the controller, `autoscaling_state.py` — one RPC
        serves both directions instead of the controller fanning out
        per-replica metric polls)."""
        with self._lock:
            ds = self._apps.get(app_name, {}).get(deployment_name)
            if ds is None:
                return {"version": -1, "replicas": {}}
            if (
                router_id is not None
                and handle_stats is not None
                and handle_stats.get("incarnation") == ds.incarnation
            ):
                # routers report CUMULATIVE counters; the controller
                # folds per-router deltas into monotonic deployment
                # totals so router restarts never decrease the series.
                # Reports against a different incarnation (stale router
                # across a delete+redeploy) are ignored entirely.
                now_mono = time.monotonic()
                key = (app_name, deployment_name)
                last = self._router_stats.setdefault(key, {})
                prev = last.get(router_id, (0.0, {}))[1]
                totals = self._deployment_stats.setdefault(
                    key, {"completed": 0.0, "latency_sum_s": 0.0}
                )
                # "rejected" counts router-side admission rejections
                # (the request never reached a replica, so no replica
                # counter can see it); .get defaults keep pre-overload
                # checkpoints and old routers folding cleanly
                for field_ in ("completed", "latency_sum_s", "rejected"):
                    delta = (handle_stats.get(field_, 0.0)
                             - prev.get(field_, 0.0))
                    if delta > 0:
                        totals[field_] = totals.get(field_, 0.0) + delta
                last[router_id] = (now_mono, dict(handle_stats))
                # dead routers leave permanent per-process entries
                # otherwise (ids are unique per process)
                for rid_, (ts_, _st) in list(last.items()):
                    if now_mono - ts_ > 600.0:
                        del last[rid_]
            if router_id is not None and handle_metrics is not None:
                now = time.monotonic()
                per_router = self._handle_metrics.setdefault(
                    (app_name, deployment_name), {}
                )
                per_router[router_id] = (now, dict(handle_metrics))
                # prune on the write path too: non-autoscaling
                # deployments never reach _pushed_ongoing's sweep, and
                # router ids are unique per client process
                for rid_, (ts, _c) in list(per_router.items()):
                    if now - ts > 60.0:
                        del per_router[rid_]
            return ds.routing_table()

    def get_app_for_route(self, path: str) -> Optional[Dict[str, str]]:
        with self._lock:
            best = None
            for prefix, app in self._routes.items():
                norm = prefix.rstrip("/") or "/"
                if path == norm or path.startswith(norm + "/") or norm == "/":
                    if best is None or len(norm) > len(best[0]):
                        best = (norm, app)
            if best is None:
                return None
            prefix, app = best
            return {"app": app, "ingress": self._ingress[app], "prefix": prefix,
                    "streaming": self._ingress_streaming.get(app, False)}

    def list_applications(self) -> List[str]:
        with self._lock:
            return list(self._apps)

    def get_ingress(self, app_name: str) -> Optional[str]:
        with self._lock:
            return self._ingress.get(app_name)

    def get_serve_status(self) -> Dict[str, Any]:
        with self._lock:
            return {
                app_name: {
                    name: {
                        "target_replicas": ds.target_replicas,
                        "running": sum(
                            1 for r in ds.replicas.values() if r.state == RUNNING
                        ),
                        "version": ds.version,
                        # overload plane: the serve panel shows how
                        # much work this deployment is refusing or
                        # shedding (0/0 when never overloaded)
                        "overload": _overload_summary(
                            ds,
                            self._deployment_stats.get(
                                (app_name, name), {}
                            ).get("rejected", 0.0),
                        ),
                        **{
                            k: self._deployment_stats.get(
                                (app_name, name), {}
                            ).get(k, 0.0)
                            for k in ("completed", "latency_sum_s")
                        },
                        # per-replica load panel for /api/serve: queue
                        # depth plus any user stats() signals (the LLM
                        # engine's per-tick live tokens, block-pool
                        # occupancy, prefix-cache hit rate, ...)
                        "replicas": {
                            rid: {
                                "state": r.state,
                                "ongoing": r.metrics.get("ongoing", 0),
                                "rejected": r.metrics.get("rejected", 0),
                                "queue_depth": _replica_depth(r),
                                **(
                                    {"engine": r.metrics["user_stats"]}
                                    if isinstance(
                                        r.metrics.get("user_stats"), dict
                                    ) else {}
                                ),
                            }
                            for rid, r in ds.replicas.items()
                        },
                    }
                    for name, ds in deployments.items()
                }
                for app_name, deployments in self._apps.items()
            }

    def get_slo_status(self) -> Dict[str, Any]:
        """Per-deployment SLO burn rates (serve/slo.py) for
        rt.slo_status() and the dashboard's /api/slo: configured
        targets, multi-window burn rates folded from the replicas'
        ledger counter blocks, and an ok verdict."""
        from ray_tpu.serve import slo as _slo

        with self._lock:
            return {
                app_name: {
                    name: _slo.status_for(
                        getattr(ds, "slo_tracker", None),
                        getattr(ds.config, "slo_config", None),
                    )
                    for name, ds in deployments.items()
                }
                for app_name, deployments in self._apps.items()
            }

    @staticmethod
    def _forget_slo_replica(ds: _DeploymentState, rid: str):
        """Replica removed: drop its last-seen counter block so a
        replacement reusing the id delta-folds from zero."""
        tracker = getattr(ds, "slo_tracker", None)
        if tracker is not None:
            tracker.forget_replica(rid)

    def ping(self) -> bool:
        return True

    def get_replica_metrics(self) -> Dict[str, Any]:
        """Per-replica request metrics (reference: `serve/metrics.py`
        replica-tagged series), refreshed on the health-check cadence;
        exported as Prometheus series by the dashboard's /metrics."""
        with self._lock:
            return {
                app_name: {
                    name: {
                        rid: dict(r.metrics)
                        for rid, r in ds.replicas.items()
                        if r.metrics
                    }
                    for name, ds in deployments.items()
                }
                for app_name, deployments in self._apps.items()
            }

    # -- routing-table push (reference: serve's long_poll.py) ---------
    def _notify_routes(self, app_name: str, name: str, version: int,
                       deleted: bool = False):
        """Push a table-change notification on the cluster pubsub so
        routers refetch immediately instead of waiting out their poll
        period.  The notification carries only (app, deployment,
        version) — routers fetch the table over the existing RPC, which
        also keeps the metrics piggyback intact."""
        from ray_tpu.core.runtime import get_runtime

        try:
            get_runtime().controller_call("publish", {
                "channel": "serve:routes",
                "msg": {"app": app_name, "deployment": name,
                        "version": version, "deleted": deleted},
            })
        except Exception as e:
            # routers still converge via their periodic refresh
            logger.debug("route-change publish dropped: %s", e)

    # -- per-node proxy fleet -----------------------------------------
    def ensure_proxies(self, host: str, port: int) -> Dict[str, tuple]:
        """Start (or adopt) one HTTP proxy per cluster node (reference:
        `proxy.py:1140` — a ProxyActor on every node).  Returns
        {node_id: (host, port)}.  The reconcile loop keeps the fleet
        matched to cluster membership afterwards."""
        with self._lock:
            self._http_options = (host, port)
        self._reconcile_proxies()
        self._checkpoint()
        with self._lock:
            return {nid: addr for nid, (_h, addr) in self._proxies.items()}

    def get_proxy_addresses(self) -> Dict[str, tuple]:
        with self._lock:
            return {nid: addr for nid, (_h, addr) in self._proxies.items()}

    def _proxy_loop(self):
        while not self._stop.is_set():
            try:
                self._reconcile_proxies()
            except Exception:  # noqa: BLE001 — the loop must survive
                traceback.print_exc()
            self._stop.wait(2.0)

    def _reconcile_proxies(self):
        with self._proxy_lock:
            self._reconcile_proxies_locked()

    def _reconcile_proxies_locked(self):
        import json as _json

        from ray_tpu.core.runtime import get_runtime

        with self._lock:
            opts = self._http_options
        if opts is None:
            return
        host, port = opts
        try:
            nodes = get_runtime().controller_call("get_nodes")
        except Exception as e:
            logger.debug("get_nodes failed (%s); proxy fleet unchanged", e)
            return
        alive = {n["node_id"] for n in nodes if n.get("alive", True)}
        changed = False
        with self._lock:
            fleet = dict(self._proxies)
        # drop proxies whose node died
        for nid in set(fleet) - alive:
            handle, _addr = fleet.pop(nid)
            changed = True
            try:
                rt.kill(handle)
            except Exception as e:
                logger.debug("killing proxy of dead node %s: %s", nid, e)
        # health-check the live fleet; a dead proxy actor is replaced
        for nid, (handle, _addr) in list(fleet.items()):
            try:
                rt.get(handle.num_requests.remote(), timeout=10)
            except Exception as e:
                logger.debug("proxy on %s unhealthy (%s); replacing", nid, e)
                del fleet[nid]
                changed = True
                try:
                    rt.kill(handle)
                except Exception as e2:
                    logger.debug("killing unhealthy proxy: %s", e2)
        for nid in alive - set(fleet):
            # the configured port goes to the FIRST proxy; the rest
            # bind ephemeral ports (nodes share a host in test
            # clusters; on real multi-host fleets every node could
            # use the same fixed port)
            want_port = port if not fleet else 0
            proxy = self._start_proxy(nid, host, want_port)
            if proxy is not None:
                fleet[nid] = proxy
                changed = True
        with self._lock:
            self._proxies = fleet
        if changed:
            addrs = {nid: list(addr) for nid, (_h, addr) in fleet.items()}
            try:
                kv = get_runtime()
                kv.kv_put("serve:http_addresses",
                          _json.dumps(addrs).encode())
                if addrs:  # legacy single-address key: any live proxy
                    first = sorted(addrs)[0]
                    kv.kv_put("serve:http_address",
                              _json.dumps(addrs[first]).encode())
            except Exception as e:
                logger.debug("publishing proxy addresses failed: %s", e)

    def _start_proxy(self, node_id: str, host: str, port: int):
        from ray_tpu.serve.proxy import HTTPProxy
        from ray_tpu.util.scheduling_strategies import (
            NodeAffinitySchedulingStrategy,
        )

        name = f"SERVE_PROXY::{node_id}"
        try:
            # controller restart: adopt the live proxy by name.  start()
            # is idempotent — an adopted-but-never-started proxy (crash
            # between create and start) binds here instead of having
            # its unbound (host, 0) address published
            handle = rt.get_actor(name, CONTROLLER_NAMESPACE)
            bound = rt.get(handle.start.remote(), timeout=30)
            return (handle, (host, bound))
        except ValueError:
            pass
        except Exception as e:
            logger.debug("adopting existing proxy on %s failed: %s",
                         node_id, e)
            return None
        try:
            handle = (
                rt.remote(HTTPProxy)
                .options(
                    name=name,
                    namespace=CONTROLLER_NAMESPACE,
                    max_concurrency=16,
                    num_cpus=0,
                    scheduling_strategy=NodeAffinitySchedulingStrategy(
                        node_id
                    ),
                )
                .remote(host, port)
            )
            bound = rt.get(handle.start.remote(), timeout=30)
            return (handle, (host, bound))
        except Exception:
            traceback.print_exc()
            return None

    # -- reconcile loop ----------------------------------------------
    def _control_loop(self):
        """Reference: the controller's run_control_loop — reconcile +
        health checks + autoscaling on a short period."""
        while not self._stop.is_set():
            try:
                self._reconcile_once()
                self._autoscale()
            except Exception:  # noqa: BLE001 — the loop must survive
                traceback.print_exc()
            self._stop.wait(0.2)

    def _reconcile_once(self):
        with self._lock:
            all_ds = [
                ds
                for deployments in self._apps.values()
                for ds in deployments.values()
            ]
        for ds in all_ds:
            try:
                self._reconcile_deployment(ds)
            except Exception:
                traceback.print_exc()

    def _reconcile_deployment(self, ds: _DeploymentState):
        now = time.monotonic()
        with self._lock:
            if ds.deleted:
                return
            changed = False
            # 1. health-check replicas; replace dead/unresponsive ones
            for rid, r in list(ds.replicas.items()):
                if r.health_ref is None:
                    due = (
                        r.state == STARTING
                        or now - r.health_sent >= ds.config.health_check_period_s
                    )
                    if due:
                        r.health_ref = r.handle.check_health.remote()
                        r.health_sent = now
                    continue
                done, _ = rt.wait([r.health_ref], timeout=0)
                if done:
                    try:
                        reply = rt.get(r.health_ref)
                        if isinstance(reply, dict):
                            r.metrics = reply
                            tracker = getattr(ds, "slo_tracker", None)
                            if tracker is not None:
                                # delta-fold the replica's cumulative
                                # SLO counter block; snapshot() is
                                # internally throttled to >= 1 s
                                tracker.fold(rid, reply.get("slo"))
                                tracker.snapshot()
                        if r.state == STARTING:
                            r.state = RUNNING
                            changed = True
                    except Exception as e:
                        logger.debug("replica %s failed health check: %s",
                                     rid, e)
                        del ds.replicas[rid]
                        changed = True
                        self._forget_slo_replica(ds, rid)
                        self._kill_quietly(r)
                    r.health_ref = None
                elif now - r.health_sent > ds.config.health_check_timeout_s:
                    del ds.replicas[rid]
                    changed = True
                    self._forget_slo_replica(ds, rid)
                    self._kill_quietly(r)
            # 2. scale up to target
            while len(ds.replicas) < ds.target_replicas:
                self._start_replica(ds)
                changed = True
            # 3. scale down from target (newest first)
            excess = len(ds.replicas) - ds.target_replicas
            victims: List[_ReplicaState] = []
            if excess > 0:
                order = sorted(
                    ds.replicas, key=lambda rid: int(rid.rsplit("#", 1)[1])
                )
                for rid in order[-excess:]:
                    victims.append(ds.replicas.pop(rid))
                    self._forget_slo_replica(ds, rid)
                changed = True
            if changed:
                ds.version += 1
        if changed:
            # publish the shrunk table BEFORE draining scale-down
            # victims: routers must stop admitting new requests to a
            # draining replica, so the graceful window is spent on
            # genuinely in-flight work.  A stale-table straggler that
            # still lands on a victim either executes normally (no
            # drain hook) or — once `__serve_drain__` has told the
            # callable to stop admitting, as the LLM engine does —
            # gets a typed, retryable BackPressureError (503 +
            # Retry-After at the proxy) rather than being silently
            # dropped with the replica
            self._checkpoint()
            self._notify_routes(ds.app_name, ds.name, ds.version)
        for r in victims:
            self._stop_replica(r, timeout_s=ds.config.graceful_shutdown_timeout_s)

    def _start_replica(self, ds: _DeploymentState):
        rid = f"{ds.app_name}#{ds.name}#{ds.next_replica_idx}"
        ds.next_replica_idx += 1
        opts = dict(ds.resources)
        opts.setdefault("num_cpus", 0)
        handle = (
            rt.remote(Replica)
            .options(
                # named so a restarted controller can re-adopt live
                # replicas instead of orphaning them (reference:
                # recovery from checkpoint re-binds replica actors)
                name=f"SERVE_REPLICA::{rid}",
                namespace=CONTROLLER_NAMESPACE,
                # headroom over max_ongoing_requests so control-plane
                # methods (health checks, metrics, drain) never starve
                # behind a full complement of user requests — the data
                # plane is already capped by the router's per-replica
                # in-flight accounting
                max_concurrency=ds.config.max_ongoing_requests + 4,
                **opts,
            )
            .remote(
                ds.name,
                rid,
                ds.callable_def,
                tuple(ds.init_args),
                dict(ds.init_kwargs),
                user_config=ds.config.user_config,
                max_ongoing_requests=ds.config.max_ongoing_requests,
            )
        )
        ds.replicas[rid] = _ReplicaState(rid, handle, ds.config.max_ongoing_requests)

    def _stop_replica(self, r: _ReplicaState, timeout_s: float):
        try:
            ref = r.handle.drain.remote(timeout_s)
            rt.wait([ref], timeout=timeout_s + 1.0)
        except Exception as e:
            logger.debug("drain of %s failed: %s", r.replica_id, e)
        self._kill_quietly(r)

    def _kill_quietly(self, r: _ReplicaState):
        try:
            rt.kill(r.handle)
        except Exception as e:
            logger.debug("killing replica %s: %s", r.replica_id, e)

    # -- autoscaling --------------------------------------------------
    def _autoscale(self):
        """Reference: `autoscaling_state.py` + `serve/autoscaling_policy.py`
        — desired = ceil(current * (ongoing/replica) / target_ongoing).

        Deployments with an SLO-configured AutoscalingConfig
        (`target_ttft_s` / `target_queue_depth`) use the SLO policy
        instead (`serve/autoscaling.py`): the decision consumes ONLY
        controller-collected per-replica stats (the health-check
        piggyback — queue depth, TTFT EMA, shed counters), normalized
        to a load ratio that is smoothed over the same look-back
        window and gated by the same cooldowns."""
        with self._lock:
            all_ds = [
                ds
                for deployments in self._apps.values()
                for ds in deployments.values()
            ]
        for ds in all_ds:
            ac = ds.config.autoscaling_config
            if ac is None:
                continue
            with self._lock:
                running = [
                    r for r in ds.replicas.values() if r.state == RUNNING
                ]
            if not running:
                continue
            if getattr(ds, "policy", None) is not None:
                self._autoscale_slo(ds, ac, running)
                continue
            total_ongoing = self._pushed_ongoing(ds, ac)
            if total_ongoing is None:
                # no router has pushed metrics recently (e.g. handles in
                # threads that went quiet): fall back to polling the
                # replicas directly — O(replicas) RPCs, but only on this
                # cold path rather than every tick
                refs = [r.handle.get_metrics.remote() for r in running]
                done, _ = rt.wait(refs, num_returns=len(refs), timeout=1.0)
                if not done:
                    # no metrics observed (busy/unreachable replicas) is
                    # not evidence of zero load — hold the current target
                    continue
                total_ongoing = 0.0
                for ref in done:
                    try:
                        total_ongoing += rt.get(ref)["ongoing"]
                    except Exception as e:
                        logger.debug("ongoing-count probe failed: %s", e)
            now = time.monotonic()
            # smooth over look_back_period_s (reference: the autoscaling
            # policy averages handle metrics over a look-back window) so
            # a single idle instant between request waves can't trigger
            # a downscale
            window = ds.samples = [
                (ts, v)
                for ts, v in ds.samples
                if now - ts < ac.look_back_period_s
            ] + [(now, total_ongoing)]
            avg_ongoing = sum(v for _, v in window) / len(window)
            desired = ac.desired_replicas(avg_ongoing, len(running))
            with self._lock:
                delay = (
                    ac.upscale_delay_s
                    if desired > ds.target_replicas
                    else ac.downscale_delay_s
                )
                if desired != ds.target_replicas:
                    if now - ds.last_scale_change >= delay:
                        ds.target_replicas = desired
                        ds.last_scale_change = now
                else:
                    ds.last_scale_change = now

    def _autoscale_slo(self, ds: _DeploymentState, ac, running):
        """One SLO-policy scaling decision: instantaneous pressure from
        the replicas' piggybacked metrics, smoothed over the look-back
        window, pushed through the hysteresis/cooldown gates."""
        now = time.monotonic()
        with self._lock:
            metrics = [dict(r.metrics) for r in running]
        ratio = ds.policy.pressure(metrics)
        window = ds.samples = [
            (ts, v)
            for ts, v in ds.samples
            if now - ts < ac.look_back_period_s
        ] + [(now, ratio)]
        avg_ratio = sum(v for _, v in window) / len(window)
        if ds.policy.refusal_forced:
            # fresh sheds/rejections BYPASS the smoothing window: the
            # deployment is refusing work NOW, and averaging a forced
            # above-band sample into a quiet look-back would dilute it
            # below the band — clients would keep eating 503s for a
            # whole window before any scale-out.  The upscale cooldown
            # still rate-limits the reaction.
            avg_ratio = max(avg_ratio, ratio)
        desired = ds.policy.desired_replicas(avg_ratio, len(running))
        with self._lock:
            delay = (
                ac.upscale_delay_s
                if desired > ds.target_replicas
                else ac.downscale_delay_s
            )
            if desired != ds.target_replicas:
                if now - ds.last_scale_change >= delay:
                    logger.info(
                        "SLO autoscale %s/%s: ratio=%.2f (avg %.2f) "
                        "replicas %d -> %d",
                        ds.app_name, ds.name, ratio, avg_ratio,
                        ds.target_replicas, desired,
                    )
                    ds.target_replicas = desired
                    ds.last_scale_change = now
            else:
                ds.last_scale_change = now

    def _pushed_ongoing(self, ds: _DeploymentState, ac) -> Optional[float]:
        """Sum of router-pushed in-flight counts for a deployment, or
        None when every router's report is stale (reference: handle
        metrics drive autoscaling, `autoscaling_state.py`)."""
        now = time.monotonic()
        with self._lock:
            per_router = self._handle_metrics.get((ds.app_name, ds.name))
            if not per_router:
                return None
            horizon = max(2.0, ac.look_back_period_s)
            # prune dead routers here (router ids are unique per process;
            # without pruning the map grows for the controller's life)
            for router_id, (ts, _counts) in list(per_router.items()):
                if now - ts > 10 * horizon:
                    del per_router[router_id]
            fresh = {
                router_id: counts
                for router_id, (ts, counts) in per_router.items()
                if now - ts < horizon
            }
            if not fresh:
                return None
            live = set(ds.replicas)
            return float(sum(
                n for counts in fresh.values()
                for rid, n in counts.items()
                if rid in live
            ))
