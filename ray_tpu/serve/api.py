"""Serve public API: @deployment, bind, run, status, shutdown.

Reference: `python/ray/serve/api.py` (`@serve.deployment:244`,
`serve.run:510`) — deployments are declared with a decorator, composed
into applications with `.bind()`, and deployed by `serve.run`, which
returns a handle to the ingress deployment.
"""

from __future__ import annotations

import inspect
import json
import logging
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Union

import ray_tpu as rt
from ray_tpu.serve.config import AutoscalingConfig, DeploymentConfig, GRPCOptions, HTTPOptions
from ray_tpu.serve.slo import SLOConfig
from ray_tpu.serve.controller import (
    CONTROLLER_NAME,
    CONTROLLER_NAMESPACE,
    ServeController,
)
from ray_tpu.serve.handle import DeploymentHandle
from ray_tpu.util import sanitizer as _sanitizer

logger = logging.getLogger(__name__)

_state: Dict[str, Any] = {}
# outermost in the declared order: start() holds it across rt.get()
# while the controller ping round-trips, so runtime._state_lock nests
# inside it (see ray_tpu/util/sanitizer.py for the full order table)
_state_lock = _sanitizer.wrap_lock(
    threading.Lock(), "serve.api._state_lock", _sanitizer.SERVE_STATE_LOCK
)


# ----------------------------------------------------------------------
# deployment declaration
# ----------------------------------------------------------------------
class Application:
    """A bound deployment graph node (reference: the object returned by
    `Deployment.bind`, `serve/deployment.py`)."""

    def __init__(self, deployment: "Deployment", args: tuple, kwargs: dict):
        self.deployment = deployment
        self.args = args
        self.kwargs = kwargs


class Deployment:
    """Reference: `serve/deployment.py` Deployment."""

    def __init__(self, func_or_class, name: str, config: DeploymentConfig,
                 resources: Optional[Dict[str, float]] = None):
        self.func_or_class = func_or_class
        self.name = name
        self.config = config
        self.resources = resources or {}

    def bind(self, *args, **kwargs) -> Application:
        return Application(self, args, kwargs)

    def options(self, **kwargs) -> "Deployment":
        import copy

        cfg = copy.deepcopy(self.config)
        name = kwargs.pop("name", self.name)
        resources = kwargs.pop("ray_actor_options", None) or kwargs.pop(
            "resources", None
        )
        for k, v in kwargs.items():
            if k == "autoscaling_config":
                v = _coerce_autoscaling(v)
            if k == "slo_config":
                v = _coerce_slo(v)
            if hasattr(cfg, k):
                setattr(cfg, k, v)
            else:
                raise TypeError(f"unknown deployment option {k!r}")
        return Deployment(
            self.func_or_class, name, cfg,
            dict(resources) if resources else dict(self.resources),
        )

    def __call__(self, *a, **k):
        raise TypeError(
            "deployments are not directly callable; use .bind() and serve.run"
        )


def _coerce_autoscaling(v) -> Optional[AutoscalingConfig]:
    if v is None or isinstance(v, AutoscalingConfig):
        return v
    return AutoscalingConfig(**v)


def _coerce_slo(v) -> Optional[SLOConfig]:
    if v is None or isinstance(v, SLOConfig):
        return v
    return SLOConfig(**v)


def deployment(
    _func_or_class: Optional[Callable] = None,
    *,
    name: Optional[str] = None,
    num_replicas: Union[int, str, None] = None,
    max_ongoing_requests: int = 16,
    max_queued_requests: int = -1,
    autoscaling_config: Union[AutoscalingConfig, dict, None] = None,
    slo_config: Union[SLOConfig, dict, None] = None,
    user_config: Optional[Any] = None,
    health_check_period_s: float = 2.0,
    health_check_timeout_s: float = 10.0,
    graceful_shutdown_timeout_s: float = 5.0,
    ray_actor_options: Optional[Dict[str, float]] = None,
):
    """Reference: `serve/api.py:244` @serve.deployment."""

    def _wrap(func_or_class):
        n = num_replicas
        auto = _coerce_autoscaling(autoscaling_config)
        if n == "auto":
            auto = auto or AutoscalingConfig(min_replicas=1, max_replicas=8)
            n = None
        cfg = DeploymentConfig(
            num_replicas=n or 1,
            max_ongoing_requests=max_ongoing_requests,
            max_queued_requests=max_queued_requests,
            autoscaling_config=auto,
            slo_config=_coerce_slo(slo_config),
            user_config=user_config,
            health_check_period_s=health_check_period_s,
            health_check_timeout_s=health_check_timeout_s,
            graceful_shutdown_timeout_s=graceful_shutdown_timeout_s,
        )
        return Deployment(
            func_or_class,
            name or getattr(func_or_class, "__name__", "deployment"),
            cfg,
            ray_actor_options,
        )

    if _func_or_class is not None:
        return _wrap(_func_or_class)
    return _wrap


def ingress(_app=None, **_kwargs):
    """FastAPI-style ingress adapter is out of scope; the proxy hands
    plain `serve.Request` objects to the ingress deployment."""

    def _wrap(cls):
        return cls

    return _wrap if _app is None else _app


# ----------------------------------------------------------------------
# controller / proxy lifecycle
# ----------------------------------------------------------------------
def start(http_options: Optional[HTTPOptions] = None, *, proxy: bool = True,
          grpc_options: Optional[Union[GRPCOptions, Dict[str, Any]]] = None):
    """Start the serve control plane (reference: `serve/api.py` serve.start).

    grpc_options (a `GRPCOptions` or `{"host", "port"}` dict) starts
    the generic gRPC ingress alongside HTTP (reference: `gRPCProxy`,
    `proxy.py:545`; see `serve/grpc_proxy.py` for the routing
    contract)."""
    with _state_lock:
        # stale module state survives a full runtime shutdown+restart in
        # the same process (the cached handles point into the DEAD
        # cluster) — validate before reuse, reset if the controller is
        # gone
        c = _state.get("controller")
        if c is not None:
            try:
                rt.get(c.ping.remote(), timeout=10)
            except Exception as e:
                logger.debug("cached serve controller dead (%s); "
                             "resetting serve state", e)
                _state.clear()
                from ray_tpu.serve import handle as _handle_mod

                _handle_mod._close_routers()
        if "controller" not in _state:
            try:
                controller = rt.get_actor(CONTROLLER_NAME, CONTROLLER_NAMESPACE)
            except ValueError:
                controller = (
                    rt.remote(ServeController)
                    .options(
                        name=CONTROLLER_NAME,
                        namespace=CONTROLLER_NAMESPACE,
                        max_concurrency=16,
                        num_cpus=0,
                        # effectively infinite: a crashed controller
                        # restarts and rehydrates from its KV checkpoint
                        # (reference: `controller.py:81-91` recovery)
                        max_restarts=1_000_000_000,
                    )
                    .remote()
                )
                rt.get(controller.ping.remote())
            _state["controller"] = controller
        if proxy and "proxy_fleet" not in _state:
            # per-node proxy fleet (reference: `proxy.py:1140` — one
            # ProxyActor per node): the controller starts/adopts one
            # HTTP proxy per cluster node and keeps the fleet matched
            # to membership in its reconcile loop; addresses land in
            # the KV (`serve:http_addresses`) for discovery
            opts = http_options or HTTPOptions(port=0)
            addrs = rt.get(
                _state["controller"].ensure_proxies.remote(
                    opts.host, opts.port
                ),
                timeout=60,
            )
            _state["proxy_fleet"] = True
            if addrs:
                first = sorted(addrs)[0]
                _state["http_address"] = tuple(addrs[first])
        if grpc_options is not None and "grpc_proxy" not in _state:
            from ray_tpu.serve.config import GRPCOptions
            from ray_tpu.serve.grpc_proxy import GRPCProxy

            if isinstance(grpc_options, dict):
                gopts = GRPCOptions(**grpc_options)
            else:
                gopts = grpc_options
            from ray_tpu.core.runtime import get_runtime

            try:  # another process may already run it (same pattern
                # as the controller above); failed starts leave a
                # named actor that must be reaped before retrying
                gp = rt.get_actor("SERVE_GRPC_PROXY", CONTROLLER_NAMESPACE)
                gport = rt.get(gp.address.remote())[1]
            except ValueError:
                gp = (
                    rt.remote(GRPCProxy)
                    .options(
                        name="SERVE_GRPC_PROXY",
                        namespace=CONTROLLER_NAMESPACE,
                        max_concurrency=16,
                        num_cpus=0,
                    )
                    .remote(gopts.host, gopts.port)
                )
                try:
                    gport = rt.get(gp.start.remote())
                except Exception:
                    rt.kill(gp)
                    raise
            _state["grpc_proxy"] = gp
            _state["grpc_address"] = (gopts.host, gport)
            get_runtime().kv_put(
                "serve:grpc_address",
                json.dumps([gopts.host, gport]).encode(),
            )
    return _state["controller"]


def _get_controller():
    c = _state.get("controller")
    if c is not None:
        return c
    c = rt.get_actor(CONTROLLER_NAME, CONTROLLER_NAMESPACE)
    _state["controller"] = c
    return c


async def _get_controller_async():
    """Loop-thread-safe controller lookup (used by routers/proxies from
    the runtime's io loop, where blocking `rt.get_actor` would deadlock)."""
    c = _state.get("controller")
    if c is not None:
        return c
    from ray_tpu.api import ActorHandle
    from ray_tpu.core.ids import ActorID
    from ray_tpu.core.runtime import get_runtime

    info = await get_runtime().controller.call(
        "get_actor", {"name": CONTROLLER_NAME, "namespace": CONTROLLER_NAMESPACE}
    )
    if info is None or info.get("state") == "DEAD":
        raise RuntimeError("serve controller is not running")
    c = ActorHandle(
        ActorID(info["actor_id"]), info["address"], CONTROLLER_NAME,
        info.get("max_task_retries", 0),
    )
    _state["controller"] = c
    return c


def _discover_address(state_key: str, kv_key: str) -> Optional[tuple]:
    """Cached ingress address; a proxy started by ANOTHER process (REST
    deploy via the dashboard) is discovered through the controller KV."""
    addr = _state.get(state_key)
    if addr is not None:
        return addr
    from ray_tpu.core.runtime import get_runtime, is_initialized

    if not is_initialized():
        return None
    raw = get_runtime().kv_get(kv_key)
    if raw:
        host, port = json.loads(raw)
        _state[state_key] = (host, int(port))
        return _state[state_key]
    return None


def http_address() -> Optional[tuple]:
    return _discover_address("http_address", "serve:http_address")


def http_addresses() -> Dict[str, tuple]:
    """All live proxy addresses, one per cluster node (reference:
    per-node ProxyActors): {node_id: (host, port)}.  Uncached — the
    fleet changes with cluster membership."""
    from ray_tpu.core.runtime import get_runtime, is_initialized

    if not is_initialized():
        return {}
    raw = get_runtime().kv_get("serve:http_addresses")
    if not raw:
        return {}
    return {
        nid: (host, int(port))
        for nid, (host, port) in json.loads(raw).items()
    }


def grpc_address() -> Optional[tuple]:
    return _discover_address("grpc_address", "serve:grpc_address")


# ----------------------------------------------------------------------
# run / shutdown
# ----------------------------------------------------------------------
def _collect_deployments(app: Application, out: Dict[str, dict]):
    """Post-order walk of the bound graph: nested Applications become
    DeploymentHandles passed to the parent's constructor (reference:
    build_app in `serve/_private/build_app.py`)."""

    def _convert(v, app_name):
        if isinstance(v, Application):
            _collect(v)
            return DeploymentHandle(v.deployment.name, app_name)
        return v

    app_name = out["__app_name__"]

    def _collect(node: Application):
        d = node.deployment
        args = tuple(_convert(a, app_name) for a in node.args)
        kwargs = {k: _convert(v, app_name) for k, v in node.kwargs.items()}
        if d.name in out and out[d.name]["callable_def"] is not d.func_or_class:
            raise ValueError(f"duplicate deployment name {d.name!r}")
        out[d.name] = {
            "name": d.name,
            "callable_def": d.func_or_class,
            "init_args": args,
            "init_kwargs": kwargs,
            "config": d.config,
            "resources": d.resources,
        }

    _collect(app)


def _callable_is_streaming(func_or_class) -> bool:
    """True when the deployment's request entrypoint is a generator /
    async generator: its HTTP responses stream chunked."""
    c = func_or_class
    if isinstance(c, type):
        c = inspect.getattr_static(c, "__call__", None)
    return inspect.isgeneratorfunction(c) or inspect.isasyncgenfunction(c)


def run(
    target: Application,
    *,
    name: str = "default",
    route_prefix: Optional[str] = "/",
    wait_for_ready: bool = True,
    timeout_s: float = 60.0,
) -> DeploymentHandle:
    """Deploy an application and return a handle to its ingress
    (reference: `serve/api.py:510` serve.run)."""
    if not isinstance(target, Application):
        raise TypeError("serve.run expects the Application from .bind()")
    from ray_tpu.util.usage_stats import record_library_usage

    record_library_usage("serve")
    controller = start(proxy=True)
    collected: Dict[str, Any] = {"__app_name__": name}
    _collect_deployments(target, collected)
    collected.pop("__app_name__")
    app_config = {
        "name": name,
        "route_prefix": route_prefix,
        "ingress": target.deployment.name,
        "ingress_streaming": _callable_is_streaming(
            target.deployment.func_or_class
        ),
        "deployments": list(collected.values()),
    }
    rt.get(controller.deploy_application.remote(app_config), timeout=timeout_s)
    if wait_for_ready:
        _wait_for_app(controller, name, timeout_s)
    return DeploymentHandle(target.deployment.name, name)


def _wait_for_app(controller, name: str, timeout_s: float):
    deadline = time.time() + timeout_s
    while time.time() < deadline:
        status = rt.get(controller.get_serve_status.remote())
        app = status.get(name, {})
        if app and all(
            d["running"] >= 1 and d["running"] >= d["target_replicas"]
            for d in app.values()
        ):
            return
        time.sleep(0.1)
    raise TimeoutError(f"application {name!r} did not become ready")


def delete(name: str):
    controller = _get_controller()
    rt.get(controller.delete_application.remote(name))


def status() -> Dict[str, Any]:
    controller = _get_controller()
    return rt.get(controller.get_serve_status.remote())


def slo_status() -> Dict[str, Any]:
    """Per-deployment SLO burn rates: {app: {deployment: row}} where
    row carries the configured targets, multi-window burn rates folded
    from the replicas' ledger counters, and an `ok` verdict (see
    serve/slo.py).  Deployments without an `slo_config` report
    {"configured": False}."""
    controller = _get_controller()
    return rt.get(controller.get_slo_status.remote())


def get_app_handle(name: str = "default") -> DeploymentHandle:
    controller = _get_controller()
    ingress = rt.get(controller.get_ingress.remote(name))
    if ingress is None:
        raise ValueError(f"no application named {name!r}")
    return DeploymentHandle(ingress, name)


def get_deployment_handle(deployment_name: str, app_name: str = "default"):
    return DeploymentHandle(deployment_name, app_name)


def shutdown():
    """Tear down all applications, the proxy, and the controller."""
    with _state_lock:
        controller = _state.pop("controller", None)
        proxy = _state.pop("proxy", None)
        grpc_proxy = _state.pop("grpc_proxy", None)
        _state.pop("proxy_fleet", None)
        _state.pop("http_address", None)
        _state.pop("grpc_address", None)
    from ray_tpu.serve import handle as _handle_mod

    _handle_mod._close_routers()
    # the control plane may have been started by ANOTHER process (REST
    # deploy via the dashboard): resolve the named actors so shutdown
    # tears them down from anywhere
    if controller is None:
        try:
            controller = rt.get_actor(CONTROLLER_NAME, CONTROLLER_NAMESPACE)
        except Exception as e:
            logger.debug("no serve controller to shut down: %s", e)
            controller = None
    fleet_proxies: List[Any] = []
    if proxy is None:
        try:  # legacy single-proxy deployments
            proxy = rt.get_actor("SERVE_PROXY", CONTROLLER_NAMESPACE)
        except Exception as e:
            logger.debug("no legacy proxy to shut down: %s", e)
            proxy = None
        # per-node fleet: resolvable from anywhere via the KV address
        # map even when the controller itself is unreachable
        try:
            from ray_tpu.core.runtime import get_runtime, is_initialized

            if is_initialized():
                raw = get_runtime().kv_get("serve:http_addresses")
                for nid in (json.loads(raw) if raw else {}):
                    try:
                        fleet_proxies.append(rt.get_actor(
                            f"SERVE_PROXY::{nid}", CONTROLLER_NAMESPACE
                        ))
                    except Exception as e:
                        logger.debug("fleet proxy %s gone: %s", nid, e)
        except Exception as e:
            logger.debug("fleet proxy discovery failed: %s", e)
    if grpc_proxy is None:
        try:
            grpc_proxy = rt.get_actor("SERVE_GRPC_PROXY",
                                      CONTROLLER_NAMESPACE)
        except Exception as e:
            logger.debug("no grpc proxy to shut down: %s", e)
            grpc_proxy = None
    try:
        from ray_tpu.core.runtime import get_runtime, is_initialized

        if is_initialized():
            get_runtime().kv_del("serve:http_address")
            get_runtime().kv_del("serve:http_addresses")
            get_runtime().kv_del("serve:grpc_address")
    except Exception as e:
        logger.debug("clearing serve address keys failed: %s", e)
    for p in (proxy, grpc_proxy, *fleet_proxies):
        if p is not None:
            try:
                rt.get(p.stop.remote(), timeout=5)
            except Exception as e:
                logger.debug("proxy stop failed: %s", e)
            try:
                rt.kill(p)
            except Exception as e:
                logger.debug("proxy kill failed: %s", e)
    if controller is not None:
        try:
            rt.get(controller.shutdown.remote(), timeout=30)
        except Exception as e:
            logger.debug("controller shutdown call failed: %s", e)
        try:
            rt.kill(controller)
        except Exception as e:
            logger.debug("controller kill failed: %s", e)
    # clear the FT snapshot only once the controller is dead: its own
    # _checkpoint calls would recreate the key, and a timed-out teardown
    # must not leave a snapshot that resurrects deleted apps on the next
    # serve.start()
    try:
        from ray_tpu.core.runtime import get_runtime, is_initialized
        from ray_tpu.serve.controller import STATE_KV_KEY

        if is_initialized():
            get_runtime().kv_del(STATE_KV_KEY)
    except Exception as e:
        logger.debug("clearing serve FT snapshot failed: %s", e)
    from ray_tpu.serve import handle as _h

    _h._close_routers()
