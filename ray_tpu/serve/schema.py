"""Declarative Serve config schema for REST / CLI deploys.

Reference: `python/ray/serve/schema.py` — `ServeDeploySchema` /
`ServeApplicationSchema` / `DeploymentSchema` / `RayActorOptionsSchema`,
the pydantic-validated document accepted by `serve deploy` and the
dashboard REST API.  Same document shape here (multi-app config with
per-deployment overrides applied on top of the code's `@serve.deployment`
settings), validated with pydantic v2.
"""

from __future__ import annotations

import importlib
import sys
from typing import Any, Dict, List, Optional, Union

from pydantic import BaseModel, ConfigDict, Field, field_validator

from ray_tpu.serve.config import AutoscalingConfig, LLMEngineConfig


class LLMEngineSchema(BaseModel):
    """Declarative knobs for the continuous-batching LLM engine —
    the validated form of `config.LLMEngineConfig`, accepted in a
    deployment's `user_config` (ContinuousLlamaService applies it via
    `engine_config=`) or anywhere a deploy document wants to pin the
    decode/quantization plane (`decode_kernel`, `kv_dtype`,
    `weight_dtype`) alongside the batching shape."""

    model_config = ConfigDict(extra="forbid")

    slots: int = Field(default=32, ge=1)
    chunk: int = Field(default=8, ge=1)
    max_len: Optional[int] = Field(default=None, ge=2)
    block_size: int = Field(default=16, ge=1)
    kv_blocks: Optional[int] = Field(default=None, ge=1)
    prefix_cache: bool = True
    max_queued: Optional[int] = Field(default=None, ge=0)
    decode_kernel: str = "auto"
    kv_dtype: str = "model"
    weight_dtype: str = "model"
    chunk_cache_cap: int = Field(default=8, ge=1)

    @field_validator("decode_kernel")
    @classmethod
    def _kernel_valid(cls, v):
        if v not in ("auto", "pallas", "gather"):
            raise ValueError(
                'decode_kernel must be "auto", "pallas" or "gather"'
            )
        return v

    @field_validator("kv_dtype", "weight_dtype")
    @classmethod
    def _dtype_valid(cls, v):
        if v not in ("model", "int8"):
            raise ValueError('dtype knobs must be "model" or "int8"')
        return v

    def to_config(self) -> LLMEngineConfig:
        return LLMEngineConfig(**self.model_dump()).validate()


class RayActorOptionsSchema(BaseModel):
    """Per-replica actor resources (reference: `schema.py`
    RayActorOptionsSchema)."""

    model_config = ConfigDict(extra="forbid")

    num_cpus: Optional[float] = None
    num_tpus: Optional[float] = None
    memory: Optional[float] = None
    resources: Dict[str, float] = Field(default_factory=dict)
    runtime_env: Optional[Dict[str, Any]] = None

    def to_actor_options(self) -> Dict[str, Any]:
        """Option-style dict splatted into the replica actor's
        `.options(**...)` (the shape `@serve.deployment
        ray_actor_options` takes) — runtime_env rides through as a real
        actor option, not a resource."""
        out: Dict[str, Any] = {}
        for f in ("num_cpus", "num_tpus", "memory", "runtime_env"):
            v = getattr(self, f)
            if v is not None:
                out[f] = v
        if self.resources:
            out["resources"] = dict(self.resources)
        return out


class AutoscalingConfigSchema(BaseModel):
    model_config = ConfigDict(extra="forbid")

    min_replicas: int = Field(default=1, ge=0)
    max_replicas: int = Field(default=1, ge=1)
    target_ongoing_requests: float = Field(default=2.0, gt=0)
    upscale_delay_s: float = Field(default=0.5, ge=0)
    downscale_delay_s: float = Field(default=2.0, ge=0)
    metrics_interval_s: float = Field(default=0.2, gt=0)
    look_back_period_s: float = Field(default=2.0, gt=0)
    # SLO-driven policy (serve/autoscaling.py): either target opts in
    target_ttft_s: Optional[float] = Field(default=None, gt=0)
    target_queue_depth: Optional[float] = Field(default=None, gt=0)
    hysteresis: float = Field(default=0.1, ge=0, lt=1)

    @field_validator("max_replicas")
    @classmethod
    def _max_ge_min(cls, v, info):
        if "min_replicas" in info.data and v < info.data["min_replicas"]:
            raise ValueError("max_replicas must be >= min_replicas")
        return v

    def to_config(self) -> AutoscalingConfig:
        return AutoscalingConfig(**self.model_dump())


class DeploymentSchema(BaseModel):
    """Overrides for one named deployment (reference: `schema.py`
    DeploymentSchema).  Only fields the user sets are applied on top of
    the code's `@serve.deployment` values."""

    model_config = ConfigDict(extra="forbid")

    name: str
    num_replicas: Union[int, str, None] = None
    max_ongoing_requests: Optional[int] = Field(default=None, gt=0)
    max_queued_requests: Optional[int] = None
    autoscaling_config: Optional[AutoscalingConfigSchema] = None
    user_config: Optional[Any] = None
    health_check_period_s: Optional[float] = Field(default=None, gt=0)
    health_check_timeout_s: Optional[float] = Field(default=None, gt=0)
    graceful_shutdown_timeout_s: Optional[float] = Field(default=None, ge=0)
    ray_actor_options: Optional[RayActorOptionsSchema] = None

    @field_validator("num_replicas")
    @classmethod
    def _replicas_valid(cls, v):
        if isinstance(v, str) and v != "auto":
            raise ValueError('num_replicas must be an int or "auto"')
        if isinstance(v, int) and v < 0:
            raise ValueError("num_replicas must be >= 0")
        return v

    def override_kwargs(self) -> Dict[str, Any]:
        """Kwargs for `Deployment.options()` — only the fields set."""
        out: Dict[str, Any] = {}
        for f in ("num_replicas", "max_ongoing_requests",
                  "max_queued_requests", "user_config",
                  "health_check_period_s", "health_check_timeout_s",
                  "graceful_shutdown_timeout_s"):
            v = getattr(self, f)
            if v is not None:
                out[f] = v
        if self.autoscaling_config is not None:
            out["autoscaling_config"] = self.autoscaling_config.to_config()
        if out.get("num_replicas") == "auto":
            out.pop("num_replicas")
            out.setdefault(
                "autoscaling_config",
                AutoscalingConfig(min_replicas=1, max_replicas=8),
            )
        if self.ray_actor_options is not None:
            out["ray_actor_options"] = (
                self.ray_actor_options.to_actor_options()
            )
        return out


class ServeApplicationSchema(BaseModel):
    """One application: where to import it and what to override
    (reference: `schema.py` ServeApplicationSchema)."""

    model_config = ConfigDict(extra="forbid")

    name: str = "default"
    route_prefix: Optional[str] = "/"
    import_path: str
    import_dirs: List[str] = Field(default_factory=list)
    args: Dict[str, Any] = Field(default_factory=dict)
    deployments: List[DeploymentSchema] = Field(default_factory=list)

    @field_validator("import_path")
    @classmethod
    def _import_path_valid(cls, v):
        mod, sep, var = v.partition(":")
        if not (mod and sep and var):
            raise ValueError(
                'import_path must be "module.submodule:variable"'
            )
        return v

    @field_validator("deployments")
    @classmethod
    def _unique_names(cls, v):
        names = [d.name for d in v]
        if len(names) != len(set(names)):
            raise ValueError("duplicate deployment names in overrides")
        return v


class ServeDeploySchema(BaseModel):
    """The whole declarative deploy document (reference: `schema.py`
    ServeDeploySchema): a list of applications with unique names and
    non-overlapping route prefixes."""

    model_config = ConfigDict(extra="forbid")

    applications: List[ServeApplicationSchema]

    @field_validator("applications")
    @classmethod
    def _apps_consistent(cls, v):
        names = [a.name for a in v]
        if len(names) != len(set(names)):
            raise ValueError("duplicate application names")
        prefixes = [a.route_prefix for a in v if a.route_prefix]
        if len(prefixes) != len(set(prefixes)):
            raise ValueError("duplicate route_prefix across applications")
        return v


# ----------------------------------------------------------------------
# schema -> running application
# ----------------------------------------------------------------------
def _rewrite_with_overrides(app, overrides: Dict[str, Dict[str, Any]]):
    """Return a copy of the bound graph with `.options(**ov)` applied to
    every deployment named in `overrides` (reference: config overrides
    merged over code defaults in `application_state.py` build)."""
    from ray_tpu.serve.api import Application

    def _rewrite(node: Application) -> Application:
        args = tuple(
            _rewrite(a) if isinstance(a, Application) else a
            for a in node.args
        )
        kwargs = {
            k: _rewrite(v) if isinstance(v, Application) else v
            for k, v in node.kwargs.items()
        }
        d = node.deployment
        ov = overrides.get(d.name)
        if ov:
            d = d.options(**ov)
        return Application(d, args, kwargs)

    return _rewrite(app)


def build_application(schema: ServeApplicationSchema):
    """Import the app named by import_path, apply argument binding and
    per-deployment overrides.  Returns the Application to pass to
    `serve.run`."""
    added = []
    for d in schema.import_dirs:
        if d not in sys.path:
            sys.path.insert(0, d)
            added.append(d)
    try:
        mod_name, _, var = schema.import_path.partition(":")
        if mod_name in sys.modules:
            # redeploy must see edited code, not the import cache
            mod = importlib.reload(sys.modules[mod_name])
        else:
            mod = importlib.import_module(mod_name)
        target = getattr(mod, var)
    finally:
        for d in added:
            try:
                sys.path.remove(d)
            except ValueError:
                pass
    from ray_tpu.serve.api import Application, Deployment

    if isinstance(target, Deployment):
        target = target.bind(**schema.args)
    elif callable(target) and not isinstance(target, Application):
        # app-builder function taking the args dict (reference:
        # `serve/api.py` build callable support)
        target = target(schema.args) if schema.args else target({})
    if not isinstance(target, Application):
        raise TypeError(
            f"{schema.import_path} is not an Application/Deployment/builder"
        )
    overrides = {
        d.name: d.override_kwargs() for d in schema.deployments
    }
    if overrides:
        target = _rewrite_with_overrides(target, overrides)
    return target


def deploy_from_schema(doc: Union[ServeDeploySchema, dict]) -> List[str]:
    """Validate + deploy every application in the document; returns the
    deployed app names.  The REST `PUT /api/serve/applications` body
    lands here (reference: `dashboard/modules/serve/serve_head.py`)."""
    from ray_tpu import serve

    if not isinstance(doc, ServeDeploySchema):
        doc = ServeDeploySchema.model_validate(doc)
    names = []
    for app_schema in doc.applications:
        app = build_application(app_schema)
        serve.run(
            app,
            name=app_schema.name,
            route_prefix=app_schema.route_prefix,
        )
        names.append(app_schema.name)
    return names
