"""Serve configuration dataclasses.

Mirrors the reference's `python/ray/serve/config.py` (`DeploymentConfig`,
`AutoscalingConfig`, `HTTPOptions`) so users find the same knobs; kept as
plain dataclasses (the reference uses pydantic — a validation detail, not
a capability).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, Optional

from ray_tpu.serve.slo import SLOConfig


@dataclass
class AutoscalingConfig:
    """Reference: `serve/config.py` AutoscalingConfig — replica count is
    driven by the average number of ongoing requests per replica.

    Setting either SLO field switches the deployment to the
    **SLO-driven policy** (`serve/autoscaling.py`): replica counts are
    computed from the controller-collected per-replica engine signals
    (queue depth, TTFT EMA, shed/rejection counters piggybacked on
    health checks) instead of router-pushed in-flight counts —

    - `target_ttft_s`: keep the worst replica's time-to-first-token
      EMA at or below this;
    - `target_queue_depth`: keep the mean per-replica backlog
      (engine queued + active) at or below this;
    - `hysteresis`: dead band around the SLO — the load ratio must
      leave [1-h, 1+h] before the target moves, so jitter at the
      boundary can't flap replicas.

    `upscale_delay_s` / `downscale_delay_s` stay the scale cooldowns
    for both policies."""

    min_replicas: int = 1
    max_replicas: int = 1
    target_ongoing_requests: float = 2.0
    upscale_delay_s: float = 0.5
    downscale_delay_s: float = 2.0
    metrics_interval_s: float = 0.2
    look_back_period_s: float = 2.0
    # SLO-driven policy (either one opts in)
    target_ttft_s: Optional[float] = None
    target_queue_depth: Optional[float] = None
    hysteresis: float = 0.1

    def has_slo(self) -> bool:
        return (self.target_ttft_s is not None
                or self.target_queue_depth is not None)

    def desired_replicas(self, total_ongoing: float, current: int) -> int:
        if current <= 0:
            return max(self.min_replicas, 1)
        per_replica = total_ongoing / current
        desired = current * per_replica / max(self.target_ongoing_requests, 1e-9)
        import math

        desired = int(math.ceil(desired))
        return max(self.min_replicas, min(self.max_replicas, desired))


@dataclass
class DeploymentConfig:
    """Reference: `serve/config.py` DeploymentConfig."""

    num_replicas: int = 1
    max_ongoing_requests: int = 16
    max_queued_requests: int = -1  # -1 == unbounded
    autoscaling_config: Optional[AutoscalingConfig] = None
    health_check_period_s: float = 2.0
    health_check_timeout_s: float = 10.0
    graceful_shutdown_timeout_s: float = 5.0
    user_config: Optional[Any] = None
    # per-deployment SLOs (serve/slo.py): the controller tracks
    # multi-window burn rates against these from the replica-shipped
    # ledger counters; surfaced via rt.slo_status() / /api/slo
    slo_config: Optional[SLOConfig] = None

    def initial_replicas(self) -> int:
        if self.autoscaling_config is not None:
            return max(self.autoscaling_config.min_replicas, 1)
        return self.num_replicas


@dataclass
class LLMEngineConfig:
    """Knobs for the continuous-batching LLM engine
    (`serve/llm_engine.py`), validated once and expanded into
    `LlamaEngine(**engine_kwargs())` by the serving wrappers
    (`examples/serve_llm.py` ContinuousLlamaService).

    The decode/quantization plane:
    - `decode_kernel`: "auto" (fused Pallas paged-attention kernel on
      TPU, compiled gather+`decode_step_vec` elsewhere), "pallas"
      (force the kernel; interpret mode off-TPU), or "gather" (force
      the reference route).
    - `kv_dtype`: "model" stores KV in the compute dtype; "int8"
      stores per-row-scaled int8 (half the pool HBM, f32 scale
      sidecar, dequant fused in the kernel / applied on gather).
    - `weight_dtype`: "model" serves the params as given; "int8"
      applies `llama.quantize_weights_int8` at replica init
      (per-output-channel scales, matmuls dequant on the fly).
    """

    slots: int = 32
    chunk: int = 8
    max_len: Optional[int] = None
    block_size: int = 16
    kv_blocks: Optional[int] = None
    prefix_cache: bool = True
    max_queued: Optional[int] = None
    decode_kernel: str = "auto"
    kv_dtype: str = "model"
    weight_dtype: str = "model"
    chunk_cache_cap: int = 8

    def validate(self) -> "LLMEngineConfig":
        if self.decode_kernel not in ("auto", "pallas", "gather"):
            raise ValueError(
                f"decode_kernel={self.decode_kernel!r} not in "
                "('auto', 'pallas', 'gather')"
            )
        if self.kv_dtype not in ("model", "int8"):
            raise ValueError(
                f"kv_dtype={self.kv_dtype!r} not in ('model', 'int8')"
            )
        if self.weight_dtype not in ("model", "int8"):
            raise ValueError(
                f"weight_dtype={self.weight_dtype!r} not in "
                "('model', 'int8')"
            )
        if self.slots < 1:
            raise ValueError(f"slots={self.slots} must be >= 1")
        if self.chunk < 1:
            raise ValueError(f"chunk={self.chunk} must be >= 1")
        if self.block_size < 1:
            raise ValueError(
                f"block_size={self.block_size} must be >= 1"
            )
        if self.chunk_cache_cap < 1:
            raise ValueError(
                f"chunk_cache_cap={self.chunk_cache_cap} must be >= 1"
            )
        return self

    def engine_kwargs(self) -> Dict[str, Any]:
        """Kwargs for `LlamaEngine(...)` — everything except
        `weight_dtype`, which the serving wrapper applies to the params
        BEFORE constructing the engine."""
        return {
            "slots": self.slots,
            "chunk": self.chunk,
            "max_len": self.max_len,
            "block_size": self.block_size,
            "kv_blocks": self.kv_blocks,
            "prefix_cache": self.prefix_cache,
            "max_queued": self.max_queued,
            "decode_kernel": self.decode_kernel,
            "kv_dtype": self.kv_dtype,
            "chunk_cache_cap": self.chunk_cache_cap,
        }


@dataclass
class ReplicaConfig:
    """What it takes to construct one replica: the callable plus its init
    args and per-replica resources (reference: `serve/config.py`
    ReplicaConfig)."""

    import_blob: bytes = b""  # cloudpickled class or function
    init_args: tuple = ()
    init_kwargs: Dict[str, Any] = field(default_factory=dict)
    resources: Dict[str, float] = field(default_factory=dict)


@dataclass
class HTTPOptions:
    """Reference: `serve/config.py` HTTPOptions."""

    host: str = "127.0.0.1"
    port: int = 8000


@dataclass
class GRPCOptions:
    """Reference: `serve/config.py` gRPCOptions; here the generic
    bytes-through proxy (`serve/grpc_proxy.py`), so no servicer
    function list is needed."""

    host: str = "127.0.0.1"
    port: int = 0  # 0 = ephemeral
