"""HTTP request/response types handed to ingress deployments.

The reference hands Starlette `Request` objects to HTTP deployments
(`serve/_private/proxy.py`, `http_util.py`); this framework keeps the
same shape (method/url/headers/query_params/json()/body()) on a
dependency-free class.
"""

from __future__ import annotations

import json as _json
from typing import Any, Dict, Optional
from urllib.parse import parse_qsl, urlsplit


class Request:
    def __init__(self, method: str, path: str, headers: Dict[str, str],
                 body: bytes = b""):
        self.method = method.upper()
        split = urlsplit(path)
        self.path = split.path
        self.query_params: Dict[str, str] = dict(parse_qsl(split.query))
        self.headers = {k.lower(): v for k, v in headers.items()}
        self._body = body

    def body(self) -> bytes:
        return self._body

    def json(self) -> Any:
        return _json.loads(self._body or b"null")

    @property
    def text(self) -> str:
        return self._body.decode("utf-8", errors="replace")

    def __repr__(self):
        return f"Request({self.method} {self.path})"


class Response:
    """Optional explicit response (status + headers); plain return
    values are encoded as JSON/text/bytes by the proxy."""

    def __init__(self, content: Any = b"", status_code: int = 200,
                 content_type: Optional[str] = None,
                 headers: Optional[Dict[str, str]] = None):
        self.content = content
        self.status_code = status_code
        self.content_type = content_type
        self.headers = headers or {}
