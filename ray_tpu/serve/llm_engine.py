"""Continuous-batching LLM engine: step-level scheduling over a PAGED
KV cache with radix prefix reuse.

Reference capability: the vLLM-on-Ray serving pattern (continuous
batching) extended with its two production levers — PagedAttention
(Kwon et al., SOSP 2023: block-granular KV allocation) and
RadixAttention (Zheng et al., 2024: prefix-tree KV sharing) — rebuilt
TPU-native.  New requests join a RESIDENT decode batch mid-flight; the
KV cache is one fixed block pool instead of a per-slot `max_len` ring.

TPU-native design points:
- STATIC shapes from a SMALL family of compiled programs: the block
  pool `[L, num_blocks, block_size, KV, hd]` is allocated once; each
  chunk dispatch gathers every slot's live blocks into a dense
  `[L, slots, W*block_size, ...]` view, runs `chunk` decode steps on
  it (one `lax.scan` per dispatch, per-row positions via
  `llama.decode_step_vec`), and scatters the blocks back.  The gather
  width W is the pow-2 bucket of the LONGEST live sequence's block
  count — per-step attention cost tracks LIVE tokens, not the pool
  budget, killing the measured "ring size is a per-step tax" cost
  (PERF.md round 5: a 1024-ring ran ~20x slower than a 192-ring).
- FUSED DECODE KERNEL (`decode_kernel="pallas"`, auto on TPU): the
  gather/scatter copies die entirely — `llama.decode_step_paged`
  reads and writes the pool IN PLACE through the block tables via the
  Pallas kernels in `ops/paged_attention.py` (tables in SMEM, split-KV
  walk with an online softmax, `input_output_aliases` for the append).
  The gather route above remains the reference/fallback; both produce
  the same greedy tokens (`tests/test_paged_attention.py`).  With
  `kv_dtype="int8"` the pool stores per-row-scaled int8 K/V (half the
  HBM — double the resident batch at a fixed budget) and the kernel
  fuses the dequant; the gather fallback dequants the gathered view
  and requantizes ONLY the rows each chunk wrote, so stored KV never
  drifts through repeated round trips.
- RADIX PREFIX CACHE: prompt prefixes are cached in a block-granular
  token trie (`serve/kv_cache.py`).  A request whose prompt prefix is
  cached pins those blocks (zero-copy sharing — its block table simply
  points at them) and prefills only the suffix, attending over the
  gathered prefix KV (`llama.forward_with_prefix`).  Completed
  requests donate their full prompt blocks to the trie; unpinned
  nodes are LRU-evicted when the pool runs low.  The dominant
  consumer-scale shape — a shared system prompt — skips its prefill
  entirely after the first request.
- CHUNKED stepping + ONE host transfer per chunk, exactly as before:
  the chunk emits its pre-chunk token row so admission never needs a
  device->host read, and the token read of chunk N overlaps chunk
  N+1's compute.

Greedy outputs are bit-identical to a dedicated `llama.generate` for
the same prompt, with the prefix cache on or off
(`tests/test_llm_engine.py`).
"""

from __future__ import annotations

import logging
import os
import threading
import time as _time
from collections import OrderedDict, deque
from concurrent.futures import Future
from typing import Dict, List, Optional

import numpy as np

from ray_tpu.exceptions import BackPressureError, DeadlineExceededError
from ray_tpu.serve import request_ledger as _rl
from ray_tpu.serve.kv_cache import SCRATCH_BLOCK, BlockPool, RadixCache

logger = logging.getLogger(__name__)


# per-tick phase timing to stdout (the tool that found the
# per-admission host read and the unoverlapped chunk sync)
_TRACE = os.environ.get("RT_LLM_ENGINE_TRACE", "") not in ("", "0")


def _next_pow2(n: int) -> int:
    return 1 << max(0, n - 1).bit_length()


def _cdiv(a: int, b: int) -> int:
    return -(-a // b)


class LlamaEngine:
    """Resident continuous-batching decode engine over a paged KV pool.

    submit() is thread-safe and returns a `concurrent.futures.Future`
    resolving to the generated token ids (greedy — identical to what a
    dedicated `llama.generate` would produce for the same prompt).

    `max_len` caps one sequence (prompt + generation); `kv_blocks`
    sizes the SHARED pool (default: enough for every slot at max_len,
    i.e. ring-equivalent capacity — but unlike the ring, an
    over-provisioned pool costs HBM only, not per-step time).
    `prefix_cache=False` disables radix reuse (every request prefills
    its whole prompt)."""

    def __init__(self, cfg, params, *, slots: int = 32,
                 max_len: Optional[int] = None, chunk: int = 8,
                 block_size: int = 16, kv_blocks: Optional[int] = None,
                 prefix_cache: bool = True,
                 max_queued: Optional[int] = None,
                 decode_kernel: str = "auto", kv_dtype: str = "model",
                 chunk_cache_cap: int = 8):
        import jax
        import jax.numpy as jnp

        from ray_tpu.models import llama

        self._jax, self._jnp, self._llama = jax, jnp, llama
        self.cfg = cfg
        self.params = params
        self.slots = slots
        self.max_len = int(max_len or cfg.max_seq_len)
        self.chunk = chunk
        self.block_size = int(block_size)
        # blocks a maximal sequence needs (highest touched index is
        # max_len - 1)
        self._max_seq_blocks = _cdiv(self.max_len, self.block_size)
        budget = (int(kv_blocks) if kv_blocks is not None
                  else slots * self._max_seq_blocks)
        if budget < self._max_seq_blocks:
            raise ValueError(
                f"kv_blocks={budget} cannot hold one max_len sequence "
                f"({self._max_seq_blocks} blocks of {self.block_size})"
            )
        # +1: reserved scratch block.  kv_dtype is validated (and
        # carried) by the pool: "int8" halves pool HBM and adds the f32
        # scale sidecar the paged kernels dequant from.
        self._pool = BlockPool(budget + 1, kv_dtype=kv_dtype)
        self._kv_int8 = self._pool.kv_dtype == "int8"
        if decode_kernel not in ("auto", "pallas", "gather"):
            raise ValueError(
                f"decode_kernel={decode_kernel!r} not in "
                "('auto', 'pallas', 'gather')"
            )
        mode = decode_kernel
        if mode == "auto":
            # the fused kernel exists for TPU HBM bandwidth; on CPU the
            # interpret-mode path is a correctness vehicle, not a win —
            # auto keeps CPU deployments on the compiled gather route
            mode = "pallas" if jax.default_backend() == "tpu" else "gather"
        if mode == "pallas":
            from ray_tpu.testing import pallas_kernel_support

            ok, why = pallas_kernel_support("paged")
            if not ok:
                logger.warning(
                    "decode_kernel=pallas unavailable (%s); falling "
                    "back to the gather+decode_step_vec route", why,
                )
                mode = "gather"
        self._decode_kernel = mode  # resolved: "pallas" | "gather"
        self._paged_interpret = jax.default_backend() != "tpu"
        if prefix_cache and getattr(cfg, "attention", "dense") != "dense":
            # the suffix prefill (`llama.forward_with_prefix`) mirrors
            # the DENSE attention numerics; under flash/ring/ulysses
            # the full prefill would use different reduction orders and
            # a near-tie greedy argmax could diverge between cache-on
            # and cache-off — keep the bit-identity guarantee instead
            logger.info(
                "prefix cache disabled: suffix prefill matches dense "
                "attention numerics only (cfg.attention=%r)",
                cfg.attention,
            )
            prefix_cache = False
        self._radix: Optional[RadixCache] = (
            RadixCache(self.block_size, self._pool) if prefix_cache
            else None
        )

        L, KV, hd = cfg.n_layers, cfg.n_kv_heads, cfg.head_dim
        pool_dtype = jnp.int8 if self._kv_int8 else cfg.dtype
        self._k_pool = jnp.zeros(
            (L, self._pool.num_blocks, self.block_size, KV, hd), pool_dtype
        )
        self._v_pool = jnp.zeros_like(self._k_pool)
        # int8 scale sidecar: one f32 scale per (layer, row, kv-head),
        # written by the same paths that write KV rows
        self._k_scale = self._v_scale = None
        if self._kv_int8:
            self._k_scale = jnp.zeros(
                (L, self._pool.num_blocks, self.block_size, KV),
                jnp.float32,
            )
            self._v_scale = jnp.zeros_like(self._k_scale)
        self._pos = jnp.zeros((slots,), jnp.int32)
        self._tok = jnp.zeros((slots,), jnp.int32)

        # compiled-program families (each keyed by a static shape).
        # The chunk family is LRU-BOUNDED: each entry retains a
        # compiled executable (host + device memory) per gather width,
        # and a long-lived replica sweeping many widths would otherwise
        # grow it without bound (same rationale as _DECODE_JIT_CACHE)
        self._chunk_cache: "OrderedDict[int, object]" = OrderedDict()
        self._chunk_cache_cap = max(1, int(chunk_cache_cap))
        self._chunk_cache_evictions = 0
        self._decode_kernel_dispatches = 0   # fused-kernel chunk ticks
        self._decode_fallback_dispatches = 0  # gather-route chunk ticks
        self._prefill_cache: Dict[int, object] = {}        # prompt bucket
        self._suffix_cache: Dict[tuple, object] = {}       # (S_bucket, P_blocks)
        self._write_cache: Dict[tuple, object] = {}        # (T_in, nb)

        self._lock = threading.Lock()
        # the submit queue lives under its OWN condition/lock: the
        # engine thread holds `_lock` across admission dispatches
        # (which COMPILE on new shapes — seconds), and submit() runs on
        # the replica's event loop, which must never wait that out
        # (same rationale as the bounded-wait stats())
        self._wake = threading.Condition(threading.Lock())
        self._queue: deque = deque()
        self._free: List[int] = list(range(slots))
        # slot -> dict(fut, out, want, since, pos_host, blocks, ...)
        self._active: Dict[int, Dict] = {}
        self._slot_blocks: List[List[int]] = [[] for _ in range(slots)]
        self._running = True
        self._pending_toks = None  # deferred-harvest chunk (see _loop)
        # requests popped from the queue but not yet admitted: they
        # are in neither _queue nor _active while the admission loop
        # compiles/dispatches, and queue_depth must keep counting them
        # or the busiest replica under-reports exactly while it is
        # wedged in admission work (plain int: GIL-atomic updates)
        self._pending_admissions = 0
        self._chunk_seq = 0  # dispatch counter: requests are tagged
        # with the first chunk that can contain their tokens, so the
        # deferred harvest of an OLDER chunk never credits a slot's
        # new occupant with its previous occupant's tokens

        # per-tick metrics exported via stats() (live on the engine
        # thread; reads take the lock)
        self._hit_tokens = 0          # prefix tokens served from cache
        self._prefill_tokens = 0      # tokens actually prefilled
        self._prefix_hits = 0         # requests with a non-empty match
        self._prefill_calls = 0       # prefill dispatches (full+suffix)
        # overload plane: bound the admission queue and shed queued
        # requests whose caller has (or must have) given up BEFORE
        # they burn prefill compute.  All counters are plain ints
        # (GIL-atomic) so submit() can reject without any engine lock.
        self.max_queued = None if max_queued is None else int(max_queued)
        if self.max_queued is not None and self.max_queued < 0:
            raise ValueError(f"max_queued={max_queued} must be >= 0")
        self._rejected_total = 0      # queue-full submit() rejections
        self._shed_expired = 0        # queued past their deadline
        self._shed_predicted = 0      # predicted TTFT > remaining budget
        self._draining = False        # begin_drain(): reject new work
        self._ttft_ema_s = 0.0
        # windowed TTFT samples (monotonic ts, ttft): the shed
        # predictor and the SLO autoscaler consume the p90 over
        # RT_SERVE_TTFT_WINDOW_S, which DECAYS as samples age out —
        # unlike the lifetime EMA (kept for back-compat reporting), a
        # storm-inflated history stops biasing decisions one window
        # after the storm ends.  Touched only on the engine thread
        # (appends in _harvest, reads in _maybe_shed/_stats_locked).
        self._ttft_window_s = float(
            os.environ.get("RT_SERVE_TTFT_WINDOW_S", "10") or 10
        )
        self._ttft_samples: deque = deque(maxlen=256)
        # tick introspection ring: the last N per-tick records (batch
        # composition, live tokens, gather width, kernel route, shed
        # counters, phase wall times) exposed via stats() for the
        # dashboard and postmortems.  Bounded; one dict per tick, no
        # per-request cost.
        self._tick_ring: deque = deque(maxlen=max(1, int(
            os.environ.get("RT_ENGINE_TICK_RING", "32") or 32
        )))
        self._tick_ema_s = 0.0
        self._last_gather_blocks = 0  # W of the latest chunk dispatch
        # last computed stats() dict, served when the engine lock is
        # busy (admission compiles hold it for seconds) — whole-dict
        # swaps only, so readers never see a partial snapshot.  Seeded
        # BEFORE the thread starts: the first admission's compile is
        # exactly the window the fallback exists for, and an empty
        # dict there would blind queue-depth routing during startup
        self._stats_snapshot: Dict[str, object] = self._stats_locked()

        self._thread = threading.Thread(
            target=self._loop, name="llm-engine", daemon=True
        )
        self._thread.start()

    # -- public surface ------------------------------------------------
    def retry_after_hint_s(self) -> float:
        """When a rejected caller should retry: the estimated time for
        the current backlog to drain one admission wave (ticks needed
        at the ≤16-per-tick admission budget, priced at the tick EMA).
        A heuristic, not a promise — floored/capped so cold engines
        (no EMA yet) and pathological backlogs still hint sanely."""
        backlog = len(self._queue) + self._pending_admissions
        per_tick = float(max(1, min(16, self.slots)))
        est = self._tick_ema_s * max(1.0, backlog / per_tick)
        if est <= 0.0:
            est = 1.0  # no tick has completed yet: default hint
        return max(0.05, min(30.0, est))

    def begin_drain(self) -> None:
        """Graceful scale-down entry: stop ADMITTING new requests
        (submit() rejects with BackPressureError) while live sequences
        decode to completion.  KV blocks release as each finishes;
        shutdown() then returns the pool to the allocator."""
        self._draining = True

    def submit(self, prompt_ids: List[int], max_new_tokens: int,
               timeout_s: Optional[float] = None) -> Future:
        """`timeout_s` is the caller's remaining end-to-end budget: the
        request carries its admission deadline through the queue, and
        the admission loop sheds it BEFORE prefill once the deadline
        has passed (or predictably must pass) — see _maybe_shed."""
        limit = self.max_len - 1
        if not prompt_ids or len(prompt_ids) >= limit:
            f: Future = Future()
            f.set_exception(ValueError(
                f"prompt length must be in [1, {limit - 1}]"
            ))
            return f
        n_new = max(1, min(int(max_new_tokens), limit - len(prompt_ids)))
        # engine slice of the request's latency ledger: None (zero
        # allocations) unless an ambient ledger or sampled trace exists
        tk = _rl.engine_ticket()
        # no pool-size check needed: __init__ guarantees the pool holds
        # a full max_len sequence, and T + n_new - 1 <= max_len - 1
        now = _time.monotonic()
        deadline = None if timeout_s is None else now + max(0.0, timeout_s)
        fut: Future = Future()
        with self._wake:
            if not self._running:
                if tk is not None:
                    tk.refused("shutdown")
                fut.set_exception(RuntimeError("engine is shut down"))
                return fut
            if self._draining:
                self._rejected_total += 1
                if tk is not None:
                    tk.refused("draining")
                fut.set_exception(BackPressureError(
                    "engine is draining (replica scaling down)",
                    retry_after_s=self.retry_after_hint_s(),
                ))
                return fut
            if (self.max_queued is not None
                    and len(self._queue) + self._pending_admissions
                    >= self.max_queued + len(self._free)):
                # bounded queue: reject NOW — queueing past the cap
                # only converts this request into a guaranteed timeout
                # that still costs a prefill.  Free slots extend the
                # bound (work that will be admitted on the next tick
                # is not really WAITING), so max_queued=0 still means
                # "serve when capacity is free, never queue" rather
                # than "reject everything".  Under saturation free
                # slots are zero and the queue is bounded at exactly
                # max_queued.
                self._rejected_total += 1
                if tk is not None:
                    tk.refused("queue_full")
                fut.set_exception(BackPressureError(
                    f"engine queue full (max_queued={self.max_queued})",
                    retry_after_s=self.retry_after_hint_s(),
                ))
                return fut
            if deadline is not None and now >= deadline:
                self._shed_expired += 1
                if tk is not None:
                    tk.refused("expired_at_submit")
                fut.set_exception(DeadlineExceededError(
                    "request budget already spent at submission",
                    timeout_s=timeout_s,
                ))
                return fut
            self._queue.append(
                (list(prompt_ids), n_new, fut, now, deadline, tk)
            )
            self._wake.notify()
        return fut

    def stats(self) -> Dict[str, object]:
        """Engine load/health signals (floats plus the `decode_kernel`
        / `kv_dtype` mode strings): consumed by the serve replica's
        metrics piggyback (queue-depth routing + the dashboard's
        /api/serve) and by the tick-trace benchmark.

        NON-BLOCKING by contract: the engine thread holds its lock
        across admission dispatches, which COMPILE on first use of a
        new shape (seconds to tens of seconds on a real model).  A
        health check blocked that long would get a healthy replica
        killed (health_check_timeout_s defaults to 10 s), so when the
        lock isn't free within a bounded wait this returns the last
        per-tick snapshot instead."""
        if not self._lock.acquire(timeout=0.25):
            return dict(self._stats_snapshot)
        try:
            # snapshot updated under the lock: an unlocked write here
            # could land AFTER the engine loop's fresher per-tick one
            snap = self._stats_snapshot = self._stats_locked()
        finally:
            self._lock.release()
        return dict(snap)

    def _ttft_p90(self) -> float:
        """p90 TTFT over the trailing window — 0.0 once every sample
        has aged out, so load-shedding and autoscaling decisions built
        on it decay naturally after a storm (the lifetime EMA never
        did; see _maybe_shed)."""
        cutoff = _time.monotonic() - self._ttft_window_s
        live = sorted(v for ts, v in self._ttft_samples if ts >= cutoff)
        if not live:
            return 0.0
        return live[min(len(live) - 1, int(len(live) * 0.9))]

    def _stats_locked(self) -> Dict[str, object]:
        served = self._hit_tokens + self._prefill_tokens
        cached = self._radix.cached_blocks if self._radix else 0
        return {
                "active": len(self._active),
                "queued": len(self._queue),
                "free_slots": len(self._free),
                "queue_depth": (len(self._active) + len(self._queue)
                                + self._pending_admissions),
                "live_tokens": sum(
                    r["pos_host"] for r in self._active.values()
                ),
                "blocks_total": self._pool.capacity,
                "blocks_free": self._pool.free_blocks,
                "blocks_cached": cached,
                "block_occupancy": (
                    1.0 - self._pool.free_blocks / self._pool.capacity
                ),
                "prefix_hit_tokens": self._hit_tokens,
                "prefill_tokens": self._prefill_tokens,
                "prefix_hit_rate": (
                    self._hit_tokens / served if served else 0.0
                ),
                "prefill_calls": self._prefill_calls,
                "gather_blocks": self._last_gather_blocks,
                # decode-kernel / quantization plane: which route the
                # chunk dispatches take and what the pool costs in HBM
                # (payload and int8 scale sidecar reported separately,
                # so the ½-bytes-at-equal-blocks claim stays auditable)
                "decode_kernel": self._decode_kernel,
                "kv_dtype": self._pool.kv_dtype,
                "kv_pool_bytes": (self._k_pool.nbytes
                                  + self._v_pool.nbytes),
                "kv_scale_bytes": (
                    (self._k_scale.nbytes + self._v_scale.nbytes)
                    if self._kv_int8 else 0
                ),
                "decode_kernel_dispatch_total":
                    self._decode_kernel_dispatches,
                "decode_fallback_dispatch_total":
                    self._decode_fallback_dispatches,
                "chunk_cache_size": len(self._chunk_cache),
                "chunk_cache_evictions": self._chunk_cache_evictions,
                "ttft_ema_s": self._ttft_ema_s,
                # windowed TTFT percentile (decays to 0 as samples age
                # out): the shed predictor and AutoscalingPolicy
                # .pressure() consume THIS, not the lifetime EMA
                "ttft_p90_s": self._ttft_p90(),
                "ttft_window_s": self._ttft_window_s,
                "tick_ema_s": self._tick_ema_s,
                "ticks": self._chunk_seq,
                # tick introspection ring: last N per-tick records for
                # the dashboard / postmortems (list of small dicts;
                # numeric-bridge consumers skip non-float values)
                "tick_ring": list(self._tick_ring),
                # overload plane (admission control + shedding):
                # consumed by the SLO autoscaler and /api/serve
                "max_queued": (-1 if self.max_queued is None
                               else self.max_queued),
                "rejected_total": self._rejected_total,
                "shed_expired": self._shed_expired,
                "shed_predicted": self._shed_predicted,
                "shed_total": self._shed_expired + self._shed_predicted,
                "draining": 1.0 if self._draining else 0.0,
            }

    def shutdown(self):
        with self._wake:
            self._running = False
            self._wake.notify()
        self._thread.join(timeout=10)
        with self._lock:
            for req in list(self._active.values()):
                if not req["fut"].done():
                    req["fut"].cancel()
            self._active.clear()
        with self._wake:
            for item in self._queue:
                if not item[2].done():
                    item[2].cancel()
            self._queue.clear()

    # -- compiled-program families ------------------------------------
    def _chunk_step_for(self, W: int):
        """Chunk stepper for gather width W, under the `decode_kernel`
        knob:

        - "pallas": the fused paged route — `llama.decode_step_paged`
          reads/writes the pool IN PLACE through the block tables (the
          Pallas kernels in `ops/paged_attention.py`); no gather, no
          scatter, no dense copy.  Per-step HBM traffic is the live KV
          once, not three times.
        - "gather": the reference route — gather every slot's blocks
          into a dense W-block view, run `llama.decode_step_vec`,
          scatter the blocks back.  Per-step cost is O(W * block_size)
          per slot — live tokens, not pool budget.

        Entries are LRU-bounded at `chunk_cache_cap` programs; an
        evicted width recompiles on next use (degradation, not
        growth)."""
        fn = self._chunk_cache.get(W)
        if fn is not None:
            self._chunk_cache.move_to_end(W)
            return fn
        jax, jnp, llama = self._jax, self._jnp, self._llama
        cfg, bs, chunk = self.cfg, self.block_size, self.chunk
        L, KV, hd = cfg.n_layers, cfg.n_kv_heads, cfg.head_dim
        S = self.slots

        if self._decode_kernel == "pallas":
            interp = self._paged_interpret
            if self._kv_int8:
                def _fn(params, k_pool, v_pool, k_scale, v_scale,
                        tables, tok, pos):
                    def body(carry, _):
                        tok, kp, vp, ks, vs, pos = carry
                        logits, kp, vp, ks, vs = llama.decode_step_paged(
                            cfg, params, tok, kp, vp, tables, pos,
                            kv_scales=(ks, vs), interpret=interp,
                        )
                        nt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                        pos2 = jnp.minimum(pos + 1, self.max_len - 1)
                        return (nt, kp, vp, ks, vs, pos2), nt

                    tok_in = tok
                    (tok, k_pool, v_pool, k_scale, v_scale, pos), toks = \
                        jax.lax.scan(
                            body,
                            (tok, k_pool, v_pool, k_scale, v_scale, pos),
                            None, length=chunk,
                        )
                    return (k_pool, v_pool, k_scale, v_scale, tok, pos,
                            jnp.concatenate([tok_in[None], toks], axis=0))

                fn = jax.jit(_fn, donate_argnums=(1, 2, 3, 4))
            else:
                def _fn(params, k_pool, v_pool, tables, tok, pos):
                    def body(carry, _):
                        tok, kp, vp, pos = carry
                        logits, kp, vp = llama.decode_step_paged(
                            cfg, params, tok, kp, vp, tables, pos,
                            interpret=interp,
                        )
                        nt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                        # clamp: idle/finished slots must never walk
                        # their position past the sequence cap
                        pos2 = jnp.minimum(pos + 1, self.max_len - 1)
                        return (nt, kp, vp, pos2), nt

                    tok_in = tok  # pre-chunk tokens (see gather route)
                    (tok, k_pool, v_pool, pos), toks = jax.lax.scan(
                        body, (tok, k_pool, v_pool, pos), None,
                        length=chunk,
                    )
                    return k_pool, v_pool, tok, pos, jnp.concatenate(
                        [tok_in[None], toks], axis=0
                    )

                fn = jax.jit(_fn, donate_argnums=(1, 2))
        elif self._kv_int8:
            from ray_tpu.ops import paged_attention as _pa

            def _fn(params, k_pool, v_pool, k_scale, v_scale, tables,
                    tok, pos):
                # gather payload + scales, dequant to the compute dtype
                kq = jnp.take(k_pool, tables, axis=1).reshape(
                    L, S, W * bs, KV, hd
                )
                vq = jnp.take(v_pool, tables, axis=1).reshape(
                    L, S, W * bs, KV, hd
                )
                ks = jnp.take(k_scale, tables, axis=1).reshape(
                    L, S, W * bs, KV
                )
                vs = jnp.take(v_scale, tables, axis=1).reshape(
                    L, S, W * bs, KV
                )
                k = _pa.dequantize_int8(kq, ks, cfg.dtype)
                v = _pa.dequantize_int8(vq, vs, cfg.dtype)
                pos0 = pos

                def body(carry, _):
                    tok, kv, pos = carry[0], (carry[1], carry[2]), carry[3]
                    logits, (k2, v2) = llama.decode_step_vec(
                        cfg, params, tok, kv, pos
                    )
                    nt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                    pos2 = jnp.minimum(pos + 1, self.max_len - 1)
                    return (nt, k2, v2, pos2), nt

                tok_in = tok
                (tok, k, v, pos), toks = jax.lax.scan(
                    body, (tok, k, v, pos), None, length=chunk
                )
                # requantize ONLY the rows this chunk wrote; untouched
                # rows keep their stored payload+scale bit-exactly, so
                # repeated gather/scatter cycles cannot drift the cache
                # (a full-view requant would re-round every row through
                # the compute dtype each chunk)
                idx = jnp.arange(W * bs)[None, :]
                touched = ((idx >= pos0[:, None])
                           & (idx < pos0[:, None] + chunk))  # [S, M]
                kq2, ks2 = _pa.quantize_int8(k)
                vq2, vs2 = _pa.quantize_int8(v)
                t_p = touched[None, :, :, None, None]
                t_s = touched[None, :, :, None]
                kq2 = jnp.where(t_p, kq2, kq)
                vq2 = jnp.where(t_p, vq2, vq)
                ks2 = jnp.where(t_s, ks2, ks)
                vs2 = jnp.where(t_s, vs2, vs)
                k_pool = k_pool.at[:, tables].set(
                    kq2.reshape(L, S, W, bs, KV, hd)
                )
                v_pool = v_pool.at[:, tables].set(
                    vq2.reshape(L, S, W, bs, KV, hd)
                )
                k_scale = k_scale.at[:, tables].set(
                    ks2.reshape(L, S, W, bs, KV)
                )
                v_scale = v_scale.at[:, tables].set(
                    vs2.reshape(L, S, W, bs, KV)
                )
                return (k_pool, v_pool, k_scale, v_scale, tok, pos,
                        jnp.concatenate([tok_in[None], toks], axis=0))

            fn = jax.jit(_fn, donate_argnums=(1, 2, 3, 4))
        else:
            def _fn(params, k_pool, v_pool, tables, tok, pos):
                # tables [slots, W] -> dense [L, slots, W*bs, KV, hd]
                k = jnp.take(k_pool, tables, axis=1).reshape(
                    L, S, W * bs, KV, hd
                )
                v = jnp.take(v_pool, tables, axis=1).reshape(
                    L, S, W * bs, KV, hd
                )

                def body(carry, _):
                    tok, kv, pos = carry[0], (carry[1], carry[2]), carry[3]
                    logits, (k2, v2) = llama.decode_step_vec(
                        cfg, params, tok, kv, pos
                    )
                    nt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                    # clamp: idle/finished slots must never walk their
                    # position past the sequence cap
                    pos2 = jnp.minimum(pos + 1, self.max_len - 1)
                    return (nt, k2, v2, pos2), nt

                tok_in = tok  # pre-chunk tokens: a freshly admitted
                # slot's FIRST token (from prefill) — emitting it here
                # means admission never needs its own device->host read
                # (one ~100 ms round trip PER REQUEST on a remote tunnel)
                (tok, k, v, pos), toks = jax.lax.scan(
                    body, (tok, k, v, pos), None, length=chunk
                )
                # scatter the (updated) blocks back into the pool.
                # Shared prefix blocks scatter identical, unmodified
                # values from every sharer; padding rows target the
                # scratch block — both make duplicate indices benign.
                kb = k.reshape(L, S, W, bs, KV, hd)
                vb = v.reshape(L, S, W, bs, KV, hd)
                k_pool = k_pool.at[:, tables].set(kb)
                v_pool = v_pool.at[:, tables].set(vb)
                # [1 + chunk, slots]: row 0 = pre-chunk tokens
                return k_pool, v_pool, tok, pos, jnp.concatenate(
                    [tok_in[None], toks], axis=0
                )

            fn = jax.jit(_fn, donate_argnums=(1, 2))

        while len(self._chunk_cache) >= self._chunk_cache_cap:
            old_w, _old = self._chunk_cache.popitem(last=False)
            self._chunk_cache_evictions += 1
            logger.info(
                "chunk-program cache evicted W=%d (cap=%d, evictions=%d)",
                old_w, self._chunk_cache_cap, self._chunk_cache_evictions,
            )
        self._chunk_cache[W] = fn
        return fn

    def _prefill_for(self, bucket: int):
        fn = self._prefill_cache.get(bucket)
        if fn is None:
            jax, jnp, llama = self._jax, self._jnp, self._llama

            def _pf(params, prompt):  # prompt [1, bucket]
                # full-sequence logits (not llama.prefill's last-pos
                # form): the prompt is right-padded to the bucket, so
                # the real continuation logit lives at position T-1.
                # Garbage KV rows written for pad positions stay masked
                # (pos starts at T) and are overwritten as decoding
                # advances through them.
                logits, (ks, vs) = llama.forward(
                    self.cfg, params, prompt, return_kv=True
                )
                return logits[0], ks, vs  # ks/vs [L, 1, bucket, KV, hd]

            fn = self._prefill_cache[bucket] = jax.jit(_pf)
        return fn

    def _suffix_prefill_for(self, s_bucket: int, p_blocks: int):
        """Prefix-hit prefill: gather the matched prefix blocks and run
        the suffix forward against them (compiles per (suffix-bucket,
        prefix-width) pair)."""
        key = (s_bucket, p_blocks)
        fn = self._suffix_cache.get(key)
        if fn is None:
            jax, jnp, llama = self._jax, self._jnp, self._llama
            cfg, bs = self.cfg, self.block_size
            L, KV, hd = cfg.n_layers, cfg.n_kv_heads, cfg.head_dim

            if self._kv_int8:
                from ray_tpu.ops import paged_attention as _pa

                def _pf(params, k_pool, v_pool, k_scale, v_scale,
                        suffix, blk_ids, prefix_len):
                    pk = _pa.dequantize_int8(
                        jnp.take(k_pool, blk_ids, axis=1),
                        jnp.take(k_scale, blk_ids, axis=1), cfg.dtype,
                    ).reshape(L, 1, p_blocks * bs, KV, hd)
                    pv = _pa.dequantize_int8(
                        jnp.take(v_pool, blk_ids, axis=1),
                        jnp.take(v_scale, blk_ids, axis=1), cfg.dtype,
                    ).reshape(L, 1, p_blocks * bs, KV, hd)
                    logits, (ks, vs) = llama.forward_with_prefix(
                        cfg, params, suffix, (pk, pv), prefix_len
                    )
                    return logits[0], ks, vs
            else:
                def _pf(params, k_pool, v_pool, suffix, blk_ids,
                        prefix_len):
                    pk = jnp.take(k_pool, blk_ids, axis=1).reshape(
                        L, 1, p_blocks * bs, KV, hd
                    )
                    pv = jnp.take(v_pool, blk_ids, axis=1).reshape(
                        L, 1, p_blocks * bs, KV, hd
                    )
                    logits, (ks, vs) = llama.forward_with_prefix(
                        cfg, params, suffix, (pk, pv), prefix_len
                    )
                    return logits[0], ks, vs

            fn = self._suffix_cache[key] = jax.jit(_pf)
        return fn

    def _write_blocks_for(self, t_in: int, nb: int):
        """Write freshly prefilled KV (time axis `t_in`) into `nb` pool
        blocks and set the slot's pos/tok rows.  Serves both prefill
        shapes — full prompt from position 0, or a suffix starting at a
        block boundary — since the write target is just a block-id
        list."""
        key = (t_in, nb)
        fn = self._write_cache.get(key)
        if fn is None:
            jax, jnp = self._jax, self._jnp
            bs = self.block_size
            L, KV, hd = (self.cfg.n_layers, self.cfg.n_kv_heads,
                         self.cfg.head_dim)
            target = nb * bs

            def _clip(k1, v1):
                # k1/v1 [L, 1, t_in, KV, hd] -> exactly nb blocks
                if t_in < target:
                    pad = [(0, 0), (0, 0), (0, target - t_in), (0, 0),
                           (0, 0)]
                    return jnp.pad(k1, pad), jnp.pad(v1, pad)
                if t_in > target:
                    return k1[:, :, :target], v1[:, :, :target]
                return k1, v1

            if self._kv_int8:
                from ray_tpu.ops import paged_attention as _pa

                def _fn(k_pool, v_pool, k_scale, v_scale, k1, v1,
                        blk_ids, slot, pos0, tok0, pos, tok):
                    k1, v1 = _clip(k1, v1)
                    kq, ksc = _pa.quantize_int8(k1)  # [L,1,target,KV]
                    vq, vsc = _pa.quantize_int8(v1)
                    k_pool = k_pool.at[:, blk_ids].set(
                        kq.reshape(L, nb, bs, KV, hd)
                    )
                    v_pool = v_pool.at[:, blk_ids].set(
                        vq.reshape(L, nb, bs, KV, hd)
                    )
                    k_scale = k_scale.at[:, blk_ids].set(
                        ksc.reshape(L, nb, bs, KV)
                    )
                    v_scale = v_scale.at[:, blk_ids].set(
                        vsc.reshape(L, nb, bs, KV)
                    )
                    pos = pos.at[slot].set(pos0)
                    tok = tok.at[slot].set(tok0)
                    return k_pool, v_pool, k_scale, v_scale, pos, tok

                fn = self._write_cache[key] = jax.jit(
                    _fn, donate_argnums=(0, 1, 2, 3)
                )
            else:
                def _fn(k_pool, v_pool, k1, v1, blk_ids, slot, pos0,
                        tok0, pos, tok):
                    k1, v1 = _clip(k1, v1)
                    kb = k1.astype(k_pool.dtype).reshape(
                        L, nb, bs, KV, hd
                    )
                    vb = v1.astype(v_pool.dtype).reshape(
                        L, nb, bs, KV, hd
                    )
                    k_pool = k_pool.at[:, blk_ids].set(kb)
                    v_pool = v_pool.at[:, blk_ids].set(vb)
                    pos = pos.at[slot].set(pos0)
                    tok = tok.at[slot].set(tok0)
                    return k_pool, v_pool, pos, tok

                fn = self._write_cache[key] = jax.jit(
                    _fn, donate_argnums=(0, 1)
                )
        return fn

    # -- admission -----------------------------------------------------
    def _maybe_shed(self, fut: Future, deadline: Optional[float],
                    tk=None) -> bool:
        """Deadline-aware load shedding, applied when a request is
        popped for admission — the last instant before it costs a
        prefill dispatch.  Sheds when the deadline has already passed,
        or when the predicted time-to-first-token (the windowed TTFT
        p90, which tracks queueing + prefill under load) must overrun
        the remaining budget: a backed-up engine stops doing work
        nobody will read.  The predictor is the WINDOWED percentile,
        not the old lifetime EMA, so it decays to zero within
        `_ttft_window_s` of the load ending — the PR-10 busy gate
        (which existed only because a storm-inflated, never-decaying
        EMA would otherwise shed from an idle engine forever) is
        retired with it.  Sheds are breaker-NEUTRAL downstream (the
        router classifies DeadlineExceededError as neutral, PR-1
        convention): an overloaded-but-reachable replica must not
        accrue breaker failures for honest sheds."""
        if deadline is None or fut.done():
            return False
        now = _time.monotonic()
        pred = self._ttft_p90()
        if now >= deadline:
            self._shed_expired += 1
            why = "deadline already expired in queue"
            reason = "shed_expired"
        elif pred > 0.0 and now + pred >= deadline:
            self._shed_predicted += 1
            why = (f"predicted TTFT ({pred * 1e3:.0f} ms windowed p90) "
                   "exceeds the remaining budget")
            reason = "shed_predicted"
        else:
            return False
        if tk is not None:
            tk.refused(reason)
        fut.set_exception(DeadlineExceededError(
            f"shed before prefill: {why}",
            timeout_s=max(0.0, deadline - now),
        ))
        return True

    def _alloc_or_evict(self, n: int) -> Optional[List[int]]:
        own = self._pool.alloc(n)
        if own is None and self._radix is not None:
            self._radix.evict(n - self._pool.free_blocks)
            own = self._pool.alloc(n)
        return own

    def _admit(self, prompt: List[int], n_new: int, fut: Future,
               t_submit: float, tk=None) -> bool:
        """Returns False (without consuming anything) when the pool
        cannot cover the request right now — the caller requeues it."""
        jnp = self._jnp
        bs = self.block_size
        T = len(prompt)
        # highest KV index a WANTED token's step touches is T+n_new-2
        total_blocks = _cdiv(T + n_new - 1, bs)

        shared: List[int] = []
        path: List = []
        if self._radix is not None:
            shared, path = self._radix.match(prompt)
        P = len(shared) * bs
        own = self._alloc_or_evict(total_blocks - len(shared))
        if own is None:
            if self._radix is not None:
                self._radix.release(path)
            return False
        if tk is not None:
            # queue wait ends here: the request holds a slot and its
            # blocks; everything after is prefill dispatch
            tk.admitted(_time.time())

        slot = self._free.pop()
        if P > 0:
            # PREFIX HIT: prefill only the suffix, attending over the
            # gathered prefix blocks (pow-2 buckets on both axes)
            S = T - P
            s_bucket = min(_next_pow2(S), self.max_len - 1)
            p_bucket = _next_pow2(len(shared))
            blk_ids = jnp.asarray(
                shared + [SCRATCH_BLOCK] * (p_bucket - len(shared)),
                jnp.int32,
            )
            suffix = jnp.asarray(
                [prompt[P:] + [0] * (s_bucket - S)], jnp.int32
            )
            sfn = self._suffix_prefill_for(s_bucket, p_bucket)
            if self._kv_int8:
                logits, k1, v1 = sfn(
                    self.params, self._k_pool, self._v_pool,
                    self._k_scale, self._v_scale, suffix, blk_ids,
                    jnp.asarray(P, jnp.int32),
                )
            else:
                logits, k1, v1 = sfn(
                    self.params, self._k_pool, self._v_pool, suffix,
                    blk_ids, jnp.asarray(P, jnp.int32),
                )
            tok0 = jnp.argmax(logits[S - 1], axis=-1).astype(jnp.int32)
            # suffix KV starts exactly at block boundary P//bs; write
            # only the blocks holding real suffix tokens — bucket-pad
            # garbage past them is dropped, garbage within the last
            # real block is masked by pos until decode overwrites it
            nb_real = _cdiv(S, bs)
            write_ids = own[:nb_real]
            self._hit_tokens += P
            self._prefill_tokens += S
            self._prefix_hits += 1
            wfn = self._write_blocks_for(s_bucket, nb_real)
        else:
            # pow-2 length buckets: RIGHT-pad (the scheme depends on it
            # — causal prefill keeps positions 0..T-1 correct, the pad
            # tail's garbage KV is masked by the starting pos and
            # overwritten as decoding advances)
            bucket = min(_next_pow2(T), self.max_len - 1)
            padded = prompt + [0] * (bucket - T)
            logits, k1, v1 = self._prefill_for(bucket)(
                self.params, jnp.asarray([padded], jnp.int32)
            )
            # first generated token comes from the LAST REAL prompt
            # position; it STAYS on device — the next chunk emits it in
            # its pre-chunk token row, so admission costs only async
            # dispatches
            tok0 = jnp.argmax(logits[T - 1], axis=-1).astype(jnp.int32)
            nb_real = _cdiv(T, bs)
            write_ids = own[:nb_real]
            self._prefill_tokens += T
            wfn = self._write_blocks_for(bucket, nb_real)
        self._prefill_calls += 1

        if self._kv_int8:
            (self._k_pool, self._v_pool, self._k_scale, self._v_scale,
             self._pos, self._tok) = wfn(
                self._k_pool, self._v_pool, self._k_scale,
                self._v_scale, k1, v1,
                jnp.asarray(write_ids, jnp.int32),
                jnp.asarray(slot, jnp.int32), jnp.asarray(T, jnp.int32),
                tok0, self._pos, self._tok,
            )
        else:
            self._k_pool, self._v_pool, self._pos, self._tok = wfn(
                self._k_pool, self._v_pool, k1, v1,
                jnp.asarray(write_ids, jnp.int32),
                jnp.asarray(slot, jnp.int32), jnp.asarray(T, jnp.int32),
                tok0, self._pos, self._tok,
            )

        # donate this prompt's full blocks to the radix cache (pinned
        # until completion); blocks the trie adopts stop being
        # request-owned so completion doesn't double-free them
        own_set = list(own)
        if self._radix is not None:
            donatable = own[: max(0, (T - 1) // bs - len(shared))]
            path, adopted = self._radix.insert(prompt, path, donatable)
            if adopted:
                adopted_set = set(adopted)
                own_set = [b for b in own_set if b not in adopted_set]

        self._slot_blocks[slot] = shared + own
        if tk is not None:
            # host-side dispatch timestamp: the prefill computes async
            # on device, but the ledger phases are wall-clock anyway
            tk.prefilled(_time.time())
        self._active[slot] = {
            "fut": fut, "out": [], "want": n_new,
            "since": self._chunk_seq + 1,  # first chunk with its steps
            "pos_host": T, "own_blocks": own_set, "tree_path": path,
            "t_submit": t_submit, "first_tok": False, "tk": tk,
        }
        return True

    def _release(self, slot: int, req: Dict):
        self._slot_blocks[slot] = []
        self._free.append(slot)
        if self._radix is not None and req["tree_path"]:
            self._radix.release(req["tree_path"])
        self._pool.free(req["own_blocks"])

    # -- engine loop ---------------------------------------------------
    def _gather_width(self) -> int:
        """Blocks per slot the next chunk must see: covers every active
        slot's highest touched index, capped per slot at its own
        allocation (overshoot past a finished budget reads scratch
        garbage that only ever lands in truncated surplus tokens)."""
        need = 1
        for slot, req in self._active.items():
            hi = min(req["pos_host"] + self.chunk - 1, self.max_len - 1)
            w = min(hi // self.block_size + 1,
                    len(self._slot_blocks[slot]))
            need = max(need, w)
        return min(_next_pow2(need), self._max_seq_blocks)

    def _harvest(self, toks_host: np.ndarray, seq: int):
        """toks_host [1 + chunk, slots] from dispatch `seq` (row 0 =
        pre-chunk tokens): append per active slot, finish those that
        reached their budget.  Slots admitted after `seq` was
        dispatched are skipped — their tokens start in a later chunk.
        A request's FIRST chunk contributes from row 0 (its prefill
        token rode along); later chunks from row 1."""
        now = _time.monotonic()
        wall = _time.time()
        done = []
        for slot, req in self._active.items():
            if req["since"] > seq:
                continue
            start = 0 if req["since"] == seq else 1
            need = req["want"] - len(req["out"])
            if need > 0:
                req["out"].extend(
                    int(t) for t in toks_host[start:start + need, slot]
                )
            if req["out"] and not req["first_tok"]:
                req["first_tok"] = True
                ttft = now - req["t_submit"]
                self._ttft_ema_s = (
                    ttft if self._ttft_ema_s == 0.0
                    else 0.8 * self._ttft_ema_s + 0.2 * ttft
                )
                self._ttft_samples.append((now, ttft))
                if req["tk"] is not None:
                    req["tk"].first_token(wall)
            if len(req["out"]) >= req["want"]:
                done.append(slot)
        for slot in done:
            req = self._active.pop(slot)
            self._release(slot, req)
            if req["tk"] is not None:
                req["tk"].done(len(req["out"][:req["want"]]), wall)
            if not req["fut"].done():
                req["fut"].set_result(req["out"][:req["want"]])

    def _loop(self):
        jnp = self._jnp
        while True:
            with self._wake:
                while (self._running and not self._active
                       and not (self._queue and self._free)):
                    self._wake.wait()
                if not self._running:
                    # the engine thread sweeps its own state on exit:
                    # shutdown()'s sweep runs after a BOUNDED join, so
                    # an admission compile outlasting the join would
                    # otherwise register requests into _active AFTER
                    # that sweep and strand their futures forever
                    for item in self._queue:
                        if not item[2].done():
                            item[2].cancel()
                    self._queue.clear()
                    with self._lock:
                        for req in self._active.values():
                            if not req["fut"].done():
                                req["fut"].cancel()
                        self._active.clear()
                    return
                admissions = []
                # bound by the FREE SLOTS, not just the cap: _admit
                # consumes a slot per entry after this loop.  The cap
                # keeps one straggler admission from starving active
                # slots of decode ticks, but filling MATTERS — an
                # engine below full occupancy wastes its whole premise
                budget = min(16, len(self._free))
                while self._queue and len(admissions) < budget:
                    admissions.append(self._queue.popleft())
                self._pending_admissions = len(admissions)
            try:
                t0 = _time.perf_counter()
                requeue = []
                for i, (prompt, n_new, fut, ts, dl, tk) in \
                        enumerate(admissions):
                    # shed BEFORE the prefill dispatch: an expired (or,
                    # under load, predictably-expiring) request consumes
                    # neither a slot nor a KV block nor a compile
                    if self._maybe_shed(fut, dl, tk):
                        self._pending_admissions -= 1
                        continue
                    with self._lock:
                        if not self._admit(prompt, n_new, fut, ts, tk):
                            # pool exhausted by LIVE sequences: wait for
                            # completions, preserving arrival order
                            requeue = admissions[i:]
                            break
                        self._pending_admissions -= 1
                if requeue:
                    with self._wake:
                        self._queue.extendleft(reversed(requeue))
                        self._pending_admissions = 0
                    admissions = admissions[:len(admissions) - len(requeue)]
                else:
                    self._pending_admissions = 0
                t1 = _time.perf_counter()
                with self._lock:
                    have_active = bool(self._active)
                    W = self._gather_width() if have_active else 0
                    if have_active:
                        tables = np.zeros((self.slots, W), np.int32)
                        for slot in self._active:
                            blocks = self._slot_blocks[slot][:W]
                            tables[slot, :len(blocks)] = blocks
                toks = None
                if have_active:
                    self._last_gather_blocks = W
                    cfn = self._chunk_step_for(W)
                    if self._kv_int8:
                        (self._k_pool, self._v_pool, self._k_scale,
                         self._v_scale, self._tok, self._pos,
                         toks) = cfn(
                            self.params, self._k_pool, self._v_pool,
                            self._k_scale, self._v_scale,
                            jnp.asarray(tables), self._tok, self._pos,
                        )
                    else:
                        (self._k_pool, self._v_pool, self._tok,
                         self._pos, toks) = cfn(
                            self.params, self._k_pool, self._v_pool,
                            jnp.asarray(tables), self._tok, self._pos,
                        )
                    if self._decode_kernel == "pallas":
                        self._decode_kernel_dispatches += 1
                    else:
                        self._decode_fallback_dispatches += 1
                    self._chunk_seq += 1
                    with self._lock:
                        for req in self._active.values():
                            req["pos_host"] = min(
                                req["pos_host"] + self.chunk,
                                self.max_len - 1,
                            )
                # OVERLAP: harvest the PREVIOUS chunk's tokens while
                # the current chunk computes — the device->host read is
                # round-trip latency (~90 ms through a remote tunnel,
                # ~half the synced chunk wall time), and the dispatch
                # above is async, so the read rides under the compute.
                # Cost: finish detection lags one chunk.
                t2 = _time.perf_counter()
                if self._pending_toks is not None:
                    p_toks, p_seq = self._pending_toks
                    toks_host = np.asarray(p_toks)
                    with self._lock:
                        self._harvest(toks_host, p_seq)
                self._pending_toks = (
                    (toks, self._chunk_seq) if toks is not None else None
                )
                t3 = _time.perf_counter()
                self._tick_ema_s = (
                    (t3 - t0) if self._tick_ema_s == 0.0
                    else 0.8 * self._tick_ema_s + 0.2 * (t3 - t0)
                )
                with self._lock:  # keep the lock-free stats() fallback
                    # one introspection record per tick (bounded ring;
                    # shipped through stats() -> health piggyback ->
                    # /api/serve for batch-composition postmortems)
                    self._tick_ring.append({
                        "seq": self._chunk_seq,
                        "admitted": len(admissions),
                        "active": len(self._active),
                        "queued": len(self._queue),
                        "free_slots": len(self._free),
                        "live_tokens": sum(
                            r["pos_host"] for r in self._active.values()
                        ),
                        "gather_blocks": W,
                        "kernel": self._decode_kernel,
                        "admit_s": t1 - t0,
                        "dispatch_s": t2 - t1,
                        "harvest_s": t3 - t2,
                        "shed_expired": self._shed_expired,
                        "shed_predicted": self._shed_predicted,
                        "rejected_total": self._rejected_total,
                    })
                    self._stats_snapshot = self._stats_locked()  # fresh
                if _TRACE:
                    with self._lock:
                        na, nf = len(self._active), len(self._free)
                        bf = self._pool.free_blocks
                    print(f"tick adm={len(admissions)} "
                          f"admit={1e3*(t1-t0):.0f} "
                          f"dispatch={1e3*(t2-t1):.0f} "
                          f"read+harvest={1e3*(t3-t2):.0f}ms "
                          f"W={W} blkfree={bf} "
                          f"active={na} free={nf}", flush=True)
            except Exception as e:  # engine must not die silently
                logger.exception("llm engine tick failed; failing %d "
                                 "active request(s)", len(self._active))
                self._pending_toks = None
                with self._lock:
                    for slot, req in list(self._active.items()):
                        if not req["fut"].done():
                            req["fut"].set_exception(e)
                    # admissions popped from the queue but not (yet)
                    # registered in _active would otherwise hang their
                    # callers forever
                    for _p, _n, fut, _ts, _dl, _tk in admissions:
                        if not fut.done():
                            fut.set_exception(e)
                    self._active.clear()
                    self._free = list(range(self.slots))
                    self._slot_blocks = [[] for _ in range(self.slots)]
                    self._pending_admissions = 0
                    # host bookkeeping restarts from scratch: every
                    # block returns to the pool and the radix cache
                    # empties (its pinned paths died with the requests)
                    self._pool = BlockPool(self._pool.num_blocks,
                                           kv_dtype=self._pool.kv_dtype)
                    if self._radix is not None:
                        self._radix = RadixCache(
                            self.block_size, self._pool
                        )
                # the failed tick may have DONATED pool buffers without
                # ever rebinding them — rebuild the device state (int8
                # scale sidecars included: they are donated too) or
                # every later dispatch dies on invalid donated buffers
                self._k_pool = jnp.zeros(
                    (self.cfg.n_layers, self._pool.num_blocks,
                     self.block_size, self.cfg.n_kv_heads,
                     self.cfg.head_dim),
                    jnp.int8 if self._kv_int8 else self.cfg.dtype,
                )
                self._v_pool = jnp.zeros_like(self._k_pool)
                if self._kv_int8:
                    self._k_scale = jnp.zeros(
                        (self.cfg.n_layers, self._pool.num_blocks,
                         self.block_size, self.cfg.n_kv_heads),
                        jnp.float32,
                    )
                    self._v_scale = jnp.zeros_like(self._k_scale)
                self._pos = jnp.zeros((self.slots,), jnp.int32)
                self._tok = jnp.zeros((self.slots,), jnp.int32)
