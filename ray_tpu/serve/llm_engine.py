"""Continuous-batching LLM engine: step-level request scheduling.

Reference capability: the vLLM-on-Ray serving pattern (what the
reference ecosystem deploys behind Ray Serve for LLMs) — new requests
join a RESIDENT decode batch mid-flight instead of waiting for the
current batch to finish, so the decode batch stays full and weight
reads amortize over every active sequence.  Gather-batching
(`@serve.batch` + `llama.generate`) serializes prefill+decode per
gathered group and idles slots as sequences finish; measured on v5e-1
this engine nearly doubles served throughput at the same model/shapes
(PERF.md round 5).

TPU-native design points:
- STATIC shapes end-to-end: a fixed slot count, a fixed max_len ring
  of KV cache, per-row positions (`llama.decode_step_vec`), pow-2
  prompt-length buckets for the prefill program — the whole serving
  life runs on a handful of compiled programs.
- CHUNKED stepping: `chunk` decode steps run inside one compiled
  `lax.scan` per dispatch, so per-dispatch overhead (large on a
  remote-tunnel device, nonzero everywhere) amortizes over
  chunk x slots tokens; finish detection happens at chunk granularity
  and surplus tokens are truncated host-side.
- ONE host transfer per chunk (the emitted token block), never
  per token.

The engine is model-specific to the in-tree Llama (the only decoder
family here); the scheduling core (slots/admission/chunking) is the
reusable part.
"""

from __future__ import annotations

import logging
import os
import threading
import time as _time
from collections import deque
from concurrent.futures import Future
from typing import Dict, List, Optional

import numpy as np

logger = logging.getLogger(__name__)


# per-tick phase timing to stdout (the tool that found the
# per-admission host read and the unoverlapped chunk sync)
_TRACE = os.environ.get("RT_LLM_ENGINE_TRACE", "") not in ("", "0")


def _next_pow2(n: int) -> int:
    return 1 << max(0, n - 1).bit_length()


class LlamaEngine:
    """Resident continuous-batching decode engine.

    submit() is thread-safe and returns a `concurrent.futures.Future`
    resolving to the generated token ids (greedy — identical to what a
    dedicated `llama.generate` would produce for the same prompt)."""

    def __init__(self, cfg, params, *, slots: int = 32,
                 max_len: Optional[int] = None, chunk: int = 8):
        import jax
        import jax.numpy as jnp

        from ray_tpu.models import llama

        self._jax, self._jnp, self._llama = jax, jnp, llama
        self.cfg = cfg
        self.params = params
        self.slots = slots
        self.max_len = int(max_len or cfg.max_seq_len)
        self.chunk = chunk

        L, KV, hd = cfg.n_layers, cfg.n_kv_heads, cfg.head_dim
        self._k = jnp.zeros((L, slots, self.max_len, KV, hd), cfg.dtype)
        self._v = jnp.zeros_like(self._k)
        self._pos = jnp.zeros((slots,), jnp.int32)
        self._tok = jnp.zeros((slots,), jnp.int32)

        # one compiled chunk-stepper for the engine's whole life
        def _chunk_fn(params, k, v, tok, pos):
            def body(carry, _):
                tok, kv, pos = carry[0], (carry[1], carry[2]), carry[3]
                logits, (k2, v2) = llama.decode_step_vec(
                    cfg, params, tok, kv, pos
                )
                nt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                # clamp: idle/finished slots must never walk their
                # position past the cache ring
                pos2 = jnp.minimum(pos + 1, self.max_len - 1)
                return (nt, k2, v2, pos2), nt

            tok_in = tok  # pre-chunk tokens: a freshly admitted
            # slot's FIRST token (from prefill) — emitting it here
            # means admission never needs its own device->host read
            # (one ~100 ms round trip PER REQUEST on a remote tunnel)
            (tok, k, v, pos), toks = jax.lax.scan(
                body, (tok, k, v, pos), None, length=chunk
            )
            # [1 + chunk, slots]: row 0 = pre-chunk tokens
            return k, v, tok, pos, jnp.concatenate(
                [tok_in[None], toks], axis=0
            )

        self._chunk_step = jax.jit(_chunk_fn, donate_argnums=(1, 2))
        # per prompt-length-bucket prefill (compiles per bucket)
        self._prefill_cache: Dict[int, object] = {}

        def _write_slot(k, v, k1, v1, slot, pos0, tok0, pos, tok):
            # k1/v1 [L, 1, max_len, KV, hd] -> batch slot `slot`
            k = jax.lax.dynamic_update_slice(
                k, k1.astype(k.dtype), (0, slot, 0, 0, 0)
            )
            v = jax.lax.dynamic_update_slice(
                v, v1.astype(v.dtype), (0, slot, 0, 0, 0)
            )
            pos = pos.at[slot].set(pos0)
            tok = tok.at[slot].set(tok0)
            return k, v, pos, tok

        self._write_slot = jax.jit(_write_slot, donate_argnums=(0, 1))

        self._lock = threading.Lock()
        self._wake = threading.Condition(self._lock)
        self._queue: deque = deque()
        self._free: List[int] = list(range(slots))
        # slot -> dict(fut, out, want)
        self._active: Dict[int, Dict] = {}
        self._running = True
        self._pending_toks = None  # deferred-harvest chunk (see _loop)
        self._chunk_seq = 0  # dispatch counter: requests are tagged
        # with the first chunk that can contain their tokens, so the
        # deferred harvest of an OLDER chunk never credits a slot's
        # new occupant with its previous occupant's tokens
        self._thread = threading.Thread(
            target=self._loop, name="llm-engine", daemon=True
        )
        self._thread.start()

    # -- public surface ------------------------------------------------
    def submit(self, prompt_ids: List[int], max_new_tokens: int) -> Future:
        limit = self.max_len - 1
        if not prompt_ids or len(prompt_ids) >= limit:
            f: Future = Future()
            f.set_exception(ValueError(
                f"prompt length must be in [1, {limit - 1}]"
            ))
            return f
        n_new = max(1, min(int(max_new_tokens), limit - len(prompt_ids)))
        fut: Future = Future()
        with self._wake:
            if not self._running:
                fut.set_exception(RuntimeError("engine is shut down"))
                return fut
            self._queue.append((list(prompt_ids), n_new, fut))
            self._wake.notify()
        return fut

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {
                "active": len(self._active),
                "queued": len(self._queue),
                "free_slots": len(self._free),
            }

    def shutdown(self):
        with self._wake:
            self._running = False
            self._wake.notify()
        self._thread.join(timeout=10)
        with self._lock:
            for req in list(self._active.values()):
                if not req["fut"].done():
                    req["fut"].cancel()
            for _, _, fut in self._queue:
                if not fut.done():
                    fut.cancel()
            self._active.clear()
            self._queue.clear()

    # -- engine loop ---------------------------------------------------
    def _prefill_for(self, bucket: int):
        fn = self._prefill_cache.get(bucket)
        if fn is None:
            jax, jnp, llama = self._jax, self._jnp, self._llama

            def _pf(params, prompt):  # prompt [1, bucket]
                # full-sequence logits (not llama.prefill's last-pos
                # form): the prompt is right-padded to the bucket, so
                # the real continuation logit lives at position T-1.
                # Garbage KV rows written for pad positions stay masked
                # (pos starts at T) and are overwritten as decoding
                # advances through them.
                logits, (ks, vs) = llama.forward(
                    self.cfg, params, prompt, return_kv=True
                )
                pad = [(0, 0), (0, 0), (0, self.max_len - bucket),
                       (0, 0), (0, 0)]
                return logits[0], jnp.pad(ks, pad), jnp.pad(vs, pad)

            fn = self._prefill_cache[bucket] = jax.jit(_pf)
        return fn

    def _admit(self, prompt: List[int], n_new: int, fut: Future):
        jnp = self._jnp
        slot = self._free.pop()
        T = len(prompt)
        # pow-2 length buckets: RIGHT-pad (the scheme depends on it —
        # causal prefill keeps positions 0..T-1 correct, the pad tail's
        # garbage KV is masked by the starting pos and overwritten as
        # decoding advances)
        bucket = min(_next_pow2(T), self.max_len - 1)
        padded = prompt + [0] * (bucket - T)
        logits, k1, v1 = self._prefill_for(bucket)(
            self.params, jnp.asarray([padded], jnp.int32)
        )
        # first generated token comes from the LAST REAL prompt
        # position; it STAYS on device — the next chunk emits it in its
        # pre-chunk token row, so admission costs only async dispatches
        tok0 = jnp.argmax(logits[T - 1], axis=-1).astype(jnp.int32)
        self._k, self._v, self._pos, self._tok = self._write_slot(
            self._k, self._v, k1, v1, slot, jnp.asarray(T, jnp.int32),
            tok0, self._pos, self._tok,
        )
        self._active[slot] = {
            "fut": fut, "out": [], "want": n_new,
            "since": self._chunk_seq + 1,  # first chunk with its steps
        }

    def _harvest(self, toks_host: np.ndarray, seq: int):
        """toks_host [1 + chunk, slots] from dispatch `seq` (row 0 =
        pre-chunk tokens): append per active slot, finish those that
        reached their budget.  Slots admitted after `seq` was
        dispatched are skipped — their tokens start in a later chunk.
        A request's FIRST chunk contributes from row 0 (its prefill
        token rode along); later chunks from row 1."""
        done = []
        for slot, req in self._active.items():
            if req["since"] > seq:
                continue
            start = 0 if req["since"] == seq else 1
            need = req["want"] - len(req["out"])
            if need > 0:
                req["out"].extend(
                    int(t) for t in toks_host[start:start + need, slot]
                )
            if len(req["out"]) >= req["want"]:
                done.append(slot)
        for slot in done:
            req = self._active.pop(slot)
            self._free.append(slot)
            if not req["fut"].done():
                req["fut"].set_result(req["out"][:req["want"]])

    def _loop(self):
        while True:
            with self._wake:
                while (self._running and not self._active
                       and not (self._queue and self._free)):
                    self._wake.wait()
                if not self._running:
                    return
                admissions = []
                # bound by the FREE SLOTS, not just the cap: _admit
                # consumes a slot per entry after this loop.  The cap
                # keeps one straggler admission from starving active
                # slots of decode ticks, but filling MATTERS — an
                # engine below full occupancy wastes its whole premise
                budget = min(16, len(self._free))
                while self._queue and len(admissions) < budget:
                    admissions.append(self._queue.popleft())
            try:
                t0 = _time.perf_counter()
                for prompt, n_new, fut in admissions:
                    with self._lock:
                        self._admit(prompt, n_new, fut)
                t1 = _time.perf_counter()
                with self._lock:
                    have_active = bool(self._active)
                toks = None
                if have_active:
                    self._k, self._v, self._tok, self._pos, toks = (
                        self._chunk_step(
                            self.params, self._k, self._v, self._tok,
                            self._pos,
                        )
                    )
                    self._chunk_seq += 1
                # OVERLAP: harvest the PREVIOUS chunk's tokens while
                # the current chunk computes — the device->host read is
                # round-trip latency (~90 ms through a remote tunnel,
                # ~half the synced chunk wall time), and the dispatch
                # above is async, so the read rides under the compute.
                # Cost: finish detection lags one chunk.
                t2 = _time.perf_counter()
                if self._pending_toks is not None:
                    p_toks, p_seq = self._pending_toks
                    toks_host = np.asarray(p_toks)
                    with self._lock:
                        self._harvest(toks_host, p_seq)
                self._pending_toks = (
                    (toks, self._chunk_seq) if toks is not None else None
                )
                if _TRACE:
                    t3 = _time.perf_counter()
                    with self._lock:
                        na, nf = len(self._active), len(self._free)
                    print(f"tick adm={len(admissions)} "
                          f"admit={1e3*(t1-t0):.0f} "
                          f"dispatch={1e3*(t2-t1):.0f} "
                          f"read+harvest={1e3*(t3-t2):.0f}ms "
                          f"active={na} free={nf}", flush=True)
            except Exception as e:  # engine must not die silently
                logger.exception("llm engine tick failed; failing %d "
                                 "active request(s)", len(self._active))
                self._pending_toks = None
                with self._lock:
                    for slot, req in list(self._active.items()):
                        if not req["fut"].done():
                            req["fut"].set_exception(e)
                    # admissions popped from the queue but not (yet)
                    # registered in _active would otherwise hang their
                    # callers forever
                    for _p, _n, fut in admissions:
                        if not fut.done():
                            fut.set_exception(e)
                    self._active.clear()
                    self._free = list(range(self.slots))
                # the failed tick may have DONATED k/v without ever
                # rebinding them — rebuild the device state or every
                # later dispatch dies on invalid donated buffers
                jnp = self._jnp
                self._k = jnp.zeros(
                    (self.cfg.n_layers, self.slots, self.max_len,
                     self.cfg.n_kv_heads, self.cfg.head_dim),
                    self.cfg.dtype,
                )
                self._v = jnp.zeros_like(self._k)
                self._pos = jnp.zeros((self.slots,), jnp.int32)
                self._tok = jnp.zeros((self.slots,), jnp.int32)
