"""gRPC proxy actor: the serve data-plane's second ingress.

Reference: `serve/_private/proxy.py` `gRPCProxy:545` — a gRPC server in
the proxy actor routing RPCs to deployment handles the same way the
HTTP proxy does.  Here a `grpc.aio` server with a GENERIC handler: no
compiled protos are required — the method path selects the
application, raw request bytes pass through to the app's ingress
deployment, and whatever bytes it returns become the response:

    /<application>/<method>    -> ingress handle.remote(Request(...))
    /ray.serve.ServeAPIService/Healthz           -> b"ok"
    /ray.serve.ServeAPIService/ListApplications  -> json app list

Deployments see a `serve.Request` with method="GRPC",
path="/<application>/<method>", and `body()` = the raw request bytes;
they return `bytes` (or str / dict / Response — encoded like the HTTP
proxy does).  Unary-unary only (the reference's streaming gRPC path is
proto-specific and out of scope here).
"""

from __future__ import annotations

import asyncio
import json
import logging
from typing import Optional, Tuple

logger = logging.getLogger(__name__)

from ray_tpu import exceptions as _exc
from ray_tpu.serve.handle import DeploymentHandle
from ray_tpu.serve.request import Request, Response

_HEALTH = "/ray.serve.ServeAPIService/Healthz"
_LIST = "/ray.serve.ServeAPIService/ListApplications"


def _classify_error(e: BaseException):
    """(status_code_name, retry_after_s | None) for a dispatch failure
    — kept grpc-import-free so the translation is unit-testable:

    - BackPressureError (direct, or a replica-side rejection wrapped
      in TaskError) -> RESOURCE_EXHAUSTED, with the retry hint also
      surfaced as `retry-after` trailing metadata (seconds, decimal);
    - a deadline expiry / engine shed -> DEADLINE_EXCEEDED;
    - anything else -> INTERNAL (unchanged)."""
    retry_after = _exc.backpressure_retry_after(e)
    if retry_after is not None:
        return "RESOURCE_EXHAUSTED", retry_after
    if _exc.is_deadline_expiry(e):
        return "DEADLINE_EXCEEDED", None
    return "INTERNAL", None


def _encode(value) -> bytes:
    if isinstance(value, Response):
        value = value.content
    if isinstance(value, (bytes, bytearray)):
        return bytes(value)
    if isinstance(value, str):
        return value.encode()
    return json.dumps(value).encode()


class GRPCProxy:
    """Async actor; the grpc.aio server lives on the actor's loop."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self._host = host
        self._port = port
        self._server = None
        self._num_requests = 0

    async def start(self) -> int:
        import grpc

        proxy = self

        class _Generic(grpc.GenericRpcHandler):
            def service(self, call_details):
                method = call_details.method

                # a real async def: grpc.aio awaits handlers only when
                # iscoroutinefunction(handler) is true
                async def behavior(request, ctx, _m=method):
                    return await proxy._handle(_m, request, ctx)

                return grpc.unary_unary_rpc_method_handler(
                    behavior,
                    request_deserializer=None,   # raw bytes through
                    response_serializer=None,
                )

        self._server = grpc.aio.server()
        self._server.add_generic_rpc_handlers((_Generic(),))
        requested = self._port
        self._port = self._server.add_insecure_port(
            f"{self._host}:{self._port}"
        )
        if self._port == 0:
            # grpc reports bind failure as port 0, not an exception
            raise OSError(
                f"gRPC proxy could not bind {self._host}:{requested}"
            )
        await self._server.start()
        return self._port

    def address(self) -> Tuple[str, int]:
        return (self._host, self._port)

    def num_requests(self) -> int:
        return self._num_requests

    async def stop(self) -> bool:
        if self._server is not None:
            await self._server.stop(grace=1.0)
            self._server = None
        return True

    async def _handle(self, method: str, request: bytes, ctx) -> bytes:
        import grpc

        self._num_requests += 1
        if method == _HEALTH:
            return b"ok"
        from ray_tpu.core.runtime import get_runtime
        from ray_tpu.serve.api import _get_controller_async

        controller = await _get_controller_async()
        if method == _LIST:
            ref = controller.list_applications.remote()
            apps = await get_runtime()._get_one(ref)
            return json.dumps(sorted(apps)).encode()
        parts = method.strip("/").split("/", 1)
        if len(parts) != 2:
            await ctx.abort(grpc.StatusCode.UNIMPLEMENTED,
                            f"malformed method {method!r}")
        app = parts[0]
        ref = controller.get_ingress.remote(app)
        ingress = await get_runtime()._get_one(ref)
        if ingress is None:
            await ctx.abort(grpc.StatusCode.NOT_FOUND,
                            f"no application named {app!r}")
        handle = DeploymentHandle(ingress, app)
        try:
            value = await handle.remote(
                Request("GRPC", method, {}, request or b"")
            )
        except Exception as e:  # rtlint: disable=RT005
            # boundary to gRPC: ctx.abort() RAISES, surfacing e as the
            # call's status — nothing is swallowed.  Overload signals
            # map to RESOURCE_EXHAUSTED (+ retry-after trailing
            # metadata) / DEADLINE_EXCEEDED so clients can tell
            # "retry later" from "server bug" (see _classify_error)
            status_name, retry_after = _classify_error(e)
            if retry_after is not None:
                ctx.set_trailing_metadata(
                    (("retry-after", f"{retry_after:.3f}"),)
                )
            await ctx.abort(getattr(grpc.StatusCode, status_name), str(e))
        if isinstance(value, Response) and not (
            200 <= value.status_code < 300
        ):
            # like the HTTP proxy: a non-2xx Response is an ERROR reply
            await ctx.abort(
                _status_for(grpc, value.status_code),
                _encode(value).decode(errors="replace"),
            )
        return _encode(value)


def _status_for(grpc, http_status: int):
    if http_status == 404:
        return grpc.StatusCode.NOT_FOUND
    if http_status in (401, 403):
        return grpc.StatusCode.PERMISSION_DENIED
    if http_status == 429:
        return grpc.StatusCode.RESOURCE_EXHAUSTED
    if 400 <= http_status < 500:
        return grpc.StatusCode.INVALID_ARGUMENT
    return grpc.StatusCode.INTERNAL
