"""SLO burn-rate tracking for serve deployments.

Reference: the multi-window burn-rate alerting model (SRE workbook ch.5)
— an SLO like "99% of requests see TTFT under 200ms" defines an error
budget (1%), and the *burn rate* over a window is the fraction of the
budget the deployment is currently consuming per unit time: burn 1.0
means exactly on budget, burn 14.4 over 5 minutes means the monthly
budget gone in two days.

The controller is the natural place to compute this: replicas already
piggyback their metrics on health checks, so each replica ships a
compact cumulative counter block (request count, error count, and
per-bucket TTFT/e2e latency counts over the cataloged boundaries) and
the controller folds the per-replica deltas into a deployment-cumulative
series (`BurnRateTracker`).  Burn rates are then windowed differences of
that series — no per-request state crosses the wire, and replica
restarts fold in as zero-delta resets exactly like the router stats.

Latency targets are snapped to the catalog's bucket resolution
(`metric_defs._LATENCY_S`): a request landing in the bucket that
CONTAINS the target counts as bad, so the reported burn rate is
conservative (never under-reports a violation).
"""

from __future__ import annotations

import bisect
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu.metrics.metric_defs import _LATENCY_S

# shared bucket boundaries for the ledger's SLO counter blocks; the
# final implicit bucket is +Inf, so a counter block has len(BOUNDS)+1
# entries
BOUNDS: Tuple[float, ...] = _LATENCY_S

# burn-rate windows (seconds): short/medium/long, the classic
# multi-window set — a short-window spike confirms the long-window
# signal is current, the long window keeps one blip from paging
DEFAULT_WINDOWS: Tuple[float, ...] = (60.0, 300.0, 3600.0)


@dataclass
class SLOConfig:
    """Per-deployment service-level objectives.

    `objective` is the target success fraction (0.99 == "99% of
    requests meet each latency target"); its complement is the error
    budget that burn rates are measured against.  `target_error_rate`
    overrides the budget for the error-rate dimension only (defaults to
    the same 1 - objective budget)."""

    target_ttft_s: Optional[float] = None
    target_e2e_s: Optional[float] = None
    target_error_rate: Optional[float] = None
    objective: float = 0.99
    windows: Tuple[float, ...] = DEFAULT_WINDOWS

    def __post_init__(self):
        if not 0.0 < self.objective < 1.0:
            raise ValueError("objective must be in (0, 1)")
        for t in (self.target_ttft_s, self.target_e2e_s):
            if t is not None and t <= 0:
                raise ValueError("latency targets must be positive")
        if self.target_error_rate is not None and not (
                0.0 < self.target_error_rate < 1.0):
            raise ValueError("target_error_rate must be in (0, 1)")
        self.windows = tuple(sorted(float(w) for w in self.windows))
        if not self.windows:
            raise ValueError("at least one burn-rate window is required")

    def has_any(self) -> bool:
        return (self.target_ttft_s is not None
                or self.target_e2e_s is not None
                or self.target_error_rate is not None)

    @property
    def error_budget(self) -> float:
        return 1.0 - self.objective


def empty_counters() -> Dict[str, Any]:
    """A zeroed cumulative counter block (the shape replicas ship)."""
    n = len(BOUNDS) + 1
    return {"n": 0, "errors": 0, "ttft": [0] * n, "e2e": [0] * n}


def bucket_index(value_s: float) -> int:
    """Index of the (non-cumulative) bucket a latency lands in."""
    return bisect.bisect_left(BOUNDS, value_s)


def bad_fraction(delta: Dict[str, Any], dim: str,
                 target_s: float) -> Optional[float]:
    """Fraction of requests in `delta` whose `dim` latency exceeded
    `target_s`, judged at bucket resolution (the bucket containing the
    target counts as bad).  None when the window saw no requests."""
    counts = delta.get(dim)
    if not counts:
        return None
    total = sum(counts)
    if total <= 0:
        return None
    # buckets with upper boundary <= target are definitively good
    good = sum(counts[:bisect.bisect_right(BOUNDS, target_s)])
    return (total - good) / total


class BurnRateTracker:
    """Deployment-cumulative SLO counter series with windowed burn-rate
    queries.  `fold()` ingests one replica's cumulative block (deltas
    are clamped at zero so a replica restart folds in as a reset, the
    same contract as the controller's router-stats folding);
    `snapshot()` appends the current totals to a bounded time ring;
    `burn_rates()` reads windowed differences off the ring."""

    # ring sized to cover the longest default window at the controller's
    # >=1s snapshot throttle
    RING = 4000
    MIN_SNAP_INTERVAL_S = 1.0

    def __init__(self):
        self._lock = threading.Lock()
        self._last_seen: Dict[str, Dict[str, Any]] = {}
        self._totals = empty_counters()
        self._ring: deque = deque(maxlen=self.RING)

    def forget_replica(self, replica_id: str):
        with self._lock:
            self._last_seen.pop(replica_id, None)

    def fold(self, replica_id: str, counters: Optional[Dict[str, Any]]):
        if not counters:
            return
        with self._lock:
            prev = self._last_seen.get(replica_id) or empty_counters()
            tot = self._totals
            tot["n"] += max(0, int(counters.get("n", 0)) - prev["n"])
            tot["errors"] += max(
                0, int(counters.get("errors", 0)) - prev["errors"])
            for dim in ("ttft", "e2e"):
                cur = counters.get(dim) or []
                old = prev[dim]
                agg = tot[dim]
                for i in range(min(len(cur), len(agg))):
                    o = old[i] if i < len(old) else 0
                    agg[i] += max(0, int(cur[i]) - o)
            self._last_seen[replica_id] = {
                "n": int(counters.get("n", 0)),
                "errors": int(counters.get("errors", 0)),
                "ttft": [int(c) for c in (counters.get("ttft") or [])],
                "e2e": [int(c) for c in (counters.get("e2e") or [])],
            }

    def snapshot(self, now: Optional[float] = None):
        now = time.time() if now is None else now
        with self._lock:
            if self._ring and now - self._ring[-1][0] < \
                    self.MIN_SNAP_INTERVAL_S:
                return
            self._ring.append((now, {
                "n": self._totals["n"],
                "errors": self._totals["errors"],
                "ttft": list(self._totals["ttft"]),
                "e2e": list(self._totals["e2e"]),
            }))

    def _delta_over(self, window_s: float,
                    now: float) -> Tuple[float, Dict[str, Any]]:
        """(actual_window_s, counter deltas) against the newest ring
        entry at least `window_s` old (oldest entry when the ring does
        not yet span the window)."""
        cutoff = now - window_s
        base_ts, base = self._ring[0]
        for ts, snap in reversed(self._ring):
            if ts <= cutoff:
                base_ts, base = ts, snap
                break
        head_ts, head = self._ring[-1]
        delta = {
            "n": head["n"] - base["n"],
            "errors": head["errors"] - base["errors"],
            "ttft": [h - b for h, b in zip(head["ttft"], base["ttft"])],
            "e2e": [h - b for h, b in zip(head["e2e"], base["e2e"])],
        }
        return max(head_ts - base_ts, 1e-9), delta

    def burn_rates(self, cfg: SLOConfig,
                   now: Optional[float] = None) -> Dict[str, Any]:
        """Burn rate per window per dimension: observed bad fraction
        over the window divided by the error budget.  1.0 == consuming
        exactly the budget; None == no data / no target."""
        now = time.time() if now is None else now
        with self._lock:
            if not self._ring:
                return {"windows": {}, "requests_total": 0}
            out: Dict[str, Any] = {"windows": {}}
            for w in cfg.windows:
                span_s, delta = self._delta_over(w, now)
                row: Dict[str, Any] = {
                    "window_s": w,
                    "actual_window_s": round(span_s, 3),
                    "requests": delta["n"],
                }
                budget = cfg.error_budget
                if cfg.target_ttft_s is not None:
                    frac = bad_fraction(delta, "ttft", cfg.target_ttft_s)
                    row["ttft_burn"] = (
                        None if frac is None else frac / budget)
                if cfg.target_e2e_s is not None:
                    frac = bad_fraction(delta, "e2e", cfg.target_e2e_s)
                    row["e2e_burn"] = (
                        None if frac is None else frac / budget)
                err_budget = (cfg.target_error_rate
                              if cfg.target_error_rate is not None
                              else budget)
                if delta["n"] > 0:
                    row["error_burn"] = (
                        delta["errors"] / delta["n"]) / err_budget
                else:
                    row["error_burn"] = None
                out["windows"][str(int(w))] = row
            out["requests_total"] = self._ring[-1][1]["n"]
            return out


def status_for(tracker: Optional[BurnRateTracker],
               cfg: Optional[SLOConfig]) -> Dict[str, Any]:
    """The `/api/slo` row for one deployment: configured targets plus
    current burn rates and an `ok` verdict (every computed burn <= 1)."""
    if cfg is None or not cfg.has_any():
        return {"configured": False}
    row: Dict[str, Any] = {
        "configured": True,
        "objective": cfg.objective,
        "targets": {
            "ttft_s": cfg.target_ttft_s,
            "e2e_s": cfg.target_e2e_s,
            "error_rate": (cfg.target_error_rate
                           if cfg.target_error_rate is not None
                           else cfg.error_budget),
        },
    }
    rates = (tracker.burn_rates(cfg) if tracker is not None
             else {"windows": {}, "requests_total": 0})
    row.update(rates)
    burns: List[float] = [
        v for win in rates["windows"].values()
        for k, v in win.items()
        if k.endswith("_burn") and v is not None
    ]
    row["ok"] = all(b <= 1.0 for b in burns) if burns else True
    return row
