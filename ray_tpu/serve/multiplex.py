"""Model multiplexing: many models per replica with LRU residency.

Reference: `python/ray/serve/multiplex.py` (`@serve.multiplexed`) +
`serve.get_multiplexed_model_id()` — a replica lazily loads models by id
on first request and keeps at most `max_num_models_per_replica` resident
(LRU eviction).  Callers pick the model per request via
`handle.options(multiplexed_model_id=...)`.

On TPU, residency is the whole point: a loaded model is a set of
device-resident arrays (and usually a compiled program); reloading per
request would forfeit both.
"""

from __future__ import annotations

import asyncio
import contextvars
import functools
import inspect
import threading
from collections import OrderedDict
from typing import Any, Callable, Optional

_current_model_id: contextvars.ContextVar[str] = contextvars.ContextVar(
    "serve_multiplexed_model_id", default=""
)

MODEL_ID_KWARG = "__serve_model_id__"


def _set_model_id(model_id: str):
    # contextvars are per-thread AND per-asyncio-task: the replica sets
    # this on the exact thread/task that runs the user code, and
    # overwrites at every request start — a reset token would restore a
    # PREVIOUS request's model id, which is exactly the leak to avoid
    _current_model_id.set(model_id)  # rtlint: disable=RT006


def get_multiplexed_model_id() -> str:
    """Inside a request: the model id the caller asked for (reference:
    `serve.get_multiplexed_model_id`)."""
    return _current_model_id.get()


class _ModelCache:
    def __init__(self, loader: Callable, max_models: int):
        self._loader = loader
        self._max = max_models
        self._models: OrderedDict[str, Any] = OrderedDict()
        self._loading: dict = {}  # model_id -> Future (in-flight dedup)
        self._lock = asyncio.Lock()

    async def get(self, owner, model_id: str):
        while True:
            async with self._lock:
                if model_id in self._models:
                    self._models.move_to_end(model_id)
                    return self._models[model_id]
                fut = self._loading.get(model_id)
                if fut is None:
                    fut = asyncio.get_running_loop().create_future()
                    self._loading[model_id] = fut
                    break
            # another request is loading this model: share its result
            return await asyncio.shield(fut)
        try:
            out = self._loader(owner, model_id)
            if inspect.isawaitable(out):
                out = await out
        except BaseException as e:
            async with self._lock:
                self._loading.pop(model_id, None)
            if not fut.done():
                fut.set_exception(e)
            raise
        async with self._lock:
            self._models[model_id] = out
            self._models.move_to_end(model_id)
            while len(self._models) > self._max:
                self._models.popitem(last=False)  # LRU eviction; the
                # arrays free when the last reference drops
            self._loading.pop(model_id, None)
        if not fut.done():
            fut.set_result(out)
        return out


def multiplexed(_fn: Optional[Callable] = None, *,
                max_num_models_per_replica: int = 3):
    """Decorate an `async def load_model(self, model_id)` method; calls
    become LRU-cached per replica instance."""

    def _decorate(fn: Callable):
        attr = f"__serve_model_cache_{id(fn)}"

        @functools.wraps(fn)
        async def wrapper(self, model_id: Optional[str] = None):
            if model_id is None:
                model_id = get_multiplexed_model_id()
            cache = getattr(self, attr, None)
            if cache is None:
                cache = _ModelCache(fn, max_num_models_per_replica)
                setattr(self, attr, cache)
            return await cache.get(self, model_id)

        wrapper._is_serve_multiplexed = True
        return wrapper

    if _fn is not None:
        return _decorate(_fn)
    return _decorate
