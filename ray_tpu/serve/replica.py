"""Replica actor: wraps the user callable and executes requests.

Reference: `python/ray/serve/_private/replica.py` (`ReplicaActor:231`,
`UserCallableWrapper:756`) — each replica is one actor hosting one
instance of the user's deployment class (or function), executing
requests concurrently up to `max_ongoing_requests`, reporting its queue
length for power-of-two routing and autoscaling.
"""

from __future__ import annotations

import asyncio
import inspect
import logging
import time
from typing import Any, Dict, Optional

from ray_tpu import exceptions as _exc
from ray_tpu.exceptions import BackPressureError
from ray_tpu.serve import request_ledger as _rl
from ray_tpu.util import tracing as _tracing

logger = logging.getLogger(__name__)


def _terminal_of(e: BaseException) -> str:
    """Ledger terminal classification of a replica-side failure:
    backpressure (engine admission, replica cap) == rejected, deadline
    expiry == shed, anything else == error."""
    if _exc.backpressure_retry_after(e) is not None:
        return "rejected"
    if _exc.is_deadline_expiry(e):
        return "shed"
    return "error"


async def _ensure_coro(awaitable):
    return await awaitable


# histogram boundaries for per-replica request latency (the classic
# Prometheus latency ladder; the last +Inf bucket is implicit)
LATENCY_BOUNDARIES = (
    0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
)


class Replica:
    """Created with `max_concurrency > 1` so requests interleave on the
    actor's event loop, the same execution model as the reference's
    asyncio replica."""

    def __init__(
        self,
        deployment_name: str,
        replica_id: str,
        callable_def: Any,
        init_args: tuple,
        init_kwargs: Dict[str, Any],
        user_config: Any = None,
        max_ongoing_requests: int = 16,
    ):
        self._deployment_name = deployment_name
        self._replica_id = replica_id
        # replica ids are "{app}#{deployment}#{idx}" — the app tag for
        # the request ledger's histogram series
        self._app = (replica_id.split("#", 1)[0]
                     if "#" in replica_id else "default")
        self._max_ongoing = max_ongoing_requests
        self._ongoing = 0
        self._total = 0
        # per-replica Prometheus series (reference: `serve/metrics.py`
        # replica-tagged request counter/latency): collected by the
        # controller on the health-check cadence, exported at /metrics
        self._latency_sum_s = 0.0
        self._latency_buckets = [0] * len(LATENCY_BOUNDARIES)
        self._completed = 0  # finished requests (histogram count basis)
        # overload plane: requests rejected at the replica cap.  The
        # router already caps ITS OWN in-flight at max_ongoing, but N
        # routers can overshoot the replica in aggregate — this is the
        # authoritative per-replica bound (reference: replicas enforce
        # max_ongoing_requests themselves and the router retries)
        self._rejected = 0
        if isinstance(callable_def, type):
            self._callable = callable_def(*init_args, **init_kwargs)
        else:
            self._callable = callable_def
        self._is_function = not isinstance(callable_def, type)
        if user_config is not None:
            self._apply_user_config(user_config)

    def _apply_user_config(self, user_config):
        rc = getattr(self._callable, "reconfigure", None)
        if rc is None:
            raise RuntimeError(
                f"user_config provided but {self._deployment_name} has no "
                "reconfigure() method"
            )
        rc(user_config)

    # -- data plane ---------------------------------------------------
    async def handle_request(self, method_name: str, *args, **kwargs):
        """Execute one request (reference: `replica.py:463`
        `handle_request`).

        Async user code runs on the event loop (and must use async
        handle composition); sync user code runs on the worker thread
        pool where blocking `.result()` composition is safe — the same
        split the reference makes between async and sync callables.
        """
        from ray_tpu.serve.multiplex import MODEL_ID_KWARG, _set_model_id

        model_id = kwargs.pop(MODEL_ID_KWARG, "")
        # replica-side ledger: its trace identity joins the request's
        # trace (the execution_span installed the propagated context);
        # None — and zero per-request allocations — when telemetry is
        # off
        led = _rl.start_request("replica", self._app,
                                self._deployment_name, self._replica_id)
        try:
            self._reject_if_saturated()
        except BackPressureError:
            if led is not None:
                led.finish("rejected", "replica_saturated")
            raise
        self._ongoing += 1
        self._total += 1
        t0 = time.monotonic()
        try:
            if led is not None:
                led.begin("execute")
            if self._is_function:
                target = self._callable
            else:
                target = getattr(self._callable, method_name or "__call__")
            if asyncio.iscoroutinefunction(target):
                _set_model_id(model_id)
                with _rl.use_ledger(led):
                    out = await target(*args, **kwargs)
            else:
                from ray_tpu.core.runtime import get_runtime

                tctx = _tracing.current_context()

                def _call_with_ctx():
                    # pool threads inherit neither contextvar: restore
                    # the trace context and the ledger so engine-side
                    # telemetry stays attached to this request
                    _set_model_id(model_id)
                    with _tracing.use_context(tctx), _rl.use_ledger(led):
                        return target(*args, **kwargs)

                loop = asyncio.get_running_loop()
                out = await loop.run_in_executor(
                    get_runtime()._exec_pool, _call_with_ctx
                )
                if inspect.isawaitable(out):
                    out = await out
            return out
        except Exception as e:  # noqa: BLE001 — terminal classification
            if led is not None:
                led.finish(_terminal_of(e), type(e).__name__)
            raise
        finally:
            self._ongoing -= 1
            self._observe_latency(time.monotonic() - t0)
            if led is not None:
                led.finish("ok")  # no-op if a terminal already landed

    async def handle_request_streaming(self, method_name: str, *args, **kwargs):
        """Streaming request path (reference: `replica.py:463-492`
        `handle_request_streaming`): the user target is a generator /
        async generator (or returns an iterable) and each produced item
        flows back to the caller incrementally as one streamed object —
        this method is itself an async generator, so the actor runtime
        streams it (`num_returns="streaming"`)."""
        from ray_tpu.serve.multiplex import MODEL_ID_KWARG, _set_model_id

        model_id = kwargs.pop(MODEL_ID_KWARG, "")
        led = _rl.start_request("replica", self._app,
                                self._deployment_name, self._replica_id)
        try:
            self._reject_if_saturated()
        except BackPressureError:
            if led is not None:
                led.finish("rejected", "replica_saturated")
            raise
        self._ongoing += 1
        self._total += 1
        t0 = time.monotonic()
        try:
            if led is not None:
                led.begin("execute")
            if self._is_function:
                target = self._callable
            else:
                target = getattr(self._callable, method_name or "__call__")
            _set_model_id(model_id)
            tctx = _tracing.current_context()
            if inspect.isasyncgenfunction(target):
                # the generator body runs at iteration, not creation:
                # keep the ledger installed around the whole drive (the
                # ambient var is ours again on every resume; between
                # our own yields it is visible to the stream driver,
                # which never touches it)
                with _rl.use_ledger(led):
                    async for item in target(*args, **kwargs):
                        yield item
                return
            loop = asyncio.get_running_loop()
            from ray_tpu.core.runtime import get_runtime

            pool = get_runtime()._exec_pool
            if inspect.iscoroutinefunction(target):
                with _rl.use_ledger(led):
                    out = await target(*args, **kwargs)
            else:
                # sync targets run on pool threads, which do NOT inherit
                # this task's contextvars — set the model id, trace
                # context and ledger on the executing thread (same
                # pattern as handle_request's _call_with_ctx)
                def _call_with_ctx():
                    _set_model_id(model_id)
                    with _tracing.use_context(tctx), _rl.use_ledger(led):
                        return target(*args, **kwargs)

                out = await loop.run_in_executor(pool, _call_with_ctx)
            if inspect.isgenerator(out):
                _END = object()

                def _next():
                    _set_model_id(model_id)  # any pool thread may run this
                    with _tracing.use_context(tctx), _rl.use_ledger(led):
                        try:
                            return next(out)
                        except StopIteration:
                            return _END

                while True:
                    item = await loop.run_in_executor(pool, _next)
                    if item is _END:
                        return
                    yield item
            elif hasattr(out, "__aiter__"):
                with _rl.use_ledger(led):
                    async for item in out:
                        yield item
            elif isinstance(out, (list, tuple)):
                for item in out:
                    yield item
            else:
                yield out
        except Exception as e:  # noqa: BLE001 — terminal classification
            if led is not None:
                led.finish(_terminal_of(e), type(e).__name__)
            raise
        finally:
            self._ongoing -= 1
            self._observe_latency(time.monotonic() - t0)
            if led is not None:
                led.finish("ok")  # no-op if a terminal already landed

    # -- control plane ------------------------------------------------
    def _reject_if_saturated(self):
        """Per-replica admission bound: `max_ongoing_requests` holds in
        AGGREGATE, not just per router.  Rejections carry a retry-after
        hint priced at the replica's observed mean request latency (one
        slot frees roughly that often under saturation); the hint rides
        the exception message across the TaskError wire wrapping."""
        if self._ongoing < self._max_ongoing:
            return
        self._rejected += 1
        mean_s = (self._latency_sum_s / self._completed
                  if self._completed else 0.0)
        raise BackPressureError(
            f"replica {self._replica_id} at "
            f"max_ongoing_requests={self._max_ongoing}",
            retry_after_s=max(0.05, min(30.0, mean_s or 1.0)),
        )

    def _observe_latency(self, seconds: float):
        self._completed += 1
        self._latency_sum_s += seconds
        for i, bound in enumerate(LATENCY_BOUNDARIES):
            if seconds <= bound:
                self._latency_buckets[i] += 1
                break

    def get_metrics(self) -> Dict[str, Any]:
        out = {
            "replica_id": self._replica_id,
            "ongoing": self._ongoing,
            "total": self._total,  # started (includes in-flight)
            "completed": self._completed,  # histogram count basis
            "rejected": self._rejected,  # replica-cap backpressure
            "latency_sum_s": self._latency_sum_s,
            "latency_buckets": list(self._latency_buckets),
        }
        # cumulative SLO counter block from the request ledger (slo.py
        # shape): the controller delta-folds it into the deployment's
        # burn-rate tracker.  Absent when telemetry never ran here.
        slo_blk = _rl.slo_snapshot().get(
            f"{self._app}/{self._deployment_name}"
        )
        if slo_blk is not None:
            out["slo"] = slo_blk
        # user-callable load signals (reference: the pow-2 scheduler's
        # queue-len RPC): a deployment exposing `stats()` — e.g. the
        # continuous-batching LLM engine's queue depth / TTFT / block
        # occupancy — gets them piggybacked to the controller, where
        # they feed queue-depth routing and the /api/serve dashboard.
        # CONTRACT: stats() runs on the health-check path, so it must
        # be fast and non-blocking (the engine's bounds its lock wait
        # to 0.25 s) — a stats() that stalls past
        # health_check_timeout_s gets its replica restarted, the same
        # deal user check_health() methods already have
        stats_fn = getattr(self._callable, "stats", None)
        if callable(stats_fn):
            try:
                user = stats_fn()
            except Exception as e:
                # load signals are advisory; request serving must not
                # depend on them
                logger.debug("stats() of %s failed: %s",
                             self._replica_id, e)
                user = None
            if inspect.isawaitable(user):
                # an `async def stats()` would otherwise be silently
                # dropped (and warn 'never awaited' every health tick)
                if inspect.iscoroutine(user):  # Futures have no close()
                    user.close()
                logger.debug("stats() of %s is async; load signals "
                             "must be a plain sync method",
                             self._replica_id)
                user = None
            if isinstance(user, dict):
                out["user_stats"] = user
                try:
                    out["engine_queue_depth"] = float(
                        user["queue_depth"]
                    )
                except (KeyError, TypeError, ValueError) as e:
                    logger.debug("queue_depth signal of %s unusable: "
                                 "%s", self._replica_id, e)
        return out

    def get_queue_len(self) -> int:
        return self._ongoing

    def check_health(self) -> Dict[str, Any]:
        """Runs on the worker thread pool (sync method); async user
        health checks are driven to completion on the actor's loop.
        The reply piggybacks per-replica metrics so the controller's
        health cadence doubles as the metrics collection cadence
        (reference: `serve/metrics.py` replica series) — a failing
        user health check raises so the controller's error path fires."""
        hc = getattr(self._callable, "check_health", None)
        if hc is not None:
            out = hc()
            if inspect.isawaitable(out):
                from ray_tpu.core.runtime import get_runtime

                out = asyncio.run_coroutine_threadsafe(
                    _ensure_coro(out), get_runtime().loop
                ).result(10)
            if out is not None and not bool(out):
                raise RuntimeError(
                    f"user health check failed on {self._replica_id}"
                )
        return self.get_metrics()

    def reconfigure(self, user_config) -> bool:
        self._apply_user_config(user_config)
        return True

    async def _call_user_hook(self, name: str):
        """Optional drain-lifecycle hooks on the user callable (dunder
        names so they can't collide with request methods): sync or
        async, failures logged — a broken hook must not block the
        controller's drain sequence."""
        hook = getattr(self._callable, name, None)
        if not callable(hook):
            return
        try:
            out = hook()
            if inspect.isawaitable(out):
                await out
        except Exception as e:
            logger.debug("%s hook of %s failed: %s",
                         name, self._replica_id, e)

    async def drain(self, timeout_s: float = 5.0) -> bool:
        """Graceful drain before shutdown (reference:
        graceful_shutdown_timeout_s handling in `replica.py`): by the
        time this runs the controller has already removed the replica
        from routing tables, so no NEW requests arrive except a brief
        stale-table race.  Sequence: `__serve_drain__` tells the user
        callable to stop admitting (the LLM engine rejects new
        submissions but finishes live sequences), the loop waits out
        in-flight requests, and `__serve_shutdown__` releases device
        state (KV block pool) deterministically before the kill."""
        await self._call_user_hook("__serve_drain__")
        deadline = time.monotonic() + timeout_s
        while self._ongoing > 0 and time.monotonic() < deadline:
            await asyncio.sleep(0.02)
        drained = self._ongoing == 0
        # run the release hook even on a TIMED-OUT drain: the
        # controller kills the replica either way, and a wedged
        # request is exactly the case where deterministic device-state
        # release beats actor-kill teardown
        await self._call_user_hook("__serve_shutdown__")
        return drained
