"""SLO-driven serve autoscaling policy.

Reference: `python/ray/serve/autoscaling_policy.py` +
`_private/autoscaling_state.py` — but where the reference scales on
handle-reported ongoing-request counts, this policy consumes the
ENGINE-grade signals the stats() piggyback already delivers to the
controller on the health-check cadence (PR 6): per-replica queue
depth, windowed TTFT p90, and shed/rejection counters.  That makes the scaling
loop close over the metric users actually experience (time to first
token) instead of a proxy for it, and lets an overloaded system that
is actively REFUSING work scale out even when its smoothed latency
EMAs still look acceptable.

The controller owns the cadence and the cooldowns; this module owns
the decision:

    pressure(metrics)          -> instantaneous load ratio r
    desired_replicas(avg_r, n) -> target replica count

`r` is normalized so 1.0 means "exactly at SLO": the controller
smooths it over `look_back_period_s` and applies
`upscale_delay_s`/`downscale_delay_s` exactly as for the legacy
ongoing-requests policy.
"""

from __future__ import annotations

import math
from typing import Any, Dict, List

from ray_tpu.serve.config import AutoscalingConfig

# how much headroom above the hysteresis band a shed/rejection burst
# asserts: refusing work is the strongest possible "under-provisioned"
# signal, so it must clear the dead band whatever the EMAs say
_SHED_PRESSURE_MARGIN = 0.01


def replica_depth(m: Dict[str, Any]) -> float:
    """Backlog signal for one replica's metrics dict: the engine's
    reported queue depth when the deployment exposes stats(), else the
    plain in-flight count.  THE definition of per-replica backlog —
    the controller's routing tables, the status panel, and the SLO
    policy all call this one helper, so queue-depth routing and
    autoscaling pressure can never silently diverge on what "backlog"
    means."""
    try:
        return float(m.get("engine_queue_depth",
                           m.get("ongoing", 0) or 0))
    except (TypeError, ValueError):
        return 0.0


class AutoscalingPolicy:
    """SLO policy state for ONE deployment (held by its
    `_DeploymentState`): tracks per-replica shed counters across ticks
    so a *rate* (new sheds since the last decision) is observable from
    the monotonic totals the engine exports."""

    def __init__(self, config: AutoscalingConfig):
        self.config = config
        # replica_id -> last seen (shed_total + rejections) totals;
        # replicas that restart reset their counters, so deltas are
        # clamped at zero rather than trusted to be monotonic
        self._last_refused: Dict[str, float] = {}
        # True when the LAST pressure() reading was forced above the
        # band by fresh refusals: the controller lets that reading
        # bypass its look-back smoothing (a one-tick burst of 503s
        # averaged into a quiet window would otherwise dilute below
        # the band and never scale — see _autoscale_slo)
        self.refusal_forced = False

    # -- signals -------------------------------------------------------
    def _refused_delta(self, metrics: List[Dict[str, Any]]) -> float:
        """New sheds + rejections since the previous pressure() call,
        summed across replicas (engine shed/rejected counters plus the
        replica-level max_ongoing rejections)."""
        total_delta = 0.0
        seen = {}
        for m in metrics:
            rid = str(m.get("replica_id", ""))
            us = m.get("user_stats") or {}
            refused = 0.0
            for src, key in ((us, "shed_total"), (us, "rejected_total"),
                             (m, "rejected")):
                try:
                    refused += float(src.get(key, 0) or 0)
                except (TypeError, ValueError):
                    pass
            seen[rid] = refused
            total_delta += max(0.0, refused - self._last_refused.get(rid, 0.0))
        # dropped replicas leave the map with their counters; a fresh
        # replica reusing the id starts over (delta clamped at 0)
        self._last_refused = seen
        return total_delta

    def pressure(self, metrics: List[Dict[str, Any]]) -> float:
        """Instantaneous load ratio for the deployment: the max over
        configured SLOs of observed/target.

        - TTFT: the WORST replica's `ttft_p90_s` — the engine's
          WINDOWED percentile over `RT_SERVE_TTFT_WINDOW_S` (a
          p99-flavored reading — one replica missing the SLO means
          real users missing it, however good the mean looks).  The
          windowed percentile decays to zero once its samples age out,
          so a storm-inflated reading stops asserting pressure within
          one window of the storm ending.  The PR-10 idle override
          (zero the ratio when nothing is in flight) existed only
          because the old lifetime TTFT EMA never decayed; it is
          retired along with the EMA input.
        - queue depth: the MEAN per-replica backlog (depth is additive
          across replicas, so the mean is what scaling actually
          changes);
        - sheds/rejections since the last tick force the ratio above
          the hysteresis band: a system refusing work is
          under-provisioned by definition."""
        cfg = self.config
        depths = [replica_depth(m) for m in metrics]
        refused = self._refused_delta(metrics)
        self.refusal_forced = refused > 0.0
        if not metrics:
            return 0.0
        r = 0.0
        if cfg.target_queue_depth is not None and depths:
            mean_depth = sum(depths) / len(depths)
            r = max(r, mean_depth / max(cfg.target_queue_depth, 1e-9))
        if cfg.target_ttft_s is not None:
            worst = 0.0
            for m in metrics:
                us = m.get("user_stats") or {}
                try:
                    worst = max(worst, float(us.get("ttft_p90_s", 0) or 0))
                except (TypeError, ValueError):
                    pass
            r = max(r, worst / max(cfg.target_ttft_s, 1e-9))
        if refused > 0.0:
            r = max(r, 1.0 + cfg.hysteresis + _SHED_PRESSURE_MARGIN)
        return r

    # -- decision ------------------------------------------------------
    def desired_replicas(self, avg_ratio: float, current: int) -> int:
        """Target replica count from the smoothed load ratio.

        Inside the hysteresis band [1-h, 1+h] the target holds (the
        cooldown clocks in the controller handle *time*; the band
        handles *amplitude*).  Above it, scale proportionally — capped
        at doubling per decision, so one noisy reading can't fork a
        fleet.  Below it, scale to the smallest count that would still
        sit under the band's ceiling, so the post-shrink ratio does
        not immediately re-trigger an upscale."""
        cfg = self.config
        current = max(1, current)
        h = max(0.0, cfg.hysteresis)
        if avg_ratio > 1.0 + h:
            desired = math.ceil(current * min(avg_ratio, 2.0))
        elif avg_ratio < 1.0 - h:
            desired = math.ceil(current * avg_ratio / max(1.0 - h, 1e-9))
        else:
            desired = current
        return max(cfg.min_replicas, min(cfg.max_replicas, max(desired, 0)))
