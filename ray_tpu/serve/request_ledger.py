"""Per-request latency ledger for the serve path.

A `RequestLedger` is a compact timestamp struct that rides one serve
request end to end — proxy arrival → router assignment wait → replica
queue → engine admission → prefill → first token → decode → terminal
(ok / shed / rejected / error) — and is surfaced three ways at terminal
time:

  * windowed histograms (`rt_serve_*_seconds` in the metric catalog),
    observed in the process that measured each phase and shipped on the
    existing obs-frame path to the merged `/metrics`;
  * phase-attributed trace spans on the PR-12 trace plane, with
    **tail-based capture**: the ledger buffers its span tree locally
    and commits it only at terminal time, so a request whose e2e
    latency lands in the slowest K% (`RT_SERVE_TAIL_PCT`, default 5) —
    or ANY shed/rejected/errored request — retains its spans even when
    the head-sampling roll at the root said drop;
  * cumulative SLO counter blocks (`slo.empty_counters` shape) that
    replicas piggyback on health checks for the controller's burn-rate
    tracker.

Hot-path discipline: `start_request` returns None unless metrics or
tracing is enabled, and every call site is a `led is not None` test —
a disabled ledger adds zero per-request allocations (asserted in
tests/test_serve_overload.py).  The ledger itself is `__slots__`-only
and defers ALL span-dict construction to the terminal path.

Threading note: the ambient ledger rides a contextvar (like the trace
context) so it crosses the proxy → handle → router chain without
plumbing; replica-side it is re-installed explicitly inside executor
thunks because `run_in_executor` does not propagate contextvars.
"""

from __future__ import annotations

import contextvars
import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

from ray_tpu.metrics import metric_defs as _md
from ray_tpu.serve import slo as _slo
from ray_tpu.util import tracing as _tracing

# slowest-K% capture knobs: a terminal e2e at or above the ring's
# (100 - PCT) percentile force-retains the span tree
TAIL_PCT = float(os.environ.get("RT_SERVE_TAIL_PCT", "5") or 5)
TAIL_RING = int(os.environ.get("RT_SERVE_TAIL_RING", "512") or 512)
# below this many observations the tail threshold is undefined and
# nothing qualifies as tail (refused requests are still retained)
TAIL_MIN_SAMPLES = 16

# phase name -> cataloged histogram observed at terminal time
_PHASE_METRICS = {
    "queue_wait": "rt_serve_queue_wait_seconds",
    "prefill": "rt_serve_prefill_seconds",
}
# note key -> cataloged histogram (values measured engine-side)
_NOTE_METRICS = {
    "ttft_s": "rt_serve_ttft_seconds",
    "tpot_s": "rt_serve_tpot_seconds",
    "prefill_s": "rt_serve_prefill_seconds",
    "queue_wait_s": "rt_serve_queue_wait_seconds",
}

_ledger_var: contextvars.ContextVar = contextvars.ContextVar(
    "rt_serve_ledger", default=None
)


def enabled() -> bool:
    """Ledger structs are allocated only when some consumer exists."""
    return _md.enabled() or _tracing.is_enabled()


def current() -> Optional["RequestLedger"]:
    return _ledger_var.get()


class use_ledger:
    """Install `led` as the ambient request ledger (set + reset in the
    same frame).  None is a no-op so call sites stay branch-free."""

    def __init__(self, led: Optional["RequestLedger"]):
        self._led = led
        self._token = None

    def __enter__(self):
        if self._led is not None:
            self._token = _ledger_var.set(self._led)
        return self._led

    def __exit__(self, exc_type, exc, tb):
        if self._token is not None:
            _ledger_var.reset(self._token)
            self._token = None
        return False


class _TailSampler:
    """Bounded ring of recent completed-request e2e latencies defining
    the slowest-K% retention threshold for this process."""

    __slots__ = ("_ring", "_lock")

    def __init__(self, maxlen: int = TAIL_RING):
        self._ring: deque = deque(maxlen=maxlen)
        self._lock = threading.Lock()

    def observe(self, e2e_s: float):
        with self._lock:
            self._ring.append(e2e_s)

    def is_tail(self, e2e_s: float) -> bool:
        with self._lock:
            n = len(self._ring)
            if n < TAIL_MIN_SAMPLES:
                return False
            k = max(1, int(n * TAIL_PCT / 100.0))
            threshold = sorted(self._ring)[-k]
        return e2e_s >= threshold

    def reset(self):
        with self._lock:
            self._ring.clear()


_tail = _TailSampler()


# per-process cumulative SLO counter blocks, keyed (app, deployment);
# replicas ship their process's block on the health piggyback
_slo_lock = threading.Lock()
_slo_agg: Dict[tuple, Dict[str, Any]] = {}


def slo_snapshot() -> Dict[str, Dict[str, Any]]:
    """{"app/deployment": counter block} for this process (cumulative;
    the controller folds deltas)."""
    with _slo_lock:
        return {
            f"{app}/{dep}": {
                "n": blk["n"], "errors": blk["errors"],
                "ttft": list(blk["ttft"]), "e2e": list(blk["e2e"]),
            }
            for (app, dep), blk in _slo_agg.items()
        }


def _slo_record(app: str, dep: str, e2e_s: float,
                ttft_s: Optional[float], ok: bool):
    with _slo_lock:
        blk = _slo_agg.get((app, dep))
        if blk is None:
            blk = _slo_agg[(app, dep)] = _slo.empty_counters()
        blk["n"] += 1
        if not ok:
            blk["errors"] += 1
        blk["e2e"][_slo.bucket_index(e2e_s)] += 1
        if ttft_s is not None:
            blk["ttft"][_slo.bucket_index(ttft_s)] += 1


def _reset_for_tests():
    _tail.reset()
    with _slo_lock:
        _slo_agg.clear()


class RequestLedger:
    """One request's phase timeline.  Built by `start_request`, carried
    ambiently (`use_ledger`) or explicitly, closed exactly once by
    `finish`."""

    __slots__ = ("kind", "app", "deployment", "replica", "trace_id",
                 "root_id", "parent_id", "sampled", "t0", "t_end",
                 "phases", "notes", "status", "reason", "_cur", "_cur_t",
                 "_extra_spans")

    def __init__(self, kind: str, app: str, deployment: str,
                 replica: str = "-"):
        self.kind = kind
        self.app = app
        self.deployment = deployment
        self.replica = replica
        self.t0 = time.time()
        self.t_end: Optional[float] = None
        self.phases: List[tuple] = []  # (name, t_start, t_end)
        self.notes: Dict[str, Any] = {}
        self.status = "ok"
        self.reason: Optional[str] = None
        self._cur: Optional[str] = None
        self._cur_t = self.t0
        self._extra_spans: List[Dict[str, Any]] = []
        # trace identity: join an ambient sampled trace, inherit a
        # NOT_SAMPLED decision (fresh id kept aside for tail capture),
        # or make the head-sampling roll ourselves as a new root
        self.parent_id: Optional[str] = None
        if _tracing.is_enabled():
            parent = _tracing.current_context()
            if parent and parent.get("trace_id"):
                self.trace_id = parent["trace_id"]
                self.parent_id = parent.get("span_id")
                self.sampled = True
            else:
                self.trace_id = _tracing.new_id()
                self.sampled = (parent is None and _tracing._sampled())
            self.root_id = _tracing.new_id()
        else:
            self.trace_id = ""
            self.root_id = ""
            self.sampled = False

    # -- trace context ------------------------------------------------
    def ctx(self) -> Optional[Dict[str, str]]:
        """Ambient trace context to install around downstream work.
        Sampled requests expose the real (trace_id, root span) so the
        runtime's submit/run spans join the request's trace; unsampled
        ones expose NOT_SAMPLED so the whole lineage does zero span
        work — tail capture then retains the ledger's own phase tree."""
        if not self.trace_id:
            return None
        if self.sampled:
            return {"trace_id": self.trace_id, "span_id": self.root_id}
        return dict(_tracing.NOT_SAMPLED)

    # -- phase timeline -----------------------------------------------
    def begin(self, phase: str, now: Optional[float] = None):
        """Close the current phase (if any) and open `phase`.  Phases
        are contiguous, so their durations sum to e2e exactly."""
        now = time.time() if now is None else now
        if self._cur is not None:
            self.phases.append((self._cur, self._cur_t, now))
        self._cur = phase
        self._cur_t = now

    def note(self, key: str, value: Any):
        self.notes[key] = value

    def add_span(self, name: str, start: float, end: float,
                 **attrs: Any):
        """Attach a pre-measured child span (engine-side phases carry
        exact loop-thread timestamps).  Buffered until terminal time —
        tail capture decides whether it ever records."""
        if not self.trace_id:
            return
        rec: Dict[str, Any] = {
            "name": name, "trace_id": self.trace_id,
            "span_id": _tracing.new_id(), "parent_id": self.root_id,
            "start": start, "end": end, "kind": "INTERNAL",
        }
        if attrs:
            rec["attrs"] = attrs
        self._extra_spans.append(rec)

    # -- terminal -----------------------------------------------------
    def finish(self, status: str = "ok", reason: Optional[str] = None,
               now: Optional[float] = None) -> float:
        """Close the ledger exactly once: observe histograms, fold SLO
        counters, and commit the span tree when retained (sampled, or
        refused/errored, or slowest-K% e2e).  Returns e2e seconds."""
        if self.t_end is not None:
            return self.t_end - self.t0
        now = time.time() if now is None else now
        if self._cur is not None:
            self.phases.append((self._cur, self._cur_t, now))
            self._cur = None
        if status != "ok":
            # zero-duration terminal marker: refused/errored requests
            # carry their reason as an inspectable phase (and span)
            self.phases.append((f"terminal:{status}", now, now))
        self.t_end = now
        self.status = status
        self.reason = reason
        e2e = now - self.t0
        tags = {"app": self.app, "deployment": self.deployment,
                "replica": self.replica}
        _md.observe("rt_serve_e2e_seconds", e2e, tags=tags)
        for name, ts, te in self.phases:
            mname = _PHASE_METRICS.get(name)
            if mname is not None:
                _md.observe(mname, te - ts, tags=tags)
        for key, mname in _NOTE_METRICS.items():
            v = self.notes.get(key)
            if v is not None:
                _md.observe(mname, float(v), tags=tags)
        # SLO counters fold replica-side only: the proxy-side ledger
        # would double-count the same request
        if self.replica != "-":
            ttft = self.notes.get("ttft_s")
            _slo_record(self.app, self.deployment, e2e,
                        float(ttft) if ttft is not None else None,
                        ok=(status == "ok"))
        # -- tail-based span retention --------------------------------
        if self.trace_id and _tracing.is_enabled():
            refused = status != "ok"
            retain = self.sampled or refused or _tail.is_tail(e2e)
            if not refused:
                _tail.observe(e2e)
            if retain:
                _tracing.record_spans(self._spans())
        self._extra_spans = []
        return e2e

    def _spans(self) -> List[Dict[str, Any]]:
        attrs: Dict[str, Any] = {
            "status": self.status, "kind": self.kind, "app": self.app,
            "deployment": self.deployment, "replica": self.replica,
        }
        if self.reason:
            attrs["reason"] = self.reason
        for k, v in self.notes.items():
            attrs[k] = v
        root: Dict[str, Any] = {
            "name": f"serve.request:{self.deployment}",
            "trace_id": self.trace_id, "span_id": self.root_id,
            "parent_id": self.parent_id, "start": self.t0,
            "end": self.t_end, "kind": "SERVER", "attrs": attrs,
        }
        if self.status != "ok":
            root["error"] = self.reason or self.status
        out = [root]
        for name, ts, te in self.phases:
            out.append({
                "name": f"serve.{name}", "trace_id": self.trace_id,
                "span_id": _tracing.new_id(), "parent_id": self.root_id,
                "start": ts, "end": te, "kind": "INTERNAL",
            })
        out.extend(self._extra_spans)
        return out


def start_request(kind: str, app: str, deployment: str,
                  replica: str = "-") -> Optional[RequestLedger]:
    """The single ledger entry point: None (and therefore zero further
    allocations) unless metrics or tracing is on."""
    if not enabled():
        return None
    return RequestLedger(kind, app, deployment, replica)


class EngineTicket:
    """The engine-side sliver of the ledger: one per admitted request,
    timestamps assigned on the engine loop thread (plain attribute
    stores, no allocation), assembled into ledger notes + spans only at
    the request's terminal tick."""

    __slots__ = ("ledger", "trace_ctx", "t_submit", "t_admit",
                 "t_prefill_done", "t_first", "t_done", "n_tokens")

    def __init__(self, ledger: Optional[RequestLedger],
                 trace_ctx: Optional[Dict[str, str]]):
        self.ledger = ledger
        self.trace_ctx = trace_ctx
        self.t_submit = time.time()
        self.t_admit = 0.0
        self.t_prefill_done = 0.0
        self.t_first = 0.0
        self.t_done = 0.0
        self.n_tokens = 0

    def admitted(self, now: float):
        self.t_admit = now

    def prefilled(self, now: float):
        self.t_prefill_done = now

    def first_token(self, now: float):
        self.t_first = now

    def done(self, n_tokens: int, now: Optional[float] = None):
        """Terminal assembly: compute TTFT/TPOT/prefill, note them on
        the ledger (the replica's `finish` observes the histograms with
        the right tags) and attach the engine phase spans."""
        self.t_done = time.time() if now is None else now
        self.n_tokens = n_tokens
        led = self.ledger
        ttft = (self.t_first - self.t_submit) if self.t_first else None
        prefill = ((self.t_prefill_done - self.t_admit)
                   if self.t_prefill_done and self.t_admit else None)
        tpot = None
        if self.t_first and n_tokens > 1:
            tpot = (self.t_done - self.t_first) / (n_tokens - 1)
        if led is not None:
            if ttft is not None:
                led.note("ttft_s", ttft)
            if prefill is not None:
                led.note("prefill_s", prefill)
            if tpot is not None:
                led.note("tpot_s", tpot)
            led.note("n_tokens", n_tokens)
            if self.t_admit:
                led.add_span("serve.admission", self.t_submit,
                             self.t_admit)
            if prefill is not None:
                led.add_span("serve.prefill", self.t_admit,
                             self.t_prefill_done)
            if self.t_first:
                led.add_span("serve.decode", self.t_prefill_done
                             or self.t_first, self.t_done,
                             n_tokens=n_tokens)
        elif self.trace_ctx and self.trace_ctx.get("trace_id"):
            # direct engine use under a sampled trace (no serve ledger):
            # record the phase spans immediately
            spans = []
            if self.t_admit:
                spans.append(self._span("serve.admission",
                                        self.t_submit, self.t_admit))
            if prefill is not None:
                spans.append(self._span("serve.prefill", self.t_admit,
                                        self.t_prefill_done))
            if self.t_first:
                spans.append(self._span(
                    "serve.decode", self.t_prefill_done or self.t_first,
                    self.t_done))
            _tracing.record_spans(spans)

    def refused(self, reason: str, now: Optional[float] = None):
        """Shed/rejected inside the engine: stamp the terminal reason
        on the ledger (the replica-side finish records the terminal
        phase; tail capture always retains refused requests)."""
        self.t_done = time.time() if now is None else now
        led = self.ledger
        if led is not None:
            led.note("engine_refused", reason)
            led.add_span("serve.shed", self.t_submit, self.t_done,
                         reason=reason)

    def _span(self, name: str, start: float, end: float) -> Dict[str, Any]:
        return {
            "name": name, "trace_id": self.trace_ctx["trace_id"],
            "span_id": _tracing.new_id(),
            "parent_id": self.trace_ctx.get("span_id"),
            "start": start, "end": end, "kind": "INTERNAL",
        }


def engine_ticket() -> Optional[EngineTicket]:
    """Ticket for one engine submit: rides the ambient ledger and/or a
    sampled ambient trace; None (no allocation) when neither exists."""
    led = _ledger_var.get()
    ctx = _tracing.current_context() if _tracing.is_enabled() else None
    if led is None and (ctx is None or not ctx.get("trace_id")):
        return None
    return EngineTicket(led, ctx)
