"""Paged KV-cache bookkeeping: block pool + radix prefix cache.

The vLLM/SGLang serving levers (Kwon et al., SOSP 2023 PagedAttention;
Zheng et al., 2024 RadixAttention), host-side and TPU-shaped: the
device holds one fixed block pool (`[L, num_blocks, block_size, KV,
hd]` — a STATIC allocation, so XLA never re-plans memory), and these
classes decide which pool blocks each sequence's block table points at.

- `BlockPool`: free-list allocator over pool block ids.  Block 0 is a
  reserved scratch block: idle slots and block-table padding point at
  it, so gathers/scatters of inactive rows land somewhere harmless
  without any dynamic shapes.
- `RadixCache`: a token trie at BLOCK granularity whose nodes pin pool
  blocks holding the KV of one block's worth of prompt prefix.  A
  request whose prompt walks k nodes reuses k*block_size tokens of KV
  and skips prefill for them.  Only FULL prompt blocks are ever
  shared: a partially-filled tail block is also the block decode
  appends into, and sharing it would let one sequence's appends
  clobber another's reads.  Matching pins the path (refcounts);
  unpinned nodes are LRU-evicted when the pool runs low.

Everything here is plain host Python mutated only by the engine's
single scheduler thread — no locks, no device calls.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

SCRATCH_BLOCK = 0


KV_DTYPES = ("model", "int8")


class BlockPool:
    """Free-list allocator over device KV-pool block ids.

    `num_blocks` counts ALL blocks including the reserved scratch block
    0, which is never handed out.

    `kv_dtype` declares the DEVICE pool's element type: "model" stores
    K/V in the model's compute dtype; "int8" stores a symmetric
    per-row-per-kv-head int8 payload (half of bf16 per element) with an
    f32 scale sidecar `[L, num_blocks, block_size, KV]` living beside
    the pool — the engine allocates both and the kernels in
    `ops/paged_attention.py` fuse the dequant.  Pure bookkeeping here
    (block ids are dtype-blind); the pool carries the declaration so
    every consumer sizes and interprets the device tensors the same
    way."""

    def __init__(self, num_blocks: int, kv_dtype: str = "model"):
        if num_blocks < 2:
            raise ValueError("block pool needs >= 2 blocks (1 is scratch)")
        if kv_dtype not in KV_DTYPES:
            raise ValueError(
                f"kv_dtype={kv_dtype!r} not in {KV_DTYPES}"
            )
        self.num_blocks = num_blocks
        self.kv_dtype = kv_dtype
        # pop() from the tail hands out low ids first (stable layouts
        # across runs -> deterministic tests)
        self._free: List[int] = list(range(num_blocks - 1, 0, -1))

    @property
    def free_blocks(self) -> int:
        return len(self._free)

    @property
    def capacity(self) -> int:
        """Allocatable blocks (scratch excluded)."""
        return self.num_blocks - 1

    def alloc(self, n: int) -> Optional[List[int]]:
        """n blocks, or None if the pool can't cover them (caller
        evicts from the radix cache and retries)."""
        if n < 0:
            raise ValueError(f"alloc({n})")
        if n > len(self._free):
            return None
        out = [self._free.pop() for _ in range(n)]
        return out

    def free(self, ids: Sequence[int]) -> None:
        for b in ids:
            if b == SCRATCH_BLOCK:
                raise ValueError("freeing the scratch block")
            self._free.append(b)


class _Node:
    __slots__ = ("children", "parent", "key", "block", "refs", "last_use")

    def __init__(self, parent: Optional["_Node"], key: Optional[tuple],
                 block: Optional[int]):
        self.children: Dict[tuple, _Node] = {}
        self.parent = parent
        self.key = key
        self.block = block
        self.refs = 0
        self.last_use = 0


class RadixCache:
    """Prefix trie over prompt token blocks; nodes own pool blocks.

    Contract with the engine:
    - `match(tokens)` walks full prompt blocks (capped at len-1 tokens
      so at least one suffix token remains to produce logits), PINS the
      matched path, and returns (block_ids, path).
    - `insert(tokens, path, owned)` extends the matched path with the
      request's remaining full prompt blocks, adopting ids from
      `owned`; returns (full_path, adopted_ids).  The full path stays
      pinned until `release`.
    - `release(path)` unpins; blocks stay cached (refs 0 = evictable).
    - `evict(need)` frees up to `need` blocks from unpinned LEAVES,
      least-recently-matched first (a parent only becomes evictable
      once its children are gone, so eviction never orphans a deeper
      cached prefix).
    """

    def __init__(self, block_size: int, pool: BlockPool):
        if block_size < 1:
            raise ValueError(f"block_size={block_size}")
        self.block_size = block_size
        self._pool = pool
        self._root = _Node(None, None, None)
        # logical clock, not wall time: LRU order is deterministic
        # under test replay
        self._clock = 0
        self.cached_blocks = 0
        self.evicted_blocks = 0

    # -- lookup -------------------------------------------------------
    def _shareable_blocks(self, tokens: Sequence[int]) -> int:
        """Full blocks of `tokens` eligible for sharing: at least one
        token must stay un-shared (prefill needs >=1 position to emit
        the continuation logit)."""
        return max(0, (len(tokens) - 1) // self.block_size)

    def match(self, tokens: Sequence[int]) -> Tuple[List[int], List[_Node]]:
        bs = self.block_size
        self._clock += 1
        node = self._root
        blocks: List[int] = []
        path: List[_Node] = []
        for i in range(self._shareable_blocks(tokens)):
            child = node.children.get(tuple(tokens[i * bs:(i + 1) * bs]))
            if child is None:
                break
            child.refs += 1
            child.last_use = self._clock
            blocks.append(child.block)
            path.append(child)
            node = child
        return blocks, path

    def release(self, path: Sequence[_Node]) -> None:
        for n in path:
            n.refs -= 1

    # -- insertion ----------------------------------------------------
    def insert(self, tokens: Sequence[int], path: List[_Node],
               owned: Sequence[int]) -> Tuple[List[_Node], List[int]]:
        """Donate this request's full-prompt blocks to the trie.

        `path` is the pinned result of `match`; `owned` holds the
        request's freshly-prefilled block ids in position order
        starting at block index len(path).  Returns the extended
        (pinned) path and the ids the trie adopted — the caller must
        stop treating adopted ids as request-owned.  If a key already
        exists (possible only after a partial eviction raced... it
        cannot in the single-threaded engine, but stay defensive), the
        existing node is pinned and the caller keeps its duplicate
        block."""
        bs = self.block_size
        self._clock += 1
        node = path[-1] if path else self._root
        full_path = list(path)
        adopted: List[int] = []
        j = 0
        for i in range(len(path), self._shareable_blocks(tokens)):
            if j >= len(owned):
                break
            key = tuple(tokens[i * bs:(i + 1) * bs])
            child = node.children.get(key)
            if child is None:
                child = _Node(node, key, owned[j])
                node.children[key] = child
                adopted.append(owned[j])
                self.cached_blocks += 1
            child.refs += 1
            child.last_use = self._clock
            full_path.append(child)
            node = child
            j += 1
        return full_path, adopted

    # -- eviction -----------------------------------------------------
    def _leaves(self) -> List[_Node]:
        out, stack = [], list(self._root.children.values())
        while stack:
            n = stack.pop()
            if n.children:
                stack.extend(n.children.values())
            elif n.refs <= 0:
                out.append(n)
        return out

    def evict(self, need: int) -> int:
        """Free up to `need` blocks back to the pool; returns the count
        actually freed.  LRU over unpinned leaves, repeated so a freed
        leaf's parent becomes eligible within the same call."""
        freed = 0
        while freed < need:
            leaves = sorted(self._leaves(), key=lambda n: n.last_use)
            if not leaves:
                break
            for n in leaves:
                if freed >= need:
                    break
                del n.parent.children[n.key]
                self._pool.free([n.block])
                self.cached_blocks -= 1
                self.evicted_blocks += 1
                freed += 1
        return freed
