"""DeploymentHandle: Python-level calls into a deployment.

Reference: `python/ray/serve/handle.py` (`DeploymentHandle.remote:710,782`):
the composition primitive — deployments hold handles to other
deployments and call them like functions.  `.remote()` returns a
`DeploymentResponse`: `.result()` blocks (sync callers), `await response`
resolves on the event loop (async callers), and responses passed as
arguments to further `.remote()` calls resolve to their values before
the downstream request executes (the reference converts them to
ObjectRefs; the runtime's ObjectRef capture does the same here).

Submission is lazy: the replica is chosen when the response is first
awaited/resolved/passed on, which lets one `.remote()` API serve both
the blocking and the event-loop path without ever blocking the runtime's
io loop from inside it.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Any, Dict, Optional

import ray_tpu as rt
from ray_tpu.serve.router import Router

logger = logging.getLogger(__name__)

_routers: Dict[tuple, Router] = {}
_routers_lock = threading.Lock()
# routing-table push watcher (reference: serve's long_poll.py — the
# controller pushes table-change notifications instead of routers
# polling): one subscription + daemon thread per process, fanning
# refreshes out to the cached routers
_route_watch: Dict[str, Any] = {"thread": None, "sub": None}


def _close_routers():
    """Close and forget all cached routers (serve shutdown / reset)."""
    with _routers_lock:
        routers = list(_routers.values())
        _routers.clear()
        sub = _route_watch.pop("sub", None)
        _route_watch["thread"] = None
        _route_watch["sub"] = None
    for r in routers:
        r.close()
    if sub is not None:
        try:
            sub.close()
        except Exception as e:
            logger.debug("closing route-watch subscription: %s", e)


def _ensure_route_watcher():
    """Start the per-process push listener (idempotent).  Failure to
    subscribe is non-fatal: routers still converge via their periodic
    refresh, pushes just make table changes take effect immediately.
    The subscribe RPC runs INSIDE the watcher thread — callers may be
    on the runtime's io loop (proxy dispatch), where a blocking
    subscribe would deadlock the loop."""
    with _routers_lock:
        t = _route_watch.get("thread")
        if t is not None and t.is_alive():
            return
        t = threading.Thread(
            target=_route_watch_main, daemon=True,
            name="serve-route-watch",
        )
        _route_watch["thread"] = t
        t.start()


def _route_watch_main():
    try:
        from ray_tpu.core.runtime import get_runtime

        sub = get_runtime().subscribe("serve:routes")
    except Exception as e:
        logger.debug("route-watch subscribe failed (%s); routers fall "
                     "back to periodic refresh", e)
        with _routers_lock:
            if _route_watch.get("thread") is threading.current_thread():
                _route_watch["thread"] = None
        return
    with _routers_lock:
        if _route_watch.get("thread") is not threading.current_thread():
            # closed while subscribing: drop the registration
            sub_stale = sub
        else:
            _route_watch["sub"] = sub
            sub_stale = None
    if sub_stale is not None:
        try:
            sub_stale.close()
        except Exception as e:
            logger.debug("closing stale route-watch subscription: %s", e)
        return
    _route_watch_loop(sub)


def _route_watch_loop(sub):
    import queue as _q

    while _route_watch.get("sub") is sub:
        try:
            msg = sub.next_message(timeout=1.0)
        except _q.Empty:
            continue
        except Exception as e:
            logger.debug("route-watch subscription broke (%s); exiting "
                         "watcher", e)
            return
        if not isinstance(msg, dict):
            continue
        key = (msg.get("app"), msg.get("deployment"))
        with _routers_lock:
            r = _routers.get(key)
        if r is None:
            continue
        try:
            if msg.get("deleted") or msg.get("version", -1) > r._version:
                r._refresh(force=True)
        except Exception as e:
            # next push or periodic refresh retries
            logger.debug("pushed route refresh failed: %s", e)


def _on_runtime_loop() -> bool:
    """True when running on the runtime's io-loop thread, where blocking
    runtime calls would deadlock."""
    from ray_tpu.core.runtime import get_runtime, is_initialized

    if not is_initialized():
        return False
    try:
        loop = get_runtime().loop
        import asyncio

        return asyncio.get_running_loop() is loop
    except RuntimeError:
        return False


async def _await_ready(ref):
    """Await an owned ref's readiness before submission so the runtime's
    synchronous dependency-resolution fast path applies (submitting a
    pending ref from the io loop would otherwise fall into the blocking
    resolver and deadlock the loop)."""
    from ray_tpu.core.runtime import get_runtime

    st = get_runtime().objects.get(ref.binary())
    if st is not None:
        await st.ready.wait()


def _router_for(app_name: str, deployment_name: str) -> Router:
    key = (app_name, deployment_name)
    with _routers_lock:
        r = _routers.get(key)
        created = r is None
        if created:
            r = Router(deployment_name, app_name)
            _routers[key] = r
    if created:
        _ensure_route_watcher()
    return r


class DeploymentResponse:
    """Future-like result of a handle call (reference:
    `serve/handle.py` DeploymentResponse)."""

    def __init__(self, router: Router, method: str, args: tuple, kwargs: dict,
                 timeout_s: Optional[float] = None):
        self._router = router
        self._method = method
        self._args = args
        self._kwargs = kwargs
        # handle-level timeout_s, anchored at CALL time so the budget
        # covers assignment queueing too; propagated into the replica
        # task's end-to-end deadline by the router
        self._deadline_s = (
            None if timeout_s is None else time.monotonic() + timeout_s
        )
        self._lock = threading.Lock()
        self._ref = None
        # Eager submission off the runtime's io loop (drivers, sync
        # replicas): requests overlap the way the reference's do.  On
        # the loop (async replicas, proxy) submission stays lazy and
        # happens at first await, which is async-safe.
        if not _on_runtime_loop():
            self._ensure_submitted()

    # -- submission ---------------------------------------------------
    def _ensure_submitted(self):
        with self._lock:
            if self._ref is None:
                args = tuple(
                    a._to_object_ref() if isinstance(a, DeploymentResponse) else a
                    for a in self._args
                )
                kwargs = {
                    k: (
                        v._to_object_ref()
                        if isinstance(v, DeploymentResponse)
                        else v
                    )
                    for k, v in self._kwargs.items()
                }
                self._ref = self._router.assign_request(
                    self._method, args, kwargs,
                    deadline_s=self._deadline_s,
                )
        return self._ref

    async def _ensure_submitted_async(self):
        if self._ref is None:
            args = []
            for a in self._args:
                if isinstance(a, DeploymentResponse):
                    a = await a._to_object_ref_async()
                    await _await_ready(a)
                args.append(a)
            kwargs = {}
            for k, v in self._kwargs.items():
                if isinstance(v, DeploymentResponse):
                    v = await v._to_object_ref_async()
                    await _await_ready(v)
                kwargs[k] = v
            ref = await self._router.assign_request_async(
                self._method, tuple(args), kwargs,
                deadline_s=self._deadline_s,
            )
            with self._lock:
                if self._ref is None:
                    self._ref = ref
        return self._ref

    # -- resolution ---------------------------------------------------
    def result(self, timeout_s: Optional[float] = None) -> Any:
        """Blocking resolution; must not be called from inside an async
        replica method — `await` the response there instead (same rule
        as the reference's handle API).  Without an explicit timeout,
        a handle-level `options(timeout_s=...)` budget bounds the wait."""
        ref = self._ensure_submitted()
        if timeout_s is None and self._deadline_s is not None:
            # slack past the deadline so the owner-side watchdog's
            # typed DeadlineExceededError lands on the ref before this
            # get's generic wait-timeout fires
            timeout_s = max(0.001, self._deadline_s - time.monotonic()) + 0.25
        return rt.get(ref, timeout=timeout_s)

    def __await__(self):
        from ray_tpu.core.runtime import get_runtime

        async def _resolve():
            ref = await self._ensure_submitted_async()
            return await get_runtime()._get_one(ref)

        return _resolve().__await__()

    def _to_object_ref(self):
        return self._ensure_submitted()

    async def _to_object_ref_async(self):
        return await self._ensure_submitted_async()

    def __reduce__(self):
        # A response captured inside task/actor args travels as its
        # underlying ObjectRef, so the downstream task awaits the value.
        return (_identity, (self._to_object_ref(),))


def _identity(x):
    return x


class DeploymentResponseGenerator:
    """Streaming result of a handle call made with
    `handle.options(stream=True)` (reference: `serve/handle.py`
    DeploymentResponseGenerator): iterating yields the values the
    replica's generator produces, incrementally."""

    def __init__(self, router: Router, method: str, args: tuple, kwargs: dict,
                 timeout_s: Optional[float] = None):
        self._router = router
        self._method = method
        self._args = args
        self._kwargs = kwargs
        # same anchoring as DeploymentResponse: the handle-level budget
        # covers assignment AND the replica generator's execution
        self._deadline_s = (
            None if timeout_s is None else time.monotonic() + timeout_s
        )
        self._gen = None  # ObjectRefGenerator once submitted
        self._lock = threading.Lock()
        if not _on_runtime_loop():
            self._ensure_submitted()

    def _ensure_submitted(self):
        with self._lock:
            if self._gen is None:
                args = tuple(
                    a._to_object_ref() if isinstance(a, DeploymentResponse) else a
                    for a in self._args
                )
                kwargs = {
                    k: (v._to_object_ref()
                        if isinstance(v, DeploymentResponse) else v)
                    for k, v in self._kwargs.items()
                }
                self._gen = self._router.assign_request(
                    self._method, args, kwargs, streaming=True,
                    deadline_s=self._deadline_s,
                )
        return self._gen

    async def _ensure_submitted_async(self):
        if self._gen is None:
            args = []
            for a in self._args:
                if isinstance(a, DeploymentResponse):
                    a = await a._to_object_ref_async()
                    await _await_ready(a)
                args.append(a)
            kwargs = {}
            for k, v in self._kwargs.items():
                if isinstance(v, DeploymentResponse):
                    v = await v._to_object_ref_async()
                    await _await_ready(v)
                kwargs[k] = v
            gen = await self._router.assign_request_async(
                self._method, tuple(args), kwargs, streaming=True,
                deadline_s=self._deadline_s,
            )
            with self._lock:
                if self._gen is None:
                    self._gen = gen
        return self._gen

    def __iter__(self):
        return self

    def __next__(self) -> Any:
        gen = self._ensure_submitted()
        return rt.get(next(gen))  # StopIteration propagates

    def __aiter__(self):
        return self

    async def __anext__(self) -> Any:
        from ray_tpu.core.runtime import get_runtime

        gen = await self._ensure_submitted_async()
        ref = await gen.__anext__()  # StopAsyncIteration propagates
        return await get_runtime()._get_one(ref)


class _HandleMethod:
    def __init__(self, handle: "DeploymentHandle", method_name: str):
        self._handle = handle
        self._method = method_name

    def remote(self, *args, **kwargs) -> DeploymentResponse:
        return self._handle._call(self._method, args, kwargs)


class DeploymentHandle:
    def __init__(self, deployment_name: str, app_name: str = "default",
                 _model_id: str = "", _stream: bool = False,
                 _timeout_s: Optional[float] = None):
        self.deployment_name = deployment_name
        self.app_name = app_name
        self._model_id = _model_id
        self._stream = _stream
        self._timeout_s = _timeout_s

    def _call(self, method: str, args: tuple, kwargs: dict):
        if self._model_id:
            from ray_tpu.serve.multiplex import MODEL_ID_KWARG

            kwargs = {**kwargs, MODEL_ID_KWARG: self._model_id}
        router = _router_for(self.app_name, self.deployment_name)
        if self._stream:
            return DeploymentResponseGenerator(router, method, args, kwargs,
                                               timeout_s=self._timeout_s)
        return DeploymentResponse(router, method, args, kwargs,
                                  timeout_s=self._timeout_s)

    def remote(self, *args, **kwargs) -> DeploymentResponse:
        return self._call("__call__", args, kwargs)

    def __getattr__(self, name: str) -> _HandleMethod:
        if name.startswith("_"):
            raise AttributeError(name)
        return _HandleMethod(self, name)

    def options(self, *, multiplexed_model_id: Optional[str] = None,
                stream: Optional[bool] = None,
                timeout_s: Optional[float] = None,
                **_opts) -> "DeploymentHandle":
        """`timeout_s` sets an end-to-end budget per call made through
        the returned handle: replica assignment, execution (propagated
        into the task's deadline, inherited by nested calls), and
        `.result()` all charge against it; when spent, the call fails
        with `DeadlineExceededError`."""
        from ray_tpu.api import _validate_timeout_s

        _validate_timeout_s({"timeout_s": timeout_s})
        if multiplexed_model_id is None and stream is None \
                and timeout_s is None:
            return self
        return DeploymentHandle(
            self.deployment_name, self.app_name,
            _model_id=(multiplexed_model_id
                       if multiplexed_model_id is not None
                       else self._model_id),
            _stream=self._stream if stream is None else stream,
            _timeout_s=(self._timeout_s if timeout_s is None
                        else timeout_s),
        )

    def __reduce__(self):
        return (
            DeploymentHandle,
            (self.deployment_name, self.app_name, self._model_id,
             self._stream, self._timeout_s),
        )

    def __repr__(self):
        return f"DeploymentHandle({self.app_name}/{self.deployment_name})"
