"""Model serving library (reference: `python/ray/serve/`).

Control plane: one `ServeController` actor reconciles replica sets,
health-checks them, and autoscales from replica metrics.  Data plane:
`DeploymentHandle` (Python) and `HTTPProxy` (HTTP) route requests to
replica actors with power-of-two-choices load balancing.  Replicas wrap
the user callable; `@serve.batch` batches requests into fixed-size
MXU-friendly groups so XLA-compiled inference programs are reused.
"""

from ray_tpu.serve.api import (
    Application,
    Deployment,
    delete,
    deployment,
    get_app_handle,
    get_deployment_handle,
    grpc_address,
    http_address,
    http_addresses,
    ingress,
    run,
    shutdown,
    slo_status,
    start,
    status,
)
from ray_tpu.serve.batching import batch
from ray_tpu.serve.multiplex import get_multiplexed_model_id, multiplexed
from ray_tpu.serve.config import AutoscalingConfig, DeploymentConfig, GRPCOptions, HTTPOptions
from ray_tpu.serve.handle import DeploymentHandle, DeploymentResponse
from ray_tpu.serve.request import Request, Response
from ray_tpu.serve.slo import SLOConfig

__all__ = [
    "Application",
    "AutoscalingConfig",
    "Deployment",
    "DeploymentConfig",
    "DeploymentHandle",
    "DeploymentResponse",
    "GRPCOptions",
    "HTTPOptions",
    "Request",
    "Response",
    "SLOConfig",
    "batch",
    "get_multiplexed_model_id",
    "multiplexed",
    "delete",
    "deployment",
    "get_app_handle",
    "get_deployment_handle",
    "grpc_address",
    "http_address",
    "http_addresses",
    "ingress",
    "run",
    "shutdown",
    "slo_status",
    "start",
    "status",
]
