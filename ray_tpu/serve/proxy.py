"""HTTP proxy actor: the serve data-plane ingress.

Reference: `python/ray/serve/_private/proxy.py` (`ProxyActor:1140`,
`HTTPProxy:766`) — one proxy actor serves HTTP, resolves the route
prefix to an application via the controller's route table, and forwards
the request to the app's ingress deployment through the same router the
Python handles use (pow-2 choice, `router.py`).  The reference rides
uvicorn/Starlette; here a dependency-free asyncio HTTP/1.1 server runs
directly on the worker's io loop.
"""

from __future__ import annotations

import asyncio
import json
import logging
import time
import traceback
from typing import Any, Dict, Optional, Tuple

logger = logging.getLogger(__name__)

from ray_tpu import exceptions as _exc
from ray_tpu.serve import request_ledger as _rl
from ray_tpu.serve.handle import DeploymentHandle
from ray_tpu.serve.request import Request, Response
from ray_tpu.util import tracing as _tracing

_MAX_BODY = 256 * 1024 * 1024


def _terminal_status(http_status: int) -> str:
    """Ledger terminal classification from the HTTP translation:
    503 == refused by backpressure, 504 == shed on deadline."""
    if http_status == 503:
        return "rejected"
    if http_status == 504:
        return "shed"
    return "error"


def _error_response(e: BaseException):
    """Translate a dispatch failure into an HTTP response tuple
    (status, content_type, body, extra_headers) — the overload
    boundary to HTTP:

    - BackPressureError (router-level, or replica/engine-level wrapped
      in a TaskError) -> 503 + `Retry-After` (delay-seconds, rounded
      UP so a 0.2 s hint doesn't become an immediate hot retry);
    - DeadlineExceededError / a replica-side deadline shed -> 504 (the
      caller's budget is spent; retrying the same budget cannot help);
    - anything else -> 500 with the traceback (unchanged behavior).
    """
    retry_after = _exc.backpressure_retry_after(e)
    if retry_after is not None:
        import math

        body = f"Service Unavailable: {e}".encode()
        return (503, "text/plain", body,
                {"Retry-After": str(max(1, math.ceil(retry_after)))})
    if _exc.is_deadline_expiry(e):
        return (504, "text/plain", f"Gateway Timeout: {e}".encode(), {})
    tb = traceback.format_exc()
    return (500, "text/plain",
            f"Internal Server Error: {e}\n{tb}".encode(), {})


class _StreamOut:
    """Marker wrapper: a dispatch produced a streaming response
    generator to be written chunked."""

    def __init__(self, gen):
        self.gen = gen  # DeploymentResponseGenerator (async-iterable)


def _encode_chunk(item) -> bytes:
    if isinstance(item, (bytes, bytearray)):
        return bytes(item)
    if isinstance(item, str):
        return item.encode()
    return (json.dumps(item) + "\n").encode()


class HTTPProxy:
    """Async actor; the listen socket lives on the actor's event loop."""

    def __init__(self, host: str = "127.0.0.1", port: int = 8000):
        self._host = host
        self._port = port
        self._server: Optional[asyncio.AbstractServer] = None
        self._route_cache: Dict[str, Tuple[float, Optional[Dict]]] = {}
        self._num_requests = 0

    async def start(self) -> int:
        if self._server is not None:  # idempotent (fleet re-adoption)
            return self._port
        self._server = await asyncio.start_server(
            self._handle_conn, self._host, self._port
        )
        self._port = self._server.sockets[0].getsockname()[1]
        return self._port

    def address(self) -> Tuple[str, int]:
        return (self._host, self._port)

    def num_requests(self) -> int:
        return self._num_requests

    async def stop(self) -> bool:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
            self._server = None
        return True

    # -- connection handling ------------------------------------------
    async def _handle_conn(self, reader: asyncio.StreamReader,
                           writer: asyncio.StreamWriter):
        try:
            while True:
                req = await self._read_request(reader)
                if req is None:
                    break
                self._num_requests += 1
                keep_alive = req.headers.get("connection", "keep-alive") != "close"
                # request ledger: proxy arrival is t0; control paths
                # (/-/healthz, /-/routes) are not user requests and
                # stay out of the latency surfaces
                led = (None if req.path.startswith("/-/")
                       else _rl.start_request("http", "-", "-"))
                try:
                    if led is not None:
                        led.begin("proxy")
                        # ambient trace ctx + ledger ride the dispatch
                        # chain (handle -> router -> runtime submit),
                        # so the whole request shares one trace id
                        with _tracing.use_context(led.ctx()), \
                                _rl.use_ledger(led):
                            out = await self._dispatch(req, led)
                    else:
                        out = await self._dispatch(req)
                except Exception as e:  # noqa: BLE001 — boundary to HTTP
                    # overload signals become retryable statuses (503 +
                    # Retry-After / 504), not generic 500s; 500 bodies
                    # carry the traceback
                    logger.debug("dispatch of %s failed: %s", req.path, e)
                    out = _error_response(e)
                    if led is not None:
                        led.finish(_terminal_status(out[0]),
                                   type(e).__name__)
                if isinstance(out, _StreamOut):
                    # chunked transfer: one chunk per generator item
                    # (reference: streaming responses through the proxy,
                    # `proxy.py` send_request_to_replica_streaming)
                    await self._write_stream(writer, out, keep_alive,
                                             led=led)
                    if led is not None:
                        led.finish("ok")
                else:
                    status, ctype, body, extra = out
                    if led is not None:
                        led.begin("write")
                    await self._write_response(
                        writer, status, ctype, body, extra, keep_alive
                    )
                    if led is not None:
                        led.finish("ok")
                if not keep_alive:
                    break
        except (ConnectionResetError, asyncio.IncompleteReadError):
            pass
        finally:
            try:
                writer.close()
                await writer.wait_closed()
            except Exception as e:
                logger.debug("closing http client connection: %s", e)

    async def _read_request(self, reader) -> Optional[Request]:
        try:
            line = await reader.readline()
        except (ConnectionResetError, asyncio.LimitOverrunError):
            return None
        if not line or line in (b"\r\n", b"\n"):
            return None
        parts = line.decode("latin1").strip().split()
        if len(parts) < 2:
            return None
        method, target = parts[0], parts[1]
        headers: Dict[str, str] = {}
        while True:
            line = await reader.readline()
            if not line or line in (b"\r\n", b"\n"):
                break
            if b":" in line:
                k, v = line.decode("latin1").split(":", 1)
                headers[k.strip().lower()] = v.strip()
        length = int(headers.get("content-length", "0") or "0")
        if length > _MAX_BODY:
            return None
        body = await reader.readexactly(length) if length else b""
        return Request(method, target, headers, body)

    # -- routing + dispatch -------------------------------------------
    async def _route(self, path: str) -> Optional[Dict]:
        hit = self._route_cache.get(path)
        now = time.monotonic()
        if hit is not None and now - hit[0] < 1.0:
            return hit[1]
        if len(self._route_cache) > 1024:  # drop expired entries
            self._route_cache = {
                p: (ts, r)
                for p, (ts, r) in self._route_cache.items()
                if now - ts < 1.0
            }
        from ray_tpu.core.runtime import get_runtime
        from ray_tpu.serve.api import _get_controller_async

        controller = await _get_controller_async()
        ref = controller.get_app_for_route.remote(path)
        route = await get_runtime()._get_one(ref)
        self._route_cache[path] = (now, route)
        return route

    async def _dispatch(self, req: Request, led=None):
        if req.path == "/-/healthz":
            return 200, "text/plain", b"ok", {}
        if req.path == "/-/routes":
            from ray_tpu.core.runtime import get_runtime
            from ray_tpu.serve.api import _get_controller_async

            controller = await _get_controller_async()
            ref = controller.get_serve_status.remote()
            status = await get_runtime()._get_one(ref)
            return 200, "application/json", json.dumps(status).encode(), {}
        route = await self._route(req.path)
        if route is None:
            return 404, "text/plain", b"no application for route", {}
        if led is not None:
            led.app = route["app"]
            led.deployment = route["ingress"]
            led.begin("backend")
        if route.get("streaming"):
            handle = DeploymentHandle(route["ingress"], route["app"],
                                      _stream=True)
            return _StreamOut(handle.remote(req))
        handle = DeploymentHandle(route["ingress"], route["app"])
        value = await handle.remote(req)
        return self._encode(value)

    def _encode(self, value: Any):
        if isinstance(value, Response):
            body = value.content
            ctype = value.content_type
            if isinstance(body, (dict, list)):
                body = json.dumps(body).encode()
                ctype = ctype or "application/json"
            elif isinstance(body, str):
                body = body.encode()
                ctype = ctype or "text/plain; charset=utf-8"
            elif not isinstance(body, (bytes, bytearray)):
                body = json.dumps(body).encode()
                ctype = ctype or "application/json"
            return value.status_code, ctype or "application/octet-stream", bytes(
                body
            ), value.headers
        if isinstance(value, (dict, list, int, float, bool)) or value is None:
            return 200, "application/json", json.dumps(value).encode(), {}
        if isinstance(value, str):
            return 200, "text/plain; charset=utf-8", value.encode(), {}
        if isinstance(value, (bytes, bytearray)):
            return 200, "application/octet-stream", bytes(value), {}
        return 200, "text/plain; charset=utf-8", str(value).encode(), {}

    async def _write_stream(self, writer, out: "_StreamOut",
                            keep_alive: bool, led=None):
        """Write one HTTP/1.1 chunked response, one chunk per item the
        ingress generator yields — the client sees bytes as they are
        produced, not after the generator completes.

        The first item is pulled BEFORE the status line goes out: a
        replica/router failure up to that point is a clean 500.  After
        the 200 is committed, a mid-stream failure aborts the chunked
        body WITHOUT the terminating 0-chunk and closes the connection —
        the client sees a truncated transfer, never a well-formed
        response that silently lost data (reference: proxy streaming
        error handling in `proxy.py` send_request_to_replica_streaming).
        """
        gen = out.gen.__aiter__()
        try:
            first = await gen.__anext__()
            ended = False
        except StopAsyncIteration:
            first, ended = None, True
        except Exception as e:  # noqa: BLE001 — boundary to HTTP
            # pre-commit failures translate like unary ones: a
            # backpressured stream is a clean 503 + Retry-After
            logger.debug("stream failed before first item: %s", e)
            status, ctype, body, extra = _error_response(e)
            if led is not None:
                led.finish(_terminal_status(status), type(e).__name__)
            await self._write_response(
                writer, status, ctype, body, extra, keep_alive,
            )
            return
        if ended or isinstance(first, str):
            ctype = "text/plain; charset=utf-8"
        elif isinstance(first, (bytes, bytearray)):
            ctype = "application/octet-stream"
        else:
            ctype = "application/x-ndjson"  # _encode_chunk JSON-encodes
        head = [
            "HTTP/1.1 200 OK",
            f"Content-Type: {ctype}",
            "Transfer-Encoding: chunked",
            f"Connection: {'keep-alive' if keep_alive else 'close'}",
        ]
        writer.write(("\r\n".join(head) + "\r\n\r\n").encode())
        await writer.drain()
        try:
            while not ended:
                chunk = _encode_chunk(first)
                if chunk:
                    writer.write(
                        f"{len(chunk):x}\r\n".encode() + chunk + b"\r\n"
                    )
                    await writer.drain()
                try:
                    first = await gen.__anext__()
                except StopAsyncIteration:
                    ended = True
        except Exception:  # noqa: BLE001 — mid-stream failure
            logger.exception("streaming response aborted mid-body")
            if led is not None:
                led.finish("error", "stream_aborted")
            writer.close()  # truncated chunked body signals the abort
            raise ConnectionResetError("stream aborted")
        writer.write(b"0\r\n\r\n")
        await writer.drain()

    async def _write_response(self, writer, status: int, ctype: str,
                              body: bytes, extra: Dict[str, str],
                              keep_alive: bool):
        reason = {
            200: "OK", 404: "Not Found", 500: "Internal Server Error",
            503: "Service Unavailable", 504: "Gateway Timeout",
        }.get(status, "Status")
        head = [
            f"HTTP/1.1 {status} {reason}",
            f"Content-Type: {ctype}",
            f"Content-Length: {len(body)}",
            f"Connection: {'keep-alive' if keep_alive else 'close'}",
        ]
        head += [f"{k}: {v}" for k, v in extra.items()]
        writer.write(("\r\n".join(head) + "\r\n\r\n").encode() + body)
        await writer.drain()
