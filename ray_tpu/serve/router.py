"""Request router: power-of-two-choices replica selection.

Reference: `python/ray/serve/_private/router.py` (`Router:321`) and
`replica_scheduler/pow_2_scheduler.py` (`PowerOfTwoChoicesReplicaScheduler:51`,
`choose_replica_for_request:773`): sample two candidate replicas, compare
queue lengths, send to the shorter queue; respect `max_ongoing_requests`
by retrying with backoff while all candidates are saturated.  Queue
lengths are the locally tracked in-flight counts, matching the
reference's local queue-len cache.

Two complete code paths: the sync one blocks (used from driver threads
and sync replicas) and the async one awaits on the runtime's io loop
(used from async replicas and the HTTP proxy) — mirroring the
reference's asyncio router embedded in handles.
"""

from __future__ import annotations

import asyncio
import random
import threading
import time
from typing import Any, Dict, List

import ray_tpu as rt


class _ReplicaInfo:
    __slots__ = ("replica_id", "handle", "max_ongoing", "local_inflight")

    def __init__(self, replica_id: str, handle, max_ongoing: int):
        self.replica_id = replica_id
        self.handle = handle
        self.max_ongoing = max_ongoing
        self.local_inflight = 0


class Router:
    """One per process per deployment (handles share it)."""

    # table CHANGES arrive pushed (serve:routes pubsub, handle.py's
    # route watcher); this period is the metrics-piggyback cadence and
    # the fallback for missed pushes
    REFRESH_PERIOD_S = 1.0

    def __init__(self, deployment_name: str, app_name: str = "default"):
        self._deployment = deployment_name
        self._app = app_name
        self._replicas: Dict[str, _ReplicaInfo] = {}
        self._version = -1
        self._lock = threading.Lock()
        self._last_refresh = 0.0
        import os as _os
        import uuid as _uuid

        self._router_id = f"{_os.getpid()}-{_uuid.uuid4().hex[:6]}"
        # cumulative request accounting, pushed to the controller with
        # the in-flight piggyback (reference: handles push autoscaling
        # AND observability metrics, `serve/_private/router.py` metrics
        # pusher) — drives the rt_serve_* Prometheus series
        self._completed_total = 0
        self._latency_sum_s = 0.0
        self._stats_push_pending = False
        self._deferred_task = None  # pending trailing-edge push, if any
        self._closed = False
        self._incarnation = None  # deployment identity from the table

    def close(self):
        """Cancel the trailing-edge stats push (if pending) so a serve
        shutdown doesn't leave an orphaned sleeping task on the runtime
        io loop ('Task was destroyed but it is pending!')."""
        with self._lock:
            self._closed = True
            task = self._deferred_task
            self._deferred_task = None
        if task is not None:
            try:
                from ray_tpu.core.runtime import get_runtime

                get_runtime().loop.call_soon_threadsafe(task.cancel)
            except Exception:
                pass

    # -- routing table maintenance ------------------------------------
    def _install_table(self, table):
        with self._lock:
            incarnation = table.get("incarnation")
            if incarnation != self._incarnation:
                # a redeploy under the same name: lifetime counters
                # belong to the PREVIOUS incarnation and must not fold
                # into the fresh deployment's totals
                self._incarnation = incarnation
                self._completed_total = 0
                self._latency_sum_s = 0.0
            if table["version"] != self._version:
                # surviving replicas keep their _ReplicaInfo identity:
                # completion callbacks hold references to these objects,
                # and recreating them would orphan in-flight decrements
                # (leaking capacity until the replica looks saturated)
                new: Dict[str, _ReplicaInfo] = {}
                for rid, (handle, max_ongoing) in table["replicas"].items():
                    info = self._replicas.get(rid)
                    if info is None:
                        info = _ReplicaInfo(rid, handle, max_ongoing)
                    else:
                        info.handle = handle
                        info.max_ongoing = max_ongoing
                    new[rid] = info
                self._replicas = new
                self._version = table["version"]
            self._last_refresh = time.monotonic()

    def _needs_refresh(self, force: bool) -> bool:
        return (
            force
            or not self._replicas
            or time.monotonic() - self._last_refresh > self.REFRESH_PERIOD_S
        )

    def _handle_metrics(self) -> Dict[str, int]:
        with self._lock:
            return {
                rid: r.local_inflight for rid, r in self._replicas.items()
            }

    def _handle_stats(self) -> Dict[str, float]:
        with self._lock:
            return {
                "completed": self._completed_total,
                "latency_sum_s": self._latency_sum_s,
                "incarnation": self._incarnation,
            }

    def _refresh(self, force: bool = False):
        if not self._needs_refresh(force):
            return
        from ray_tpu.serve.api import _get_controller

        try:
            controller = _get_controller()
            table = rt.get(
                controller.get_routing_table.remote(
                    self._app, self._deployment,
                    router_id=self._router_id,
                    handle_metrics=self._handle_metrics(),
                    handle_stats=self._handle_stats(),
                ),
                timeout=10,
            )
        except Exception:
            # controller down (crash/restart window): keep serving from
            # the cached table — live replicas are unaffected by a
            # control-plane outage (reference behavior during controller
            # recovery); retry on the next refresh period
            if self._replicas:
                with self._lock:
                    self._last_refresh = time.monotonic()
                return
            raise
        self._install_table(table)

    async def _deferred_stats_push(self):
        """Trailing-edge stats delivery: ride the normal refresh (which
        also installs the fetched table) after the burst settles."""
        try:
            await asyncio.sleep(1.1)
        finally:
            with self._lock:
                self._stats_push_pending = False
                self._deferred_task = None
        if self._closed:
            return
        try:
            await self._refresh_async(force=True)
        except Exception:
            pass  # stats are advisory; the next refresh re-reports

    async def _refresh_async(self, force: bool = False):
        if not self._needs_refresh(force):
            return
        from ray_tpu.core.runtime import get_runtime
        from ray_tpu.serve.api import _get_controller_async

        try:
            controller = await _get_controller_async()
            ref = controller.get_routing_table.remote(
                self._app, self._deployment,
                router_id=self._router_id,
                handle_metrics=self._handle_metrics(),
                handle_stats=self._handle_stats(),
            )
            # bounded like the sync path: calls to a RESTARTING actor
            # queue until it comes back, which could be a long outage
            table = await asyncio.wait_for(get_runtime()._get_one(ref), 10)
        except Exception:
            if self._replicas:  # see _refresh: stale table beats nothing
                with self._lock:
                    self._last_refresh = time.monotonic()
                return
            raise
        self._install_table(table)

    # -- replica choice ----------------------------------------------
    def _try_pick(self, affinity_key: str = ""):
        with self._lock:
            cands = list(self._replicas.values())
            if not cands:
                return None
            if affinity_key:
                # model multiplexing: consistent choice per model id so
                # each model stays resident on one replica instead of
                # thrashing every LRU (reference: the pow-2 scheduler's
                # multiplex-aware candidate ranking)
                cands.sort(key=lambda r: r.replica_id)
                import zlib

                pick = cands[zlib.adler32(affinity_key.encode()) % len(cands)]
                if pick.local_inflight >= pick.max_ongoing:
                    pick = None  # saturated: fall through to pow-2
                if pick is not None:
                    pick.local_inflight += 1
                    return pick
            if len(cands) == 1:
                pick = cands[0]
            else:
                a, b = random.sample(cands, 2)
                pick = a if a.local_inflight <= b.local_inflight else b
            if pick.local_inflight < pick.max_ongoing:
                pick.local_inflight += 1
                return pick
            return None

    def _submit(self, info: _ReplicaInfo, method_name, args, kwargs,
                streaming: bool = False):
        # args flattened to top-level task args so ObjectRefs among them
        # (composed responses) are materialized by the runtime before
        # the replica method runs
        if streaming:
            out = info.handle.handle_request_streaming.remote(
                method_name, *args, **kwargs
            )
        else:
            out = info.handle.handle_request.remote(method_name, *args, **kwargs)

        t0 = time.monotonic()

        def _done():
            now = time.monotonic()
            with self._lock:
                info.local_inflight = max(0, info.local_inflight - 1)
                self._completed_total += 1
                self._latency_sum_s += now - t0
                # steady traffic delivers stats via the 0.25s refresh
                # piggyback; a burst's FINAL completions need this
                # trailing-edge push or they never reach the controller
                deferred = not self._stats_push_pending
                if deferred:
                    self._stats_push_pending = True
            if deferred:
                t = asyncio.ensure_future(self._deferred_stats_push())
                with self._lock:
                    if self._closed:
                        t.cancel()
                    else:
                        self._deferred_task = t

        # capacity frees when the replica replies, not when the caller
        # resolves the response (reference: the router decrements its
        # queue-len tracker on reply) — watch completion on the io loop
        import asyncio

        from ray_tpu.core.runtime import get_runtime

        rt_ = get_runtime()

        async def _watch():
            try:
                if streaming:
                    await rt_.stream_wait_done(out.task_id)
                else:
                    st = rt_.objects.get(out.binary())
                    if st is not None:
                        await st.ready.wait()
            finally:
                _done()

        asyncio.run_coroutine_threadsafe(_watch(), rt_.loop)
        return out

    def assign_request(self, method_name: str, args: tuple, kwargs: dict,
                       timeout_s: float = 30.0, streaming: bool = False):
        """Pick a replica and submit; returns the reply ObjectRef (or
        ObjectRefGenerator when streaming)."""
        from ray_tpu.serve.multiplex import MODEL_ID_KWARG

        affinity = kwargs.get(MODEL_ID_KWARG, "")
        deadline = time.monotonic() + timeout_s
        backoff = 0.005
        while True:
            self._refresh()
            info = self._try_pick(affinity)
            if info is not None:
                return self._submit(info, method_name, args, kwargs,
                                    streaming=streaming)
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"no available replica for {self._deployment} "
                    f"within {timeout_s}s"
                )
            time.sleep(backoff)
            backoff = min(backoff * 2, 0.25)
            self._refresh(force=True)

    async def assign_request_async(self, method_name: str, args: tuple,
                                   kwargs: dict, timeout_s: float = 30.0,
                                   streaming: bool = False):
        from ray_tpu.serve.multiplex import MODEL_ID_KWARG

        affinity = kwargs.get(MODEL_ID_KWARG, "")
        deadline = time.monotonic() + timeout_s
        backoff = 0.005
        while True:
            await self._refresh_async()
            info = self._try_pick(affinity)
            if info is not None:
                return self._submit(info, method_name, args, kwargs,
                                    streaming=streaming)
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"no available replica for {self._deployment} "
                    f"within {timeout_s}s"
                )
            await asyncio.sleep(backoff)
            backoff = min(backoff * 2, 0.25)
            await self._refresh_async(force=True)

    def ongoing_requests(self) -> int:
        with self._lock:
            return sum(r.local_inflight for r in self._replicas.values())
