"""Request router: power-of-two-choices replica selection.

Reference: `python/ray/serve/_private/router.py` (`Router:321`) and
`replica_scheduler/pow_2_scheduler.py` (`PowerOfTwoChoicesReplicaScheduler:51`,
`choose_replica_for_request:773`): sample two candidate replicas, compare
queue lengths, send to the shorter queue; respect `max_ongoing_requests`
by retrying with backoff while all candidates are saturated.  Queue
lengths are the locally tracked in-flight counts, matching the
reference's local queue-len cache.

Two complete code paths: the sync one blocks (used from driver threads
and sync replicas) and the async one awaits on the runtime's io loop
(used from async replicas and the HTTP proxy) — mirroring the
reference's asyncio router embedded in handles.
"""

from __future__ import annotations

import asyncio
import logging
import random
import threading
import time
from typing import Any, Dict, List, Optional

import ray_tpu as rt
from ray_tpu import exceptions as _exc
from ray_tpu.core import rpc as _rpc
from ray_tpu.metrics import metric_defs as _md
from ray_tpu.serve import request_ledger as _rl
from ray_tpu.util import tracing as _tracing

logger = logging.getLogger(__name__)


class _ReplicaInfo:
    __slots__ = ("replica_id", "handle", "max_ongoing", "local_inflight",
                 "breaker", "reported_depth")

    def __init__(self, replica_id: str, handle, max_ongoing: int,
                 breaker=None):
        self.replica_id = replica_id
        self.handle = handle
        self.max_ongoing = max_ongoing
        self.local_inflight = 0
        # resolved once at table install: _try_pick runs per request
        # and must not take the process-wide breaker-board lock
        self.breaker = breaker
        # controller-reported queue depth (an engine replica's
        # queued+active backlog, or its in-flight count): the
        # cross-router load signal this router's local_inflight can't
        # see.  Refreshed on every table fetch.
        self.reported_depth = 0.0

    def score(self) -> float:
        """Pow-2 comparison key: the max of the locally tracked
        in-flight count and the replica-reported backlog.  max, not
        sum — the reported depth already CONTAINS this router's own
        dispatched requests, and summing would double-count them
        (herding traffic away from a replica this router just used,
        ping-ponging load on every refresh).  A replica drowning in
        OTHER routers' (or slow in-engine) work still loses the coin
        flip even when this router has sent it nothing."""
        return max(float(self.local_inflight), self.reported_depth)


class Router:
    """One per process per deployment (handles share it)."""

    # table CHANGES arrive pushed (serve:routes pubsub, handle.py's
    # route watcher); this period is the metrics-piggyback cadence and
    # the fallback for missed pushes
    REFRESH_PERIOD_S = 1.0

    def __init__(self, deployment_name: str, app_name: str = "default"):
        self._deployment = deployment_name
        self._app = app_name
        self._replicas: Dict[str, _ReplicaInfo] = {}
        self._version = -1
        self._lock = threading.Lock()
        self._last_refresh = 0.0
        # admission control (overload plane): the deployment's
        # max_queued_requests, delivered with the routing table.  Caps
        # how many of THIS router's requests may sit waiting for a
        # replica slot; the (max_queued+1)-th waiter is rejected with
        # BackPressureError immediately instead of burning its whole
        # assignment timeout (reference: handle-side max_queued
        # rejection).  -1 = unbounded (legacy behavior).
        self._max_queued = -1
        self._waiting = 0  # requests inside the assignment wait loop
        self._rejected_total = 0
        import os as _os
        import uuid as _uuid

        self._router_id = f"{_os.getpid()}-{_uuid.uuid4().hex[:6]}"
        # cumulative request accounting, pushed to the controller with
        # the in-flight piggyback (reference: handles push autoscaling
        # AND observability metrics, `serve/_private/router.py` metrics
        # pusher) — drives the rt_serve_* Prometheus series
        self._completed_total = 0
        self._latency_sum_s = 0.0
        self._stats_push_pending = False
        self._deferred_task = None  # pending trailing-edge push, if any
        self._closed = False
        self._incarnation = None  # deployment identity from the table

    def close(self):
        """Cancel the trailing-edge stats push (if pending) so a serve
        shutdown doesn't leave an orphaned sleeping task on the runtime
        io loop ('Task was destroyed but it is pending!')."""
        with self._lock:
            self._closed = True
            task = self._deferred_task
            self._deferred_task = None
        if task is not None:
            try:
                from ray_tpu.core.runtime import get_runtime

                get_runtime().loop.call_soon_threadsafe(task.cancel)
            except Exception as e:
                logger.debug("cancelling deferred refresh: %s", e)

    # -- routing table maintenance ------------------------------------
    def _install_table(self, table):
        with self._lock:
            incarnation = table.get("incarnation")
            if incarnation != self._incarnation:
                # a redeploy under the same name: lifetime counters
                # belong to the PREVIOUS incarnation and must not fold
                # into the fresh deployment's totals
                self._incarnation = incarnation
                self._completed_total = 0
                self._latency_sum_s = 0.0
            if table["version"] != self._version:
                # surviving replicas keep their _ReplicaInfo identity:
                # completion callbacks hold references to these objects,
                # and recreating them would orphan in-flight decrements
                # (leaking capacity until the replica looks saturated)
                new: Dict[str, _ReplicaInfo] = {}
                for rid, (handle, max_ongoing) in table["replicas"].items():
                    info = self._replicas.get(rid)
                    if info is None:
                        info = _ReplicaInfo(
                            rid, handle, max_ongoing,
                            breaker=_rpc.breaker_for(self._breaker_key(rid)),
                        )
                    else:
                        info.handle = handle
                        info.max_ongoing = max_ongoing
                        # re-resolve from the board: reset_breakers()
                        # (rt.shutdown) replaces board entries, and a
                        # router surviving the cycle must not keep
                        # routing on an orphaned stale breaker
                        info.breaker = _rpc.breaker_for(
                            self._breaker_key(rid)
                        )
                    new[rid] = info
                # replicas that left the table take their breakers with
                # them: keeps the board bounded by live addresses and a
                # redeploy reusing the id starts with a clean breaker
                for rid in self._replicas:
                    if rid not in new:
                        _rpc.drop_breaker(self._breaker_key(rid))
                self._replicas = new
                self._version = table["version"]
            # depth signals refresh on EVERY fetch — same-version
            # tables still carry new load numbers
            depths = table.get("depths") or {}
            for rid, info in self._replicas.items():
                if rid in depths:
                    info.reported_depth = depths[rid]
            self._max_queued = int(table.get("max_queued", -1))
            self._last_refresh = time.monotonic()

    def _needs_refresh(self, force: bool) -> bool:
        return (
            force
            or not self._replicas
            or time.monotonic() - self._last_refresh > self.REFRESH_PERIOD_S
        )

    def _handle_metrics(self) -> Dict[str, int]:
        with self._lock:
            return {
                rid: r.local_inflight for rid, r in self._replicas.items()
            }

    def _handle_stats(self) -> Dict[str, float]:
        with self._lock:
            return {
                "completed": self._completed_total,
                "latency_sum_s": self._latency_sum_s,
                # assignment-queue rejections happen ENTIRELY in this
                # router (the request never reaches a replica), so this
                # is the only place they can be counted; the controller
                # delta-folds it into the deployment's overload panel
                "rejected": self._rejected_total,
                "incarnation": self._incarnation,
            }

    def _refresh(self, force: bool = False):
        if not self._needs_refresh(force):
            return
        from ray_tpu.serve.api import _get_controller

        try:
            controller = _get_controller()
            table = rt.get(
                controller.get_routing_table.remote(
                    self._app, self._deployment,
                    router_id=self._router_id,
                    handle_metrics=self._handle_metrics(),
                    handle_stats=self._handle_stats(),
                ),
                timeout=10,
            )
        except Exception:
            # controller down (crash/restart window): keep serving from
            # the cached table — live replicas are unaffected by a
            # control-plane outage (reference behavior during controller
            # recovery); retry on the next refresh period
            if self._replicas:
                with self._lock:
                    self._last_refresh = time.monotonic()
                return
            raise
        self._install_table(table)

    async def _deferred_stats_push(self):
        """Trailing-edge stats delivery: ride the normal refresh (which
        also installs the fetched table) after the burst settles."""
        try:
            await asyncio.sleep(1.1)
        finally:
            with self._lock:
                self._stats_push_pending = False
                self._deferred_task = None
        if self._closed:
            return
        try:
            await self._refresh_async(force=True)
        except Exception as e:
            # stats are advisory; the next refresh re-reports
            logger.debug("deferred table refresh failed: %s", e)

    async def _refresh_async(self, force: bool = False):
        if not self._needs_refresh(force):
            return
        from ray_tpu.core.runtime import get_runtime
        from ray_tpu.serve.api import _get_controller_async

        try:
            controller = await _get_controller_async()
            ref = controller.get_routing_table.remote(
                self._app, self._deployment,
                router_id=self._router_id,
                handle_metrics=self._handle_metrics(),
                handle_stats=self._handle_stats(),
            )
            # bounded like the sync path: calls to a RESTARTING actor
            # queue until it comes back, which could be a long outage
            table = await asyncio.wait_for(get_runtime()._get_one(ref), 10)
        except Exception:
            if self._replicas:  # see _refresh: stale table beats nothing
                with self._lock:
                    self._last_refresh = time.monotonic()
                return
            raise
        self._install_table(table)

    # -- replica choice ----------------------------------------------
    def _breaker_key(self, replica_id: str) -> str:
        """Per-replica circuit-breaker address (core/rpc.py breaker
        board): replicas behind an open breaker are skipped by routing
        until the half-open cooldown admits a probe."""
        return f"serve:{self._app}:{self._deployment}:{replica_id}"

    def _try_pick(self, affinity_key: str = ""):
        with self._lock:
            # an open breaker ejects the replica from the candidate set;
            # in half-open, allow() admits probe traffic (non-exclusive,
            # so a probe lost to pow-2 sampling can't wedge the breaker)
            cands = [
                r for r in self._replicas.values()
                if r.breaker is None or r.breaker.allow()
            ]
            if not cands:
                return None
            if affinity_key:
                # model multiplexing: consistent choice per model id so
                # each model stays resident on one replica instead of
                # thrashing every LRU (reference: the pow-2 scheduler's
                # multiplex-aware candidate ranking).  Hash over the
                # FULL table, not the breaker-filtered candidates: a
                # breaker opening on one replica must divert only the
                # models resident THERE, not remap (and re-load) every
                # model in the deployment on each open/half-open flap.
                import zlib

                table = sorted(self._replicas.values(),
                               key=lambda r: r.replica_id)
                pick = table[zlib.adler32(affinity_key.encode())
                             % len(table)]
                if pick not in cands or \
                        pick.local_inflight >= pick.max_ongoing:
                    # broken or saturated: fall through to pow-2
                    pick = None
                if pick is not None:
                    pick.local_inflight += 1
                    return pick
            if len(cands) == 1:
                pick = cands[0]
            else:
                a, b = random.sample(cands, 2)
                pick = a if a.score() <= b.score() else b
            if pick.local_inflight < pick.max_ongoing:
                pick.local_inflight += 1
                return pick
            return None

    def _submit(self, info: _ReplicaInfo, method_name, args, kwargs,
                streaming: bool = False,
                deadline_s: Optional[float] = None):
        # args flattened to top-level task args so ObjectRefs among them
        # (composed responses) are materialized by the runtime before
        # the replica method runs
        target = (info.handle.handle_request_streaming if streaming
                  else info.handle.handle_request)
        if deadline_s is not None:
            # handle-level timeout_s becomes the task's end-to-end
            # deadline: the replica call (and anything it fans out to)
            # fails with DeadlineExceededError once the budget is spent
            remaining = deadline_s - time.monotonic()
            if remaining <= 0:
                with self._lock:  # release the slot _try_pick reserved
                    info.local_inflight = max(0, info.local_inflight - 1)
                raise _exc.DeadlineExceededError(
                    f"request to {self._deployment} expired before "
                    f"submission", timeout_s=0.0,
                )
            target = target.options(timeout_s=remaining)
        out = target.remote(method_name, *args, **kwargs)

        # the request's trace context, captured on the submitting frame:
        # the streaming watcher passes it to stream_wait_done so the
        # stream's completion joins the request's trace instead of
        # fragmenting (a NOT_SAMPLED marker propagates the negative
        # decision and records nothing)
        tctx = _tracing.current_context()
        t0 = time.monotonic()

        def _done(outcome: str):
            breaker = info.breaker
            if breaker is not None:
                if outcome == "failure":
                    breaker.record_failure()
                elif outcome == "success":
                    breaker.record_success()
                # "neutral" (deadline expiry): a request that burned its
                # budget proves nothing about reachability either way —
                # recording success here would reset the consecutive
                # count and let a black-holed replica dodge ejection
            now = time.monotonic()
            with self._lock:
                info.local_inflight = max(0, info.local_inflight - 1)
                self._completed_total += 1
                self._latency_sum_s += now - t0
                # steady traffic delivers stats via the 0.25s refresh
                # piggyback; a burst's FINAL completions need this
                # trailing-edge push or they never reach the controller
                deferred = not self._stats_push_pending
                if deferred:
                    self._stats_push_pending = True
            if deferred:
                t = asyncio.ensure_future(self._deferred_stats_push())
                with self._lock:
                    if self._closed:
                        t.cancel()
                    else:
                        self._deferred_task = t

        # capacity frees when the replica replies, not when the caller
        # resolves the response (reference: the router decrements its
        # queue-len tracker on reply) — watch completion on the io loop
        import asyncio

        from ray_tpu.core.runtime import _error_from_envelope, get_runtime

        rt_ = get_runtime()

        def _classify(envelope) -> str:
            """Breaker outcome of an error envelope.  Replica-unreachable
            classes are failures; a deadline expiry is neutral (proves
            nothing about reachability); user exceptions (TaskError) are
            successes — a deployment that raises on bad input is
            healthy."""
            try:
                err = _error_from_envelope(envelope)
            except Exception as e:
                logger.debug("undecodable error envelope (%s); treating "
                             "as user-level success", e)
                return "success"
            if isinstance(err, (
                _exc.ActorDiedError, _exc.ActorUnavailableError,
                _exc.WorkerCrashedError, _exc.NodeDiedError,
                _rpc.ConnectionLost,
            )):
                return "failure"
            if isinstance(err, _exc.DeadlineExceededError):
                return "neutral"
            if (_exc.is_deadline_expiry(err)
                    or _exc.backpressure_retry_after(err) is not None):
                # overload signals from INSIDE the replica (engine
                # sheds / admission rejections) arrive wrapped as
                # TaskError.  They are breaker-NEUTRAL: the replica is
                # provably reachable (it answered), but crediting a
                # success would reset the consecutive-failure count on
                # every shed and let a flapping replica dodge ejection
                return "neutral"
            return "success"

        async def _watch():
            outcome = "success"
            try:
                if streaming:
                    # terminal error envelope (None on clean end): a
                    # replica dying mid-stream must trip the breaker,
                    # not record a success
                    env = await rt_.stream_wait_done(out.task_id,
                                                     trace_ctx=tctx)
                    if env is not None:
                        outcome = _classify(env)
                else:
                    st = rt_.objects.get(out.binary())
                    if st is not None:
                        await st.ready.wait()
                        if st.error is not None:
                            outcome = _classify(st.error)
            finally:
                _done(outcome)

        asyncio.run_coroutine_threadsafe(_watch(), rt_.loop)
        return out

    def _enter_queue_wait(self):
        """Ledger hook at assignment entry: the queue-wait phase covers
        everything between request arrival at the router and a
        successful replica pick.  Returns (ledger-or-None, t0); zero
        work (and zero allocations) when telemetry is off."""
        led = _rl.current()
        if led is not None:
            t0 = time.time()
            led.begin("queue_wait", t0)
            return led, t0
        if _md.enabled():
            return None, time.time()
        return None, 0.0

    def _leave_queue_wait(self, led, t_q0: float):
        if led is not None:
            # the phase duration feeds rt_serve_queue_wait_seconds at
            # ledger finish — no direct observe here (double counting)
            led.begin("replica")
        elif t_q0:
            _md.observe(
                "rt_serve_queue_wait_seconds", time.time() - t_q0,
                tags={"app": self._app, "deployment": self._deployment,
                      "replica": "-"},
            )

    def _enter_wait_or_reject(self):
        """Admission control at the router: a request that found no
        free replica either joins the bounded wait pool or is rejected
        NOW with a typed BackPressureError (max_queued_requests from
        the routing table; -1 = legacy unbounded wait).  The hint is
        the table-refresh period — fresh capacity can't be discovered
        faster than that."""
        with self._lock:
            if self._max_queued >= 0 and self._waiting >= self._max_queued:
                self._rejected_total += 1
                raise _exc.BackPressureError(
                    f"no free replica for {self._deployment} and its "
                    f"assignment queue is full (max_queued_requests="
                    f"{self._max_queued}, waiting={self._waiting})",
                    retry_after_s=max(0.1, self.REFRESH_PERIOD_S),
                )
            self._waiting += 1

    def _leave_wait(self):
        with self._lock:
            self._waiting = max(0, self._waiting - 1)

    def _assign_timeout(self, deadline_s, timeout_s) -> TimeoutError:
        """Assignment-wait expiry: a handle-level deadline surfaces as
        the documented DeadlineExceededError; the legacy default wait
        keeps its plain TimeoutError shape."""
        if deadline_s is not None:
            return _exc.DeadlineExceededError(
                f"no available replica for {self._deployment} before the "
                f"handle's timeout_s budget expired"
            )
        return TimeoutError(
            f"no available replica for {self._deployment} "
            f"within {timeout_s}s"
        )

    def assign_request(self, method_name: str, args: tuple, kwargs: dict,
                       timeout_s: float = 30.0, streaming: bool = False,
                       deadline_s: Optional[float] = None):
        """Pick a replica and submit; returns the reply ObjectRef (or
        ObjectRefGenerator when streaming).  `deadline_s` (absolute
        monotonic, from the handle's `timeout_s`) bounds BOTH replica
        assignment and — propagated into the task spec — execution."""
        from ray_tpu.serve.multiplex import MODEL_ID_KWARG

        affinity = kwargs.get(MODEL_ID_KWARG, "")
        deadline = deadline_s if deadline_s is not None \
            else time.monotonic() + timeout_s
        backoff = 0.005
        waiting = False
        led, t_q0 = self._enter_queue_wait()
        try:
            while True:
                self._refresh()
                info = self._try_pick(affinity)
                if info is not None:
                    self._leave_queue_wait(led, t_q0)
                    return self._submit(info, method_name, args, kwargs,
                                        streaming=streaming,
                                        deadline_s=deadline_s)
                if not waiting:
                    self._enter_wait_or_reject()
                    waiting = True
                if time.monotonic() > deadline:
                    raise self._assign_timeout(deadline_s, timeout_s)
                time.sleep(backoff)
                backoff = min(backoff * 2, 0.25)
                self._refresh(force=True)
        finally:
            if waiting:
                self._leave_wait()

    async def assign_request_async(self, method_name: str, args: tuple,
                                   kwargs: dict, timeout_s: float = 30.0,
                                   streaming: bool = False,
                                   deadline_s: Optional[float] = None):
        from ray_tpu.serve.multiplex import MODEL_ID_KWARG

        affinity = kwargs.get(MODEL_ID_KWARG, "")
        deadline = deadline_s if deadline_s is not None \
            else time.monotonic() + timeout_s
        backoff = 0.005
        waiting = False
        led, t_q0 = self._enter_queue_wait()
        try:
            while True:
                await self._refresh_async()
                info = self._try_pick(affinity)
                if info is not None:
                    self._leave_queue_wait(led, t_q0)
                    return self._submit(info, method_name, args, kwargs,
                                        streaming=streaming,
                                        deadline_s=deadline_s)
                if not waiting:
                    self._enter_wait_or_reject()
                    waiting = True
                if time.monotonic() > deadline:
                    raise self._assign_timeout(deadline_s, timeout_s)
                await asyncio.sleep(backoff)
                backoff = min(backoff * 2, 0.25)
                await self._refresh_async(force=True)
        finally:
            if waiting:
                self._leave_wait()

    def ongoing_requests(self) -> int:
        with self._lock:
            return sum(r.local_inflight for r in self._replicas.values())
