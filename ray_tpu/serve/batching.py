"""Request batching for replicas.

Reference: `python/ray/serve/batching.py` (`@serve.batch`) — an async
decorator that queues individual calls and invokes the wrapped function
once per batch, unlocking MXU-friendly batched inference: on TPU the win
is larger than on GPU because XLA compiles per shape, so replicas batch
to a fixed `max_batch_size` and the compiled program is reused.
"""

from __future__ import annotations

import asyncio
import functools
from typing import Any, Callable, List, Optional

from ray_tpu.exceptions import BackPressureError


class _BatchQueue:
    def __init__(self, fn: Callable, max_batch_size: int,
                 batch_wait_timeout_s: float,
                 bucket_fill_timeout_s: Optional[float] = None,
                 max_queued_requests: int = -1):
        self._fn = fn
        self._max = max_batch_size
        self._wait = batch_wait_timeout_s
        self._bucket_wait = bucket_fill_timeout_s
        self._max_queued = max_queued_requests
        self._queue: asyncio.Queue = asyncio.Queue()
        self._task: Optional[asyncio.Task] = None
        self._executing = False  # a batch is inside the user function

    def _ensure_loop(self):
        if self._task is None or self._task.done():
            self._task = asyncio.get_running_loop().create_task(self._loop())

    async def submit(self, item: Any) -> Any:
        if (self._max_queued >= 0 and self._executing
                and self._queue.qsize() >= self._max_queued):
            # bounded like every other admission queue: a stalled (or
            # merely slow) batched function must surface as immediate
            # typed backpressure, not as an unbounded pending list
            # whose callers all eventually time out.  The cap applies
            # only while a batch is EXECUTING downstream — matching
            # the engine's max_queued semantics, where work that the
            # consumer will pick up immediately is not really waiting
            # (so max_queued=0 means "serve while the downstream keeps
            # up, never queue behind it", not "reject everything").
            # Hint: one batch wait — the soonest a batch can drain.
            raise BackPressureError(
                f"batch queue full (max_queued_requests="
                f"{self._max_queued})",
                retry_after_s=max(0.05, self._wait),
            )
        fut = asyncio.get_running_loop().create_future()
        self._queue.put_nowait((item, fut))
        self._ensure_loop()
        return await fut

    async def _gather_batch(self) -> List:
        batch = [await self._queue.get()]
        loop = asyncio.get_running_loop()
        deadline = loop.time() + self._wait
        capped = False
        while len(batch) < self._max:
            remaining = deadline - loop.time()
            if remaining <= 0:
                break
            # BUCKET-FILL FLUSH (PERF.md serve sweep: at max_batch=32 /
            # c=64 the batcher formed ragged 32+16 group pairs that
            # serialized per gather cycle).  Pow-2-bucketed consumers
            # pad a batch UP to the next power of two, so a batch
            # sitting exactly at a boundary gains nothing from one
            # more straggler — it would re-pad to double the size.
            # Once the batch REACHES an upper boundary (>= max/4:
            # where doubling the pad is expensive; tiny batches still
            # gather normally — padding 1->2 is cheap and halves
            # dispatches), the per-item wait STAYS capped at
            # `bucket_fill_timeout_s` — a lone straggler pushing the
            # count to boundary+1 must not reopen the full window it
            # cannot fill.
            n = len(batch)
            if (self._bucket_wait is not None
                    and n & (n - 1) == 0
                    and n >= max(2, self._max // 4)):
                capped = True
            if capped:
                remaining = min(remaining, self._bucket_wait)
            try:
                batch.append(
                    await asyncio.wait_for(self._queue.get(), timeout=remaining)
                )
            except asyncio.TimeoutError:
                break
        return batch

    async def _loop(self):
        while True:
            batch = await self._gather_batch()
            items = [b[0] for b in batch]
            futs = [b[1] for b in batch]
            self._executing = True
            try:
                results = await self._fn(items)
                if results is None or len(results) != len(items):
                    raise RuntimeError(
                        "batched function must return one result per input "
                        f"(got {0 if results is None else len(results)} for "
                        f"{len(items)} inputs)"
                    )
                for fut, res in zip(futs, results):
                    if not fut.done():
                        fut.set_result(res)
            except BaseException as e:  # noqa: BLE001 — callers must
                # never hang: even cancellation resolves the in-flight
                # batch's futures before the loop task dies
                for fut in futs:
                    if not fut.done():
                        fut.set_exception(
                            e
                            if isinstance(e, Exception)
                            else RuntimeError(f"batch loop died: {e!r}")
                        )
                if not isinstance(e, Exception):
                    raise
            finally:
                self._executing = False


def batch(
    _fn: Optional[Callable] = None,
    *,
    max_batch_size: int = 10,
    batch_wait_timeout_s: float = 0.01,
    bucket_fill_timeout_s: Optional[float] = None,
    max_queued_requests: int = -1,
):
    """Decorator: turn `async def f(self, item)`-shaped handlers into
    batched `f(self, items: List)` execution (reference:
    `serve/batching.py` `@serve.batch`).

    `bucket_fill_timeout_s` (optional, for pow-2-bucketed consumers):
    once the gathering batch sits exactly at an upper power-of-two
    boundary (>= max_batch_size/4), wait at most this long for further
    items before flushing — a trickle of stragglers otherwise re-pads
    the batch to the NEXT bucket and serializes a ragged group pair
    per gather cycle (the measured max_batch=32 stall in PERF.md's
    serve sweep).  Small batches keep gathering under the normal
    batch_wait_timeout_s, where padding up is cheap and batching pays
    the most.

    `max_queued_requests` (default -1 = unbounded) bounds the pending
    list the same way the deployment-level admission cap does: the
    overflow submit raises `BackPressureError` (translated to 503 +
    Retry-After at the HTTP proxy) instead of queueing behind a
    stalled downstream forever."""

    def _decorate(fn: Callable):
        # one queue per bound instance (methods) or per function
        attr = f"__serve_batch_queue_{id(fn)}"

        @functools.wraps(fn)
        async def wrapper(*args):
            if len(args) == 2:  # bound method: (self, item)
                owner, item = args

                async def call(items):
                    return await fn(owner, items)

            elif len(args) == 1:  # plain function: (item,)
                owner, item = wrapper, args[0]

                async def call(items):
                    return await fn(items)

            else:
                raise TypeError(
                    "@serve.batch handlers take exactly one request argument"
                )
            q = getattr(owner, attr, None)
            if q is None:
                # per-instance overrides (reference:
                # set_max_batch_size/handle options): an owner may carry
                # `__serve_batch_overrides__ = {method_name: {...}}`
                over = getattr(owner, "__serve_batch_overrides__", {}).get(
                    getattr(fn, "__name__", ""), {}
                )
                q = _BatchQueue(
                    call,
                    over.get("max_batch_size", max_batch_size),
                    over.get("batch_wait_timeout_s", batch_wait_timeout_s),
                    over.get("bucket_fill_timeout_s",
                             bucket_fill_timeout_s),
                    over.get("max_queued_requests", max_queued_requests),
                )
                setattr(owner, attr, q)
            return await q.submit(item)

        wrapper._is_serve_batch = True
        return wrapper

    if _fn is not None:
        return _decorate(_fn)
    return _decorate
