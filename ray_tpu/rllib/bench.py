"""RLlib PPO fleet benchmark harness (BASELINE config #3).

One measured shape, two consumers:

- ``bench.py --config rllib_ppo`` — the baseline-closing bench row
  (env-steps/s + learner updates/s; ``vs_baseline`` = async-overlap
  throughput over the reference's synchronous sample→update loop at
  the identical fleet shape);
- ``python -m ray_tpu.scripts.perf --config rllib_ppo`` — the tier-1
  structural row (both metrics present, exactly-once accounting).

The workload is the production shape the ROADMAP names: an
`EnvRunnerGroup` fleet of CPU sampling actors streaming rollouts as
object-plane references into a pjit learner gang (data-sharded mesh),
with async sample/train overlap.  It deliberately stresses the n:n
small-envelope actor-call path on top of the sharded owner plane.
"""

from __future__ import annotations

import os
import sys
import time
from typing import Any, Dict, Optional


def _ensure_cpu_gang_env(gang_devices: int) -> None:
    """The pjit gang needs >= gang_devices visible XLA devices; on CPU
    that is ``--xla_force_host_platform_device_count``, which only
    takes effect BEFORE jax initializes.  A no-op when jax is already
    up (make_data_mesh then raises a helpful error if short)."""
    if "jax" in sys.modules:
        return
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count="
            f"{max(8, gang_devices)}"
        )


def measure_rllib_ppo(*, num_runners: int = 8, envs_per_runner: int = 16,
                      rollout_len: int = 64, minibatch: int = 2048,
                      epochs: int = 2, gang_devices: int = 2,
                      iters: int = 4, seed: int = 0,
                      compare_sync: bool = True,
                      include_dag: bool = False,
                      num_workers: Optional[int] = None
                      ) -> Dict[str, Dict[str, float]]:
    """Run the fleet bench; returns {"rllib_ppo": async_row[,
    "rllib_ppo_sync": sync_row][, "rllib_ppo_dag": compiled-DAG row]}.
    The dag row is the same overlap shape with `use_compiled_dag=True`:
    sample hop + weights broadcast over shm tensor channels into
    resident runner loops instead of per-call actor RPCs.  Caller owns
    no cluster — this inits/shuts down its own."""
    _ensure_cpu_gang_env(gang_devices)
    import ray_tpu as rt
    from ray_tpu.rllib import PPOConfig

    rt.init(num_workers=num_workers or (num_runners + 2),
            num_cpus=max(16, 2 * num_runners))
    try:
        out: Dict[str, Dict[str, float]] = {}
        out["rllib_ppo"] = _run_mode(
            PPOConfig, True, num_runners, envs_per_runner, rollout_len,
            minibatch, epochs, gang_devices, iters, seed,
        )
        if compare_sync:
            out["rllib_ppo_sync"] = _run_mode(
                PPOConfig, False, num_runners, envs_per_runner,
                rollout_len, minibatch, epochs, gang_devices, iters, seed,
            )
        if include_dag:
            out["rllib_ppo_dag"] = _run_mode(
                PPOConfig, True, num_runners, envs_per_runner,
                rollout_len, minibatch, epochs, gang_devices, iters,
                seed, use_dag=True,
            )
        return out
    finally:
        rt.shutdown()


def _run_mode(PPOConfig, overlap: bool, num_runners: int,
              envs_per_runner: int, rollout_len: int, minibatch: int,
              epochs: int, gang_devices: int, iters: int,
              seed: int, use_dag: bool = False) -> Dict[str, float]:
    algo = (
        PPOConfig()
        .environment("CartPole-v1")
        .env_runners(num_env_runners=num_runners,
                     num_envs_per_env_runner=envs_per_runner,
                     rollout_fragment_length=rollout_len)
        .learners(num_learner_devices=gang_devices)
        .training(lr=3e-4, minibatch_size=minibatch, num_epochs=epochs,
                  sample_train_overlap=overlap, use_compiled_dag=use_dag)
        .debugging(seed=seed)
        .build()
    )
    try:
        algo.train()  # warmup: compiles the update, primes the stream
        group = algo.env_runner_group
        led0 = group.ledger.snapshot()
        steps = updates = 0
        busy_s = wait_s = 0.0
        losses = []
        t0 = time.perf_counter()
        for _ in range(iters):
            r = algo.train()
            steps += int(r["num_env_steps_sampled"])
            updates += int(r["num_learner_updates"])
            busy_s += float(r.get("sample_busy_s", 0.0))
            wait_s += float(r.get("sample_wait_s", 0.0))
            losses.append(float(r["total_loss"]))
        wall_s = time.perf_counter() - t0
        led1 = group.ledger.snapshot()
        ledger_steps = led1["env_steps"] - led0["env_steps"]
        ledger_batches = led1["batches"] - led0["batches"]
        ledger_unique = led1["unique"] - led0["unique"]
        row: Dict[str, float] = {
            "env_steps_per_s": steps / wall_s,
            "updates_per_s": updates / wall_s,
            "env_steps": float(steps),
            "updates": float(updates),
            "wall_s": wall_s,
            "iters": float(iters),
            "runners": float(num_runners),
            "gang_devices": float(algo.learner_group.num_gang_devices),
            "overlap": float(overlap),
            # exactly-once proof: every env step the training loop
            # counted is ledger-recorded exactly once, and no batch was
            # consumed twice
            "ledger_env_steps": ledger_steps,
            "ledger_batches": ledger_batches,
            "accounting_exact": float(
                steps == int(ledger_steps)
                and ledger_batches == ledger_unique
            ),
            "replacements": float(group.num_replacements),
            "final_loss": losses[-1],
            "use_compiled_dag": float(use_dag),
        }
        if overlap:
            hidden_s = max(0.0, busy_s - wait_s)
            row.update({
                "sample_busy_s": busy_s,
                "sample_wait_s": wait_s,
                "overlap_ratio": (hidden_s / busy_s) if busy_s else 0.0,
            })
        return row
    finally:
        algo.stop()
