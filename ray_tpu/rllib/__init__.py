"""RL library (reference: top-level `rllib/`, new API stack only).

EnvRunner actors sample with pure-numpy policies on CPU; the Learner
owns a jax parameter pytree and a jitted update — scaled SPMD over a
device mesh (the TPU path; `num_learner_devices` builds the pjit gang)
or via DDP learner actors with host-collective gradient allreduce (the
CPU-fleet path).  Production shape (BASELINE config #3): the runner
fleet ships sample batches as object-plane references into the gang
with async sample/train overlap and exactly-once `SampleLedger`
accounting — see docs/rllib.md "Production scale".
"""

from ray_tpu.rllib.algorithms import APPO, BC, CQL, DQN, IMPALA, PPO, SAC, Algorithm, AlgorithmConfig, APPOConfig, BCConfig, CQLConfig, DQNConfig, Dreamer, DreamerConfig, IMPALAConfig, MARWIL, MARWILConfig, MultiAgentPPO, MultiAgentPPOConfig, PPOConfig, SACConfig
from ray_tpu.rllib.connectors import (
    ConnectorPipeline,
    ConnectorV2,
    FrameStack,
    ImagePreprocess,
    MeanStdObsFilter,
    ObsClip,
    RewardClip,
    wrap_atari_connectors,
)
from ray_tpu.rllib.core import Learner, LearnerGroup, MLPModule, RLModule
from ray_tpu.rllib.core.learner import make_data_mesh
from ray_tpu.rllib.core.rl_module import CNNModule, make_default_module
from ray_tpu.rllib.env import (
    CartPoleVectorEnv,
    EnvRunner,
    EnvRunnerGroup,
    VectorEnv,
)
from ray_tpu.rllib.env.env_runner_group import (
    DuplicateSampleError,
    SampleLedger,
)
from ray_tpu.rllib.env.envs import (
    CatchPixelEnv,
    ContinuousTargetEnv,
    PendulumVectorEnv,
)

__all__ = [
    "Algorithm",
    "ConnectorPipeline",
    "ConnectorV2",
    "MeanStdObsFilter",
    "ObsClip",
    "RewardClip",
    "AlgorithmConfig",
    "APPO",
    "APPOConfig",
    "BC",
    "BCConfig",
    "MARWIL",
    "MARWILConfig",
    "CQL",
    "CQLConfig",
    "CartPoleVectorEnv",
    "CatchPixelEnv",
    "CNNModule",
    "ContinuousTargetEnv",
    "FrameStack",
    "ImagePreprocess",
    "PendulumVectorEnv",
    "make_default_module",
    "wrap_atari_connectors",
    "DQN",
    "DQNConfig",
    "Dreamer",
    "DreamerConfig",
    "IMPALA",
    "IMPALAConfig",
    "SAC",
    "SACConfig",
    "MultiAgentPPO",
    "MultiAgentPPOConfig",
    "EnvRunner",
    "EnvRunnerGroup",
    "Learner",
    "LearnerGroup",
    "DuplicateSampleError",
    "MLPModule",
    "PPO",
    "PPOConfig",
    "RLModule",
    "SampleLedger",
    "VectorEnv",
    "make_data_mesh",
]
