"""RLModule: the neural-network abstraction of the new API stack.

Reference: `rllib/core/rl_module/rl_module.py` — one object owning the
policy/value networks with three forward modes (exploration, inference,
train).  TPU-native split: parameters are a jax pytree owned by the
Learner; env runners receive *numpy* copies and run `forward_numpy`
(rollout inference is tiny MLP math on CPU actors — no jax, no device
contention with the learner's compiled programs).
"""

from __future__ import annotations

from typing import Any, Dict, List, Tuple

import numpy as np


class RLModule:
    """Interface: subclass for custom architectures."""

    def init_params(self, rng) -> Dict[str, Any]:
        raise NotImplementedError

    def forward_train(self, params, obs):
        """jax path (inside the learner's jitted loss): returns
        (logits, value)."""
        raise NotImplementedError

    def forward_numpy(self, params_np, obs: np.ndarray):
        """numpy path (env runners): returns (logits, value)."""
        raise NotImplementedError


class MLPModule(RLModule):
    """Separate policy and value MLP towers (reference default:
    `rllib/core/rl_module/default_model_config.py` fcnet)."""

    def __init__(self, observation_size: int, num_actions: int,
                 hidden: Tuple[int, ...] = (64, 64)):
        self.observation_size = observation_size
        self.num_actions = num_actions
        self.hidden = tuple(hidden)

    def _tower_dims(self, out_dim: int) -> List[Tuple[int, int]]:
        dims = [self.observation_size, *self.hidden, out_dim]
        return list(zip(dims[:-1], dims[1:]))

    def init_tower(self, rng, out_dim: int) -> List[Dict[str, Any]]:
        """One MLP tower's layers (shared by every module family so a
        layout change happens exactly once)."""
        import jax
        import jax.numpy as jnp

        layers = []
        for i, (m, n) in enumerate(self._tower_dims(out_dim)):
            rng, k = jax.random.split(rng)
            scale = float(np.sqrt(2.0 / m)) if i < len(self.hidden) else 0.01
            layers.append({
                "w": jax.random.normal(k, (m, n), jnp.float32) * scale,
                "b": jnp.zeros((n,), jnp.float32),
            })
        return layers

    def init_params(self, rng) -> Dict[str, Any]:
        import jax

        k_pi, k_vf = jax.random.split(rng)
        return {
            "pi": self.init_tower(k_pi, self.num_actions),
            "vf": self.init_tower(k_vf, 1),
        }

    def forward_train(self, params, obs):
        logits = tower_jax(params["pi"], obs)
        value = tower_jax(params["vf"], obs)[..., 0]
        return logits, value

    def forward_numpy(self, params_np, obs: np.ndarray):
        logits = tower_numpy(params_np["pi"], obs)
        value = tower_numpy(params_np["vf"], obs)[..., 0]
        return logits, value


def tower_jax(layers, x):
    """The MLP tower forward — ONE definition for jax (and mirrored in
    tower_numpy); matmul+tanh layout changes happen here only."""
    import jax.numpy as jnp

    for i, lyr in enumerate(layers):
        x = x @ lyr["w"] + lyr["b"]
        if i < len(layers) - 1:
            x = jnp.tanh(x)
    return x


def tower_numpy(layers, x):
    for i, lyr in enumerate(layers):
        x = x @ lyr["w"] + lyr["b"]
        if i < len(layers) - 1:
            x = np.tanh(x)
    return x


def params_to_numpy(params) -> Any:
    import jax

    return jax.tree.map(lambda x: np.asarray(x), params)
