"""RLModule: the neural-network abstraction of the new API stack.

Reference: `rllib/core/rl_module/rl_module.py` — one object owning the
policy/value networks with three forward modes (exploration, inference,
train).  TPU-native split: parameters are a jax pytree owned by the
Learner; env runners receive *numpy* copies and run `forward_numpy`
(rollout inference is tiny MLP math on CPU actors — no jax, no device
contention with the learner's compiled programs).
"""

from __future__ import annotations

from typing import Any, Dict, List, Tuple

import numpy as np


class RLModule:
    """Interface: subclass for custom architectures."""

    def init_params(self, rng) -> Dict[str, Any]:
        raise NotImplementedError

    def forward_train(self, params, obs):
        """jax path (inside the learner's jitted loss): returns
        (logits, value)."""
        raise NotImplementedError

    def forward_numpy(self, params_np, obs: np.ndarray):
        """numpy path (env runners): returns (logits, value)."""
        raise NotImplementedError


class MLPModule(RLModule):
    """Separate policy and value MLP towers (reference default:
    `rllib/core/rl_module/default_model_config.py` fcnet)."""

    def __init__(self, observation_size: int, num_actions: int,
                 hidden: Tuple[int, ...] = (64, 64)):
        self.observation_size = observation_size
        self.num_actions = num_actions
        self.hidden = tuple(hidden)

    def _tower_dims(self, out_dim: int) -> List[Tuple[int, int]]:
        dims = [self.observation_size, *self.hidden, out_dim]
        return list(zip(dims[:-1], dims[1:]))

    def init_tower(self, rng, out_dim: int) -> List[Dict[str, Any]]:
        """One MLP tower's layers (shared by every module family so a
        layout change happens exactly once)."""
        import jax
        import jax.numpy as jnp

        layers = []
        for i, (m, n) in enumerate(self._tower_dims(out_dim)):
            rng, k = jax.random.split(rng)
            scale = float(np.sqrt(2.0 / m)) if i < len(self.hidden) else 0.01
            layers.append({
                "w": jax.random.normal(k, (m, n), jnp.float32) * scale,
                "b": jnp.zeros((n,), jnp.float32),
            })
        return layers

    def init_params(self, rng) -> Dict[str, Any]:
        import jax

        k_pi, k_vf = jax.random.split(rng)
        return {
            "pi": self.init_tower(k_pi, self.num_actions),
            "vf": self.init_tower(k_vf, 1),
        }

    def forward_train(self, params, obs):
        logits = tower_jax(params["pi"], obs)
        value = tower_jax(params["vf"], obs)[..., 0]
        return logits, value

    def forward_numpy(self, params_np, obs: np.ndarray):
        logits = tower_numpy(params_np["pi"], obs)
        value = tower_numpy(params_np["vf"], obs)[..., 0]
        return logits, value


def conv_out_dims(h: int, w: int,
                  conv_filters) -> List[Tuple[int, int]]:
    """Per-layer output spatial dims of a SAME-padded strided conv
    stack (XLA's ceil-division semantics), input dims first."""
    dims = [(h, w)]
    for _c, _k, s in conv_filters:
        h, w = -(-h // s), -(-w // s)
        dims.append((h, w))
    return dims


def conv_stack_init(rng, in_channels: int, conv_filters):
    """He-initialized HWIO conv weights for one stack (shared by every
    conv-using module family so layout changes happen exactly once)."""
    import jax
    import jax.numpy as jnp

    layers = []
    c_in = in_channels
    for c_out, k, _s in conv_filters:
        rng, key = jax.random.split(rng)
        layers.append({
            "w": jax.random.normal(key, (k, k, c_in, c_out), jnp.float32)
            * float(np.sqrt(2.0 / (k * k * c_in))),
            "b": jnp.zeros((c_out,), jnp.float32),
        })
        c_in = c_out
    return layers


def conv_stack_apply(conv_params, x, conv_filters, activation):
    """SAME-padded strided conv stack, NHWC (XLA tiles it on the MXU);
    `activation` applied after every layer."""
    import jax

    for lyr, (_c, _k, s) in zip(conv_params, conv_filters):
        x = jax.lax.conv_general_dilated(
            x, lyr["w"], window_strides=(s, s), padding="SAME",
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        ) + lyr["b"]
        x = activation(x)
    return x


class CNNModule(RLModule):
    """Conv encoder + MLP heads for image observations.

    Reference: `rllib/core/models/configs.py:653` (`CNNEncoderConfig`)
    and `rllib/core/models/torch/encoder.py:107` (`TorchCNNEncoder`) —
    a conv stack shared by the pi and vf heads.  TPU-native split: the
    jax path uses `lax.conv_general_dilated` in NHWC (XLA lowers it
    onto the MXU); the numpy mirror (env runners) uses im2col +
    one matmul per layer so CPU rollouts stay vectorized.

    `conv_filters`: sequence of (out_channels, kernel, stride) — the
    reference's default_model_config conv_filters shape.
    """

    def __init__(self, observation_shape: Tuple[int, int, int],
                 num_actions: int,
                 conv_filters: Tuple[Tuple[int, int, int], ...] = (
                     (16, 4, 2), (32, 4, 2), (64, 3, 2),
                 ),
                 hidden: Tuple[int, ...] = (256,)):
        if len(observation_shape) != 3:
            raise ValueError(
                f"CNNModule needs (H, W, C) observations, got "
                f"{observation_shape}"
            )
        self.observation_shape = tuple(observation_shape)
        self.num_actions = num_actions
        self.conv_filters = tuple(tuple(f) for f in conv_filters)
        self.hidden = tuple(hidden)
        h, w = conv_out_dims(observation_shape[0], observation_shape[1],
                             self.conv_filters)[-1]
        self._flat = h * w * self.conv_filters[-1][0]

    # -- init ----------------------------------------------------------
    def init_params(self, rng) -> Dict[str, Any]:
        import jax
        import jax.numpy as jnp

        rng, k_conv = jax.random.split(rng)
        params: Dict[str, Any] = {
            "conv": conv_stack_init(
                k_conv, self.observation_shape[-1], self.conv_filters
            ),
            "dense": [],
        }
        dims = [self._flat, *self.hidden]
        for m, n in zip(dims[:-1], dims[1:]):
            rng, key = jax.random.split(rng)
            params["dense"].append({
                "w": jax.random.normal(key, (m, n), jnp.float32)
                * float(np.sqrt(2.0 / m)),
                "b": jnp.zeros((n,), jnp.float32),
            })
        feat = dims[-1]
        rng, k_pi, k_vf = jax.random.split(rng, 3)
        params["pi"] = {
            "w": jax.random.normal(k_pi, (feat, self.num_actions),
                                   jnp.float32) * 0.01,
            "b": jnp.zeros((self.num_actions,), jnp.float32),
        }
        params["vf"] = {
            "w": jax.random.normal(k_vf, (feat, 1), jnp.float32) * 0.01,
            "b": jnp.zeros((1,), jnp.float32),
        }
        return params

    # -- forward -------------------------------------------------------
    def _encode_jax(self, params, x):
        import jax.numpy as jnp

        x = jnp.asarray(x, jnp.float32)
        x = conv_stack_apply(
            params["conv"], x, self.conv_filters,
            lambda y: jnp.maximum(y, 0.0),
        )
        x = x.reshape(x.shape[0], -1)
        for lyr in params["dense"]:
            x = jnp.maximum(x @ lyr["w"] + lyr["b"], 0.0)
        return x

    def forward_train(self, params, obs):
        feat = self._encode_jax(params, obs)
        logits = feat @ params["pi"]["w"] + params["pi"]["b"]
        value = (feat @ params["vf"]["w"] + params["vf"]["b"])[..., 0]
        return logits, value

    def _encode_numpy(self, params_np, x):
        x = np.asarray(x, np.float32)
        for lyr, (_c, k, s) in zip(params_np["conv"], self.conv_filters):
            x = _conv2d_numpy(x, lyr["w"], lyr["b"], k, s)
            np.maximum(x, 0.0, out=x)
        x = x.reshape(x.shape[0], -1)
        for lyr in params_np["dense"]:
            x = np.maximum(x @ lyr["w"] + lyr["b"], 0.0)
        return x

    def forward_numpy(self, params_np, obs: np.ndarray):
        feat = self._encode_numpy(params_np, obs)
        logits = feat @ params_np["pi"]["w"] + params_np["pi"]["b"]
        value = (feat @ params_np["vf"]["w"] + params_np["vf"]["b"])[..., 0]
        return logits, value


def _conv2d_numpy(x: np.ndarray, w: np.ndarray, b: np.ndarray,
                  k: int, s: int) -> np.ndarray:
    """SAME-padded strided conv, NHWC x HWIO -> NHWC, via im2col +
    one matmul (the numpy mirror of the jax path above)."""
    n, h, win, c_in = x.shape
    h_out = -(-h // s)
    w_out = -(-win // s)
    # SAME padding totals (mirrors XLA's computation)
    pad_h = max((h_out - 1) * s + k - h, 0)
    pad_w = max((w_out - 1) * s + k - win, 0)
    x = np.pad(x, ((0, 0), (pad_h // 2, pad_h - pad_h // 2),
                   (pad_w // 2, pad_w - pad_w // 2), (0, 0)))
    sN, sH, sW, sC = x.strides
    cols = np.lib.stride_tricks.as_strided(
        x,
        shape=(n, h_out, w_out, k, k, c_in),
        strides=(sN, sH * s, sW * s, sH, sW, sC),
        writeable=False,
    ).reshape(n * h_out * w_out, k * k * c_in)
    out = cols @ w.reshape(k * k * c_in, -1) + b
    return out.reshape(n, h_out, w_out, -1)


def tower_jax(layers, x):
    """The MLP tower forward — ONE definition for jax (and mirrored in
    tower_numpy); matmul+tanh layout changes happen here only."""
    import jax.numpy as jnp

    for i, lyr in enumerate(layers):
        x = x @ lyr["w"] + lyr["b"]
        if i < len(layers) - 1:
            x = jnp.tanh(x)
    return x


def tower_numpy(layers, x):
    for i, lyr in enumerate(layers):
        x = x @ lyr["w"] + lyr["b"]
        if i < len(layers) - 1:
            x = np.tanh(x)
    return x


def make_default_module(spec: Dict[str, Any],
                        model_cfg: Dict[str, Any]) -> RLModule:
    """Pick the default architecture from the env spec (reference:
    `rllib/core/rl_module/default_model_config.py` — conv encoder for
    image spaces, fcnet otherwise).  `spec` is an EnvRunner env_spec;
    `model_cfg` is AlgorithmConfig.model."""
    require_discrete_actions(spec, "the default policy-gradient module")
    obs_shape = tuple(
        spec.get("observation_shape", (spec["observation_size"],))
    )
    if len(obs_shape) == 3 or "conv_filters" in model_cfg:
        return CNNModule(
            obs_shape, spec["num_actions"],
            conv_filters=tuple(
                model_cfg.get(
                    "conv_filters", ((16, 4, 2), (32, 4, 2), (64, 3, 2))
                )
            ),
            hidden=tuple(model_cfg.get("hidden", (256,))),
        )
    return MLPModule(
        spec["observation_size"], spec["num_actions"],
        hidden=tuple(model_cfg.get("hidden", (64, 64))),
    )


def require_flat_obs(spec: Dict[str, Any], algo_name: str) -> None:
    """Fail fast (at setup, with a clear message) for algorithms whose
    module/replay path is MLP-only: without this, an image env dies
    with an opaque matmul shape error inside a runner actor that the
    fault-tolerant sample loop then masks as 'all env runners
    failed'."""
    shape = tuple(spec.get("observation_shape",
                           (spec["observation_size"],)))
    if len(shape) != 1:
        raise ValueError(
            f"{algo_name} supports flat observations only (got "
            f"observation_shape={shape}); for pixel envs use "
            "PPO/APPO/IMPALA (CNN encoder) or DreamerV3 (conv world "
            "model), or flatten with a connector"
        )


def require_discrete_actions(spec: Dict[str, Any],
                             algo_name: str) -> None:
    """Fail fast for discrete-only algorithms on continuous-action
    envs: without this, num_actions=0 builds a zero-width policy head
    that dies with an opaque reduction error inside a runner actor."""
    if spec.get("continuous"):
        raise ValueError(
            f"{algo_name} supports discrete action spaces only (env "
            f"reports continuous action_dim={spec.get('action_dim')}); "
            "use SAC for continuous control"
        )


def params_to_numpy(params) -> Any:
    import jax

    return jax.tree.map(lambda x: np.asarray(x), params)
