from ray_tpu.rllib.core.learner import Learner, LearnerGroup
from ray_tpu.rllib.core.rl_module import MLPModule, RLModule

__all__ = ["Learner", "LearnerGroup", "MLPModule", "RLModule"]
