"""Learner: the compiled training side of the RL stack.

Reference: `rllib/core/learner/learner.py:117` (`compute_gradients:449`,
`apply_gradients:592`, `update_from_batch:954`) and `learner_group.py:80`.

TPU-native inversion: where the reference scales learners with torch DDP
across actors, the primary scaling path here is SPMD *inside* one
compiled update — minibatches are sharded over a `jax.sharding.Mesh`
data axis and XLA inserts the gradient psums on ICI.  A multi-actor
mode (`num_learners > 1`) with host-collective gradient allreduce keeps
the reference's process-parallel shape available for CPU fleets.

The LEARNER GANG (`gang_devices >= 2`, or an explicit mesh): the PPO
update is one pjit'd program over a data-sharded mesh — every gang
member (mesh device) sees 1/N of each minibatch and XLA inserts the
gradient psum, so adding devices widens the update without touching the
training loop.  `update_minibatch_device` keeps metrics on device
(no host sync per minibatch) — the driver thread returns to collecting
sample envelopes while XLA executes, which is what hides sampling
wall-time behind the update (the async overlap the bench measures).
"""

from __future__ import annotations

import logging
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

import ray_tpu as rt
from ray_tpu.rllib.core.rl_module import RLModule, params_to_numpy

logger = logging.getLogger(__name__)


def make_data_mesh(num_devices: int):
    """A 1-D `jax.sharding.Mesh` over the first `num_devices` local
    devices with axis name "data" — the learner gang's substrate.  On
    CPU boxes, virtual devices come from
    ``XLA_FLAGS=--xla_force_host_platform_device_count=N`` (set BEFORE
    jax initializes; bench.py and tests/conftest.py both do)."""
    import jax

    devices = jax.devices()
    if num_devices > len(devices):
        raise ValueError(
            f"gang of {num_devices} learner devices requested but only "
            f"{len(devices)} visible — on CPU set XLA_FLAGS="
            f"--xla_force_host_platform_device_count={num_devices} "
            "before jax initializes"
        )
    from jax.sharding import Mesh

    return Mesh(np.array(devices[:num_devices]).reshape(num_devices),
                ("data",))


class Learner:
    """Owns params + optimizer state; update_minibatch is jitted once
    (static minibatch shapes) and reused every epoch."""

    def __init__(self, module: RLModule, loss_fn: Callable,
                 lr: float = 3e-4, grad_clip: Optional[float] = 0.5,
                 seed: int = 0, mesh: Any = None):
        import jax
        import optax

        self.module = module
        self._loss_fn = loss_fn
        self._mesh = mesh
        self.optimizer = optax.chain(
            optax.clip_by_global_norm(grad_clip) if grad_clip else optax.identity(),
            optax.adam(lr),
        )
        self.params = module.init_params(jax.random.PRNGKey(seed))
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            # replicate params across the data axis; XLA will psum grads
            repl = NamedSharding(mesh, P())
            self.params = jax.tree.map(
                lambda x: jax.device_put(x, repl), self.params
            )
        self.opt_state = self.optimizer.init(self.params)
        self._update = self._build_update()

    def _build_update(self):
        import jax

        import optax

        def update(params, opt_state, batch):
            def loss_wrap(p):
                return self._loss_fn(self.module, p, batch)

            (loss, metrics), grads = jax.value_and_grad(
                loss_wrap, has_aux=True
            )(params)
            updates, opt_state = self.optimizer.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            metrics = dict(metrics)
            metrics["total_loss"] = loss
            return params, opt_state, metrics

        jitted = jax.jit(update, donate_argnums=(0, 1))
        if self._mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            data_sh = NamedSharding(self._mesh, P("data"))

            def sharded_update(params, opt_state, batch):
                batch = {
                    k: jax.device_put(v, data_sh) for k, v in batch.items()
                }
                return jitted(params, opt_state, batch)

            return sharded_update
        return jitted

    def update_minibatch(self, batch: Dict[str, np.ndarray]) -> Dict[str, float]:
        self.params, self.opt_state, metrics = self._update(
            self.params, self.opt_state, batch
        )
        return {k: float(v) for k, v in metrics.items()}

    def update_minibatch_device(self, batch: Dict[str, np.ndarray]
                                ) -> Dict[str, Any]:
        """One update WITHOUT the host sync: metrics stay device arrays
        (jax dispatch is async — the caller overlaps the XLA execution
        with its own work and floats the metrics once per iteration)."""
        self.params, self.opt_state, metrics = self._update(
            self.params, self.opt_state, batch
        )
        return metrics

    def get_weights_numpy(self):
        return params_to_numpy(self.params)

    def get_state(self) -> Dict[str, Any]:
        return {
            "params": params_to_numpy(self.params),
            "opt_state": params_to_numpy(self.opt_state),
        }

    def set_state(self, state: Dict[str, Any]):
        import jax.numpy as jnp
        import jax

        self.params = jax.tree.map(jnp.asarray, state["params"])
        self.opt_state = jax.tree.map(
            jnp.asarray, state["opt_state"],
            is_leaf=lambda x: isinstance(x, np.ndarray),
        )


class _RemoteLearner:
    """Actor wrapper: one DDP rank (reference: LearnerGroup's remote
    learner actors).  Gradient sync = host-collective allreduce over the
    flattened gradient vector."""

    def __init__(self, module: RLModule, loss_fn: Callable, lr: float,
                 grad_clip: Optional[float], seed: int, world_size: int,
                 rank: int, group_name: str):
        import os

        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        from ray_tpu.parallel import collectives

        self._learner = Learner(module, loss_fn, lr, grad_clip, seed=seed)
        self._world = world_size
        self._rank = rank
        self._group = collectives.init_collective_group(
            world_size, rank, group_name
        )
        self._grad_update = self._build_ddp_update()

    def _build_ddp_update(self):
        import jax
        from jax import flatten_util  # noqa: F401 — registers jax.flatten_util

        learner = self._learner

        @jax.jit
        def grads_of(params, batch):
            def loss_wrap(p):
                return learner._loss_fn(learner.module, p, batch)

            (loss, metrics), grads = jax.value_and_grad(
                loss_wrap, has_aux=True
            )(params)
            metrics = dict(metrics)
            metrics["total_loss"] = loss
            flat, _ = jax.flatten_util.ravel_pytree(grads)
            return flat, metrics

        import optax

        @jax.jit
        def apply_flat(params, opt_state, flat):
            _, unravel = jax.flatten_util.ravel_pytree(params)
            grads = unravel(flat)
            updates, opt_state = learner.optimizer.update(
                grads, opt_state, params
            )
            params = optax.apply_updates(params, updates)
            return params, opt_state

        def update(batch):
            flat, metrics = grads_of(learner.params, batch)
            mean = self._group.allreduce(np.asarray(flat), op="mean")
            learner.params, learner.opt_state = apply_flat(
                learner.params, learner.opt_state, mean
            )
            return {k: float(v) for k, v in metrics.items()}

        return update

    def update_minibatch(self, batch) -> Dict[str, float]:
        return self._grad_update(batch)

    def get_weights_numpy(self):
        return self._learner.get_weights_numpy()

    def get_state(self):
        return self._learner.get_state()

    def set_state(self, state):
        self._learner.set_state(state)
        return True

    def ping(self):
        return True


class LearnerGroup:
    """Reference: `learner_group.py:80`.  num_learners=0 → local learner
    in the driver process (the TPU path: one process, mesh-sharded
    update); num_learners>=1 → remote DDP actors.

    `gang_devices >= 2` builds the pjit learner gang: a 1-D "data" mesh
    over that many local devices, the update compiled once as a single
    sharded program (the production learner shape for BASELINE config
    #3 — see make_data_mesh)."""

    def __init__(self, module: RLModule, loss_fn: Callable, *,
                 num_learners: int = 0, lr: float = 3e-4,
                 grad_clip: Optional[float] = 0.5, seed: int = 0,
                 mesh: Any = None, gang_devices: int = 0):
        self._num = num_learners
        if gang_devices >= 2:
            if num_learners:
                raise ValueError(
                    "gang_devices (mesh-sharded pjit gang) and "
                    "num_learners (DDP actors) are alternative scaling "
                    "axes — set one"
                )
            if mesh is None:
                mesh = make_data_mesh(gang_devices)
        self._gang_devices = (
            int(mesh.devices.size) if mesh is not None else (
                0 if num_learners else 1
            )
        )
        if num_learners == 0:
            self._local = Learner(module, loss_fn, lr, grad_clip, seed, mesh)
            self._actors: List = []
        else:
            self._local = None
            group = f"learner_ddp_{seed}_{id(self)}"
            self._actors = [
                rt.remote(_RemoteLearner).options(num_cpus=1).remote(
                    module, loss_fn, lr, grad_clip, seed, num_learners,
                    rank, group,
                )
                for rank in range(num_learners)
            ]
            rt.get([a.ping.remote() for a in self._actors])

    def update_minibatch_device(self, batch: Dict[str, np.ndarray]
                                ) -> Dict[str, Any]:
        """Sync-free update for the overlap pipeline (local/gang mode
        only; DDP actors already return host floats).  Duration metrics
        are the caller's job — dispatch is async, so wall time is only
        meaningful once the metrics are read back."""
        if self._local is not None:
            return self._local.update_minibatch_device(batch)
        return self.update_minibatch(batch)

    def update_minibatch(self, batch: Dict[str, np.ndarray]) -> Dict[str, float]:
        if self._local is not None:
            return self._local.update_minibatch(batch)
        # split the minibatch across ranks; every rank applies the same
        # allreduced gradient so params stay identical
        n = batch["obs"].shape[0]
        if n < self._num:
            raise ValueError(
                f"minibatch of {n} rows cannot be split across "
                f"{self._num} learners — an empty shard would produce "
                "NaN gradients; raise minibatch_size or lower num_learners"
            )
        shard = n // self._num
        refs = []
        for i, a in enumerate(self._actors):
            sl = slice(i * shard, (i + 1) * shard if i < self._num - 1 else n)
            refs.append(a.update_minibatch.remote(
                {k: v[sl] for k, v in batch.items()}
            ))
        all_metrics = rt.get(refs)
        return {
            k: float(np.mean([m[k] for m in all_metrics]))
            for k in all_metrics[0]
        }

    def get_weights_numpy(self):
        if self._local is not None:
            return self._local.get_weights_numpy()
        return rt.get(self._actors[0].get_weights_numpy.remote())

    def get_state(self):
        if self._local is not None:
            return self._local.get_state()
        return rt.get(self._actors[0].get_state.remote())

    def set_state(self, state):
        if self._local is not None:
            self._local.set_state(state)
        else:
            rt.get([a.set_state.remote(state) for a in self._actors])

    @property
    def num_gang_devices(self) -> int:
        """Mesh width of the pjit gang (1 = single local device,
        0 = DDP actors carry the parallelism instead)."""
        return self._gang_devices

    def stop(self):
        for a in self._actors:
            try:
                rt.kill(a)
            except Exception as e:
                logger.debug("learner actor kill on stop failed: %s", e)
