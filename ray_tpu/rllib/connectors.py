"""Connector pipelines: composable transforms on the env<->module path.

Reference: `rllib/connectors/` ConnectorV2 — pluggable pieces that
transform observations before the module sees them (env-to-module),
actions before the env sees them (module-to-env), and rewards before
they land in the train batch.  TPU-native shape: connectors run inside
the numpy EnvRunner actor (the CPU side), and the TRANSFORMED
observations are what the rollout batch stores, so the compiled learner
trains on exactly what the policy acted on — no recompute and no
train/act skew.

Stateful connectors (the running mean/std filter) expose
`get_state`/`set_state`; the EnvRunnerGroup merges per-runner states
periodically (reference: connector state aggregation across
EnvRunners) so every runner normalizes with the fleet-wide statistics.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

import numpy as np


class ConnectorV2:
    """One transform stage.  Override any hook; defaults pass through.

    Hooks run per vector-env step on numpy arrays:
    - `on_observations(obs[B, D])` before the module forward (and on
      truncation-bootstrap/final observations),
    - `on_actions(actions[B])` before `env.step`,
    - `on_rewards(rewards[B])` before the rollout buffer.
    """

    def on_observations(self, obs: np.ndarray) -> np.ndarray:
        return obs

    def on_actions(self, actions: np.ndarray) -> np.ndarray:
        return actions

    def on_rewards(self, rewards: np.ndarray) -> np.ndarray:
        return rewards

    def transformed_observation_shape(
        self, shape: Sequence[int],
    ) -> Sequence[int]:
        """Static shape mapping of `on_observations` (no state touched):
        lets module construction know the post-connector obs shape
        without running a sample (reference: connectors recompute the
        observation space for the module spec)."""
        return tuple(shape)

    def on_episode_boundaries(self, done_mask: np.ndarray) -> None:
        """Called by the EnvRunner after env.step with the per-sub-env
        done mask, so temporal connectors (frame stacking) reset their
        per-env state at episode boundaries."""
        pass

    def on_final_observations(self, obs: np.ndarray,
                              env_indices: np.ndarray) -> np.ndarray:
        """Transform final/bootstrap observations of a SUBSET of
        sub-envs (truncation value bootstrap).  Temporal connectors
        override this to read their per-env state without advancing
        it; stateless/statistical connectors treat it as a normal
        observation batch."""
        return self.on_observations(obs)

    def get_state(self) -> Dict[str, Any]:
        """Report-and-reset: return the state accumulated since the
        last call (stateful connectors POP their delta here — see
        MeanStdObsFilter) so fleet merges never double-count."""
        return {}

    def set_state(self, state: Dict[str, Any]) -> None:
        """Adopt merged fleet state as the new base (must not clear
        locally accumulated-but-unreported state)."""
        pass

    @staticmethod
    def merge_states(states: List[Dict[str, Any]]) -> Dict[str, Any]:
        """Combine per-runner states into the fleet state; default:
        first non-empty wins (stateless connectors don't care)."""
        for s in states:
            if s:
                return s
        return {}


class ConnectorPipeline(ConnectorV2):
    """Ordered composition (reference: ConnectorPipelineV2)."""

    def __init__(self, connectors: Sequence[ConnectorV2] = ()):
        self.connectors = list(connectors)

    def on_observations(self, obs):
        for c in self.connectors:
            obs = c.on_observations(obs)
        return obs

    def on_actions(self, actions):
        for c in self.connectors:
            actions = c.on_actions(actions)
        return actions

    def on_rewards(self, rewards):
        for c in self.connectors:
            rewards = c.on_rewards(rewards)
        return rewards

    def transformed_observation_shape(self, shape):
        for c in self.connectors:
            shape = c.transformed_observation_shape(shape)
        return tuple(shape)

    def on_episode_boundaries(self, done_mask):
        for c in self.connectors:
            c.on_episode_boundaries(done_mask)

    def on_final_observations(self, obs, env_indices):
        for c in self.connectors:
            obs = c.on_final_observations(obs, env_indices)
        return obs

    def get_state(self):
        return {str(i): c.get_state() for i, c in enumerate(self.connectors)}

    def set_state(self, state):
        for i, c in enumerate(self.connectors):
            if str(i) in state:
                c.set_state(state[str(i)])

    def merge_states(self, states):  # type: ignore[override]
        out = {}
        for i, c in enumerate(self.connectors):
            key = str(i)
            out[key] = c.merge_states([s.get(key, {}) for s in states])
        return out


def _welford_add(count, mean, m2, flat):
    n = flat.shape[0]
    if n == 0:
        return count, mean, m2
    batch_mean = flat.mean(axis=0)
    batch_m2 = ((flat - batch_mean) ** 2).sum(axis=0)
    delta = batch_mean - mean
    total = count + n
    mean = mean + delta * n / total
    m2 = m2 + batch_m2 + delta ** 2 * count * n / total
    return total, mean, m2


def _welford_combine(a, b):
    """Parallel-variance combination of two (count, mean, m2) stats."""
    ca, ma, m2a = a
    cb, mb, m2b = b
    if cb <= 0:
        return a
    if ca <= 0:
        return b
    delta = mb - ma
    total = ca + cb
    return (
        total,
        ma + delta * cb / total,
        m2a + m2b + delta ** 2 * ca * cb / total,
    )


class MeanStdObsFilter(ConnectorV2):
    """Running observation normalization (reference:
    `connectors/env_to_module/mean_std_filter.py`): Welford-style
    running mean/var per feature, observations standardized and
    clipped.

    Fleet protocol: the filter keeps a synced BASE (set by
    `set_state` with the merged fleet stats) and a local DELTA of
    samples seen since; `get_state` reports the delta only, and the
    merge combines base + one delta per runner — runners never
    re-contribute history they already reported (a full-state merge
    would double shared history N-fold per sync and freeze the
    normalizer on early statistics)."""

    def __init__(self, clip: float = 10.0, eps: float = 1e-8):
        self.clip = clip
        self.eps = eps
        self._base = None  # (count, mean, m2) merged fleet stats
        self._delta = None  # (count, mean, m2) local since last sync

    def _ensure(self, dim):
        if self._delta is None:
            zero = (0.0, np.zeros(dim, np.float64), np.zeros(dim, np.float64))
            self._delta = zero
        if self._base is None:
            self._base = (
                0.0, np.zeros(dim, np.float64), np.zeros(dim, np.float64)
            )

    def on_observations(self, obs):
        obs = np.asarray(obs, np.float32)
        self._ensure(obs.shape[-1])
        flat = obs.reshape(-1, obs.shape[-1]).astype(np.float64)
        self._delta = _welford_add(*self._delta, flat)
        count, mean, m2 = _welford_combine(self._base, self._delta)
        std = np.sqrt(m2 / max(count, 1.0)) + self.eps
        out = (obs - mean.astype(np.float32)) / std.astype(np.float32)
        return np.clip(out, -self.clip, self.clip).astype(np.float32)

    def get_state(self):
        """POP the delta to contribute to the next fleet merge: the
        report itself resets local accumulation, so samples arriving
        between this pop and the later `set_state` land in a FRESH
        delta instead of being zeroed (async rollouts execute in that
        window), and a lost `set_state` push can never double-report —
        the popped samples already live in the merged base."""
        if self._delta is None:
            return {}
        c, m, m2 = self._delta
        dim = m.shape[0]
        self._delta = (
            0.0, np.zeros(dim, np.float64), np.zeros(dim, np.float64)
        )
        return {"count": c, "mean": m, "m2": m2}

    def set_state(self, state):
        """Adopt merged fleet stats as the new base.  The local delta
        is NOT touched: it only holds samples not yet reported (see
        get_state's pop semantics)."""
        if not state:
            return
        self._base = (
            state["count"], np.array(state["mean"]), np.array(state["m2"])
        )

    @staticmethod
    def merge_states(states):
        live = [s for s in states if s and s.get("mean") is not None]
        if not live:
            return {}
        acc = (0.0, np.zeros_like(np.asarray(live[0]["mean"])),
               np.zeros_like(np.asarray(live[0]["m2"])))
        for s in live:
            acc = _welford_combine(
                acc, (s["count"], np.asarray(s["mean"]), np.asarray(s["m2"]))
            )
        return {"count": acc[0], "mean": acc[1], "m2": acc[2]}


class RewardClip(ConnectorV2):
    """Clip rewards to [-bound, bound] (the Atari-style stabilizer)."""

    def __init__(self, bound: float = 1.0):
        self.bound = bound

    def on_rewards(self, rewards):
        return np.clip(rewards, -self.bound, self.bound)


class ObsClip(ConnectorV2):
    def __init__(self, bound: float = 10.0):
        self.bound = bound

    def on_observations(self, obs):
        return np.clip(obs, -self.bound, self.bound)


class ImagePreprocess(ConnectorV2):
    """Atari-style image pipeline: grayscale + nearest-neighbor resize
    + scale to [0, 1] (reference: `atari_wrappers.py` WarpFrame /
    `wrap_atari_for_new_api_stack:324`), in vectorized numpy on
    [B, H, W, C] frames."""

    def __init__(self, size: int = 84, grayscale: bool = True,
                 scale: float = 1.0 / 255.0):
        self.size = size
        self.grayscale = grayscale
        self.scale = scale

    def transformed_observation_shape(self, shape):
        h, w, c = shape
        return (self.size, self.size, 1 if self.grayscale else c)

    def on_observations(self, obs):
        obs = np.asarray(obs, np.float32)
        if self.grayscale and obs.shape[-1] != 1:
            if obs.shape[-1] == 3:
                # ITU-R 601 luma (what cv2.cvtColor uses in the ref)
                obs = (obs @ np.array([0.299, 0.587, 0.114],
                                      np.float32))[..., None]
            else:
                # keep the 1-channel shape contract for any input
                # channel count (e.g. RGBA renders): plain mean
                obs = obs.mean(axis=-1, keepdims=True)
        h, w = obs.shape[1], obs.shape[2]
        if (h, w) != (self.size, self.size):
            ri = (np.arange(self.size) * h // self.size).clip(0, h - 1)
            ci = (np.arange(self.size) * w // self.size).clip(0, w - 1)
            obs = obs[:, ri[:, None], ci[None, :], :]
        if self.scale != 1.0:
            obs = obs * self.scale
        return obs.astype(np.float32)


class FrameStack(ConnectorV2):
    """Stack the last `k` frames along the channel axis (reference:
    `atari_wrappers.py` FrameStackEnv / the frame-stacking connector in
    `wrap_atari_for_new_api_stack`).  Per-sub-env buffers reset at
    episode boundaries via `on_episode_boundaries`; bootstrap/final
    observations (recognized by batch size != num live buffers only
    when the runner passes a subset) are stacked against the current
    buffers WITHOUT advancing them."""

    def __init__(self, k: int = 4):
        self.k = k
        self._frames = None  # [B, H, W, C*k] rolling buffer
        self._pending_reset = None  # done mask applied on next obs

    def transformed_observation_shape(self, shape):
        h, w, c = shape
        return (h, w, c * self.k)

    def on_observations(self, obs):
        obs = np.asarray(obs, np.float32)
        b, h, w, c = obs.shape
        if self._frames is None or self._frames.shape[0] != b:
            # first batch (or a bootstrap subset before any full batch):
            # initialize by repeating the frame k times
            stacked = np.tile(obs, (1, 1, 1, self.k))
            if self._frames is None and b > 0:
                self._frames = stacked.copy()
            return stacked
        if self._pending_reset is not None:
            # sub-envs that finished last step start a fresh stack with
            # their reset frame repeated
            m = self._pending_reset
            self._frames[m] = np.tile(obs[m], (1, 1, 1, self.k))
            self._pending_reset = None
            keep = ~m
        else:
            keep = np.ones(b, np.bool_)
        # shift one frame: drop oldest channels, append the new frame
        self._frames[keep] = np.concatenate(
            [self._frames[keep][..., c:], obs[keep]], axis=-1
        )
        return self._frames.copy()

    def on_final_observations(self, final_obs: np.ndarray,
                              env_indices: np.ndarray) -> np.ndarray:
        """Stack final/bootstrap observations against the CURRENT
        per-env buffers without advancing them."""
        final_obs = np.asarray(final_obs, np.float32)
        c = final_obs.shape[-1]
        if self._frames is None:
            return np.tile(final_obs, (1, 1, 1, self.k))
        cur = self._frames[env_indices]
        return np.concatenate([cur[..., c:], final_obs], axis=-1)

    def on_episode_boundaries(self, done_mask):
        done_mask = np.asarray(done_mask, np.bool_)
        if done_mask.any():
            self._pending_reset = done_mask.copy()


def wrap_atari_connectors(size: int = 84, grayscale: bool = True,
                          frame_stack: int = 4,
                          clip_rewards: bool = True) -> ConnectorPipeline:
    """The standard Atari pixel pipeline as one connector stack
    (reference: `atari_wrappers.py:324` wrap_atari_for_new_api_stack:
    warp + scale + frame-stack + reward clip)."""
    stages: List[ConnectorV2] = [
        ImagePreprocess(size=size, grayscale=grayscale),
        FrameStack(frame_stack),
    ]
    if clip_rewards:
        stages.append(RewardClip(1.0))
    return ConnectorPipeline(stages)
