from ray_tpu.rllib.env.env_runner import EnvRunner
from ray_tpu.rllib.env.env_runner_group import EnvRunnerGroup
from ray_tpu.rllib.env.envs import (
    CartPoleVectorEnv,
    GymnasiumVectorEnv,
    VectorEnv,
    make_vector_env,
)

__all__ = [
    "CartPoleVectorEnv",
    "EnvRunner",
    "EnvRunnerGroup",
    "GymnasiumVectorEnv",
    "VectorEnv",
    "make_vector_env",
]
