"""EnvRunnerGroup: fleet of sampling actors with fault tolerance.

Reference: `rllib/env/env_runner_group.py:71` — owns N remote EnvRunner
actors, broadcasts weights, gathers samples, and restores failed runners
(reference: `algorithm.py:235` restore_workers).

Production shape (this repo's BASELINE config #3 workload): sample
batches move as OBJECT-PLANE REFERENCES — each runner `rt.put`s its
rollout locally and returns a small envelope, so a fleet of
tens-to-hundreds of CPU actors fans small envelopes (not megabytes)
into the driver's owner shards, and the learner fetches batch payloads
zero-copy from shm.  Weights broadcast the same way: ONE `rt.put` per
version, every runner pulls at most once per version
(`EnvRunner.set_weights_ref`).

Exactly-once accounting: every consumed batch is recorded in a
`SampleLedger` under its (slot, incarnation, seq) key.  Runner
replacement bumps the incarnation, so a dead runner's in-flight batches
can never collide with — or be double-counted against — its
replacement's.  With `deterministic_replay=True` (sync fleets), a
replacement rebuilds the dead runner's exact env/rng state by replaying
its weights history, so a kill-storm run consumes bit-identical batches
to an unkilled control run.
"""

from __future__ import annotations

import logging
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

import ray_tpu as rt
from ray_tpu.metrics import metric_defs as _mdefs
from ray_tpu.rllib.env.env_runner import (
    EnvRunner,
    flatten_tree,
)

logger = logging.getLogger(__name__)


class DuplicateSampleError(RuntimeError):
    """A sample batch was consumed twice — the exactly-once fleet
    accounting is broken.  NEVER swallowed by the fault-tolerant
    consumption paths (which treat other fetch failures as a dead
    producer): this is a correctness bug, not a runner death."""


class SampleLedger:
    """Exactly-once consumption ledger for the runner fleet.

    Every batch the learner side consumes is recorded under its
    (slot, incarnation, seq) identity; a duplicate delivery raises —
    double-counting a rollout would silently skew both the bench
    numbers and the training distribution."""

    def __init__(self):
        self._seen: set = set()
        self.batches = 0
        self.env_steps = 0
        self.bytes = 0
        self.sample_s = 0.0

    def record(self, meta: Dict[str, Any]) -> None:
        key = (meta["slot"], meta["incarnation"], meta["seq"])
        if key in self._seen:
            raise DuplicateSampleError(
                f"duplicate sample batch consumed: {key} — the "
                "exactly-once fleet accounting is broken"
            )
        self._seen.add(key)
        self.batches += 1
        self.env_steps += int(meta["env_steps"])
        self.bytes += int(meta.get("bytes", 0))
        self.sample_s += float(meta.get("sample_s", 0.0))
        _mdefs.inc("rt_rllib_env_steps_total", float(meta["env_steps"]))
        _mdefs.inc("rt_rllib_sample_batch_bytes_total",
                   float(meta.get("bytes", 0)))

    def snapshot(self) -> Dict[str, float]:
        return {
            "batches": float(self.batches),
            "env_steps": float(self.env_steps),
            "bytes": float(self.bytes),
            "sample_s": self.sample_s,
            "unique": float(len(self._seen)),
        }


class EnvRunnerGroup:
    def __init__(self, env: Any, num_runners: int, num_envs_per_runner: int,
                 rollout_length: int, seed: int = 0,
                 env_kwargs: Optional[Dict] = None,
                 connector: Any = None,
                 deterministic_replay: bool = False):
        self._env = env
        self._num_runners = num_runners
        self._num_envs = num_envs_per_runner
        self._T = rollout_length
        self._seed = seed
        self._env_kwargs = env_kwargs or {}
        self._connector_factory = connector
        self._connector_base: Dict = {}  # merged fleet connector state
        self._runners: List = []
        self._incarnations: List[int] = [0] * num_runners
        self._weights: Any = None
        self._weights_version = 0
        #: one boxed `{"ref": ObjectRef}` per published version (1-based
        #: version v lives at index v-1).  With deterministic_replay the
        #: whole history is retained (replacements replay it); otherwise
        #: only the latest ref is kept alive.
        self._weights_refs: List[Dict[str, Any]] = []
        self._deterministic_replay = deterministic_replay
        if deterministic_replay and connector is not None:
            raise ValueError(
                "deterministic_replay rebuilds runner state from the "
                "seed + weights history alone; stateful connector "
                "pipelines receive out-of-band set_connector_state "
                "pushes that replay cannot reproduce — use one or the "
                "other"
            )
        self.ledger = SampleLedger()
        self._replacements = 0
        # compiled-DAG channel plane (use_compiled_dag) state
        self._chan_mode = False
        self._chan_id = ""
        self._sample_chans: Dict[int, Any] = {}
        self._weights_chans: Dict[int, Any] = {}
        self._chan_loop_refs: Dict[int, Any] = {}
        self._chan_loops_reaped: set = set()
        self._chan_rr = 0
        self._chan_last_health = 0.0
        self._chan_attempt = 0  # makes every bootstrap's ring names
        # unique, so a slow-exiting failed loop can never close the
        # rings of the retry that replaced it
        for i in range(num_runners):
            self._runners.append(self._make_runner(i))
        _mdefs.set_gauge("rt_rllib_env_runners", float(num_runners))

    def _make_runner(self, idx: int):
        return rt.remote(EnvRunner).options(num_cpus=1).remote(
            self._env, self._num_envs, self._T,
            seed=self._seed + idx * 10_000, env_kwargs=self._env_kwargs,
            connector=self._connector_factory,
            slot=idx, incarnation=self._incarnations[idx],
        )

    def env_spec(self) -> Dict[str, int]:
        return rt.get(self._runners[0].env_spec.remote())

    # -- weights broadcast (by reference: one put per version) ---------
    def _publish_weights(self, params_np: Any) -> Dict[str, Any]:
        self._weights = params_np
        self._weights_version += 1
        # inline=False: small policies would otherwise live in the
        # driver's memory and every runner pull would be an owner RPC
        # through the daemon (N round-trips per version); through shm,
        # node-local runners read the one published copy zero-copy
        boxed = {"ref": rt.put(params_np, inline=False)}
        if self._deterministic_replay:
            self._weights_refs.append(boxed)
        else:
            self._weights_refs = [boxed]
        return boxed

    def sync_weights(self, params_np: Any):
        if self._chan_mode:
            # resident loops occupy the actors: the RPC broadcast
            # would queue behind them forever — ride the channels
            self.sync_weights_channel(params_np)
            return
        boxed = self._publish_weights(params_np)
        refs = [
            r.set_weights_ref.remote(boxed, self._weights_version)
            for r in self._runners
        ]
        rt.wait(refs, num_returns=len(refs), timeout=30)

    def sync_weights_async(self, params_np: Any):
        """Non-blocking weight broadcast: runners adopt the new weights
        for their NEXT rollout; in-flight rollouts stay stale (V-trace
        or PPO's ratio clip absorbs one version of staleness)."""
        boxed = self._publish_weights(params_np)
        for r in self._runners:
            r.set_weights_ref.remote(boxed, self._weights_version)
        # connector stats ride the same cadence on the async path
        if (
            self._connector_factory is not None
            and self._weights_version % 8 == 0
        ):
            self.sync_connector_states()

    def _bootstrap_replacement(self, idx: int) -> bool:
        """Bring a fresh incarnation up to date: deterministic replay of
        the dead runner's weights history when enabled, else just the
        latest weights.  A bootstrap failure (the replacement itself
        killed under a sustained storm) is survivable: the un-weighted
        runner's next sample fails, which routes back through the
        replacement path — the fleet self-heals once kills stop."""
        try:
            if (self._deterministic_replay
                    and self._replay_module is not None):
                history = self._weights_refs[:-1]
                if history:
                    rt.get(self._runners[idx].replay.remote(
                        self._replay_module, history,
                    ), timeout=300)
            if self._weights_refs:
                rt.get(self._runners[idx].set_weights_ref.remote(
                    self._weights_refs[-1], self._weights_version,
                ), timeout=60)
            return True
        except Exception as e:
            logger.debug(
                "replacement runner %d bootstrap failed (%s); its next "
                "sample re-triggers replacement", idx, e,
            )
            return False

    def _replace_runner_sync(self, idx: int):
        self._incarnations[idx] += 1
        self._replacements += 1
        self._runners[idx] = self._make_runner(idx)
        self._bootstrap_replacement(idx)
        _mdefs.set_gauge("rt_rllib_env_runners", float(self._num_runners))

    # module used for deterministic replay (set by sample()/streams)
    _replay_module: Any = None

    # -- synchronous fleet sampling ------------------------------------
    def sample(self, module_def, explore=None) -> List[Dict[str, np.ndarray]]:
        """One rollout from every runner, shipped by reference.

        Failed runners are replaced in place; with deterministic_replay
        their round is RETRIED on the replacement (the replayed state
        regenerates the identical rollout), otherwise it is skipped
        this round (reference: EnvRunnerGroup fault tolerance)."""
        self._replay_module = module_def
        refs = [r.sample_ref.remote(module_def, explore)
                for r in self._runners]
        out: List[Dict[str, np.ndarray]] = []
        for i, ref in enumerate(refs):
            attempts = 0
            while True:
                try:
                    envelope = rt.get(ref, timeout=120)
                    out.append(self._consume(envelope))
                    break
                except DuplicateSampleError:
                    raise  # accounting bug, not a runner death
                except Exception as e:
                    attempts += 1
                    logger.debug(
                        "env runner %d failed mid-sample (%s); replacing",
                        i, e,
                    )
                    self._replace_runner_sync(i)
                    if not (self._deterministic_replay and attempts < 3):
                        break
                    ref = self._runners[i].sample_ref.remote(
                        module_def, explore
                    )
        if not out:
            raise RuntimeError("all env runners failed")
        # fleet-wide connector statistics converge once per sampling
        # round — centralized here so EVERY algorithm built on the
        # group gets it (not a per-algorithm opt-in)
        if self._connector_factory is not None:
            self.sync_connector_states()
        return out

    def fetch(self, envelope: Dict[str, Any]
              ) -> Tuple[Dict[str, Any], Dict[str, np.ndarray]]:
        """Fetch an envelope's batch payload from the object plane and
        record it in the exactly-once ledger.  Returns (meta, batch).

        The ledger records AFTER the payload fetch succeeds: a batch
        whose producer died between envelope delivery and payload read
        is never counted as consumed."""
        batch = rt.get(envelope["batch"], timeout=120)
        self.ledger.record(envelope["meta"])
        return envelope["meta"], batch

    def _consume(self, envelope: Dict[str, Any]) -> Dict[str, np.ndarray]:
        return self.fetch(envelope)[1]

    # -- async ref stream (the sample/train-overlap shape) -------------
    def start_ref_stream(self, module_def, *, inflight_per_runner: int = 2,
                         explore=None):
        """Keep every runner busy with up to `inflight_per_runner`
        outstanding sample_ref() calls (reference: IMPALA's async
        request manager, `impala.py` AsyncRequestsManager).  Batches
        land in the object plane; `collect()` hands back envelopes."""
        if self._deterministic_replay:
            raise ValueError(
                "deterministic_replay assumes one rollout per weights "
                "version (the sync fleet shape); the async ref stream "
                "pipelines several rollouts per version, so a replayed "
                "replacement would diverge from the dead incarnation — "
                "use the sync sample() path for deterministic "
                "replacement, or disable it for overlap"
            )
        self._replay_module = module_def
        self._async_module = module_def
        self._async_explore = explore
        self._async_inflight = inflight_per_runner
        self._pending: Dict[Any, int] = {}
        self._inflight_count = [0] * self._num_runners
        for i in range(self._num_runners):
            for _ in range(inflight_per_runner):
                self._submit_async(i)

    # back-compat alias (IMPALA's original entry point)
    def start_async_sampling(self, module_def, *,
                             inflight_per_runner: int = 2, explore=None):
        self.start_ref_stream(module_def,
                              inflight_per_runner=inflight_per_runner,
                              explore=explore)

    def _submit_async(self, idx: int):
        ref = self._runners[idx].sample_ref.remote(
            self._async_module, self._async_explore
        )
        self._pending[ref] = idx
        self._inflight_count[idx] += 1

    def collect(self, max_batches: int = 4,
                timeout: Optional[float] = 120.0,
                block: bool = True) -> List[Dict[str, Any]]:
        """Collect completed envelopes (blocking for at least one when
        `block`) and immediately re-dispatch their runners — the
        learner never waits for the slowest runner.  Dead runners are
        replaced in place (fresh incarnation; their other in-flight
        refs are dropped, so the ledger stays exactly-once)."""
        assert self._pending, "call start_ref_stream first"
        out: List[Dict[str, Any]] = []
        if block:
            ready, rest = rt.wait(
                list(self._pending), num_returns=1, timeout=timeout
            )
        else:
            ready, rest = rt.wait(
                list(self._pending),
                num_returns=min(max_batches, len(self._pending)),
                timeout=0,
            )
        if block and rest and max_batches > 1:
            more, _ = rt.wait(
                rest,
                num_returns=min(max_batches - 1, len(rest)),
                timeout=0,
            )
            ready = list(ready) + list(more)
        for ref in ready:
            idx = self._pending.pop(ref, None)
            if idx is None:
                # its runner was replaced earlier in this loop (its
                # other in-flight refs were dropped with it)
                continue
            self._inflight_count[idx] -= 1
            try:
                out.append(rt.get(ref))
            except Exception as e:
                logger.debug(
                    "env runner %d died with a rollout in flight (%s); "
                    "replacing", idx, e,
                )
                self._replace_runner(idx)
            self._submit_async(idx)
        return out

    def get_ready_samples(self, max_batches: int = 4,
                          timeout: Optional[float] = 120.0
                          ) -> List[Dict[str, np.ndarray]]:
        """Envelope stream + payload fetch in one call — the IMPALA
        surface.  Every returned batch is ledger-recorded."""
        out = []
        for envelope in self.collect(max_batches=max_batches,
                                     timeout=timeout):
            try:
                out.append(self._consume(envelope))
            except DuplicateSampleError:
                raise  # accounting bug, not a runner death
            except Exception as e:
                # the producing runner died between envelope delivery
                # and payload fetch; its replacement resamples
                logger.debug("sample payload fetch failed: %s", e)
        return out

    def _replace_runner(self, idx: int):
        # drop the dead runner's other pending refs so they don't
        # resubmit onto the replacement twice
        for ref, i in list(self._pending.items()):
            if i == idx:
                del self._pending[ref]
        self._inflight_count[idx] = 0
        self._incarnations[idx] += 1
        self._replacements += 1
        self._runners[idx] = self._make_runner(idx)
        self._bootstrap_replacement(idx)
        _mdefs.set_gauge("rt_rllib_env_runners", float(self._num_runners))
        while self._inflight_count[idx] < self._async_inflight - 1:
            self._submit_async(idx)

    # -- compiled-DAG channel plane (use_compiled_dag=True) ------------
    def start_channel_stream(self, module_def, *, explore=None):
        """The fast-plane analog of start_ref_stream: every runner
        hosts a RESIDENT sample loop (`run_sample_channel_loop`) and
        the runner->learner sample hop + the weights broadcast ride shm
        tensor channels instead of per-call actor RPCs.  Exactly-once
        accounting is unchanged: every batch still carries its (slot,
        incarnation, seq) meta and is ledger-recorded on consumption —
        channel delivery consumes each published message exactly once
        by construction."""
        if self._deterministic_replay:
            raise ValueError(
                "deterministic_replay replays the weights-ref history "
                "over the actor-call path; the channel plane broadcasts "
                "by value — use one or the other"
            )
        if self._weights is None:
            raise RuntimeError("sync_weights before start_channel_stream")
        import uuid

        self._replay_module = module_def
        self._chan_mode = True
        self._chan_id = uuid.uuid4().hex[:8]
        self._chan_module = module_def
        self._chan_explore = explore
        try:
            for i in range(self._num_runners):
                self._start_runner_channels(i)
        except BaseException:
            # mid-fleet bootstrap failure: roll the whole plane back
            # (already-started loops + rings) — a half-started stream
            # would leak pinned rings and queue a second resident loop
            # behind the first on any retry
            try:
                self.stop_channel_stream()
            except Exception as e:
                logger.debug("channel stream rollback failed: %s", e)
            raise

    def _start_runner_channels(self, idx: int):
        from ray_tpu.core.runtime import get_runtime
        from ray_tpu.dag.channel import Channel
        from ray_tpu.dag.compiled_dag import resolve_actor_node

        # force placement: a fresh (replacement) runner has no address
        # until it is scheduled, and its ring must land on its node
        rt.get(self._runners[idx].ping.remote(), timeout=60)
        self._chan_attempt += 1
        base = (f"rl{self._chan_id}_r{idx}i{self._incarnations[idx]}"
                f"a{self._chan_attempt}")
        s_ref = (base + "s", get_runtime().node_id)  # ring at the learner
        w_ref = (base + "w", resolve_actor_node(self._runners[idx]))
        template, leaves = flatten_tree(self._weights)
        plan = {
            "sample_chan": s_ref,
            "weights_chan": w_ref,
            "weights_ring_slots": 4,
            "module": self._chan_module,
            "explore": self._chan_explore,
            "weights_template": template,
        }
        s_ch = Channel(*s_ref)
        w_ch = Channel(*w_ref, ring_slots=4)
        try:
            loop_ref = self._runners[idx].run_sample_channel_loop.remote(
                plan
            )
            # seed the incarnation with the current version (its loop
            # blocks on the weights channel until one arrives)
            w_ch.write_tensors(
                leaves, extra={"version": self._weights_version}
            )
        except BaseException:
            # register NOTHING on a partial bootstrap: a half-wired
            # runner would look healthy (rings present, forever idle)
            # and the self-healing would never retry it
            for ch in (s_ch, w_ch):
                try:
                    ch.destroy()
                except Exception as e:
                    logger.debug("bootstrap ring cleanup failed: %s", e)
            raise
        self._sample_chans[idx] = s_ch
        self._weights_chans[idx] = w_ch
        self._chan_loop_refs[idx] = loop_ref

    def _try_read_channel(self, idx: int, timeout_s: float):
        """One bounded read from runner `idx`'s sample channel.
        Returns (meta, batch), or None when nothing is ready.  A read
        failure other than timeout means the producer died — replace
        it in place (fresh incarnation, fresh rings)."""
        from ray_tpu.dag.channel import ChannelPollTimeout

        ch = self._sample_chans.get(idx)
        if ch is None:
            return None
        try:
            batch, meta = ch.read_tensors(timeout_s=timeout_s)
        except ChannelPollTimeout:
            return None
        except Exception as e:  # ChannelClosed or any reader failure:
            # either way the producer is gone (or its stream is
            # corrupt) — replace it in place
            logger.debug(
                "sample channel of runner %d failed (%s); replacing",
                idx, e,
            )
            self._replace_runner_channel(idx)
            return None
        self.ledger.record(meta)
        return meta, batch

    def _check_channel_loops(self):
        """Reap failed resident loops (SIGKILLed runner: its channel
        goes silent but its loop TASK fails) and replace their
        runners."""
        from ray_tpu.dag.compiled_dag import reap_failed_loop_tasks

        by_ref = {ref: idx for idx, ref in self._chan_loop_refs.items()}
        for ref, e in reap_failed_loop_tasks(list(by_ref),
                                             self._chan_loops_reaped):
            idx = by_ref[ref]
            if self._chan_loop_refs.get(idx) is not ref:
                continue  # already replaced
            logger.debug(
                "runner %d sample loop died (%s); replacing", idx, e,
            )
            self._replace_runner_channel(idx)

    def _replace_runner_channel(self, idx: int):
        for chans in (self._sample_chans, self._weights_chans):
            ch = chans.pop(idx, None)
            if ch is not None:
                try:
                    ch.destroy()
                except Exception as e:
                    logger.debug("stale ring destroy failed: %s", e)
        self._chan_loop_refs.pop(idx, None)
        # the replaced actor may still be ALIVE (transient read/ping
        # failure): kill it, or every replacement leaks a resident
        # runner process + its vector envs until cluster shutdown
        try:
            rt.kill(self._runners[idx])
        except Exception as e:
            logger.debug("old runner %d kill failed: %s", idx, e)
        self._incarnations[idx] += 1
        self._replacements += 1
        self._runners[idx] = self._make_runner(idx)
        try:
            self._start_runner_channels(idx)
        except Exception as e:
            # replacement itself died (sustained storm): the next empty
            # collect pass re-detects the missing channels and retries
            logger.debug(
                "replacement runner %d channel bootstrap failed (%s); "
                "will retry on next stall", idx, e,
            )
        _mdefs.set_gauge("rt_rllib_env_runners", float(self._num_runners))

    def collect_channel(self, max_batches: int = 4,
                        timeout: Optional[float] = 120.0,
                        block: bool = True) -> List[Tuple[Dict, Dict]]:
        """Collect ready (meta, batch) pairs off the sample channels
        (blocking for at least one when `block`).  Every returned batch
        is ledger-recorded; DuplicateSampleError propagates (accounting
        bug, never a runner death)."""
        assert self._chan_mode, "call start_channel_stream first"
        out: List[Tuple[Dict, Dict]] = []
        deadline = (None if timeout is None
                    else time.monotonic() + max(0.0, timeout))
        # a dead runner's channel goes silent while survivors keep the
        # stream busy, so liveness CANNOT wait for a fully-empty pass —
        # sweep the loop refs on a cheap time throttle as well
        if time.monotonic() - self._chan_last_health > 2.0:
            self._heal_channel_fleet()
        while True:
            # sweep everything that is already published
            for idx in sorted(self._sample_chans):
                while len(out) < max_batches:
                    got = self._try_read_channel(idx, timeout_s=0.001)
                    if got is None:
                        break
                    out.append(got)
                if len(out) >= max_batches:
                    return out
            if out or not block:
                return out
            if deadline is not None and time.monotonic() >= deadline:
                return out
            # nothing ready: look for dead producers, then park briefly
            # on one channel round-robin (readers cost nothing while
            # parked — the ring condvar wakes them).  The heal is
            # throttled (~2s) and an empty fleet pays the park as a
            # plain sleep — a persistently failing bootstrap must not
            # spawn replacement actors in a tight loop
            if time.monotonic() - self._chan_last_health > 2.0:
                self._heal_channel_fleet()
            if not self._sample_chans:
                time.sleep(0.25)  # rtlint: disable=RT006 — not a
                # retry loop: paced wait for the throttled heal above
                continue
            idxs = sorted(self._sample_chans)
            self._chan_rr = (self._chan_rr + 1) % len(idxs)
            got = self._try_read_channel(idxs[self._chan_rr], timeout_s=0.25)
            if got is not None:
                out.append(got)

    def _heal_channel_fleet(self):
        """Reap failed resident loops and re-bootstrap any runner index
        with no rings (a storm can kill a replacement mid-bootstrap)."""
        self._chan_last_health = time.monotonic()
        self._check_channel_loops()
        for idx in range(self._num_runners):
            if self._chan_mode and idx not in self._sample_chans:
                try:
                    self._start_runner_channels(idx)
                except Exception as e:
                    logger.debug(
                        "runner %d channel re-bootstrap failed (%s); "
                        "replacing the actor", idx, e,
                    )
                    self._replace_runner_channel(idx)

    def sync_weights_channel(self, params_np: Any):
        """Non-blocking weights broadcast over the reverse channels:
        one tensor publication per runner ring.  A full ring (runner
        deep in a rollout, several unread versions queued) SKIPS that
        runner for this version — it drains to the newest on its next
        boundary, the same bounded staleness the ref path allows."""
        assert self._chan_mode, "call start_channel_stream first"
        self._weights = params_np
        self._weights_version += 1
        _template, leaves = flatten_tree(params_np)
        for idx, ch in list(self._weights_chans.items()):
            try:
                ch.write_tensors(
                    leaves, extra={"version": self._weights_version},
                    timeout_s=0.05,
                )
            except TimeoutError:
                logger.debug(
                    "weights ring of runner %d full at v%d; it adopts "
                    "the newest on drain", idx, self._weights_version,
                )
            except Exception as e:
                logger.debug(
                    "weights publish to runner %d failed (%s); stall "
                    "detection will replace it", idx, e,
                )

    def stop_channel_stream(self):
        """Tear the channel plane down: close the weights rings (the
        resident loops exit at their next rollout boundary), drain
        sample rings so a writer blocked on a full ring unwedges, then
        free every ring."""
        if not self._chan_mode:
            return
        from ray_tpu.dag.channel import ChannelPollTimeout

        for ch in self._weights_chans.values():
            ch.close()
        deadline = time.monotonic() + 20.0
        pending = dict(self._sample_chans)
        while pending and time.monotonic() < deadline:
            for idx, ch in list(pending.items()):
                try:
                    ch.read_tensors(timeout_s=0.05)
                except ChannelPollTimeout:
                    continue
                except Exception as e:  # ChannelClosed (producer
                    # exited) or a dead producer's broken stream
                    logger.debug("sample ring %d drained (%s)", idx, e)
                    del pending[idx]
        refs = list(self._chan_loop_refs.values())
        if refs:
            try:
                rt.wait(refs, num_returns=len(refs), timeout=15)
            except Exception as e:
                logger.debug("channel loop drain wait failed: %s", e)
        for chans in (self._sample_chans, self._weights_chans):
            for ch in chans.values():
                try:
                    ch.destroy()
                except Exception as e:
                    logger.debug("ring destroy failed: %s", e)
            chans.clear()
        self._chan_loop_refs.clear()
        self._chan_loops_reaped.clear()
        self._chan_mode = False

    # -- connector state (reference: connector aggregation across
    # EnvRunners) ------------------------------------------------------
    def sync_connector_states(self):
        """Merge per-runner connector DELTAS over the tracked fleet
        base and push the result back (reference: connector state
        aggregation across EnvRunners).  Runners report only samples
        seen since their last sync, so shared history is never
        double-counted."""
        if self._connector_factory is None:
            return None
        refs = [r.get_connector_state.remote() for r in self._runners]
        states = [self._connector_base]
        for ref in refs:
            try:
                states.append(rt.get(ref, timeout=30))
            except Exception as e:
                logger.debug("connector state fetch failed: %s", e)
                states.append({})
        proto = self._connector_factory()
        merged = proto.merge_states(states)
        if merged:
            self._connector_base = merged
            set_refs = [r.set_connector_state.remote(merged)
                        for r in self._runners]
            rt.wait(set_refs, num_returns=len(set_refs), timeout=30)
        return merged

    def connector_state(self) -> Optional[Dict]:
        """Fleet connector state for checkpoints (the merged base; a
        restored policy must act on the SAME normalization it trained
        with)."""
        if self._connector_factory is None:
            return None
        return self._connector_base

    def restore_connector_state(self, state: Optional[Dict]):
        if self._connector_factory is None or not state:
            return
        self._connector_base = state
        refs = [r.set_connector_state.remote(state)
                for r in self._runners]
        rt.wait(refs, num_returns=len(refs), timeout=30)

    def pop_metrics(self) -> List[Dict[str, float]]:
        metrics: List[Dict[str, float]] = []
        refs = [r.pop_metrics.remote() for r in self._runners]
        for ref in refs:
            try:
                metrics.extend(rt.get(ref, timeout=30))
            except Exception as e:
                logger.debug("episode metrics fetch failed: %s", e)
        return metrics

    def ping_fleet(self, timeout: float = 30.0) -> int:
        """Healthy-runner count (chaos tests assert full restoration)."""
        alive = 0
        for r in self._runners:
            try:
                if rt.get(r.ping.remote(), timeout=timeout):
                    alive += 1
            except Exception as e:
                logger.debug("runner ping failed: %s", e)
        return alive

    @property
    def num_runners(self) -> int:
        return self._num_runners

    @property
    def num_replacements(self) -> int:
        return self._replacements

    @property
    def weights_version(self) -> int:
        return self._weights_version

    def stop(self):
        try:
            self.stop_channel_stream()
        except Exception as e:
            logger.debug("channel stream stop failed: %s", e)
        for r in self._runners:
            try:
                rt.kill(r)
            except Exception as e:
                logger.debug("runner kill on stop failed: %s", e)
        _mdefs.set_gauge("rt_rllib_env_runners", 0.0)
