"""EnvRunnerGroup: fleet of sampling actors with fault tolerance.

Reference: `rllib/env/env_runner_group.py:71` — owns N remote EnvRunner
actors, broadcasts weights, gathers samples, and restores failed runners
(reference: `algorithm.py:235` restore_workers).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

import ray_tpu as rt
from ray_tpu.rllib.env.env_runner import EnvRunner


class EnvRunnerGroup:
    def __init__(self, env: Any, num_runners: int, num_envs_per_runner: int,
                 rollout_length: int, seed: int = 0,
                 env_kwargs: Optional[Dict] = None):
        self._env = env
        self._num_runners = num_runners
        self._num_envs = num_envs_per_runner
        self._T = rollout_length
        self._seed = seed
        self._env_kwargs = env_kwargs or {}
        self._runners: List = []
        self._weights: Any = None
        self._weights_version = 0
        for i in range(num_runners):
            self._runners.append(self._make_runner(i))

    def _make_runner(self, idx: int):
        return rt.remote(EnvRunner).options(num_cpus=1).remote(
            self._env, self._num_envs, self._T,
            seed=self._seed + idx * 10_000, env_kwargs=self._env_kwargs,
        )

    def env_spec(self) -> Dict[str, int]:
        return rt.get(self._runners[0].env_spec.remote())

    def sync_weights(self, params_np: Any):
        self._weights = params_np
        self._weights_version += 1
        refs = [
            r.set_weights.remote(params_np, self._weights_version)
            for r in self._runners
        ]
        rt.wait(refs, num_returns=len(refs), timeout=30)

    def sample(self, module_def, explore=None) -> List[Dict[str, np.ndarray]]:
        """One rollout from every healthy runner; failed runners are
        replaced and their sample skipped this round (reference:
        EnvRunnerGroup fault tolerance)."""
        refs = [r.sample.remote(module_def, explore) for r in self._runners]
        out: List[Dict[str, np.ndarray]] = []
        for i, ref in enumerate(refs):
            try:
                out.append(rt.get(ref, timeout=120))
            except Exception:
                self._runners[i] = self._make_runner(i)
                rt.get(self._runners[i].set_weights.remote(
                    self._weights, self._weights_version))
        if not out:
            raise RuntimeError("all env runners failed")
        return out

    def pop_metrics(self) -> List[Dict[str, float]]:
        metrics: List[Dict[str, float]] = []
        refs = [r.pop_metrics.remote() for r in self._runners]
        for ref in refs:
            try:
                metrics.extend(rt.get(ref, timeout=30))
            except Exception:
                pass
        return metrics

    @property
    def num_runners(self) -> int:
        return self._num_runners

    def stop(self):
        for r in self._runners:
            try:
                rt.kill(r)
            except Exception:
                pass
