"""EnvRunnerGroup: fleet of sampling actors with fault tolerance.

Reference: `rllib/env/env_runner_group.py:71` — owns N remote EnvRunner
actors, broadcasts weights, gathers samples, and restores failed runners
(reference: `algorithm.py:235` restore_workers).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

import ray_tpu as rt
from ray_tpu.rllib.env.env_runner import EnvRunner


class EnvRunnerGroup:
    def __init__(self, env: Any, num_runners: int, num_envs_per_runner: int,
                 rollout_length: int, seed: int = 0,
                 env_kwargs: Optional[Dict] = None,
                 connector: Any = None):
        self._env = env
        self._num_runners = num_runners
        self._num_envs = num_envs_per_runner
        self._T = rollout_length
        self._seed = seed
        self._env_kwargs = env_kwargs or {}
        self._connector_factory = connector
        self._connector_base: Dict = {}  # merged fleet connector state
        self._runners: List = []
        self._weights: Any = None
        self._weights_version = 0
        for i in range(num_runners):
            self._runners.append(self._make_runner(i))

    def _make_runner(self, idx: int):
        return rt.remote(EnvRunner).options(num_cpus=1).remote(
            self._env, self._num_envs, self._T,
            seed=self._seed + idx * 10_000, env_kwargs=self._env_kwargs,
            connector=self._connector_factory,
        )

    def env_spec(self) -> Dict[str, int]:
        return rt.get(self._runners[0].env_spec.remote())

    def sync_weights(self, params_np: Any):
        self._weights = params_np
        self._weights_version += 1
        refs = [
            r.set_weights.remote(params_np, self._weights_version)
            for r in self._runners
        ]
        rt.wait(refs, num_returns=len(refs), timeout=30)

    def sample(self, module_def, explore=None) -> List[Dict[str, np.ndarray]]:
        """One rollout from every healthy runner; failed runners are
        replaced and their sample skipped this round (reference:
        EnvRunnerGroup fault tolerance)."""
        refs = [r.sample.remote(module_def, explore) for r in self._runners]
        out: List[Dict[str, np.ndarray]] = []
        for i, ref in enumerate(refs):
            try:
                out.append(rt.get(ref, timeout=120))
            except Exception:
                self._runners[i] = self._make_runner(i)
                rt.get(self._runners[i].set_weights.remote(
                    self._weights, self._weights_version))
        if not out:
            raise RuntimeError("all env runners failed")
        # fleet-wide connector statistics converge once per sampling
        # round — centralized here so EVERY algorithm built on the
        # group gets it (not a per-algorithm opt-in)
        if self._connector_factory is not None:
            self.sync_connector_states()
        return out

    # -- async sampling (the IMPALA shape) -----------------------------
    def start_async_sampling(self, module_def, *, inflight_per_runner: int = 2,
                             explore=None):
        """Keep every runner busy with up to `inflight_per_runner`
        outstanding sample() calls (reference: IMPALA's async request
        manager, `impala.py` AsyncRequestsManager)."""
        self._async_module = module_def
        self._async_explore = explore
        self._async_inflight = inflight_per_runner
        self._pending: Dict[Any, int] = {}
        self._inflight_count = [0] * self._num_runners
        for i in range(self._num_runners):
            for _ in range(inflight_per_runner):
                self._submit_async(i)

    def _submit_async(self, idx: int):
        ref = self._runners[idx].sample.remote(
            self._async_module, self._async_explore
        )
        self._pending[ref] = idx
        self._inflight_count[idx] += 1

    def get_ready_samples(self, max_batches: int = 4,
                          timeout: Optional[float] = 120.0
                          ) -> List[Dict[str, np.ndarray]]:
        """Collect completed rollouts (blocking for at least one) and
        immediately re-dispatch their runners — the learner never waits
        for the slowest runner (the async architecture IMPALA exists
        for).  Dead runners are replaced in place."""
        assert self._pending, "call start_async_sampling first"
        out: List[Dict[str, np.ndarray]] = []
        # block for ONE rollout, then sweep whatever else is already
        # done — never a barrier on the slowest runner (that barrier is
        # exactly what IMPALA's async architecture removes)
        ready, rest = rt.wait(
            list(self._pending), num_returns=1, timeout=timeout
        )
        if rest and max_batches > 1:
            more, _ = rt.wait(
                rest,
                num_returns=min(max_batches - 1, len(rest)),
                timeout=0,
            )
            ready = list(ready) + list(more)
        for ref in ready:
            idx = self._pending.pop(ref, None)
            if idx is None:
                # its runner was replaced earlier in this loop (its
                # other in-flight refs were dropped with it)
                continue
            self._inflight_count[idx] -= 1
            try:
                out.append(rt.get(ref))
            except Exception:
                self._replace_runner(idx)
            self._submit_async(idx)
        return out

    def _replace_runner(self, idx: int):
        # drop the dead runner's other pending refs so they don't
        # resubmit onto the replacement twice
        for ref, i in list(self._pending.items()):
            if i == idx:
                del self._pending[ref]
        self._inflight_count[idx] = 0
        self._runners[idx] = self._make_runner(idx)
        rt.get(self._runners[idx].set_weights.remote(
            self._weights, self._weights_version))
        while self._inflight_count[idx] < self._async_inflight - 1:
            self._submit_async(idx)

    def sync_weights_async(self, params_np: Any):
        """Non-blocking weight broadcast: runners adopt the new weights
        for their NEXT rollout; in-flight rollouts stay stale (V-trace
        corrects them)."""
        self._weights = params_np
        self._weights_version += 1
        for r in self._runners:
            r.set_weights.remote(params_np, self._weights_version)
        # connector stats ride the same cadence on the async path
        if (
            self._connector_factory is not None
            and self._weights_version % 8 == 0
        ):
            self.sync_connector_states()

    def sync_connector_states(self):
        """Merge per-runner connector DELTAS over the tracked fleet
        base and push the result back (reference: connector state
        aggregation across EnvRunners).  Runners report only samples
        seen since their last sync, so shared history is never
        double-counted."""
        if self._connector_factory is None:
            return None
        refs = [r.get_connector_state.remote() for r in self._runners]
        states = [self._connector_base]
        for ref in refs:
            try:
                states.append(rt.get(ref, timeout=30))
            except Exception:
                states.append({})
        proto = self._connector_factory()
        merged = proto.merge_states(states)
        if merged:
            self._connector_base = merged
            set_refs = [r.set_connector_state.remote(merged)
                        for r in self._runners]
            rt.wait(set_refs, num_returns=len(set_refs), timeout=30)
        return merged

    def connector_state(self) -> Optional[Dict]:
        """Fleet connector state for checkpoints (the merged base; a
        restored policy must act on the SAME normalization it trained
        with)."""
        if self._connector_factory is None:
            return None
        return self._connector_base

    def restore_connector_state(self, state: Optional[Dict]):
        if self._connector_factory is None or not state:
            return
        self._connector_base = state
        refs = [r.set_connector_state.remote(state)
                for r in self._runners]
        rt.wait(refs, num_returns=len(refs), timeout=30)

    def pop_metrics(self) -> List[Dict[str, float]]:
        metrics: List[Dict[str, float]] = []
        refs = [r.pop_metrics.remote() for r in self._runners]
        for ref in refs:
            try:
                metrics.extend(rt.get(ref, timeout=30))
            except Exception:
                pass
        return metrics

    @property
    def num_runners(self) -> int:
        return self._num_runners

    def stop(self):
        for r in self._runners:
            try:
                rt.kill(r)
            except Exception:
                pass
