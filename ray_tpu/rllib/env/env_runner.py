"""EnvRunner: CPU sampling actors.

Reference: `rllib/env/single_agent_env_runner.py:61` (`sample():131`) —
each runner steps a vectorized env with the current policy and returns
fixed-shape rollout batches.  TPU-native split: runners are numpy-only
(see rl_module.py); fixed rollout length T keeps downstream learner
batch shapes static so the PPO update compiles once.

Batch layout (time-major): obs[T,B,D], actions[T,B], logp[T,B],
values[T,B], rewards[T,B], dones[T,B], final_obs[B] for bootstrap.

Production shape (the reference's EnvRunnerGroup fleet): `sample_ref`
ships the rollout through the OBJECT PLANE — the batch is `rt.put`
inside the actor and only a small envelope (ref + accounting metadata)
travels back on the actor-call completion path, so a fleet of hundreds
of runners fans references, not megabytes, into the driver's owner
shards.  Weights travel the other way by reference too
(`set_weights_ref`): the learner puts one weights object per version
and every runner pulls it from the store at most once per version.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

import numpy as np

from ray_tpu.rllib.env.envs import make_vector_env


def _softmax(x: np.ndarray) -> np.ndarray:
    z = x - x.max(axis=-1, keepdims=True)
    e = np.exp(z)
    return e / e.sum(axis=-1, keepdims=True)


def flatten_tree(tree: Any):
    """Flatten a dict/list/tuple pytree of arrays to (template, leaves)
    — the template mirrors the structure with leaf INDICES at the
    leaves.  Pure python: the channel weights broadcast uses it so
    runner workers stay numpy-only (no jax import for unflattening)."""
    leaves: List[Any] = []

    def walk(t):
        if isinstance(t, dict):
            return {k: walk(v) for k, v in t.items()}
        if isinstance(t, (list, tuple)):
            out = [walk(v) for v in t]
            return out if isinstance(t, list) else tuple(out)
        leaves.append(t)
        return len(leaves) - 1

    return walk(tree), leaves


def unflatten_tree(template: Any, leaves: List[Any]):
    if isinstance(template, dict):
        return {k: unflatten_tree(v, leaves) for k, v in template.items()}
    if isinstance(template, (list, tuple)):
        out = [unflatten_tree(v, leaves) for v in template]
        return out if isinstance(template, list) else tuple(out)
    return leaves[template]


class EnvRunner:
    """One sampling actor (hosts the vector env + numpy policy copy)."""

    def __init__(self, env: Any, num_envs: int, rollout_length: int,
                 seed: int = 0, env_kwargs: Optional[Dict] = None,
                 connector: Any = None, slot: int = 0,
                 incarnation: int = 0):
        self._env = make_vector_env(env, num_envs, seed=seed,
                                    **(env_kwargs or {}))
        self._T = rollout_length
        self._rng = np.random.default_rng(seed + 1)
        self._obs = self._env.reset(seed=seed)
        self._params: Any = None
        self._weights_version = -1
        # fleet identity for exactly-once sample accounting: `slot` is
        # the stable position in the group, `incarnation` bumps on every
        # replacement, `seq` numbers this incarnation's rollouts — the
        # ledger key (slot, incarnation, seq) can never collide between
        # a dead runner's in-flight batches and its replacement's
        self._slot = slot
        self._incarnation = incarnation
        self._seq = 0
        # env<->module transform pipeline (reference: rllib/connectors/
        # ConnectorV2); a factory callable lets the spec ship by value
        self._connector = connector() if callable(connector) else connector
        # end-of-rollout transformed obs, reused by the next sample()
        self._cached_transformed_obs: Optional[np.ndarray] = None
        # per-sub-env running episode accounting for metrics
        self._ep_return = np.zeros(self._env.num_envs, dtype=np.float64)
        self._ep_len = np.zeros(self._env.num_envs, dtype=np.int64)
        self._completed: List[Dict[str, float]] = []

    # -- control ------------------------------------------------------
    def set_weights(self, params_np: Any, version: int) -> bool:
        self._params = params_np
        self._weights_version = version
        return True

    def get_weights_version(self) -> int:
        return self._weights_version

    def env_spec(self) -> Dict[str, Any]:
        raw_shape = self._env.observation_shape
        # what the MODULE sees: the connector's static shape mapping
        # applied to the raw env shape (reference: connectors recompute
        # the module spec's observation space)
        shape = (
            tuple(self._connector.transformed_observation_shape(raw_shape))
            if self._connector is not None else tuple(raw_shape)
        )
        return {
            "observation_size": int(np.prod(shape)),
            "observation_shape": shape,
            "raw_observation_shape": tuple(raw_shape),
            "num_actions": self._env.num_actions,
            "num_envs": self._env.num_envs,
            "continuous": bool(getattr(self._env, "continuous", False)),
            "action_dim": int(getattr(self._env, "action_dim", 0)),
            "action_low": float(getattr(self._env, "action_low", -1.0)),
            "action_high": float(getattr(self._env, "action_high", 1.0)),
        }

    # -- sampling (HOT LOOP of the RL stack) --------------------------
    def sample(self, module_def, explore=None) -> Dict[str, np.ndarray]:
        assert self._params is not None, "set_weights before sample"
        T, B = self._T, self._env.num_envs
        spec = self.env_spec()
        shape = spec["observation_shape"]
        continuous = spec["continuous"]
        obs_buf = np.empty((T, B, *shape), np.float32)
        # continuous actions are [-1, 1]^A module outputs, rescaled to
        # the env's bounds only at the step boundary — the learner
        # trains on exactly what the policy emitted
        act_buf = (
            np.empty((T, B, spec["action_dim"]), np.float32)
            if continuous else np.empty((T, B), np.int32)
        )
        lo, hi = spec["action_low"], spec["action_high"]
        logp_buf = np.empty((T, B), np.float32)
        val_buf = np.empty((T, B), np.float32)
        rew_buf = np.empty((T, B), np.float32)
        term_buf = np.empty((T, B), np.bool_)
        trunc_buf = np.empty((T, B), np.bool_)
        # V(final_obs) where an episode was truncated this step — the
        # bootstrap GAE uses instead of zero (truncation is not failure)
        boot_buf = np.zeros((T, B), np.float32)

        select = getattr(module_def, "select_actions_numpy", None)
        conn = self._connector
        obs = self._obs
        for t in range(T):
            if conn is not None:
                # the TRANSFORMED observation is what the policy acts on
                # AND what the rollout stores — learner and actor see
                # the same features (no train/act skew).  The previous
                # rollout already transformed (and ingested) its final
                # obs for the bootstrap value: reuse that result so the
                # boundary row is neither double-counted in running
                # stats nor normalized differently than its bootstrap.
                if t == 0 and self._cached_transformed_obs is not None:
                    obs = self._cached_transformed_obs
                    self._cached_transformed_obs = None
                else:
                    obs = conn.on_observations(obs)
            if select is not None:
                # module-defined exploration (epsilon-greedy DQN,
                # squashed-Gaussian sampling for continuous SAC)
                actions, logp, value = select(
                    self._params, obs, self._rng, explore
                )
                actions = (
                    actions.astype(np.float32) if continuous
                    else actions.astype(np.int32)
                )
            else:
                logits, value = module_def.forward_numpy(self._params, obs)
                probs = _softmax(logits)
                u = self._rng.random((B, 1))
                actions = (probs.cumsum(axis=-1) > u).argmax(axis=-1).astype(np.int32)
                logp = np.log(np.take_along_axis(
                    probs, actions[:, None], axis=-1
                )[:, 0] + 1e-10)
            env_actions = (
                conn.on_actions(actions) if conn is not None else actions
            )
            if continuous:
                # linear map [-1, 1] -> [low, high]
                env_actions = lo + (env_actions + 1.0) * 0.5 * (hi - lo)
            next_obs, rewards, terminated, truncated, info = self._env.step(
                env_actions
            )
            done = terminated | truncated
            obs_buf[t], act_buf[t] = obs, actions
            logp_buf[t], val_buf[t] = logp, value
            # the buffer stores transformed rewards (clip/scale); the
            # episode metrics below keep the RAW return
            rew_buf[t] = (
                conn.on_rewards(rewards) if conn is not None else rewards
            )
            term_buf[t], trunc_buf[t] = terminated, truncated
            if truncated.any():
                final = info["final_observation"][truncated]
                if conn is not None:
                    # subset path: temporal connectors (frame stack)
                    # read their per-env state without advancing it
                    final = conn.on_final_observations(
                        final, np.flatnonzero(truncated)
                    )
                _, fv = module_def.forward_numpy(self._params, final)
                boot_buf[t, truncated] = fv
            if conn is not None and done.any():
                conn.on_episode_boundaries(done)
            # episode metrics
            self._ep_return += rewards
            self._ep_len += 1
            if done.any():
                for i in np.flatnonzero(done):
                    self._completed.append({
                        "episode_return": float(self._ep_return[i]),
                        "episode_len": float(self._ep_len[i]),
                    })
                self._ep_return[done] = 0.0
                self._ep_len[done] = 0
            obs = next_obs
        self._obs = obs
        if conn is not None:
            obs = conn.on_observations(obs)
            self._cached_transformed_obs = obs
        _, final_value = module_def.forward_numpy(self._params, obs)
        return {
            "final_obs": obs.copy(),
            "obs": obs_buf,
            "actions": act_buf,
            "logp": logp_buf,
            "values": val_buf,
            "rewards": rew_buf,
            "terminated": term_buf,
            "truncated": trunc_buf,
            "bootstrap_values": boot_buf,
            "final_value": final_value.astype(np.float32),
        }

    # -- object-plane sampling (production path) ----------------------
    def sample_ref(self, module_def, explore=None) -> Dict[str, Any]:
        """One rollout shipped as an object-plane reference.

        Returns a small ENVELOPE — `{"batch": ObjectRef, "meta": {...}}`
        — instead of the multi-megabyte batch: the rollout is `rt.put`
        into this worker's shm store and the learner side fetches it
        zero-copy.  `meta` carries the exactly-once ledger key and the
        sampling wall time (the overlap-ratio numerator)."""
        import ray_tpu as rt

        t0 = time.perf_counter()
        batch = self.sample(module_def, explore)
        sample_s = time.perf_counter() - t0
        ref = rt.put(batch)
        env_steps = int(self._T * self._env.num_envs)
        nbytes = int(sum(
            v.nbytes for v in batch.values() if hasattr(v, "nbytes")
        ))
        meta = {
            "slot": self._slot,
            "incarnation": self._incarnation,
            "seq": self._seq,
            "env_steps": env_steps,
            "weights_version": self._weights_version,
            "sample_s": sample_s,
            "bytes": nbytes,
            "done_t": time.time(),
        }
        self._seq += 1
        return {"batch": ref, "meta": meta}

    def set_weights_ref(self, boxed: Dict[str, Any], version: int) -> bool:
        """Adopt a weights version published once to the object plane
        (`boxed = {"ref": ObjectRef}` — boxed so the ref is NOT
        materialized as a task arg).  Pull-once-per-version: a stale or
        duplicate broadcast is a no-op."""
        if version <= self._weights_version:
            return False
        import ray_tpu as rt

        self._params = rt.get(boxed["ref"])
        self._weights_version = version
        return True

    def replay(self, module_def, weight_refs: List[Dict[str, Any]],
               explore=None) -> int:
        """Deterministically rebuild this runner's state by replaying
        the rollout history of a dead predecessor: step through the
        SAME weights sequence the dead incarnation sampled with (env,
        action-rng and connector state are pure functions of the seed
        and that sequence).  Episode metrics generated during replay
        are dropped — the predecessor already reported them.  Returns
        the number of rollouts replayed."""
        import ray_tpu as rt

        for i, boxed in enumerate(weight_refs):
            self.set_weights_ref(boxed, i + 1)
            self.sample(module_def, explore)
        self._completed = []
        self._seq = len(weight_refs)
        return len(weight_refs)

    def pop_metrics(self) -> List[Dict[str, float]]:
        out, self._completed = self._completed, []
        return out

    # -- compiled-DAG fast plane (use_compiled_dag=True) ---------------
    def run_sample_channel_loop(self, plan: Dict[str, Any]) -> int:
        """Resident sampling loop over shm tensor channels — the
        compiled-DAG fast plane.  Rollout batches ride a tensor channel
        straight to the learner (raw array bytes + a small meta blob,
        ONE slot publication per rollout, no actor-RPC machinery);
        weights versions arrive over a reverse channel, adopted
        newest-wins between rollouts.  Exits (returning the rollout
        count) when the driver closes the weights channel."""
        from ray_tpu.dag.channel import (
            Channel,
            ChannelClosed,
            ChannelPollTimeout,
        )

        sample_ch = Channel(*plan["sample_chan"],
                            ring_slots=plan.get("sample_ring_slots"))
        weights_ch = Channel(*plan["weights_chan"],
                             ring_slots=plan.get("weights_ring_slots"))
        module_def = plan["module"]
        explore = plan.get("explore")
        template = plan["weights_template"]
        rollouts = 0
        try:
            while True:
                # adopt the newest published weights; block only while
                # this incarnation has none at all
                while True:
                    try:
                        leaves, extra = weights_ch.read_tensors(
                            timeout_s=None if self._params is None else 0.001
                        )
                    except ChannelPollTimeout:
                        break
                    version = int(extra["version"])
                    if version > self._weights_version:
                        self._params = unflatten_tree(template,
                                                      list(leaves))
                        self._weights_version = version
                t0 = time.perf_counter()
                batch = self.sample(module_def, explore)
                sample_s = time.perf_counter() - t0
                meta = {
                    "slot": self._slot,
                    "incarnation": self._incarnation,
                    "seq": self._seq,
                    "env_steps": int(self._T * self._env.num_envs),
                    "weights_version": self._weights_version,
                    "sample_s": sample_s,
                    "bytes": int(sum(
                        v.nbytes for v in batch.values()
                        if hasattr(v, "nbytes")
                    )),
                    "done_t": time.time(),
                    # the resident loop occupies this actor, so episode
                    # metrics ride the channel instead of pop_metrics()
                    # RPCs that would queue behind the loop forever
                    "episodes": self.pop_metrics(),
                }
                sample_ch.write_tensors(batch, extra=meta)
                self._seq += 1
                rollouts += 1
        except ChannelClosed:
            # teardown: tell the learner side this producer is done
            try:
                sample_ch.close()
            except Exception:  # rtlint: disable=RT005 — teardown race:
                pass  # the driver may have destroyed the ring already
            return rollouts

    def ping(self) -> bool:
        return True

    # -- connector state (reference: connector aggregation across
    # EnvRunners) ------------------------------------------------------
    def get_connector_state(self):
        return (
            self._connector.get_state() if self._connector is not None
            else {}
        )

    def set_connector_state(self, state) -> bool:
        if self._connector is not None and state:
            self._connector.set_state(state)
        return True
