"""Multi-agent environments + episode collection.

Reference: `rllib/env/multi_agent_env.py` (the dict-keyed env
contract), `rllib/env/multi_agent_episode.py` (per-agent trajectory
bookkeeping inside one env episode), and the policy-mapping mechanism
(`AlgorithmConfig.multi_agent(policies=..., policy_mapping_fn=...)`).

The env steps DICTS: every agent currently alive maps to an
observation/action/reward; `terminateds["__all__"]` ends the episode.
The runner demultiplexes transitions by `policy_mapping_fn` into one
time-major batch per MODULE (policy), which is what the multi-agent
learner consumes — agents sharing a policy share its batch.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np


class MultiAgentEnv:
    """Contract (reference: `multi_agent_env.py`):

    reset(seed)  -> (obs: {agent: np.ndarray}, info)
    step(actions: {agent: int}) ->
        (obs, rewards, terminateds, truncateds, info) — all dicts keyed
        by agent id; terminateds/truncateds carry the "__all__" key.
    """

    agent_ids: Tuple[str, ...] = ()
    observation_size: int = 0
    num_actions: int = 0

    def reset(self, seed: Optional[int] = None):
        raise NotImplementedError

    def step(self, actions: Dict[str, int]):
        raise NotImplementedError


class CoordinationGame(MultiAgentEnv):
    """Tiny cooperative matrix game for tests: each episode is
    `episode_len` repeated rounds; both agents receive +1 when they
    pick the SAME action, 0 otherwise.  Optimal joint policy earns
    `episode_len` per agent per episode; independent uniform play earns
    ~episode_len / num_actions — easy to verify learning against."""

    def __init__(self, num_actions: int = 2, episode_len: int = 10):
        self.agent_ids = ("agent_0", "agent_1")
        self.num_actions = num_actions
        self.observation_size = 2  # [t/episode_len, 1]
        self._len = episode_len
        self._t = 0

    def _obs(self):
        o = np.array([self._t / self._len, 1.0], np.float32)
        return {a: o.copy() for a in self.agent_ids}

    def reset(self, seed: Optional[int] = None):
        self._t = 0
        return self._obs(), {}

    def step(self, actions: Dict[str, int]):
        self._t += 1
        same = actions["agent_0"] == actions["agent_1"]
        r = 1.0 if same else 0.0
        rewards = {a: r for a in self.agent_ids}
        done = self._t >= self._len
        term = {a: done for a in self.agent_ids}
        term["__all__"] = done
        trunc = {a: False for a in self.agent_ids}
        trunc["__all__"] = False
        return self._obs(), rewards, term, trunc, {}


_MULTI_AGENT_ENVS = {"coordination": CoordinationGame}


def make_multi_agent_env(env: Any, **kwargs) -> MultiAgentEnv:
    if isinstance(env, str):
        try:
            return _MULTI_AGENT_ENVS[env](**kwargs)
        except KeyError:
            raise ValueError(
                f"unknown multi-agent env {env!r}; "
                f"registered: {sorted(_MULTI_AGENT_ENVS)}"
            ) from None
    if isinstance(env, type):
        return env(**kwargs)
    return env


class MultiAgentEnvRunner:
    """Sampling actor for multi-agent envs (reference:
    `multi_agent_env_runner.py` + MultiAgentEpisode): steps one env,
    demultiplexes per-agent transitions into per-MODULE trajectories
    via policy_mapping_fn.  Output per module: time-major arrays with a
    trailing done flag per step so the learner can compute GAE across
    the concatenated steps of many (episode, agent) lanes."""

    def __init__(self, env: Any, rollout_length: int,
                 policy_mapping: Dict[str, str],
                 seed: int = 0, env_kwargs: Optional[Dict] = None):
        self._env = make_multi_agent_env(env, **(env_kwargs or {}))
        self._T = rollout_length
        self._map = dict(policy_mapping)  # agent_id -> module_id
        # Per-module lane index for each agent: rows of agents sharing a
        # module interleave per env step, so GAE must recurse per lane.
        self._lane: Dict[str, int] = {}
        lanes_per_mod: Dict[str, int] = {}
        for agent in sorted(self._map):
            mid = self._map[agent]
            self._lane[agent] = lanes_per_mod.get(mid, 0)
            lanes_per_mod[mid] = self._lane[agent] + 1
        self._rng = np.random.default_rng(seed + 1)
        self._obs, _ = self._env.reset(seed=seed)
        self._params: Dict[str, Any] = {}
        self._weights_version = -1
        self._ep_return = 0.0
        self._completed: List[Dict[str, float]] = []

    def env_spec(self) -> Dict[str, Any]:
        return {
            "observation_size": self._env.observation_size,
            "num_actions": self._env.num_actions,
            "agent_ids": list(self._env.agent_ids),
            "module_ids": sorted(set(self._map.values())),
        }

    def set_weights(self, params_by_module: Dict[str, Any], version: int):
        self._params = params_by_module
        self._weights_version = version
        return True

    def sample(self, modules: Dict[str, Any]) -> Dict[str, Dict[str, np.ndarray]]:
        """Rollout T env steps; returns {module_id: batch} where batch
        rows are the module's agents' transitions in step order, with
        per-row `dones` separating trajectory lanes for GAE."""
        assert self._params, "set_weights before sample"
        traj: Dict[str, Dict[str, list]] = {
            m: {"obs": [], "actions": [], "logp": [], "values": [],
                "rewards": [], "dones": [], "agent_lane": []}
            for m in set(self._map.values())
        }
        obs = self._obs
        for _ in range(self._T):
            actions: Dict[str, int] = {}
            step_records = []  # (module, agent, obs, act, logp, value)
            for agent, o in obs.items():
                mid = self._map[agent]
                module = modules[mid]
                logits, value = module.forward_numpy(
                    self._params[mid], o[None]
                )
                z = logits[0] - logits[0].max()
                probs = np.exp(z) / np.exp(z).sum()
                a = int(self._rng.choice(len(probs), p=probs))
                actions[agent] = a
                step_records.append(
                    (mid, agent, o, a, float(np.log(probs[a] + 1e-10)),
                     float(value[0]))
                )
            next_obs, rewards, term, trunc, _ = self._env.step(actions)
            done = bool(term.get("__all__")) or bool(trunc.get("__all__"))
            for mid, agent, o, a, logp, value in step_records:
                t = traj[mid]
                t["obs"].append(o)
                t["actions"].append(a)
                t["logp"].append(logp)
                t["values"].append(value)
                t["rewards"].append(float(rewards.get(agent, 0.0)))
                t["dones"].append(
                    done or bool(term.get(agent)) or bool(trunc.get(agent))
                )
                t["agent_lane"].append(self._lane[agent])
            self._ep_return += float(np.mean(list(rewards.values())))
            if done:
                self._completed.append({
                    "episode_return": self._ep_return,
                    "episode_len": 0.0,
                })
                self._ep_return = 0.0
                obs, _ = self._env.reset()
            else:
                obs = next_obs
        self._obs = obs
        out = {}
        for mid, t in traj.items():
            out[mid] = {
                "obs": np.asarray(t["obs"], np.float32),
                "actions": np.asarray(t["actions"], np.int32),
                "logp": np.asarray(t["logp"], np.float32),
                "values": np.asarray(t["values"], np.float32),
                "rewards": np.asarray(t["rewards"], np.float32),
                "dones": np.asarray(t["dones"], np.bool_),
                "agent_lane": np.asarray(t["agent_lane"], np.int32),
            }
        return out

    def pop_metrics(self) -> List[Dict[str, float]]:
        out, self._completed = self._completed, []
        return out

    def ping(self) -> bool:
        return True


def multi_agent_gae(batch: Dict[str, np.ndarray], gamma: float,
                    lambda_: float) -> Tuple[np.ndarray, np.ndarray]:
    """GAE over a per-module batch whose rows interleave agents per env
    step.  `agent_lane` (when present) segments rows into per-agent
    lanes so the recursion only chains an agent's own transitions;
    within a lane, `dones` cut episodes.  The tail of an unfinished
    trajectory bootstraps with V=0 — acceptable bias for short-episode
    benchmarks; reference episodes carry their own bootstrap values.
    Advantages are returned in the original row order."""
    rewards, values = batch["rewards"], batch["values"]
    dones = batch["dones"].astype(np.float32)
    lanes = batch.get("agent_lane")
    n = len(rewards)
    adv = np.zeros(n, np.float32)
    if lanes is None:
        lane_rows = [range(n - 1, -1, -1)]
    else:
        lane_rows = [np.nonzero(lanes == lane)[0][::-1]
                     for lane in np.unique(lanes)]
    for rows in lane_rows:
        gae = 0.0
        next_value = 0.0
        for t in rows:
            nonterminal = 1.0 - dones[t]
            delta = rewards[t] + gamma * next_value * nonterminal - values[t]
            gae = delta + gamma * lambda_ * nonterminal * gae
            adv[t] = gae
            next_value = values[t]
    return adv, adv + values
