"""Vectorized environments for env runners.

Reference: the new API stack samples with gymnasium *vector* envs inside
`SingleAgentEnvRunner` (`rllib/env/single_agent_env_runner.py:61`).
Env runners here are pure-numpy CPU actors — rollout workers never touch
jax or the TPU; all compiled numeric work lives in the Learner.  A
built-in vectorized CartPole (classic Barto-Sutton-Anderson dynamics,
matching gymnasium's CartPole-v1 constants) keeps the stack
self-contained; any gymnasium env id works through `GymnasiumVectorEnv`
when the package is installed.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import numpy as np


class VectorEnv:
    """Batch-of-envs interface with same-step auto-reset:
    reset() -> obs[B, ...];
    step(actions[B]) -> (obs, rewards, terminated, truncated, info).
    For sub-envs that finished this step, `obs` is the RESET observation
    and info["final_observation"][i] carries the true last observation —
    the value-bootstrap source for truncated episodes."""

    num_envs: int
    observation_size: int
    num_actions: int

    def reset(self, seed: Optional[int] = None) -> np.ndarray:
        raise NotImplementedError

    def step(self, actions: np.ndarray):
        raise NotImplementedError


class CartPoleVectorEnv(VectorEnv):
    """Vectorized CartPole-v1 with auto-reset on termination."""

    MAX_STEPS = 500

    def __init__(self, num_envs: int = 8, seed: int = 0):
        self.num_envs = num_envs
        self.observation_size = 4
        self.num_actions = 2
        self._rng = np.random.default_rng(seed)
        self._state = np.zeros((num_envs, 4), dtype=np.float64)
        self._steps = np.zeros(num_envs, dtype=np.int64)
        # physics constants (gymnasium cartpole.py)
        self._gravity = 9.8
        self._masscart = 1.0
        self._masspole = 0.1
        self._length = 0.5
        self._force_mag = 10.0
        self._tau = 0.02
        self._theta_limit = 12 * 2 * np.pi / 360
        self._x_limit = 2.4

    def _sample_state(self, n: int) -> np.ndarray:
        return self._rng.uniform(-0.05, 0.05, size=(n, 4))

    def reset(self, seed: Optional[int] = None) -> np.ndarray:
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        self._state = self._sample_state(self.num_envs)
        self._steps[:] = 0
        return self._state.astype(np.float32)

    def step(self, actions: np.ndarray):
        x, x_dot, theta, theta_dot = self._state.T
        force = np.where(actions == 1, self._force_mag, -self._force_mag)
        costheta, sintheta = np.cos(theta), np.sin(theta)
        total_mass = self._masscart + self._masspole
        polemass_length = self._masspole * self._length
        temp = (force + polemass_length * theta_dot**2 * sintheta) / total_mass
        thetaacc = (self._gravity * sintheta - costheta * temp) / (
            self._length * (4.0 / 3.0 - self._masspole * costheta**2 / total_mass)
        )
        xacc = temp - polemass_length * thetaacc * costheta / total_mass
        x = x + self._tau * x_dot
        x_dot = x_dot + self._tau * xacc
        theta = theta + self._tau * theta_dot
        theta_dot = theta_dot + self._tau * thetaacc
        self._state = np.stack([x, x_dot, theta, theta_dot], axis=1)
        self._steps += 1

        terminated = (
            (np.abs(x) > self._x_limit) | (np.abs(theta) > self._theta_limit)
        )
        truncated = (self._steps >= self.MAX_STEPS) & ~terminated
        rewards = np.ones(self.num_envs, dtype=np.float32)
        done = terminated | truncated
        info: Dict[str, Any] = {}
        if done.any():  # same-step auto-reset of finished sub-envs
            info["final_observation"] = self._state.astype(np.float32)
            self._state[done] = self._sample_state(int(done.sum()))
            self._steps[done] = 0
        return (
            self._state.astype(np.float32),
            rewards,
            terminated,
            truncated,
            info,
        )


class GymnasiumVectorEnv(VectorEnv):
    """Vectorization over N single gymnasium envs, owned here rather
    than via `gym.make_vec`: gymnasium's vector autoreset modes changed
    semantics across versions (next-step autoreset inserts a no-op
    transition after terminals), while rollout batches need same-step
    autoreset with the true final observation exposed."""

    def __init__(self, env_id: str, num_envs: int = 8, seed: int = 0, **kwargs):
        import gymnasium as gym

        self._envs = [gym.make(env_id, **kwargs) for _ in range(num_envs)]
        self.num_envs = num_envs
        space = self._envs[0].observation_space
        self.observation_size = int(np.prod(space.shape))
        self.num_actions = int(self._envs[0].action_space.n)
        self._seed = seed

    def reset(self, seed: Optional[int] = None) -> np.ndarray:
        base = seed if seed is not None else self._seed
        obs = [e.reset(seed=base + i)[0] for i, e in enumerate(self._envs)]
        return np.stack(obs).reshape(self.num_envs, -1).astype(np.float32)

    def step(self, actions: np.ndarray):
        B = self.num_envs
        obs = np.empty((B, self.observation_size), np.float32)
        rewards = np.empty(B, np.float32)
        terminated = np.zeros(B, np.bool_)
        truncated = np.zeros(B, np.bool_)
        final_obs = None
        for i, e in enumerate(self._envs):
            o, r, term, trunc, _ = e.step(int(actions[i]))
            rewards[i], terminated[i], truncated[i] = r, term, trunc
            if term or trunc:
                if final_obs is None:
                    final_obs = np.zeros((B, self.observation_size), np.float32)
                final_obs[i] = np.asarray(o, np.float32).reshape(-1)
                o = e.reset()[0]  # same-step autoreset
            obs[i] = np.asarray(o, np.float32).reshape(-1)
        info: Dict[str, Any] = {}
        if final_obs is not None:
            info["final_observation"] = final_obs
        return obs, rewards, terminated, truncated, info


_BUILTIN = {"CartPole-v1": CartPoleVectorEnv}


def make_vector_env(env: Any, num_envs: int, seed: int = 0, **kwargs) -> VectorEnv:
    """env may be a builtin id, a gymnasium id, or a VectorEnv factory."""
    if callable(env):
        return env(num_envs=num_envs, seed=seed, **kwargs)
    if env in _BUILTIN:
        return _BUILTIN[env](num_envs=num_envs, seed=seed)
    return GymnasiumVectorEnv(env, num_envs=num_envs, seed=seed, **kwargs)
