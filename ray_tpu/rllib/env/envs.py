"""Vectorized environments for env runners.

Reference: the new API stack samples with gymnasium *vector* envs inside
`SingleAgentEnvRunner` (`rllib/env/single_agent_env_runner.py:61`).
Env runners here are pure-numpy CPU actors — rollout workers never touch
jax or the TPU; all compiled numeric work lives in the Learner.  A
built-in vectorized CartPole (classic Barto-Sutton-Anderson dynamics,
matching gymnasium's CartPole-v1 constants) keeps the stack
self-contained; any gymnasium env id works through `GymnasiumVectorEnv`
when the package is installed.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import numpy as np


class VectorEnv:
    """Batch-of-envs interface with same-step auto-reset:
    reset() -> obs[B, ...];
    step(actions[B]) -> (obs, rewards, terminated, truncated, info).
    For sub-envs that finished this step, `obs` is the RESET observation
    and info["final_observation"][i] carries the true last observation —
    the value-bootstrap source for truncated episodes."""

    num_envs: int
    observation_size: int
    num_actions: int
    # image/structured envs expose the true per-env obs shape; flat
    # envs inherit (observation_size,) via the property below
    _observation_shape: Optional[Tuple[int, ...]] = None
    # continuous-action envs set these; actions arrive as float arrays
    # [B, action_dim] in the env's native [action_low, action_high]
    continuous: bool = False
    action_dim: int = 0
    action_low: float = -1.0
    action_high: float = 1.0

    @property
    def observation_shape(self) -> Tuple[int, ...]:
        return self._observation_shape or (self.observation_size,)

    def reset(self, seed: Optional[int] = None) -> np.ndarray:
        raise NotImplementedError

    def step(self, actions: np.ndarray):
        raise NotImplementedError


class CartPoleVectorEnv(VectorEnv):
    """Vectorized CartPole-v1 with auto-reset on termination."""

    MAX_STEPS = 500

    def __init__(self, num_envs: int = 8, seed: int = 0):
        self.num_envs = num_envs
        self.observation_size = 4
        self.num_actions = 2
        self._rng = np.random.default_rng(seed)
        self._state = np.zeros((num_envs, 4), dtype=np.float64)
        self._steps = np.zeros(num_envs, dtype=np.int64)
        # physics constants (gymnasium cartpole.py)
        self._gravity = 9.8
        self._masscart = 1.0
        self._masspole = 0.1
        self._length = 0.5
        self._force_mag = 10.0
        self._tau = 0.02
        self._theta_limit = 12 * 2 * np.pi / 360
        self._x_limit = 2.4

    def _sample_state(self, n: int) -> np.ndarray:
        return self._rng.uniform(-0.05, 0.05, size=(n, 4))

    def reset(self, seed: Optional[int] = None) -> np.ndarray:
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        self._state = self._sample_state(self.num_envs)
        self._steps[:] = 0
        return self._state.astype(np.float32)

    def step(self, actions: np.ndarray):
        x, x_dot, theta, theta_dot = self._state.T
        force = np.where(actions == 1, self._force_mag, -self._force_mag)
        costheta, sintheta = np.cos(theta), np.sin(theta)
        total_mass = self._masscart + self._masspole
        polemass_length = self._masspole * self._length
        temp = (force + polemass_length * theta_dot**2 * sintheta) / total_mass
        thetaacc = (self._gravity * sintheta - costheta * temp) / (
            self._length * (4.0 / 3.0 - self._masspole * costheta**2 / total_mass)
        )
        xacc = temp - polemass_length * thetaacc * costheta / total_mass
        x = x + self._tau * x_dot
        x_dot = x_dot + self._tau * xacc
        theta = theta + self._tau * theta_dot
        theta_dot = theta_dot + self._tau * thetaacc
        self._state = np.stack([x, x_dot, theta, theta_dot], axis=1)
        self._steps += 1

        terminated = (
            (np.abs(x) > self._x_limit) | (np.abs(theta) > self._theta_limit)
        )
        truncated = (self._steps >= self.MAX_STEPS) & ~terminated
        rewards = np.ones(self.num_envs, dtype=np.float32)
        done = terminated | truncated
        info: Dict[str, Any] = {}
        if done.any():  # same-step auto-reset of finished sub-envs
            info["final_observation"] = self._state.astype(np.float32)
            self._state[done] = self._sample_state(int(done.sum()))
            self._steps[done] = 0
        return (
            self._state.astype(np.float32),
            rewards,
            terminated,
            truncated,
            info,
        )


class CatchPixelEnv(VectorEnv):
    """Vectorized pixel Catch (bsuite-style): a ball falls down an
    H x W grid, the agent moves a paddle on the bottom row (left /
    stay / right) and is rewarded +1 for catching, -1 for missing.
    Observations are (H, W, 1) float32 images — the procedural stand-in
    for ALE in this image-free environment (reference pixel pipeline:
    `rllib/env/wrappers/atari_wrappers.py:324`); PPO with a small CNN
    solves it in a few thousand steps."""

    def __init__(self, num_envs: int = 8, seed: int = 0,
                 rows: int = 10, cols: int = 5):
        self.num_envs = num_envs
        self.rows = rows
        self.cols = cols
        self._observation_shape = (rows, cols, 1)
        self.observation_size = rows * cols
        self.num_actions = 3
        self._rng = np.random.default_rng(seed)
        self._ball_r = np.zeros(num_envs, np.int64)
        self._ball_c = np.zeros(num_envs, np.int64)
        self._paddle = np.zeros(num_envs, np.int64)

    def _spawn(self, idx: np.ndarray):
        n = len(idx)
        self._ball_r[idx] = 0
        self._ball_c[idx] = self._rng.integers(0, self.cols, n)
        self._paddle[idx] = self.cols // 2

    def _render(self) -> np.ndarray:
        obs = np.zeros(
            (self.num_envs, self.rows, self.cols, 1), np.float32
        )
        b = np.arange(self.num_envs)
        obs[b, self._ball_r, self._ball_c, 0] = 1.0
        obs[b, self.rows - 1, self._paddle, 0] = 1.0
        return obs

    def reset(self, seed: Optional[int] = None) -> np.ndarray:
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        self._spawn(np.arange(self.num_envs))
        return self._render()

    def step(self, actions: np.ndarray):
        move = np.asarray(actions, np.int64) - 1  # {0,1,2} -> {-1,0,1}
        self._paddle = np.clip(self._paddle + move, 0, self.cols - 1)
        self._ball_r += 1
        at_bottom = self._ball_r >= self.rows - 1
        caught = at_bottom & (self._ball_c == self._paddle)
        rewards = np.where(
            at_bottom, np.where(caught, 1.0, -1.0), 0.0
        ).astype(np.float32)
        terminated = at_bottom.copy()
        truncated = np.zeros(self.num_envs, np.bool_)
        info: Dict[str, Any] = {}
        if at_bottom.any():
            info["final_observation"] = self._render()
            self._spawn(np.flatnonzero(at_bottom))
        return self._render(), rewards, terminated, truncated, info


class PendulumVectorEnv(VectorEnv):
    """Vectorized Pendulum-v1 (gymnasium classic-control dynamics):
    1-D torque in [-2, 2], obs (cos th, sin th, th_dot), 200-step
    truncation.  The standard continuous-control convergence target
    for SAC (reference: `rllib/algorithms/sac/` tunes Pendulum)."""

    MAX_STEPS = 200

    def __init__(self, num_envs: int = 8, seed: int = 0):
        self.num_envs = num_envs
        self.observation_size = 3
        self.num_actions = 0
        self.continuous = True
        self.action_dim = 1
        self.action_low = -2.0
        self.action_high = 2.0
        self._rng = np.random.default_rng(seed)
        self._th = np.zeros(num_envs)
        self._thdot = np.zeros(num_envs)
        self._steps = np.zeros(num_envs, np.int64)
        self._g, self._m, self._l, self._dt = 10.0, 1.0, 1.0, 0.05

    def _obs(self) -> np.ndarray:
        return np.stack(
            [np.cos(self._th), np.sin(self._th), self._thdot], axis=1
        ).astype(np.float32)

    def _sample(self, idx):
        n = len(idx)
        self._th[idx] = self._rng.uniform(-np.pi, np.pi, n)
        self._thdot[idx] = self._rng.uniform(-1.0, 1.0, n)
        self._steps[idx] = 0

    def reset(self, seed: Optional[int] = None) -> np.ndarray:
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        self._sample(np.arange(self.num_envs))
        return self._obs()

    def step(self, actions: np.ndarray):
        u = np.clip(np.asarray(actions, np.float64).reshape(
            self.num_envs), self.action_low, self.action_high)
        th, thdot = self._th, self._thdot
        norm_th = ((th + np.pi) % (2 * np.pi)) - np.pi
        costs = norm_th**2 + 0.1 * thdot**2 + 0.001 * u**2
        thdot = thdot + (
            3.0 * self._g / (2 * self._l) * np.sin(th)
            + 3.0 / (self._m * self._l**2) * u
        ) * self._dt
        thdot = np.clip(thdot, -8.0, 8.0)
        self._th = th + thdot * self._dt
        self._thdot = thdot
        self._steps += 1
        truncated = self._steps >= self.MAX_STEPS
        terminated = np.zeros(self.num_envs, np.bool_)
        info: Dict[str, Any] = {}
        if truncated.any():
            info["final_observation"] = self._obs()
            self._sample(np.flatnonzero(truncated))
        return (self._obs(), (-costs).astype(np.float32), terminated,
                truncated, info)


class ContinuousTargetEnv(VectorEnv):
    """One-step continuous regression env: obs x ~ U[-1,1]^d, reward
    -||x - a||^2, episode ends.  The optimal policy is a = x, so a
    working continuous actor drives return -> 0 within a few hundred
    updates — the fast deterministic convergence probe for SAC."""

    def __init__(self, num_envs: int = 8, seed: int = 0, dim: int = 2):
        self.num_envs = num_envs
        self.observation_size = dim
        self.num_actions = 0
        self.continuous = True
        self.action_dim = dim
        self.action_low = -1.0
        self.action_high = 1.0
        self._rng = np.random.default_rng(seed)
        self._x = np.zeros((num_envs, dim), np.float32)

    def _sample(self):
        self._x = self._rng.uniform(
            -1, 1, (self.num_envs, self.action_dim)
        ).astype(np.float32)

    def reset(self, seed: Optional[int] = None) -> np.ndarray:
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        self._sample()
        return self._x.copy()

    def step(self, actions: np.ndarray):
        a = np.asarray(actions, np.float32).reshape(self._x.shape)
        rewards = -np.sum((self._x - a) ** 2, axis=-1).astype(np.float32)
        terminated = np.ones(self.num_envs, np.bool_)
        info = {"final_observation": self._x.copy()}
        self._sample()
        return (self._x.copy(), rewards, terminated,
                np.zeros(self.num_envs, np.bool_), info)


class GymnasiumVectorEnv(VectorEnv):
    """Vectorization over N single gymnasium envs, owned here rather
    than via `gym.make_vec`: gymnasium's vector autoreset modes changed
    semantics across versions (next-step autoreset inserts a no-op
    transition after terminals), while rollout batches need same-step
    autoreset with the true final observation exposed."""

    def __init__(self, env_id: str, num_envs: int = 8, seed: int = 0, **kwargs):
        import gymnasium as gym

        self._envs = [gym.make(env_id, **kwargs) for _ in range(num_envs)]
        self.num_envs = num_envs
        space = self._envs[0].observation_space
        self.observation_size = int(np.prod(space.shape))
        # images and other structured obs keep their true shape; 1-D
        # obs flow through the historical flat layout
        if len(space.shape) >= 2:
            self._observation_shape = tuple(space.shape)
        self.num_actions = int(self._envs[0].action_space.n)
        self._seed = seed

    def _shape(self) -> Tuple[int, ...]:
        return self.observation_shape

    def reset(self, seed: Optional[int] = None) -> np.ndarray:
        base = seed if seed is not None else self._seed
        obs = [e.reset(seed=base + i)[0] for i, e in enumerate(self._envs)]
        return (np.stack(obs).reshape(self.num_envs, *self._shape())
                .astype(np.float32))

    def step(self, actions: np.ndarray):
        B = self.num_envs
        shape = self._shape()
        obs = np.empty((B, *shape), np.float32)
        rewards = np.empty(B, np.float32)
        terminated = np.zeros(B, np.bool_)
        truncated = np.zeros(B, np.bool_)
        final_obs = None
        for i, e in enumerate(self._envs):
            o, r, term, trunc, _ = e.step(int(actions[i]))
            rewards[i], terminated[i], truncated[i] = r, term, trunc
            if term or trunc:
                if final_obs is None:
                    final_obs = np.zeros((B, *shape), np.float32)
                final_obs[i] = np.asarray(o, np.float32).reshape(shape)
                o = e.reset()[0]  # same-step autoreset
            obs[i] = np.asarray(o, np.float32).reshape(shape)
        info: Dict[str, Any] = {}
        if final_obs is not None:
            info["final_observation"] = final_obs
        return obs, rewards, terminated, truncated, info


_BUILTIN = {
    "CartPole-v1": CartPoleVectorEnv,
    "Catch-v0": CatchPixelEnv,
    "Pendulum-v1": PendulumVectorEnv,
}


def make_vector_env(env: Any, num_envs: int, seed: int = 0, **kwargs) -> VectorEnv:
    """env may be a builtin id, a gymnasium id, or a VectorEnv factory."""
    if callable(env):
        return env(num_envs=num_envs, seed=seed, **kwargs)
    if env in _BUILTIN:
        return _BUILTIN[env](num_envs=num_envs, seed=seed)
    return GymnasiumVectorEnv(env, num_envs=num_envs, seed=seed, **kwargs)
