"""CQL: conservative Q-learning from OFFLINE data (discrete).

Reference: `rllib/algorithms/cql/` + the offline-RL input pipeline
(`rllib/offline/`).  No env runners: the algorithm trains purely from a
logged transition dataset — double-DQN TD learning plus the CQL
regularizer `E[logsumexp_a Q(s,a) - Q(s, a_data)]`, which pushes Q down
on actions the behavior policy never took (the out-of-distribution
overestimation offline RL must suppress).

Dataset format (numpy arrays or an .npz path):
    obs [N, D] f32, actions [N] int, rewards [N] f32,
    next_obs [N, D] f32, terminated [N] bool
Evaluation (optional, `evaluation_env`): greedy rollouts in a real env
report `evaluation_return_mean` — the offline metric that matters.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

from ray_tpu.rllib.algorithms.algorithm import Algorithm
from ray_tpu.rllib.algorithms.algorithm_config import AlgorithmConfig
from ray_tpu.rllib.algorithms.dqn import QMLPModule
from ray_tpu.rllib.core.learner import LearnerGroup


class CQLConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.lr = 1e-3
        self.cql_alpha: float = 1.0  # conservatism weight
        self.learn_batch_size: int = 256
        self.num_updates_per_iter: int = 64
        self.target_update_freq: int = 1
        self.input_: Any = None  # dict of arrays or .npz path
        self.evaluation_env: Any = None
        self.evaluation_episodes: int = 5
        self.evaluation_interval: int = 1  # iterations between evals

    def offline_data(self, *, input_: Any = None, **kwargs) -> "CQLConfig":
        """Fluent section, same surface as BCConfig.offline_data
        (reference: `AlgorithmConfig.offline_data`)."""
        if input_ is not None:
            self.input_ = input_
        self._apply(kwargs)
        return self

    def evaluation(self, *, evaluation_env=None, evaluation_episodes=None,
                   evaluation_interval=None, **kwargs) -> "CQLConfig":
        if evaluation_env is not None:
            self.evaluation_env = evaluation_env
        if evaluation_episodes is not None:
            self.evaluation_episodes = evaluation_episodes
        if evaluation_interval is not None:
            self.evaluation_interval = evaluation_interval
        self._apply(kwargs)
        return self

    @property
    def algo_class(self):
        return CQL


def make_cql_loss(cql_alpha: float):
    def cql_loss(module, params, batch):
        import jax.numpy as jnp
        from jax.scipy.special import logsumexp

        q, _ = module.forward_train(params, batch["obs"])
        actions = batch["actions"].astype(jnp.int32)
        qa = jnp.take_along_axis(q, actions[:, None], axis=-1)[:, 0]
        td = jnp.mean((qa - batch["td_target"]) ** 2)
        # conservatism: push down the soft-max over ALL actions, hold
        # up the logged action
        conservative = jnp.mean(logsumexp(q, axis=-1) - qa)
        total = td + cql_alpha * conservative
        return total, {
            "td_loss": td,
            "cql_gap": conservative,
            "q_data_mean": jnp.mean(qa),
        }

    return cql_loss


def _load_dataset(input_data) -> Dict[str, np.ndarray]:
    import os

    if isinstance(input_data, (str, bytes, os.PathLike)):
        with np.load(input_data) as z:
            data = {k: z[k] for k in z.files}
    else:
        data = dict(input_data)
    need = {"obs", "actions", "rewards", "next_obs", "terminated"}
    missing = need - set(data)
    if missing:
        raise ValueError(f"offline dataset missing fields {sorted(missing)}")
    return data


class CQL(Algorithm):
    def setup_components(self):
        import jax

        cfg = self.config
        if cfg.input_ is None:
            raise ValueError("CQL needs config.offline_data(input_=...)")
        self.dataset = _load_dataset(cfg.input_)
        obs_dim = self.dataset["obs"].shape[1]
        num_actions = int(self.dataset["actions"].max()) + 1
        self._eval_env = None
        if cfg.evaluation_env is not None:
            # the env is authoritative on the action space: a dataset
            # whose behavior policy never logged the top action must
            # not truncate the Q-head (same guard as BC)
            from ray_tpu.rllib.env.envs import make_vector_env

            self._eval_env = make_vector_env(
                cfg.evaluation_env, 1, seed=cfg.seed + 999
            )
            num_actions = max(num_actions, self._eval_env.num_actions)
        self.module = QMLPModule(
            obs_dim, num_actions,
            hidden=tuple(cfg.model.get("hidden", (64, 64))),
        )
        self.learner_group = LearnerGroup(
            self.module, make_cql_loss(cfg.cql_alpha),
            num_learners=cfg.num_learners, lr=cfg.lr,
            grad_clip=cfg.grad_clip, seed=cfg.seed, mesh=cfg.mesh,
        )
        self.target_params = self.learner_group.get_weights_numpy()
        self._rng = np.random.default_rng(cfg.seed)
        self._q = jax.jit(lambda p, o: self.module.forward_train(p, o)[0])

    def _td_targets(self, idx, online) -> np.ndarray:
        cfg = self.config
        next_obs = self.dataset["next_obs"][idx]
        q_next_t = np.asarray(self._q(self.target_params, next_obs))
        q_next_o = np.asarray(self._q(online, next_obs))
        best = q_next_o.argmax(axis=-1)
        q_next = np.take_along_axis(q_next_t, best[:, None], axis=-1)[:, 0]
        nonterminal = 1.0 - self.dataset["terminated"][idx].astype(np.float32)
        return (
            self.dataset["rewards"][idx] + cfg.gamma * q_next * nonterminal
        ).astype(np.float32)

    def training_step(self) -> Dict[str, Any]:
        cfg = self.config
        n = len(self.dataset["actions"])
        metrics_acc: List[Dict[str, float]] = []
        online = self.learner_group.get_weights_numpy()
        for _ in range(cfg.num_updates_per_iter):
            idx = self._rng.integers(0, n, cfg.learn_batch_size)
            batch = {
                "obs": self.dataset["obs"][idx],
                "actions": self.dataset["actions"][idx],
                "td_target": self._td_targets(idx, online),
            }
            metrics_acc.append(self.learner_group.update_minibatch(batch))
        if (self.iteration + 1) % cfg.target_update_freq == 0:
            self.target_params = self.learner_group.get_weights_numpy()
        result: Dict[str, Any] = {
            k: float(np.mean([m[k] for m in metrics_acc]))
            for k in metrics_acc[0]
        }
        result["num_train_steps"] = (
            cfg.num_updates_per_iter * cfg.learn_batch_size
        )
        if (
            self._eval_env is not None
            and cfg.evaluation_interval > 0
            and (self.iteration + 1) % cfg.evaluation_interval == 0
        ):
            result["evaluation_return_mean"] = self.evaluate()
        return result

    def evaluate(self) -> float:
        """Greedy rollouts in the (setup-time) evaluation env."""
        cfg = self.config
        env = self._eval_env
        weights = self.learner_group.get_weights_numpy()
        returns = []
        for _ in range(cfg.evaluation_episodes):
            obs = env.reset()
            total, done = 0.0, False
            for _step in range(1000):
                q, _ = self.module.forward_numpy(weights, obs)
                a = q.argmax(axis=-1).astype(np.int32)
                obs, r, term, trunc, _ = env.step(a)
                total += float(r[0])
                if bool(term[0] or trunc[0]):
                    break
            returns.append(total)
        return float(np.mean(returns))

    def get_state(self) -> Dict[str, Any]:
        return {
            "learner": self.learner_group.get_state(),
            "target_params": self.target_params,
            "iteration": self.iteration,
        }

    def set_state(self, state: Dict[str, Any]):
        self.learner_group.set_state(state["learner"])
        self.target_params = state["target_params"]
        self.iteration = state.get("iteration", self.iteration)

    def stop(self):
        self.learner_group.stop()
