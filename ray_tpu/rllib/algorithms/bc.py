"""BC: behavior cloning from offline data (the offline-RL entry point).

Reference: `rllib/algorithms/bc/` (`bc.py`, `bc_learner.py`,
`bc_torch_learner.py`) atop the offline-data pipeline
(`rllib/offline/`) — supervised negative-log-likelihood of the logged
actions, no environment interaction during training.

Offline input shapes accepted (the `rllib/offline/` reader surface,
reduced):
- a dict of arrays {"obs": [N, obs], "actions": [N]},
- a list of such dicts (episode batches are concatenated),
- a `ray_tpu.data.Dataset` of row-dicts {"obs": ..., "action(s)": ...}.

Evaluation (episode-return tracking) runs the cloned policy in the
configured env with a small runner group, mirroring the reference's
`evaluation_interval` behavior.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

from ray_tpu.rllib.algorithms.algorithm import Algorithm
from ray_tpu.rllib.algorithms.algorithm_config import AlgorithmConfig
from ray_tpu.rllib.core.learner import LearnerGroup
from ray_tpu.rllib.core.rl_module import (
    MLPModule, require_discrete_actions, require_flat_obs,
)


class BCConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.lr = 1e-3
        self.input_: Any = None  # offline data (see module docstring)
        self.minibatch_size = 256
        self.num_updates_per_iter: int = 32
        self.evaluation_interval: int = 0  # 0 = no env evaluation
        self.num_env_runners = 1

    def offline_data(self, *, input_: Any = None, **kwargs) -> "BCConfig":
        """Fluent section (reference: `AlgorithmConfig.offline_data`)."""
        if input_ is not None:
            self.input_ = input_
        self._apply(kwargs)
        return self

    @property
    def algo_class(self):
        return BC


def bc_loss(module, params, batch):
    """NLL of logged actions (reference: `bc_learner.py` — the policy
    head trained as a classifier; the value tower is unused)."""
    import jax
    import jax.numpy as jnp

    logits, _ = module.forward_train(params, batch["obs"])
    logp_all = jax.nn.log_softmax(logits, axis=-1)
    actions = batch["actions"].astype(jnp.int32)
    logp = jnp.take_along_axis(logp_all, actions[:, None], axis=-1)[:, 0]
    loss = -jnp.mean(logp)
    accuracy = jnp.mean(
        (jnp.argmax(logits, axis=-1) == actions).astype(jnp.float32)
    )
    return loss, {"bc_loss": loss, "action_accuracy": accuracy}


def _coerce_offline(input_: Any) -> Dict[str, np.ndarray]:
    if input_ is None:
        raise ValueError("BC requires config.offline_data(input_=...)")
    if isinstance(input_, dict):
        batches = [input_]
    elif isinstance(input_, list) and input_ and isinstance(input_[0], dict) \
            and "obs" in input_[0] and np.ndim(input_[0]["obs"]) >= 2:
        batches = input_
    else:
        # Dataset (or iterable) of row-dicts
        rows = input_.take_all() if hasattr(input_, "take_all") else list(input_)
        obs = np.asarray([r["obs"] for r in rows], np.float32)
        act_key = "actions" if "actions" in rows[0] else "action"
        actions = np.asarray([r[act_key] for r in rows])
        batches = [{"obs": obs, "actions": actions}]
    obs = np.concatenate([np.asarray(b["obs"], np.float32) for b in batches])
    actions = np.concatenate([np.asarray(b["actions"]) for b in batches])
    if obs.shape[0] != actions.shape[0]:
        raise ValueError("offline obs/actions length mismatch")
    return {"obs": obs, "actions": actions.astype(np.int32)}


class BC(Algorithm):
    # subclass hooks (MARWIL swaps both without rebuilding the learner)
    def _loss_fn(self):
        return bc_loss

    def _prepare_dataset(self):
        return _coerce_offline(self.config.input_)

    def setup_components(self):
        cfg = self.config
        self.dataset = self._prepare_dataset()
        obs_dim = self.dataset["obs"].shape[1]
        num_actions = int(self.dataset["actions"].max()) + 1
        self.env_runner_group = None
        if cfg.evaluation_interval > 0:
            from ray_tpu.rllib.env.env_runner_group import EnvRunnerGroup

            self.env_runner_group = EnvRunnerGroup(
                cfg.env, cfg.num_env_runners, cfg.num_envs_per_env_runner,
                cfg.rollout_fragment_length, seed=cfg.seed,
                env_kwargs=cfg.env_kwargs,
                connector=cfg.env_to_module_connector,
            )
            spec = self.env_runner_group.env_spec()
            require_flat_obs(spec, "BC/MARWIL")
            require_discrete_actions(spec, "BC/MARWIL")
            obs_dim = spec["observation_size"]
            num_actions = max(num_actions, spec["num_actions"])
        self.module = MLPModule(
            obs_dim, num_actions,
            hidden=tuple(cfg.model.get("hidden", (64, 64))),
        )
        self.learner_group = LearnerGroup(
            self.module, self._loss_fn(), num_learners=cfg.num_learners,
            lr=cfg.lr, grad_clip=cfg.grad_clip, seed=cfg.seed, mesh=cfg.mesh,
        )
        self._rng = np.random.default_rng(cfg.seed)

    def training_step(self) -> Dict[str, Any]:
        cfg = self.config
        n = self.dataset["obs"].shape[0]
        mb = min(cfg.minibatch_size, n)
        metrics_acc: List[Dict[str, float]] = []
        for _ in range(cfg.num_updates_per_iter):
            idx = self._rng.integers(0, n, mb)
            metrics_acc.append(self.learner_group.update_minibatch({
                "obs": self.dataset["obs"][idx],
                "actions": self.dataset["actions"][idx],
            }))
        result: Dict[str, Any] = {
            k: float(np.mean([m[k] for m in metrics_acc]))
            for k in metrics_acc[0]
        }
        result["num_offline_steps_trained"] = mb * cfg.num_updates_per_iter
        if (
            self.env_runner_group is not None
            and (self.iteration + 1) % cfg.evaluation_interval == 0
        ):
            self.env_runner_group.sync_weights(
                self.learner_group.get_weights_numpy()
            )
            self.env_runner_group.sample(self.module)
            self._track_episode_metrics(
                self.env_runner_group.pop_metrics(), result
            )
        return result

    def get_state(self) -> Dict[str, Any]:
        state = {
            "learner": self.learner_group.get_state(),
            "rng": self._rng,
            "iteration": self.iteration,
        }
        if self.env_runner_group is not None:
            # a restored offline run must keep its obs-filter statistics
            # (MeanStdObsFilter): losing them silently changes the
            # policy's effective inputs at evaluation time
            state["connector"] = self.env_runner_group.connector_state()
        return state

    def set_state(self, state: Dict[str, Any]):
        self.learner_group.set_state(state["learner"])
        if "rng" in state:
            self._rng = state["rng"]
        if self.env_runner_group is not None:
            self.env_runner_group.restore_connector_state(
                state.get("connector")
            )
        self.iteration = state.get("iteration", self.iteration)

    def stop(self):
        if self.env_runner_group is not None:
            self.env_runner_group.stop()
        self.learner_group.stop()
