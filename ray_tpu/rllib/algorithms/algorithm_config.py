"""AlgorithmConfig: fluent config-as-object.

Reference: `rllib/algorithms/algorithm_config.py` — the chained
`.environment().env_runners().training().learners()` builder surface.
"""

from __future__ import annotations

import copy
from typing import Any, Dict, Optional, Tuple, Type


class AlgorithmConfig:
    def __init__(self):
        self.env: Any = "CartPole-v1"
        self.env_kwargs: Dict[str, Any] = {}
        self.num_env_runners: int = 2
        self.num_envs_per_env_runner: int = 8
        self.rollout_fragment_length: int = 64
        self.num_learners: int = 0
        self.lr: float = 3e-4
        self.grad_clip: Optional[float] = 0.5
        self.train_batch_size: int = 0  # derived if 0
        self.minibatch_size: int = 256
        self.num_epochs: int = 4
        self.gamma: float = 0.99
        self.seed: int = 0
        self.model: Dict[str, Any] = {"hidden": (64, 64)}
        self.mesh: Any = None  # jax Mesh for SPMD learner sharding
        #: pjit learner gang width: >=2 builds a 1-D "data" mesh over
        #: that many local devices and compiles the update as ONE
        #: sharded program (alternative to `mesh`; exclusive with
        #: num_learners DDP actors)
        self.num_learner_devices: int = 0
        # env<->module connector pipeline FACTORY (reference:
        # config.env_runners(env_to_module_connector=...)); a factory —
        # not an instance — so each runner actor builds its own state
        self.env_to_module_connector: Any = None
        #: async sample/train overlap (PPO): runners keep sampling
        #: epoch N+1 while the learner gang updates on epoch N; weights
        #: broadcast non-blocking by reference.  Rollouts are then
        #: boundedly stale (~inflight_rollouts_per_runner versions) —
        #: PPO's ratio clip absorbs it (the reference's APPO/IMPALA
        #: shape, applied to the PPO loss)
        self.sample_train_overlap: bool = False
        #: pipelined sample_ref() calls per runner on the async path
        #: (reference: max_requests_in_flight_per_env_runner)
        self.inflight_rollouts_per_runner: int = 2
        #: replacement runners deterministically replay the dead
        #: incarnation's weights history (sync fleets only) — a
        #: kill-storm run consumes bit-identical batches to an
        #: unkilled control run (chaos-test contract)
        self.deterministic_replacement: bool = False
        #: compiled-DAG fast plane for the learner round: each runner
        #: hosts a resident sample loop, rollout batches ride shm
        #: tensor channels runner->learner and weights broadcasts ride
        #: reverse channels — the per-call actor RPC machinery leaves
        #: the hot path entirely (requires sample_train_overlap; see
        #: docs/compiled_dag.md)
        self.use_compiled_dag: bool = False

    # -- fluent sections (each returns self, reference-style) ----------
    def environment(self, env: Any = None, *, env_config: Optional[Dict] = None,
                    **kwargs) -> "AlgorithmConfig":
        if env is not None:
            self.env = env
        if env_config:
            self.env_kwargs.update(env_config)
        self._apply(kwargs)
        return self

    def env_runners(self, *, num_env_runners: Optional[int] = None,
                    num_envs_per_env_runner: Optional[int] = None,
                    rollout_fragment_length: Optional[int] = None,
                    **kwargs) -> "AlgorithmConfig":
        if num_env_runners is not None:
            self.num_env_runners = num_env_runners
        if num_envs_per_env_runner is not None:
            self.num_envs_per_env_runner = num_envs_per_env_runner
        if rollout_fragment_length is not None:
            self.rollout_fragment_length = rollout_fragment_length
        self._apply(kwargs)
        return self

    def learners(self, *, num_learners: Optional[int] = None,
                 num_learner_devices: Optional[int] = None,
                 **kwargs) -> "AlgorithmConfig":
        if num_learners is not None:
            self.num_learners = num_learners
        if num_learner_devices is not None:
            self.num_learner_devices = num_learner_devices
        self._apply(kwargs)
        return self

    def training(self, **kwargs) -> "AlgorithmConfig":
        self._apply(kwargs)
        return self

    def debugging(self, *, seed: Optional[int] = None, **kwargs):
        if seed is not None:
            self.seed = seed
        self._apply(kwargs)
        return self

    def rl_module(self, *, model_config: Optional[Dict] = None, **kwargs):
        if model_config:
            self.model.update(model_config)
        self._apply(kwargs)
        return self

    def _apply(self, kwargs: Dict[str, Any]):
        for k, v in kwargs.items():
            if not hasattr(self, k):
                raise TypeError(f"unknown config key {k!r}")
            setattr(self, k, v)

    def copy(self) -> "AlgorithmConfig":
        return copy.deepcopy(self)

    @property
    def algo_class(self) -> Type:
        raise NotImplementedError

    def build(self):
        from ray_tpu.util.usage_stats import record_library_usage

        record_library_usage("rllib")
        """Reference: `AlgorithmConfig.build_algo`."""
        per_step = self.num_env_runners * self.num_envs_per_env_runner
        if self.train_batch_size > 0:
            # user-specified total rollout per iteration: derive the
            # fragment length from it (the quantity sampling actually
            # consumes), so the setting has effect instead of being
            # silently ignored
            if self.train_batch_size % per_step:
                raise ValueError(
                    f"train_batch_size={self.train_batch_size} must be a "
                    f"multiple of num_env_runners*num_envs_per_env_runner "
                    f"({per_step})"
                )
            self.rollout_fragment_length = self.train_batch_size // per_step
        else:
            self.train_batch_size = per_step * self.rollout_fragment_length
        if self.use_compiled_dag:
            if not self.sample_train_overlap:
                raise ValueError(
                    "use_compiled_dag rides the overlap learner round "
                    "(resident sample loops feed channels continuously) "
                    "— set training(sample_train_overlap=True) with it"
                )
            if self.deterministic_replacement:
                raise ValueError(
                    "deterministic_replacement replays the weights-ref "
                    "history over the actor-call path; the channel "
                    "plane broadcasts by value — use one or the other"
                )
            if self.env_to_module_connector is not None:
                raise ValueError(
                    "use_compiled_dag runs a resident loop on every "
                    "runner actor, and connector-state aggregation "
                    "needs the actor-call path that loop occupies — "
                    "use the ref stream with connector pipelines"
                )
        return self.algo_class(self.copy())

    build_algo = build
