"""DreamerV3 (compact): model-based RL with an RSSM world model and an
actor-critic trained purely in imagination.

Reference: `rllib/algorithms/dreamerv3/` (`dreamerv3.py`,
`torch/dreamerv3_torch_learner.py`, `utils/summaries.py`) — the
DreamerV3 recipe (Hafner et al. 2023).  This is a faithful-but-compact
jax implementation of its core mechanics, supporting both vector and
pixel observations (conv encoder + deconv decoder, see DreamerModel):

- **RSSM**: deterministic GRU core + categorical stochastic latent
  (straight-through gradients), posterior from (h, obs embedding),
  prior from h alone, unrolled under `lax.scan` so the whole world
  model compiles to one XLA program;
- **symlog predictions** for reconstruction and reward (DreamerV3's
  scale-free regression trick);
- **KL balancing with free bits** between prior and posterior;
- **imagination training**: H-step latent rollouts from posterior
  states, lambda-returns over imagined rewards/continues, actor loss =
  reinforce-on-lambda-return + entropy, critic regresses symlog
  lambda-returns with an EMA target critic.

Deliberate reductions vs the full reference stack (documented, not
hidden): reinforce actor gradient only (no dynamics backprop mixing),
percentile return normalization reduced to EMA std scaling, no twohot
critic bins.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ray_tpu.rllib.algorithms.algorithm import Algorithm
from ray_tpu.rllib.algorithms.algorithm_config import AlgorithmConfig
from ray_tpu.rllib.env.env_runner_group import EnvRunnerGroup


# ----------------------------------------------------------------------
# numerics
# ----------------------------------------------------------------------
def symlog(x):
    import jax.numpy as jnp

    return jnp.sign(x) * jnp.log1p(jnp.abs(x))


def symexp(x):
    import jax.numpy as jnp

    return jnp.sign(x) * (jnp.exp(jnp.abs(x)) - 1.0)


class DreamerConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.lr = 3e-4
        self.actor_lr = 8e-5
        self.critic_lr = 8e-5
        # RSSM sizes (compact: 8 categoricals x 8 classes)
        self.deter_size = 128
        self.stoch_groups = 8
        self.stoch_classes = 8
        self.embed_hidden = (128,)
        self.head_hidden = (128,)
        # pixel-obs mode (image envs): conv encoder + deconv decoder
        # (reference: dreamerv3 CNN encoder/decoder for Atari/DMC)
        self.conv_filters = ((16, 4, 2), (32, 4, 2))
        # world-model training
        self.batch_length = 16
        self.batch_segments = 16
        self.free_bits = 1.0
        self.kl_balance = 0.8
        self.replay_capacity = 100_000
        # imagination
        self.horizon = 15
        self.gamma = 0.997
        self.lambda_ = 0.95
        self.entropy_coeff = 3e-3
        self.critic_ema = 0.98
        self.num_updates_per_iter = 8

    @property
    def algo_class(self):
        return Dreamer


# ----------------------------------------------------------------------
# parameter init helpers
# ----------------------------------------------------------------------
def _mlp_init(rng, dims: List[int], out_scale: float = 1.0):
    import jax
    import jax.numpy as jnp

    layers = []
    for i, (m, n) in enumerate(zip(dims[:-1], dims[1:])):
        rng, k = jax.random.split(rng)
        last = i == len(dims) - 2
        scale = 0.01 * out_scale if last else float(np.sqrt(2.0 / m))
        layers.append({
            "w": jax.random.normal(k, (m, n), jnp.float32) * scale,
            "b": jnp.zeros((n,), jnp.float32),
        })
    return layers


def _mlp(layers, x, act_last=False):
    import jax

    for i, l in enumerate(layers):
        x = x @ l["w"] + l["b"]
        if act_last or i < len(layers) - 1:
            x = jax.nn.silu(x)
    return x


class DreamerModel:
    """Pure-function world model + actor + critic (params as pytrees).

    `obs_shape` of length 3 switches to pixel mode: conv encoder +
    deconv decoder (reference: dreamerv3's CNN encoder/decoder for
    Atari/DMC); otherwise MLP encoder/decoder over flat vectors."""

    def __init__(self, cfg: DreamerConfig, obs_dim: int, num_actions: int,
                 obs_shape: Optional[Tuple[int, ...]] = None):
        self.cfg = cfg
        self.obs_dim = obs_dim
        self.obs_shape = tuple(obs_shape or (obs_dim,))
        self.pixel = len(self.obs_shape) == 3
        self.num_actions = num_actions
        self.stoch_size = cfg.stoch_groups * cfg.stoch_classes
        self.feat_size = cfg.deter_size + self.stoch_size
        if self.pixel:
            from ray_tpu.rllib.core.rl_module import conv_out_dims

            # per-conv-layer output spatial dims (SAME padding, ceil)
            self.conv_dims = conv_out_dims(
                self.obs_shape[0], self.obs_shape[1], cfg.conv_filters
            )
            h, w = self.conv_dims[-1]
            self._conv_flat = h * w * cfg.conv_filters[-1][0]

    # -- init ----------------------------------------------------------
    def _init_conv_encoder(self, rng):
        import jax

        from ray_tpu.rllib.core.rl_module import conv_stack_init

        cfg = self.cfg
        rng, k_conv, k_dense = jax.random.split(rng, 3)
        return {
            "conv": conv_stack_init(
                k_conv, self.obs_shape[-1], cfg.conv_filters
            ),
            "dense": _mlp_init(
                k_dense, [self._conv_flat, *cfg.embed_hidden]
            ),
        }

    def _init_deconv_decoder(self, rng):
        import jax
        import jax.numpy as jnp

        cfg = self.cfg
        h0, w0 = self.conv_dims[-1]
        c0 = cfg.conv_filters[-1][0]
        rng, key = jax.random.split(rng)
        dense = _mlp_init(key, [self.feat_size, h0 * w0 * c0])
        deconv = []
        # mirror the encoder stack in reverse; the last deconv emits
        # the obs channels with a small-scale linear output
        chans = [c for c, _k, _s in cfg.conv_filters]
        in_chans = chans[::-1]
        out_chans = chans[-2::-1] + [self.obs_shape[-1]]
        kernels = [k for _c, k, _s in cfg.conv_filters][::-1]
        strides = [s for _c, _k, s in cfg.conv_filters][::-1]
        for i, (ci, co, k, _s) in enumerate(
            zip(in_chans, out_chans, kernels, strides)
        ):
            rng, key = jax.random.split(rng)
            last = i == len(in_chans) - 1
            scale = 0.01 if last else float(np.sqrt(2.0 / (k * k * ci)))
            deconv.append({
                "w": jax.random.normal(key, (k, k, ci, co), jnp.float32)
                * scale,
                "b": jnp.zeros((co,), jnp.float32),
            })
        return {"dense": dense, "deconv": deconv}

    def init_params(self, rng):
        import jax

        cfg = self.cfg
        ks = list(jax.random.split(rng, 10))
        D, S, A = cfg.deter_size, self.stoch_size, self.num_actions
        E = cfg.embed_hidden[-1]
        if self.pixel:
            encoder = self._init_conv_encoder(ks[0])
            decoder = self._init_deconv_decoder(ks[4])
        else:
            encoder = _mlp_init(ks[0], [self.obs_dim, *cfg.embed_hidden])
            decoder = _mlp_init(ks[4], [self.feat_size, *cfg.head_hidden,
                                        self.obs_dim])
        return {
            "encoder": encoder,
            # GRU: input = [stoch + action_onehot] -> 3 gates over deter
            "gru": _mlp_init(ks[1], [S + A + D, 3 * D]),
            "prior": _mlp_init(ks[2], [D, *cfg.head_hidden, S]),
            "posterior": _mlp_init(ks[3], [D + E, *cfg.head_hidden, S]),
            "decoder": decoder,
            "reward": _mlp_init(ks[5], [self.feat_size, *cfg.head_hidden, 1]),
            "cont": _mlp_init(ks[6], [self.feat_size, *cfg.head_hidden, 1]),
        }

    # -- encoder/decoder (pixel or vector) -----------------------------
    def encode(self, params, obs_seq):
        """obs_seq [L, B, *obs_shape] -> embeddings [L, B, E]."""
        import jax
        import jax.numpy as jnp

        if not self.pixel:
            return _mlp(params["encoder"], symlog(obs_seq), act_last=True)
        from ray_tpu.rllib.core.rl_module import conv_stack_apply

        enc = params["encoder"]
        L, B = obs_seq.shape[:2]
        x = obs_seq.reshape(L * B, *self.obs_shape)
        x = conv_stack_apply(
            x=x, conv_params=enc["conv"],
            conv_filters=self.cfg.conv_filters, activation=jax.nn.silu,
        )
        x = x.reshape(L * B, -1)
        x = _mlp(enc["dense"], x, act_last=True)
        return x.reshape(L, B, -1)

    def decode(self, params, feats):
        """feats [L, B, F] -> reconstruction [L, B, *obs_shape] (pixel)
        or [L, B, obs_dim] symlog-space (vector)."""
        import jax
        import jax.numpy as jnp

        if not self.pixel:
            return _mlp(params["decoder"], feats)
        dec = params["decoder"]
        L, B = feats.shape[:2]
        h0, w0 = self.conv_dims[-1]
        c0 = self.cfg.conv_filters[-1][0]
        x = _mlp(dec["dense"], feats.reshape(L * B, -1), act_last=True)
        x = x.reshape(L * B, h0, w0, c0)
        strides = [s for _c, _k, s in self.cfg.conv_filters][::-1]
        targets = self.conv_dims[-2::-1]  # spatial dims to restore
        for i, (lyr, s, (th, tw)) in enumerate(
            zip(dec["deconv"], strides, targets)
        ):
            x = jax.lax.conv_transpose(
                x, lyr["w"], strides=(s, s), padding="SAME",
                dimension_numbers=("NHWC", "HWIO", "NHWC"),
            ) + lyr["b"]
            x = x[:, :th, :tw, :]  # crop ceil-division overshoot
            if i < len(dec["deconv"]) - 1:
                x = jax.nn.silu(x)
        return x.reshape(L, B, *self.obs_shape)

    def init_actor_critic(self, rng):
        import jax

        cfg = self.cfg
        k_a, k_c = jax.random.split(rng)
        return (
            _mlp_init(k_a, [self.feat_size, *cfg.head_hidden,
                            self.num_actions]),
            _mlp_init(k_c, [self.feat_size, *cfg.head_hidden, 1]),
        )

    # -- RSSM ----------------------------------------------------------
    def _sample_categorical(self, rng, logits):
        """Straight-through categorical sample over grouped classes.
        logits [..., G*C] -> one-hot sample [..., G*C] with gradients
        flowing through the softmax probabilities (DreamerV3's
        straight-through estimator) + 1% uniform mix for exploration."""
        import jax
        import jax.numpy as jnp

        cfg = self.cfg
        shape = logits.shape[:-1]
        lg = logits.reshape(*shape, cfg.stoch_groups, cfg.stoch_classes)
        probs = 0.99 * jax.nn.softmax(lg) + 0.01 / cfg.stoch_classes
        idx = jax.random.categorical(rng, jnp.log(probs))
        onehot = jax.nn.one_hot(idx, cfg.stoch_classes)
        st = onehot + probs - jax.lax.stop_gradient(probs)
        return st.reshape(*shape, -1), jnp.log(probs)

    def rssm_observe(self, params, rng, obs_seq, action_seq, first_h=None):
        """Posterior rollout over an observed segment.

        obs_seq [L, B, obs], action_seq [L, B] (action taken at t-1,
        one-hot'ed inside) -> (feats [L, B, F], prior/post logits).
        """
        import jax
        import jax.numpy as jnp

        cfg = self.cfg
        L, B = action_seq.shape
        embed = self.encode(params, obs_seq)
        a_onehot = jax.nn.one_hot(action_seq, self.num_actions)
        h0 = (
            first_h if first_h is not None
            else jnp.zeros((B, cfg.deter_size), jnp.float32)
        )
        z0 = jnp.zeros((B, self.stoch_size), jnp.float32)
        keys = jax.random.split(rng, L)

        def step(carry, inp):
            h, z = carry
            emb_t, a_t, key = inp
            h = self._gru_step(params, h, z, a_t)
            prior_logits = _mlp(params["prior"], h)
            post_logits = _mlp(
                params["posterior"], jnp.concatenate([h, emb_t], axis=-1)
            )
            z, _ = self._sample_categorical(key, post_logits)
            feat = jnp.concatenate([h, z], axis=-1)
            return (h, z), (feat, prior_logits, post_logits, h)

        (_, _), (feats, priors, posts, hs) = jax.lax.scan(
            step, (h0, z0), (embed, a_onehot, keys)
        )
        return feats, priors, posts, hs

    def _gru_step(self, params, h, stoch, a_onehot):
        """Standard GRU cell over the deterministic state."""
        import jax
        import jax.numpy as jnp

        x = jnp.concatenate([stoch, a_onehot, h], axis=-1)
        gates = _mlp(params["gru"], x)
        r, u, c = jnp.split(gates, 3, axis=-1)
        r = jax.nn.sigmoid(r)
        u = jax.nn.sigmoid(u)
        c = jnp.tanh(r * c)
        return u * c + (1.0 - u) * h

    # -- losses --------------------------------------------------------
    def world_model_loss(self, params, rng, batch):
        import jax
        import jax.numpy as jnp

        cfg = self.cfg
        obs = batch["obs"]            # [L, B, obs]
        actions = batch["prev_actions"]  # [L, B]
        rewards = batch["rewards"]    # [L, B]
        cont = 1.0 - batch["terminated"].astype(jnp.float32)

        feats, priors, posts, hs = self.rssm_observe(
            params, rng, obs, actions
        )
        recon = self.decode(params, feats)
        if self.pixel:
            # pixel decoder is a unit-variance Gaussian on [0,1] frames
            # (reference: dreamerv3 MSE image loss, summed over pixels)
            recon_loss = jnp.mean(jnp.sum(
                (recon - obs) ** 2,
                axis=tuple(range(2, recon.ndim)),
            ))
        else:
            recon_loss = jnp.mean(jnp.sum(
                (recon - symlog(obs)) ** 2, axis=-1
            ))
        rew_pred = _mlp(params["reward"], feats)[..., 0]
        reward_loss = jnp.mean((rew_pred - symlog(rewards)) ** 2)
        cont_logit = _mlp(params["cont"], feats)[..., 0]
        cont_loss = jnp.mean(
            jnp.maximum(cont_logit, 0) - cont_logit * cont
            + jnp.log1p(jnp.exp(-jnp.abs(cont_logit)))
        )

        # KL balance with free bits (DreamerV3 sec. 3): the posterior
        # is pulled toward the prior weakly, the prior toward the
        # posterior strongly
        def kl(p_logits, q_logits):
            G, C = cfg.stoch_groups, cfg.stoch_classes
            p = jax.nn.log_softmax(
                p_logits.reshape(*p_logits.shape[:-1], G, C))
            q = jax.nn.log_softmax(
                q_logits.reshape(*q_logits.shape[:-1], G, C))
            return jnp.sum(jnp.exp(p) * (p - q), axis=(-1, -2))

        dyn = jnp.maximum(
            kl(jax.lax.stop_gradient(posts), priors), cfg.free_bits
        ).mean()
        rep = jnp.maximum(
            kl(posts, jax.lax.stop_gradient(priors)), cfg.free_bits
        ).mean()
        kl_loss = cfg.kl_balance * dyn + (1 - cfg.kl_balance) * rep

        loss = recon_loss + reward_loss + cont_loss + kl_loss
        metrics = {
            "wm_loss": loss,
            "recon_loss": recon_loss,
            "reward_loss": reward_loss,
            "cont_loss": cont_loss,
            "kl_loss": kl_loss,
        }
        # posterior states ride out as imagination start states so the
        # caller never re-runs the RSSM rollout outside jit
        aux = (metrics, jax.lax.stop_gradient(hs),
               jax.lax.stop_gradient(feats))
        return loss, aux

    # -- imagination ---------------------------------------------------
    def imagine(self, params, actor, rng, start_h, start_z):
        """H-step latent rollout following the actor; returns feats
        [H+1, N, F], actions [H, N], logps [H, N]."""
        import jax
        import jax.numpy as jnp

        H = self.cfg.horizon
        keys = jax.random.split(rng, H)

        def step(carry, key):
            h, z = carry
            feat = jnp.concatenate([h, z], axis=-1)
            logits = _mlp(actor, jax.lax.stop_gradient(feat))
            ka, kz = jax.random.split(key)
            action = jax.random.categorical(ka, logits)
            logp = jnp.take_along_axis(
                jax.nn.log_softmax(logits), action[:, None], axis=-1
            )[:, 0]
            a_onehot = jax.nn.one_hot(action, self.num_actions)
            h = self._gru_step(params, h, z, a_onehot)
            prior_logits = _mlp(params["prior"], h)
            z, _ = self._sample_categorical(kz, prior_logits)
            return (h, z), (feat, action, logp)

        (h, z), (feats, actions, logps) = jax.lax.scan(
            step, (start_h, start_z), keys
        )
        last = jnp.concatenate([h, z], axis=-1)
        feats = jnp.concatenate([feats, last[None]], axis=0)
        return feats, actions, logps


def lambda_returns(rewards, conts, values, last_value, gamma, lambda_):
    """Bootstrapped lambda-returns over imagined trajectories
    [H, N] (numpy reference used by the jax scan in the learner)."""
    import jax.numpy as jnp
    from jax import lax

    def step(next_ret, inp):
        r, c, v_next = inp
        ret = r + gamma * c * (
            (1 - lambda_) * v_next + lambda_ * next_ret
        )
        return ret, ret

    v_next = jnp.concatenate([values[1:], last_value[None]], axis=0)
    _, rets = lax.scan(
        step, last_value, (rewards, conts, v_next), reverse=True
    )
    return rets


class Dreamer(Algorithm):
    """Compact DreamerV3 (reference: `rllib/algorithms/dreamerv3/`)."""

    def setup_components(self):
        import jax
        import optax

        cfg = self.config
        self.env_runner_group = EnvRunnerGroup(
            cfg.env, cfg.num_env_runners, cfg.num_envs_per_env_runner,
            cfg.rollout_fragment_length, seed=cfg.seed,
            env_kwargs=cfg.env_kwargs,
            connector=cfg.env_to_module_connector,
        )
        spec = self.env_runner_group.env_spec()
        from ray_tpu.rllib.core.rl_module import require_discrete_actions

        require_discrete_actions(spec, "DreamerV3")
        self.model = DreamerModel(
            cfg, spec["observation_size"], spec["num_actions"],
            obs_shape=spec.get("observation_shape"),
        )
        rng = jax.random.PRNGKey(cfg.seed)
        k_wm, k_ac, self._rng_key = jax.random.split(rng, 3)
        self.wm_params = self.model.init_params(k_wm)
        self.actor_params, self.critic_params = (
            self.model.init_actor_critic(k_ac)
        )
        self.target_critic = jax.tree.map(
            lambda x: x.copy(), self.critic_params
        )
        self.wm_opt = optax.adam(cfg.lr)
        self.actor_opt = optax.adam(cfg.actor_lr)
        self.critic_opt = optax.adam(cfg.critic_lr)
        self.wm_opt_state = self.wm_opt.init(self.wm_params)
        self.actor_opt_state = self.actor_opt.init(self.actor_params)
        self.critic_opt_state = self.critic_opt.init(self.critic_params)
        self._replay: List[Dict[str, np.ndarray]] = []
        self._replay_rows = 0
        self._ret_std = 1.0  # EMA return-scale normalizer
        self._np_rng = np.random.default_rng(cfg.seed)
        self._build_updates()
        # the rollout policy: actor over posterior features, computed
        # with a tiny numpy RSSM mirror is complex — instead runners
        # sample with the actor over a feature proxy.  Simpler and
        # faithful enough for vector envs: run rollouts DIRECTLY with
        # the actor on (h=0, z from posterior of a 1-step observe).
        self._policy_module = _DreamerPolicy(self)
        self.env_runner_group.sync_weights(self._policy_weights())

    # -- jitted updates ------------------------------------------------
    def _build_updates(self):
        import jax
        import jax.numpy as jnp

        model, cfg = self.model, self.config

        def wm_update(params, opt_state, rng, batch):
            (loss, (metrics, hs, feats)), grads = jax.value_and_grad(
                lambda p: model.world_model_loss(p, rng, batch),
                has_aux=True,
            )(params)
            updates, opt_state = self.wm_opt.update(grads, opt_state, params)
            params = jax.tree.map(lambda p, u: p + u, params, updates)
            D = cfg.deter_size
            start_h = hs.reshape(-1, D)
            start_z = feats.reshape(-1, feats.shape[-1])[:, D:]
            return params, opt_state, metrics, start_h, start_z

        def ac_update(wm_params, actor, critic, target_critic,
                      a_opt, c_opt, rng, start_h, start_z, ret_scale):
            feats, actions, _logps = model.imagine(
                wm_params, actor, rng, start_h, start_z
            )
            feats = jax.lax.stop_gradient(feats)
            rewards = symexp(_mlp(wm_params["reward"], feats[:-1])[..., 0])
            conts = jax.nn.sigmoid(_mlp(wm_params["cont"], feats)[..., 0])

            def critic_loss_fn(c):
                values = symexp(_mlp(c, feats)[..., 0])
                tvalues = symexp(_mlp(target_critic, feats)[..., 0])
                rets = lambda_returns(
                    rewards, conts[:-1], tvalues[:-1], tvalues[-1],
                    cfg.gamma, cfg.lambda_,
                )
                rets = jax.lax.stop_gradient(rets)
                pred = _mlp(c, feats[:-1])[..., 0]
                closs = jnp.mean((pred - symlog(rets)) ** 2)
                return closs, (rets, values[:-1])

            (closs, (rets, values)), cgrads = jax.value_and_grad(
                critic_loss_fn, has_aux=True
            )(critic)
            cupd, c_opt = self.critic_opt.update(cgrads, c_opt, critic)
            critic = jax.tree.map(lambda p, u: p + u, critic, cupd)

            def actor_loss_fn(a):
                logits = _mlp(a, feats[:-1])
                logp_all = jax.nn.log_softmax(logits)
                lp = jnp.take_along_axis(
                    logp_all, actions[..., None], axis=-1
                )[..., 0]
                adv = jax.lax.stop_gradient(
                    (rets - values) / jnp.maximum(ret_scale, 1.0)
                )
                ent = -jnp.mean(jnp.sum(jnp.exp(logp_all) * logp_all,
                                        axis=-1))
                # discount weights: imagined steps past a predicted
                # episode end contribute less
                w = jnp.cumprod(
                    jnp.concatenate([jnp.ones_like(conts[:1]),
                                     conts[:-2] * cfg.gamma], axis=0),
                    axis=0,
                )
                w = jax.lax.stop_gradient(w)
                aloss = -jnp.mean(w * lp * adv) - cfg.entropy_coeff * ent
                return aloss, ent

            (aloss, ent), agrads = jax.value_and_grad(
                actor_loss_fn, has_aux=True
            )(actor)
            aupd, a_opt = self.actor_opt.update(agrads, a_opt, actor)
            actor = jax.tree.map(lambda p, u: p + u, actor, aupd)

            target_critic = jax.tree.map(
                lambda t, c: cfg.critic_ema * t + (1 - cfg.critic_ema) * c,
                target_critic, critic,
            )
            ret_std = jnp.std(rets)
            return (actor, critic, target_critic, a_opt, c_opt, {
                "actor_loss": aloss,
                "critic_loss": closs,
                "actor_entropy": ent,
                "imagined_return_mean": jnp.mean(rets),
            }, ret_std)

        self._wm_update = jax.jit(wm_update)
        self._ac_update = jax.jit(ac_update)

    # -- rollout policy ------------------------------------------------
    def _policy_weights(self):
        import jax

        return {
            "wm": jax.tree.map(np.asarray, self.wm_params),
            "actor": jax.tree.map(np.asarray, self.actor_params),
        }

    # -- replay --------------------------------------------------------
    def _add_to_replay(self, samples: List[Dict[str, np.ndarray]]):
        for s in samples:
            T, B = s["actions"].shape
            seg = {
                "obs": s["obs"],
                # action that LED to obs[t] (shifted; a_{-1}=0)
                "prev_actions": np.concatenate(
                    [np.zeros((1, B), np.int32), s["actions"][:-1]], axis=0
                ),
                "rewards": s["rewards"],
                "terminated": s["terminated"],
            }
            self._replay.append(seg)
            self._replay_rows += T * B
        cap = self.config.replay_capacity
        while self._replay_rows > cap and len(self._replay) > 1:
            old = self._replay.pop(0)
            self._replay_rows -= (
                old["rewards"].shape[0] * old["rewards"].shape[1]
            )

    def _sample_segments(self):
        cfg = self.config
        L, S = cfg.batch_length, cfg.batch_segments
        obs_l, act_l, rew_l, term_l = [], [], [], []
        for _ in range(S):
            seg = self._replay[self._np_rng.integers(len(self._replay))]
            T, B = seg["rewards"].shape
            b = self._np_rng.integers(B)
            t0 = self._np_rng.integers(max(T - L, 0) + 1)
            sl = slice(t0, t0 + L)

            def pad(x):
                out = x[sl, b]
                if out.shape[0] < L:
                    reps = [out[-1:]] * (L - out.shape[0])
                    out = np.concatenate([out, *reps], axis=0)
                return out

            obs_l.append(pad(seg["obs"]))
            act_l.append(pad(seg["prev_actions"]))
            rew_l.append(pad(seg["rewards"]))
            term_l.append(pad(seg["terminated"]))
        return {
            "obs": np.stack(obs_l, axis=1).astype(np.float32),
            "prev_actions": np.stack(act_l, axis=1).astype(np.int32),
            "rewards": np.stack(rew_l, axis=1).astype(np.float32),
            "terminated": np.stack(term_l, axis=1),
        }

    # -- train ---------------------------------------------------------
    def training_step(self) -> Dict[str, Any]:
        import jax

        cfg = self.config
        samples = self.env_runner_group.sample(self._policy_module)
        self._add_to_replay(samples)

        metrics_acc: List[Dict[str, float]] = []
        for _ in range(cfg.num_updates_per_iter):
            batch = self._sample_segments()
            self._rng_key, k_wm, k_ac = jax.random.split(
                self._rng_key, 3
            )
            (self.wm_params, self.wm_opt_state, wm_metrics, start_h,
             start_z) = self._wm_update(
                self.wm_params, self.wm_opt_state, k_wm, batch
            )
            (self.actor_params, self.critic_params, self.target_critic,
             self.actor_opt_state, self.critic_opt_state, ac_metrics,
             ret_std) = self._ac_update(
                self.wm_params, self.actor_params, self.critic_params,
                self.target_critic, self.actor_opt_state,
                self.critic_opt_state, k_ac, start_h, start_z,
                self._ret_std,
            )
            self._ret_std = 0.99 * self._ret_std + 0.01 * float(ret_std)
            metrics_acc.append({
                **{k: float(v) for k, v in wm_metrics.items()},
                **{k: float(v) for k, v in ac_metrics.items()},
            })

        self.env_runner_group.sync_weights(self._policy_weights())
        result = {
            k: float(np.mean([m[k] for m in metrics_acc]))
            for k in metrics_acc[0]
        }
        result["replay_rows"] = self._replay_rows
        self._track_episode_metrics(
            self.env_runner_group.pop_metrics(), result
        )
        return result

    def get_state(self) -> Dict[str, Any]:
        return {
            "wm": self.wm_params,
            "actor": self.actor_params,
            "critic": self.critic_params,
            "target_critic": self.target_critic,
            "wm_opt": self.wm_opt_state,
            "actor_opt": self.actor_opt_state,
            "critic_opt": self.critic_opt_state,
            "ret_std": self._ret_std,
            "connector": self.env_runner_group.connector_state(),
            "iteration": self.iteration,
        }

    def set_state(self, state: Dict[str, Any]):
        self.wm_params = state["wm"]
        self.actor_params = state["actor"]
        self.critic_params = state["critic"]
        self.target_critic = state["target_critic"]
        for key, attr in (("wm_opt", "wm_opt_state"),
                          ("actor_opt", "actor_opt_state"),
                          ("critic_opt", "critic_opt_state")):
            if key in state:
                setattr(self, attr, state[key])
        self._ret_std = state.get("ret_std", self._ret_std)
        self.env_runner_group.restore_connector_state(
            state.get("connector")
        )
        self.iteration = state.get("iteration", self.iteration)
        # the FIRST post-restore rollout must use the restored policy,
        # not the random init shipped at setup
        self.env_runner_group.sync_weights(self._policy_weights())

    def stop(self):
        self.env_runner_group.stop()


class _DreamerPolicy:
    """Numpy rollout policy shipped to EnvRunners: a 1-step posterior
    (h=0) turns the observation into latent features, the actor picks.
    Matches the training-time feature construction for fresh episodes;
    cheap enough for CPU sampling actors."""

    def __init__(self, algo: Dreamer):
        self._cfg_sizes = (
            algo.model.cfg.deter_size,
            algo.model.cfg.stoch_groups,
            algo.model.cfg.stoch_classes,
        )
        self._num_actions = algo.model.num_actions
        self._pixel = algo.model.pixel
        self._conv_filters = tuple(algo.model.cfg.conv_filters)

    @staticmethod
    def _np_mlp(layers, x, act_last=False):
        for i, l in enumerate(layers):
            x = x @ np.asarray(l["w"]) + np.asarray(l["b"])
            if act_last or i < len(layers) - 1:
                x = x * (1.0 / (1.0 + np.exp(-x)))  # silu
        return x

    def _np_encode(self, enc, obs):
        if not self._pixel:
            x = np.sign(obs) * np.log1p(np.abs(obs))
            return self._np_mlp(enc, x, act_last=True)
        from ray_tpu.rllib.core.rl_module import _conv2d_numpy

        x = np.asarray(obs, np.float32)
        for lyr, (_c, k, s) in zip(enc["conv"], self._conv_filters):
            x = _conv2d_numpy(x, np.asarray(lyr["w"]),
                              np.asarray(lyr["b"]), k, s)
            x = x * (1.0 / (1.0 + np.exp(-x)))  # silu
        return self._np_mlp(enc["dense"], x.reshape(x.shape[0], -1),
                            act_last=True)

    def forward_numpy(self, params, obs):
        D, G, C = self._cfg_sizes
        wm, actor = params["wm"], params["actor"]
        emb = self._np_encode(wm["encoder"], obs)
        B = obs.shape[0]
        h = np.zeros((B, D), np.float32)
        post = self._np_mlp(
            wm["posterior"], np.concatenate([h, emb], axis=-1)
        )
        lg = post.reshape(B, G, C)
        e = np.exp(lg - lg.max(axis=-1, keepdims=True))
        probs = e / e.sum(axis=-1, keepdims=True)
        z = probs.reshape(B, G * C)  # expected value (deterministic)
        feat = np.concatenate([h, z], axis=-1)
        logits = self._np_mlp(actor, feat)
        value = np.zeros(B, np.float32)  # runners don't need values here
        return logits, value
