"""MARWIL: monotonic advantage re-weighted imitation learning.

Reference: `rllib/algorithms/marwil/` (`marwil.py`,
`marwil_torch_learner.py`) — offline RL between BC and full RL: the
policy is cloned from logged actions, but each sample's log-likelihood
is weighted by `exp(beta * advantage)`, so better-than-baseline actions
are imitated harder.  `beta = 0` reduces exactly to BC.  A value head
is trained on the empirical discounted returns to supply the baseline.

Departure from the reference: the advantage normalizer is the batch RMS
rather than the reference's persistent moving average
(`update_averaged_weight` in `marwil_torch_learner.py`) — stateless, so
the loss stays a pure jitted function of (params, batch); at MARWIL's
offline batch sizes the two estimates converge to the same scale.

Offline input: BC's shapes plus per-step `rewards` and episode
boundaries (`dones`/`terminateds`), from which discounted returns are
computed once at setup.  Precomputed `returns` are accepted as-is.
"""

from __future__ import annotations

from typing import Any, Dict

import numpy as np

from ray_tpu.rllib.algorithms.bc import BC, BCConfig, _coerce_offline


class MARWILConfig(BCConfig):
    def __init__(self):
        super().__init__()
        self.beta = 1.0  # 0 => plain BC
        self.vf_coeff = 1.0
        self.gamma = 0.99
        # exp-weight clip guard (reference clips the weight to avoid
        # a few high-advantage samples dominating the batch)
        self.max_weight = 20.0

    def training(self, *, beta: float = None, vf_coeff: float = None,
                 max_weight: float = None, **kwargs) -> "MARWILConfig":
        if beta is not None:
            self.beta = beta
        if vf_coeff is not None:
            self.vf_coeff = vf_coeff
        if max_weight is not None:
            self.max_weight = max_weight
        return super().training(**kwargs)

    @property
    def algo_class(self):
        return MARWIL


def make_marwil_loss(beta: float, vf_coeff: float, max_weight: float):
    """Loss factory (hyperparameters close over a jit-stable fn)."""

    def marwil_loss(module, params, batch):
        import jax
        import jax.numpy as jnp

        logits, values = module.forward_train(params, batch["obs"])
        values = values.reshape(-1)
        returns = batch["returns"]
        adv = returns - values
        # value head regresses the empirical returns
        vf_loss = jnp.mean(adv ** 2)
        # policy: advantage-weighted NLL; the weight is a constant from
        # the policy's perspective (stop_gradient, as in the reference)
        logp_all = jax.nn.log_softmax(logits, axis=-1)
        actions = batch["actions"].astype(jnp.int32)
        logp = jnp.take_along_axis(logp_all, actions[:, None], axis=-1)[:, 0]
        if beta == 0.0:
            weight = jnp.ones_like(logp)
        else:
            norm = jnp.sqrt(jnp.mean(adv ** 2) + 1e-8)
            weight = jnp.exp(
                jnp.clip(beta * adv / norm, a_max=jnp.log(max_weight))
            )
            weight = jax.lax.stop_gradient(weight)
        policy_loss = -jnp.mean(weight * logp)
        loss = policy_loss + vf_coeff * vf_loss
        accuracy = jnp.mean(
            (jnp.argmax(logits, axis=-1) == actions).astype(jnp.float32)
        )
        return loss, {
            "marwil_loss": loss,
            "policy_loss": policy_loss,
            "vf_loss": vf_loss,
            "mean_weight": jnp.mean(weight),
            "mean_advantage": jnp.mean(adv),
            "action_accuracy": accuracy,
        }

    return marwil_loss


def discounted_returns(rewards: np.ndarray, dones: np.ndarray,
                       gamma: float) -> np.ndarray:
    """Per-episode reverse discounted cumsum (the reference computes
    these in its offline pre-learner connector)."""
    out = np.zeros_like(rewards, dtype=np.float32)
    acc = 0.0
    for i in range(len(rewards) - 1, -1, -1):
        if dones[i]:
            acc = 0.0
        acc = float(rewards[i]) + gamma * acc
        out[i] = acc
    return out


def _coerce_offline_marwil(input_: Any, gamma: float) -> Dict[str, np.ndarray]:
    base = _coerce_offline(input_)
    # pull rewards/dones/returns through the same shapes BC accepts
    if isinstance(input_, dict):
        batches = [input_]
    elif isinstance(input_, list) and input_ and isinstance(input_[0], dict) \
            and "obs" in input_[0] and np.ndim(input_[0]["obs"]) >= 2:
        batches = input_
    else:
        rows = input_.take_all() if hasattr(input_, "take_all") else list(input_)
        batches = [{
            k: np.asarray([r[k] for r in rows])
            for k in rows[0]
        }]

    def _col(b, names):
        hit = next((n for n in names if n in b), None)
        return None if hit is None else np.asarray(b[hit])

    # returns are computed PER BATCH: a list of batch dicts is a list of
    # independent trajectories, so discounting must never bleed from one
    # into the previous (each batch's tail is always a boundary)
    per_batch_returns = []
    for b in batches:
        returns = _col(b, ["returns"])
        if returns is None:
            rewards = _col(b, ["rewards", "reward"])
            if rewards is None:
                raise ValueError(
                    "MARWIL needs per-step 'rewards' (+ 'dones') or "
                    "precomputed 'returns' in the offline data"
                )
            rewards = np.asarray(rewards, np.float32)
            dones = _col(b, ["dones", "terminateds", "done"])
            if dones is None:
                dones = np.zeros(len(rewards))
            if len(dones) != len(rewards):
                raise ValueError("rewards/dones length mismatch")
            dones = np.asarray(dones).astype(bool).copy()
            dones[-1] = True
            returns = discounted_returns(rewards, dones, gamma)
        per_batch_returns.append(np.asarray(returns, np.float32))
    base["returns"] = np.concatenate(per_batch_returns)
    if base["returns"].shape[0] != base["obs"].shape[0]:
        raise ValueError("returns/obs length mismatch")
    return base


class MARWIL(BC):
    def _loss_fn(self):
        cfg = self.config
        return make_marwil_loss(cfg.beta, cfg.vf_coeff, cfg.max_weight)

    def _prepare_dataset(self):
        return _coerce_offline_marwil(self.config.input_, self.config.gamma)

    def training_step(self) -> Dict[str, Any]:
        cfg = self.config
        n = self.dataset["obs"].shape[0]
        mb = min(cfg.minibatch_size, n)
        metrics_acc = []
        for _ in range(cfg.num_updates_per_iter):
            idx = self._rng.integers(0, n, mb)
            metrics_acc.append(self.learner_group.update_minibatch({
                "obs": self.dataset["obs"][idx],
                "actions": self.dataset["actions"][idx],
                "returns": self.dataset["returns"][idx],
            }))
        result: Dict[str, Any] = {
            k: float(np.mean([m[k] for m in metrics_acc]))
            for k in metrics_acc[0]
        }
        result["num_offline_steps_trained"] = mb * cfg.num_updates_per_iter
        if (
            self.env_runner_group is not None
            and (self.iteration + 1) % cfg.evaluation_interval == 0
        ):
            self.env_runner_group.sync_weights(
                self.learner_group.get_weights_numpy()
            )
            self.env_runner_group.sample(self.module)
            self._track_episode_metrics(
                self.env_runner_group.pop_metrics(), result
            )
        return result
