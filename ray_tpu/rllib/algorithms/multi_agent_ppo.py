"""Multi-agent PPO: per-policy modules/learners over dict-keyed envs.

Reference: the multi-agent path of the new API stack —
`AlgorithmConfig.multi_agent(policies=..., policy_mapping_fn=...)`,
`MultiAgentEpisode` collection, and the Learner-per-module update in
`learner_group.py`.  Agents map to MODULES via the policy mapping;
agents sharing a module share one batch and one learner (parameter
sharing), distinct modules train independently on their own agents'
experience.
"""

from __future__ import annotations

import logging
from typing import Any, Callable, Dict, List, Optional

import numpy as np

import ray_tpu as rt

logger = logging.getLogger(__name__)
from ray_tpu.rllib.algorithms.algorithm import Algorithm
from ray_tpu.rllib.algorithms.algorithm_config import AlgorithmConfig
from ray_tpu.rllib.algorithms.ppo import make_ppo_loss
from ray_tpu.rllib.core.learner import LearnerGroup
from ray_tpu.rllib.core.rl_module import MLPModule
from ray_tpu.rllib.env.multi_agent import (
    MultiAgentEnvRunner,
    make_multi_agent_env,
    multi_agent_gae,
)


class MultiAgentPPOConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.env = "coordination"
        self.clip_param: float = 0.2
        self.vf_loss_coeff: float = 0.5
        self.entropy_coeff: float = 0.01
        self.gae_lambda: float = 0.95
        self.num_epochs = 4
        self.policies: Optional[List[str]] = None  # module ids
        self.policy_mapping_fn: Optional[Callable[[str], str]] = None

    def multi_agent(self, *, policies: Optional[List[str]] = None,
                    policy_mapping_fn: Optional[Callable] = None,
                    **kwargs) -> "MultiAgentPPOConfig":
        if policies is not None:
            self.policies = list(policies)
        if policy_mapping_fn is not None:
            self.policy_mapping_fn = policy_mapping_fn
        self._apply(kwargs)
        return self

    @property
    def algo_class(self):
        return MultiAgentPPO


class MultiAgentPPO(Algorithm):
    def setup_components(self):
        cfg = self.config
        probe = make_multi_agent_env(cfg.env, **cfg.env_kwargs)
        agent_ids = list(probe.agent_ids)
        mapping_fn = cfg.policy_mapping_fn or (lambda aid: "shared")
        self._policy_mapping = {a: mapping_fn(a) for a in agent_ids}
        module_ids = cfg.policies or sorted(set(self._policy_mapping.values()))
        unknown = set(self._policy_mapping.values()) - set(module_ids)
        if unknown:
            raise ValueError(
                f"policy_mapping_fn produced module ids {sorted(unknown)} "
                f"not in policies={module_ids}"
            )

        self.modules: Dict[str, MLPModule] = {
            mid: MLPModule(
                probe.observation_size, probe.num_actions,
                hidden=tuple(cfg.model.get("hidden", (64, 64))),
            )
            for mid in module_ids
        }
        loss = make_ppo_loss(cfg.clip_param, vf_loss_coeff=cfg.vf_loss_coeff,
                             entropy_coeff=cfg.entropy_coeff)
        self.learners: Dict[str, LearnerGroup] = {
            mid: LearnerGroup(
                self.modules[mid], loss, num_learners=cfg.num_learners,
                lr=cfg.lr, grad_clip=cfg.grad_clip,
                seed=cfg.seed + i, mesh=cfg.mesh,
            )
            for i, mid in enumerate(module_ids)
        }
        Runner = rt.remote(MultiAgentEnvRunner).options(num_cpus=1)
        self._runners = [
            Runner.remote(cfg.env, cfg.rollout_fragment_length,
                          self._policy_mapping,
                          seed=cfg.seed + i * 10_000,
                          env_kwargs=cfg.env_kwargs)
            for i in range(cfg.num_env_runners)
        ]
        self._sync_weights()

    def _weights(self) -> Dict[str, Any]:
        return {
            mid: lg.get_weights_numpy() for mid, lg in self.learners.items()
        }

    def _sync_weights(self):
        w = self._weights()
        self._weights_version = getattr(self, "_weights_version", 0) + 1
        refs = [r.set_weights.remote(w, self._weights_version)
                for r in self._runners]
        rt.wait(refs, num_returns=len(refs), timeout=30)

    def training_step(self) -> Dict[str, Any]:
        cfg = self.config
        refs = [r.sample.remote(self.modules) for r in self._runners]
        per_module: Dict[str, List[Dict[str, np.ndarray]]] = {
            mid: [] for mid in self.modules
        }
        for ref in refs:
            sample = rt.get(ref, timeout=120)
            for mid, batch in sample.items():
                if len(batch["actions"]):
                    per_module[mid].append(batch)

        result: Dict[str, Any] = {}
        total_steps = 0
        rng = np.random.default_rng(cfg.seed + self.iteration)
        for mid, batches in per_module.items():
            if not batches:
                continue
            adv_l, tgt_l = [], []
            for b in batches:
                adv, tgt = multi_agent_gae(b, cfg.gamma, cfg.gae_lambda)
                adv_l.append(adv)
                tgt_l.append(tgt)
            obs = np.concatenate([b["obs"] for b in batches])
            actions = np.concatenate([b["actions"] for b in batches])
            logp = np.concatenate([b["logp"] for b in batches])
            adv = np.concatenate(adv_l)
            adv = (adv - adv.mean()) / (adv.std() + 1e-8)
            targets = np.concatenate(tgt_l)
            n = len(obs)
            total_steps += n
            mb = min(cfg.minibatch_size, n)
            n_even = (n // mb) * mb
            metrics_acc = []
            for _epoch in range(cfg.num_epochs):
                perm = rng.permutation(n)[:n_even]
                for start in range(0, n_even, mb):
                    idx = perm[start:start + mb]
                    metrics_acc.append(
                        self.learners[mid].update_minibatch({
                            "obs": obs[idx],
                            "actions": actions[idx],
                            "logp": logp[idx],
                            "advantages": adv[idx],
                            "value_targets": targets[idx],
                        })
                    )
            for k in metrics_acc[0]:
                result[f"{mid}/{k}"] = float(
                    np.mean([m[k] for m in metrics_acc])
                )
        self._sync_weights()
        result["num_env_steps_sampled"] = total_steps

        episodes: List[Dict[str, float]] = []
        for r in self._runners:
            try:
                episodes.extend(rt.get(r.pop_metrics.remote(), timeout=30))
            except Exception as e:
                logger.debug("episode metrics fetch failed: %s", e)
        self._track_episode_metrics(episodes, result)
        return result

    def get_state(self) -> Dict[str, Any]:
        return {
            "learners": {m: lg.get_state() for m, lg in self.learners.items()},
            "recent_returns": list(self._recent_returns),
            "iteration": self.iteration,
        }

    def set_state(self, state: Dict[str, Any]):
        for mid, st in state.get("learners", {}).items():
            if mid in self.learners:
                self.learners[mid].set_state(st)
        self._recent_returns = list(state.get("recent_returns", []))
        self.iteration = state.get("iteration", self.iteration)
        self._sync_weights()

    def stop(self):
        for r in self._runners:
            try:
                rt.kill(r)
            except Exception as e:
                logger.debug("runner kill on stop failed: %s", e)
        for lg in self.learners.values():
            lg.stop()
