"""PPO on the new API stack.

Reference: `rllib/algorithms/ppo/ppo.py` (`training_step:402`) +
`ppo/torch/ppo_torch_learner.py` (clipped surrogate + value clip +
entropy bonus) — re-expressed as a pure-jax loss compiled once per
minibatch shape.  GAE (`rllib/evaluation/postprocessing.py` in the old
stack, connectors in the new) runs as vectorized numpy on the driver:
it is O(T·B) pointer-chasing, not MXU work.

Production scale (`config.sample_train_overlap=True`): the EnvRunner
fleet streams rollouts as object-plane references while the pjit
learner gang updates on the PREVIOUS train batch — sampling wall-time
hides behind the update, weights broadcast back non-blocking by
reference (one staleness version, absorbed by the ratio clip).  The
per-iteration result reports the measured overlap
(`sample_busy_s`/`sample_wait_s`/`overlap_ratio`) and the exactly-once
ledger keeps env-step accounting exact through runner failures.
"""

from __future__ import annotations

import logging
import time
from typing import Any, Dict, List, Tuple

import numpy as np

from ray_tpu.metrics import metric_defs as _mdefs
from ray_tpu.rllib.algorithms.algorithm import Algorithm
from ray_tpu.rllib.algorithms.algorithm_config import AlgorithmConfig
from ray_tpu.rllib.core.learner import LearnerGroup
from ray_tpu.rllib.core.rl_module import make_default_module
from ray_tpu.rllib.env.env_runner_group import (
    DuplicateSampleError,
    EnvRunnerGroup,
)

logger = logging.getLogger(__name__)


class PPOConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.lambda_: float = 0.95
        self.clip_param: float = 0.2
        self.vf_clip_param: float = 10.0
        self.vf_loss_coeff: float = 0.5
        self.entropy_coeff: float = 0.01
        self.lr = 3e-4

    @property
    def algo_class(self):
        return PPO


def make_ppo_loss(clip_param: float = 0.2, vf_clip_param: float = 10.0,
                  vf_loss_coeff: float = 0.5, entropy_coeff: float = 0.01):
    """Clipped-surrogate PPO loss with hyperparameters bound as
    jit-time constants (they never change after config build, so they
    fold into the compiled update instead of riding every batch)."""

    def ppo_loss(module, params, batch):
        import jax
        import jax.numpy as jnp

        logits, values = module.forward_train(params, batch["obs"])
        logp_all = jax.nn.log_softmax(logits, axis=-1)
        actions = batch["actions"].astype(jnp.int32)
        logp = jnp.take_along_axis(logp_all, actions[:, None], axis=-1)[:, 0]
        ratio = jnp.exp(logp - batch["logp"])
        adv = batch["advantages"]
        surrogate = jnp.minimum(
            ratio * adv,
            jnp.clip(ratio, 1 - clip_param, 1 + clip_param) * adv,
        )
        policy_loss = -jnp.mean(surrogate)

        # value loss, clipped to stabilize (reference vf_clip_param)
        vf_err = jnp.clip(
            values - batch["value_targets"], -vf_clip_param, vf_clip_param
        )
        vf_loss = jnp.mean(vf_err**2)

        entropy = -jnp.mean(jnp.sum(jnp.exp(logp_all) * logp_all, axis=-1))
        total = policy_loss + vf_loss_coeff * vf_loss - entropy_coeff * entropy
        metrics = {
            "policy_loss": policy_loss,
            "vf_loss": vf_loss,
            "entropy": entropy,
            "mean_kl": jnp.mean(batch["logp"] - logp),
        }
        return total, metrics

    return ppo_loss


ppo_loss = make_ppo_loss()  # default-hyperparameter loss (tests, docs)


def compute_gae(sample: Dict[str, np.ndarray], gamma: float,
                lambda_: float) -> Tuple[np.ndarray, np.ndarray]:
    """Vectorized GAE over a time-major rollout [T, B].

    Termination zeroes the bootstrap; truncation bootstraps from
    V(final_obs) (`bootstrap_values`) and resets the lambda chain —
    time limits are not failures (reference: the new stack's GAE
    connector bootstraps truncated episodes the same way).
    """
    rewards, values = sample["rewards"], sample["values"]
    terminated = sample["terminated"].astype(np.float32)
    truncated = sample["truncated"].astype(np.float32)
    boot = sample["bootstrap_values"]
    T, B = rewards.shape
    adv = np.zeros((T, B), np.float32)
    next_value = sample["final_value"]
    gae = np.zeros(B, np.float32)
    for t in range(T - 1, -1, -1):
        nonterminal = 1.0 - terminated[t]
        chain = nonterminal * (1.0 - truncated[t])
        next_v = np.where(truncated[t] > 0, boot[t], next_value)
        delta = rewards[t] + gamma * next_v * nonterminal - values[t]
        gae = delta + gamma * lambda_ * chain * gae
        adv[t] = gae
        next_value = values[t]
    targets = adv + values
    return adv, targets


class PPO(Algorithm):
    def setup_components(self):
        cfg = self.config
        self.env_runner_group = EnvRunnerGroup(
            cfg.env, cfg.num_env_runners, cfg.num_envs_per_env_runner,
            cfg.rollout_fragment_length, seed=cfg.seed,
            env_kwargs=cfg.env_kwargs,
            connector=cfg.env_to_module_connector,
            deterministic_replay=cfg.deterministic_replacement,
        )
        spec = self.env_runner_group.env_spec()
        # conv encoder for image obs, fcnet otherwise
        self.module = make_default_module(spec, cfg.model)
        if cfg.num_epochs < 1:
            raise ValueError("num_epochs must be >= 1")
        loss = make_ppo_loss(
            cfg.clip_param, cfg.vf_clip_param, cfg.vf_loss_coeff,
            cfg.entropy_coeff,
        )
        self.learner_group = LearnerGroup(
            self.module, loss, num_learners=cfg.num_learners,
            lr=cfg.lr, grad_clip=cfg.grad_clip, seed=cfg.seed, mesh=cfg.mesh,
            gang_devices=cfg.num_learner_devices,
        )
        self.env_runner_group.sync_weights(
            self.learner_group.get_weights_numpy()
        )
        self._stream_started = False

    # -- shared postprocessing: GAE per rollout, flatten to [N, ...] ---
    def _postprocess(self, samples: List[Dict[str, np.ndarray]]
                     ) -> Dict[str, np.ndarray]:
        cfg = self.config
        obs, actions, logp, adv_l, tgt_l = [], [], [], [], []
        for s in samples:
            a, tg = compute_gae(s, cfg.gamma, cfg.lambda_)
            T, B = s["actions"].shape
            obs.append(s["obs"].reshape(T * B, *s["obs"].shape[2:]))
            actions.append(s["actions"].reshape(-1))
            logp.append(s["logp"].reshape(-1))
            adv_l.append(a.reshape(-1))
            tgt_l.append(tg.reshape(-1))
        advantages = np.concatenate(adv_l)
        advantages = (advantages - advantages.mean()) / (
            advantages.std() + 1e-8
        )
        return {
            "obs": np.concatenate(obs),
            "actions": np.concatenate(actions),
            "logp": np.concatenate(logp),
            "advantages": advantages,
            "value_targets": np.concatenate(tgt_l),
        }

    def _update_epochs(self, batch: Dict[str, np.ndarray],
                       device_metrics: bool = False
                       ) -> Tuple[List[Dict[str, Any]], float]:
        """Minibatch epochs over one flat train batch; returns (metric
        dicts, update wall seconds).  `device_metrics` defers the host
        sync to the end of the pass (the overlap path — the driver gets
        back to collecting envelopes while XLA executes)."""
        cfg = self.config
        n = batch["obs"].shape[0]
        mb = min(cfg.minibatch_size, n)
        n_even = (n // mb) * mb  # static minibatch shape → one compile
        rng = np.random.default_rng(cfg.seed + self.iteration)
        update = (self.learner_group.update_minibatch_device
                  if device_metrics else self.learner_group.update_minibatch)
        acc: List[Dict[str, Any]] = []
        t0 = time.perf_counter()
        for _epoch in range(cfg.num_epochs):
            perm = rng.permutation(n)[:n_even]
            for start in range(0, n_even, mb):
                idx = perm[start:start + mb]
                acc.append(update({k: v[idx] for k, v in batch.items()}))
        if device_metrics:
            acc = [{k: float(v) for k, v in m.items()} for m in acc]
        update_s = time.perf_counter() - t0
        _mdefs.observe("rt_rllib_learner_update_seconds", update_s)
        return acc, update_s

    def training_step(self) -> Dict[str, Any]:
        if self.config.sample_train_overlap:
            return self._training_step_overlap()
        cfg = self.config
        samples = self.env_runner_group.sample(self.module)
        batch = self._postprocess(samples)
        metrics_acc, _update_s = self._update_epochs(batch)

        self.env_runner_group.sync_weights(
            self.learner_group.get_weights_numpy()
        )
        result: Dict[str, Any] = {
            k: float(np.mean([m[k] for m in metrics_acc]))
            for k in metrics_acc[0]
        }
        result["num_env_steps_sampled"] = batch["obs"].shape[0]
        result["num_learner_updates"] = len(metrics_acc)
        self._track_episode_metrics(
            self.env_runner_group.pop_metrics(), result
        )
        return result

    def _collect_pairs(self, block: bool) -> List[Any]:
        """One collection pass -> list of (meta, batch) pairs, on
        whichever plane the config selected: compiled-DAG tensor
        channels (`use_compiled_dag`) or the object-plane ref stream.
        Both record every consumed batch in the exactly-once ledger."""
        group = self.env_runner_group
        cap = 4 * group.num_runners
        if self.config.use_compiled_dag:
            if block:
                return group.collect_channel(max_batches=cap, timeout=120.0)
            return group.collect_channel(max_batches=cap, block=False)
        envelopes = (group.collect(max_batches=cap, timeout=120.0) if block
                     else group.collect(max_batches=cap, block=False))
        pairs = []
        for env in envelopes:
            try:
                pairs.append(group.fetch(env))
            except DuplicateSampleError:
                raise  # accounting bug, not a runner death
            except Exception:
                logger.debug(
                    "overlap payload fetch failed; producer died — "
                    "its replacement resamples", exc_info=True,
                )
        return pairs

    def _training_step_overlap(self) -> Dict[str, Any]:
        """Async sample/train overlap: consume whatever the fleet
        produced during the previous update, top up to train_batch_size
        env steps, update, broadcast non-blocking.  The fleet keeps
        sampling the NEXT epoch the whole time — `sample_wait_s` is the
        only sampling wall-time the learner ever sees.

        With `use_compiled_dag=True` the sample hop and the weights
        broadcast ride shm tensor channels into RESIDENT runner loops —
        zero actor RPCs on the learner round's hot path."""
        cfg = self.config
        group = self.env_runner_group
        if not self._stream_started:
            if cfg.use_compiled_dag:
                group.start_channel_stream(self.module)
            else:
                group.start_ref_stream(
                    self.module,
                    inflight_per_runner=cfg.inflight_rollouts_per_runner,
                )
            self._stream_started = True

        need = cfg.train_batch_size
        metas: List[Dict[str, Any]] = []
        samples: List[Dict[str, np.ndarray]] = []
        steps = 0
        wait_s = 0.0
        # bounded collection: dead producers are replaced in place, but
        # a fleet that is alive-yet-wedged (hung env.step) returns
        # nothing forever — surface that as a failure instead of
        # hanging training_step silently
        deadline = time.monotonic() + 600.0
        # free sweep first: batches that landed while the learner ran
        pairs = self._collect_pairs(block=False)
        while True:
            for meta, b in pairs:
                metas.append(meta)
                samples.append(b)
                steps += int(meta["env_steps"])
            if steps >= need:
                break
            if time.monotonic() > deadline:
                raise RuntimeError(
                    f"overlap sample collection stalled: {steps}/{need} "
                    f"env steps after 600s — the runner fleet is alive "
                    "but not producing (hung envs?)"
                )
            t_w = time.perf_counter()
            pairs = self._collect_pairs(block=True)
            wait_s += time.perf_counter() - t_w

        batch = self._postprocess(samples)
        metrics_acc, update_s = self._update_epochs(
            batch, device_metrics=True
        )
        # non-blocking broadcast: in-flight rollouts stay one version
        # stale; the ratio clip absorbs it
        if cfg.use_compiled_dag:
            group.sync_weights_channel(
                self.learner_group.get_weights_numpy()
            )
        else:
            group.sync_weights_async(
                self.learner_group.get_weights_numpy()
            )

        result: Dict[str, Any] = {
            k: float(np.mean([m[k] for m in metrics_acc]))
            for k in metrics_acc[0]
        }
        sample_busy_s = float(sum(m["sample_s"] for m in metas))
        hidden_s = max(0.0, sample_busy_s - wait_s)
        version = group.weights_version
        result.update({
            "num_env_steps_sampled": steps,
            "num_learner_updates": len(metrics_acc),
            "num_async_batches": len(samples),
            "update_s": update_s,
            "sample_busy_s": sample_busy_s,
            "sample_wait_s": wait_s,
            "overlap_ratio": (hidden_s / sample_busy_s
                              if sample_busy_s > 0 else 0.0),
            "weights_staleness_mean": float(np.mean(
                [version - m["weights_version"] for m in metas]
            )),
        })
        if cfg.use_compiled_dag:
            # episode metrics rode the channel metas (the resident
            # loops occupy the actors; pop_metrics RPCs would queue)
            episodes = [e for m in metas for e in m.get("episodes", [])]
            self._track_episode_metrics(episodes, result)
        else:
            self._track_episode_metrics(group.pop_metrics(), result)
        return result

    def get_state(self) -> Dict[str, Any]:
        return {
            "learner": self.learner_group.get_state(),
            "connector": self.env_runner_group.connector_state(),
            "recent_returns": list(self._recent_returns),
            "iteration": self.iteration,
        }

    def set_state(self, state: Dict[str, Any]):
        self.learner_group.set_state(state["learner"])
        self.env_runner_group.restore_connector_state(
            state.get("connector")
        )
        self._recent_returns = list(state.get("recent_returns", []))
        self.iteration = state.get("iteration", self.iteration)
        self.env_runner_group.sync_weights(
            self.learner_group.get_weights_numpy()
        )

    def stop(self):
        self.env_runner_group.stop()
        self.learner_group.stop()
