"""APPO: asynchronous PPO with V-trace off-policy correction.

Reference: `rllib/algorithms/appo/` (`appo.py`, `appo_learner.py`) and
the IMPALA V-trace math it builds on (`rllib/algorithms/impala/`,
vtrace_* in the learner).  The decisive difference from PPO: rollouts
may be stale relative to the learner (async sampling / many runners),
so advantages are computed with V-trace — importance-weighted TD
corrections with clipped rho/c — instead of GAE against on-policy
values, and the surrogate clips the importance ratio against the
V-trace advantages.

TPU-native split mirrors PPO here: rollout inference is numpy on CPU
actors; the learner's update is one compiled jax program (SPMD mesh or
DDP actors via LearnerGroup).
"""

from __future__ import annotations

from typing import Any, Dict, List, Tuple

import numpy as np

from ray_tpu.rllib.algorithms.algorithm import Algorithm
from ray_tpu.rllib.algorithms.algorithm_config import AlgorithmConfig
from ray_tpu.rllib.core.learner import LearnerGroup
from ray_tpu.rllib.core.rl_module import make_default_module
from ray_tpu.rllib.env.env_runner_group import EnvRunnerGroup


class APPOConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.lr = 5e-4
        self.clip_param: float = 0.3
        self.vf_loss_coeff: float = 0.5
        self.entropy_coeff: float = 0.01
        self.minibatch_size = 256
        self.num_epochs = 1  # APPO default: one pass, fresh data faster
        # V-trace clippings (reference: vtrace rho/c thresholds)
        self.vtrace_clip_rho_threshold: float = 1.0
        self.vtrace_clip_c_threshold: float = 1.0
        # circuit breaker on catastrophic staleness
        self.target_update_frequency: int = 1

    @property
    def algo_class(self):
        return APPO


def make_appo_loss(clip_param: float, vf_loss_coeff: float,
                   entropy_coeff: float):
    """Importance-clipped surrogate against precomputed V-trace
    advantages/targets (reference: `appo_learner.py` surrogate with
    vtrace-adjusted advantages)."""

    def appo_loss(module, params, batch):
        import jax
        import jax.numpy as jnp

        logits, values = module.forward_train(params, batch["obs"])
        logp_all = jax.nn.log_softmax(logits, axis=-1)
        actions = batch["actions"].astype(jnp.int32)
        logp = jnp.take_along_axis(logp_all, actions[:, None], axis=-1)[:, 0]
        ratio = jnp.exp(logp - batch["behavior_logp"])
        adv = batch["advantages"]
        surrogate = jnp.minimum(
            ratio * adv,
            jnp.clip(ratio, 1 - clip_param, 1 + clip_param) * adv,
        )
        policy_loss = -jnp.mean(surrogate)
        vf_loss = jnp.mean((values - batch["value_targets"]) ** 2)
        entropy = -jnp.mean(jnp.sum(jnp.exp(logp_all) * logp_all, axis=-1))
        total = policy_loss + vf_loss_coeff * vf_loss - entropy_coeff * entropy
        return total, {
            "policy_loss": policy_loss,
            "vf_loss": vf_loss,
            "entropy": entropy,
            "mean_is_ratio": jnp.mean(ratio),
        }

    return appo_loss


def compute_vtrace(
    behavior_logp: np.ndarray,  # [T, B] logp of taken actions (rollout)
    target_logp: np.ndarray,  # [T, B] logp under CURRENT policy
    rewards: np.ndarray,  # [T, B]
    values: np.ndarray,  # [T, B] V under current policy at s_t
    final_value: np.ndarray,  # [B] V at s_{T} (bootstrap)
    terminated: np.ndarray,  # [T, B]
    truncated: np.ndarray,  # [T, B]
    bootstrap_values: np.ndarray,  # [T, B] V(final_obs) for truncation
    gamma: float,
    clip_rho: float = 1.0,
    clip_c: float = 1.0,
) -> Tuple[np.ndarray, np.ndarray]:
    """Numpy V-trace (Espeholt et al. 2018, the math the reference's
    vtrace implements): backward recursion

      vs_t = V(s_t) + dt + gamma * c_t * (vs_{t+1} - V(s_{t+1}))
      dt   = rho_t * (r_t + gamma * V(s_{t+1}) - V(s_t))

    with rho/c the clipped importance ratios.  Termination zeroes the
    bootstrap; truncation bootstraps from V(final_obs) and cuts the
    recursion the same way GAE does in the PPO path.
    Returns (pg_advantages, vs_targets), both [T, B].
    """
    T, B = rewards.shape
    rho = np.minimum(np.exp(target_logp - behavior_logp), clip_rho)
    c = np.minimum(np.exp(target_logp - behavior_logp), clip_c)
    vs = np.zeros((T, B), np.float32)
    next_vs_minus_v = np.zeros(B, np.float32)
    next_value = final_value
    for t in range(T - 1, -1, -1):
        nonterminal = 1.0 - terminated[t].astype(np.float32)
        chain = nonterminal * (1.0 - truncated[t].astype(np.float32))
        next_v = np.where(truncated[t], bootstrap_values[t], next_value)
        delta = rho[t] * (rewards[t] + gamma * next_v * nonterminal - values[t])
        vs_minus_v = delta + gamma * c[t] * chain * next_vs_minus_v
        vs[t] = values[t] + vs_minus_v
        next_vs_minus_v = vs_minus_v
        next_value = values[t]
    # pg advantage: rho * (r + gamma * vs_{t+1} - V(s_t))
    vs_next = np.concatenate([vs[1:], final_value[None]], axis=0)
    nonterminal = 1.0 - terminated.astype(np.float32)
    vs_next = np.where(truncated, bootstrap_values, vs_next)
    pg_adv = rho * (rewards + gamma * vs_next * nonterminal - values)
    return pg_adv.astype(np.float32), vs.astype(np.float32)


class APPO(Algorithm):
    def setup_components(self):
        cfg = self.config
        self.env_runner_group = EnvRunnerGroup(
            cfg.env, cfg.num_env_runners, cfg.num_envs_per_env_runner,
            cfg.rollout_fragment_length, seed=cfg.seed,
            env_kwargs=cfg.env_kwargs,
            connector=cfg.env_to_module_connector,
        )
        spec = self.env_runner_group.env_spec()
        self.module = make_default_module(spec, cfg.model)
        loss = make_appo_loss(
            cfg.clip_param, cfg.vf_loss_coeff, cfg.entropy_coeff
        )
        self.learner_group = LearnerGroup(
            self.module, loss, num_learners=cfg.num_learners,
            lr=cfg.lr, grad_clip=cfg.grad_clip, seed=cfg.seed, mesh=cfg.mesh,
        )
        self.env_runner_group.sync_weights(
            self.learner_group.get_weights_numpy()
        )

    def _current_forward(self, weights, obs_tb: np.ndarray):
        """Current-policy logits/values over a [T, B, obs] rollout —
        numpy MLP math, same fast path the runners use."""
        T, B = obs_tb.shape[:2]
        flat = obs_tb.reshape(T * B, *obs_tb.shape[2:])
        logits, values = self.module.forward_numpy(weights, flat)
        return (
            logits.reshape(T, B, -1),
            values.reshape(T, B).astype(np.float32),
        )

    def training_step(self) -> Dict[str, Any]:
        cfg = self.config
        samples = self.env_runner_group.sample(self.module)
        weights = self.learner_group.get_weights_numpy()

        obs_l, act_l, blogp_l, adv_l, tgt_l = [], [], [], [], []
        for s in samples:
            logits, values = self._current_forward(weights, s["obs"])
            logp_all = logits - _logsumexp(logits)
            tgt_logp = np.take_along_axis(
                logp_all, s["actions"][..., None].astype(np.int64), axis=-1
            )[..., 0]
            _, final_v = self.module.forward_numpy(weights, s["final_obs"])
            pg_adv, vs = compute_vtrace(
                behavior_logp=s["logp"],
                target_logp=tgt_logp,
                rewards=s["rewards"],
                values=values,
                final_value=final_v.astype(np.float32),
                terminated=s["terminated"],
                truncated=s["truncated"],
                bootstrap_values=s["bootstrap_values"],
                gamma=cfg.gamma,
                clip_rho=cfg.vtrace_clip_rho_threshold,
                clip_c=cfg.vtrace_clip_c_threshold,
            )
            T, B = s["actions"].shape
            obs_l.append(s["obs"].reshape(T * B, *s["obs"].shape[2:]))
            act_l.append(s["actions"].reshape(-1))
            blogp_l.append(s["logp"].reshape(-1))
            adv_l.append(pg_adv.reshape(-1))
            tgt_l.append(vs.reshape(-1))
        obs = np.concatenate(obs_l)
        actions = np.concatenate(act_l)
        behavior_logp = np.concatenate(blogp_l)
        advantages = np.concatenate(adv_l)
        targets = np.concatenate(tgt_l)
        advantages = (advantages - advantages.mean()) / (
            advantages.std() + 1e-8
        )

        n = obs.shape[0]
        mb = min(cfg.minibatch_size, n)
        n_even = (n // mb) * mb
        rng = np.random.default_rng(cfg.seed + self.iteration)
        metrics_acc: List[Dict[str, float]] = []
        for _epoch in range(cfg.num_epochs):
            perm = rng.permutation(n)[:n_even]
            for start in range(0, n_even, mb):
                idx = perm[start:start + mb]
                metrics_acc.append(self.learner_group.update_minibatch({
                    "obs": obs[idx],
                    "actions": actions[idx],
                    "behavior_logp": behavior_logp[idx],
                    "advantages": advantages[idx],
                    "value_targets": targets[idx],
                }))

        self.env_runner_group.sync_weights(
            self.learner_group.get_weights_numpy()
        )
        result: Dict[str, Any] = {
            k: float(np.mean([m[k] for m in metrics_acc]))
            for k in metrics_acc[0]
        }
        result["num_env_steps_sampled"] = n
        self._track_episode_metrics(
            self.env_runner_group.pop_metrics(), result
        )
        return result

    def get_state(self) -> Dict[str, Any]:
        return {
            "learner": self.learner_group.get_state(),
            "connector": self.env_runner_group.connector_state(),
            "recent_returns": list(self._recent_returns),
            "iteration": self.iteration,
        }

    def set_state(self, state: Dict[str, Any]):
        self.learner_group.set_state(state["learner"])
        self.env_runner_group.restore_connector_state(
            state.get("connector")
        )
        self._recent_returns = list(state.get("recent_returns", []))
        self.iteration = state.get("iteration", self.iteration)
        self.env_runner_group.sync_weights(
            self.learner_group.get_weights_numpy()
        )

    def stop(self):
        self.env_runner_group.stop()
        self.learner_group.stop()


def _logsumexp(logits: np.ndarray) -> np.ndarray:
    m = logits.max(axis=-1, keepdims=True)
    return m + np.log(np.exp(logits - m).sum(axis=-1, keepdims=True))
