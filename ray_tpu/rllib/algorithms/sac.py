"""SAC for discrete action spaces (new API stack).

Reference: `rllib/algorithms/sac/` (`sac.py`, `sac_learner.py` —
continuous there; this is the standard discrete-SAC variant: expected
Q under the full softmax policy replaces the reparameterized sample).
Components: twin Q networks with a polyak-free periodic target sync
(as the reference's discrete path does), softmax actor, and
automatically-tuned entropy temperature (log_alpha is a learned
parameter in the same pytree, so the single compiled learner update
covers actor + critics + alpha).

TD targets are computed OUTSIDE the learner with jitted target-network
forwards (the DQN pattern here): the compiled update depends only on
(obs, actions, td_target), keeping Learner/LearnerGroup unchanged.
"""

from __future__ import annotations

from typing import Any, Dict, List, Tuple

import numpy as np

from ray_tpu.rllib.algorithms.algorithm import Algorithm
from ray_tpu.rllib.algorithms.algorithm_config import AlgorithmConfig
from ray_tpu.rllib.algorithms.dqn import ReplayBuffer, _transitions
from ray_tpu.rllib.core.learner import LearnerGroup
from ray_tpu.rllib.core.rl_module import MLPModule


class SACModule(MLPModule):
    """pi tower = policy logits; twin critics q1/q2 (one Q per action);
    log_alpha rides the pytree so one optimizer updates everything."""

    def init_params(self, rng) -> Dict[str, Any]:
        import jax
        import jax.numpy as jnp

        k_pi, k_q1, k_q2 = jax.random.split(rng, 3)  # independent keys
        return {
            "pi": self.init_tower(k_pi, self.num_actions),
            "q1": self.init_tower(k_q1, self.num_actions),
            "q2": self.init_tower(k_q2, self.num_actions),
            "log_alpha": jnp.zeros(()),
        }

    def forward_train(self, params, obs):
        import jax.numpy as jnp

        from ray_tpu.rllib.core.rl_module import tower_jax

        return tower_jax(params["pi"], obs), jnp.zeros(obs.shape[0])

    def q_values(self, params, obs):
        from ray_tpu.rllib.core.rl_module import tower_jax

        return tower_jax(params["q1"], obs), tower_jax(params["q2"], obs)

    def forward_numpy(self, params_np, obs: np.ndarray):
        from ray_tpu.rllib.core.rl_module import tower_numpy

        return (tower_numpy(params_np["pi"], obs),
                np.zeros(obs.shape[0], np.float32))


class SACConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.lr = 3e-3
        self.buffer_size: int = 50_000
        self.learn_batch_size: int = 128
        self.num_updates_per_iter: int = 32
        self.target_update_freq: int = 1
        #: None -> auto: 0.5 * log(num_actions) (discrete-SAC default)
        self.target_entropy: float = None  # type: ignore[assignment]
        self.num_env_runners = 1
        self.rollout_fragment_length = 32

    @property
    def algo_class(self):
        return SAC


def make_sac_loss(target_entropy: float):
    """Joint actor + twin-critic + temperature loss (discrete SAC)."""

    def sac_loss(module, params, batch):
        import jax
        import jax.numpy as jnp

        obs = batch["obs"]
        actions = batch["actions"].astype(jnp.int32)
        logits, _ = module.forward_train(params, obs)
        logp_all = jax.nn.log_softmax(logits, axis=-1)
        probs = jnp.exp(logp_all)
        alpha = jnp.exp(params["log_alpha"])

        q1, q2 = module.q_values(params, obs)
        q1_a = jnp.take_along_axis(q1, actions[:, None], axis=-1)[:, 0]
        q2_a = jnp.take_along_axis(q2, actions[:, None], axis=-1)[:, 0]
        y = batch["td_target"]
        critic_loss = jnp.mean((q1_a - y) ** 2) + jnp.mean((q2_a - y) ** 2)

        # actor: minimize E_pi[alpha*logpi - minQ] (critics detached)
        min_q = jax.lax.stop_gradient(jnp.minimum(q1, q2))
        actor_loss = jnp.mean(jnp.sum(
            probs * (jax.lax.stop_gradient(alpha) * logp_all - min_q),
            axis=-1,
        ))

        # temperature: entropy toward the target (policy detached)
        entropy = -jnp.sum(
            jax.lax.stop_gradient(probs * logp_all), axis=-1
        )
        alpha_loss = jnp.mean(
            params["log_alpha"] * (entropy - target_entropy)
        )

        total = critic_loss + actor_loss + alpha_loss
        return total, {
            "critic_loss": critic_loss,
            "actor_loss": actor_loss,
            "alpha": alpha,
            "entropy": jnp.mean(entropy),
        }

    return sac_loss


class SAC(Algorithm):
    def setup_components(self):
        import jax

        from ray_tpu.rllib.env.env_runner_group import EnvRunnerGroup

        cfg = self.config
        self.env_runner_group = EnvRunnerGroup(
            cfg.env, cfg.num_env_runners, cfg.num_envs_per_env_runner,
            cfg.rollout_fragment_length, seed=cfg.seed,
            env_kwargs=cfg.env_kwargs,
            connector=cfg.env_to_module_connector,
        )
        spec = self.env_runner_group.env_spec()
        self.module = SACModule(
            spec["observation_size"], spec["num_actions"],
            hidden=tuple(cfg.model.get("hidden", (64, 64))),
        )
        if cfg.target_entropy is None:
            cfg.target_entropy = 0.5 * float(np.log(spec["num_actions"]))
        self.learner_group = LearnerGroup(
            self.module, make_sac_loss(cfg.target_entropy),
            num_learners=cfg.num_learners, lr=cfg.lr,
            grad_clip=cfg.grad_clip, seed=cfg.seed, mesh=cfg.mesh,
        )
        self.buffer = ReplayBuffer(cfg.buffer_size, spec["observation_size"])
        self.target_params = self.learner_group.get_weights_numpy()
        self._rng = np.random.default_rng(cfg.seed)

        def _target_terms(target_p, online_p, next_obs):
            import jax.numpy as jnp

            logits, _ = self.module.forward_train(online_p, next_obs)
            logp_all = jax.nn.log_softmax(logits, axis=-1)
            probs = jnp.exp(logp_all)
            tq1, tq2 = self.module.q_values(target_p, next_obs)
            min_q = jnp.minimum(tq1, tq2)
            alpha = jnp.exp(online_p["log_alpha"])
            return jnp.sum(probs * (min_q - alpha * logp_all), axis=-1)

        self._target_terms = jax.jit(_target_terms)
        self.env_runner_group.sync_weights(
            self.learner_group.get_weights_numpy()
        )

    def _td_targets(self, replay, online) -> np.ndarray:
        cfg = self.config
        v_next = np.asarray(self._target_terms(
            self.target_params, online, replay["next_obs"]
        ))
        nonterminal = 1.0 - replay["terminated"].astype(np.float32)
        return (replay["rewards"] + cfg.gamma * v_next * nonterminal).astype(
            np.float32
        )

    def training_step(self) -> Dict[str, Any]:
        cfg = self.config
        samples = self.env_runner_group.sample(self.module)
        steps = 0
        for s in samples:
            obs, actions, rewards, next_obs, done = _transitions(s)
            self.buffer.add_batch(obs, actions, rewards, next_obs, done)
            steps += len(actions)

        metrics_acc: List[Dict[str, float]] = []
        if len(self.buffer) >= cfg.learn_batch_size:
            online = self.learner_group.get_weights_numpy()
            for _ in range(cfg.num_updates_per_iter):
                replay = self.buffer.sample(cfg.learn_batch_size, self._rng)
                batch = {
                    "obs": replay["obs"],
                    "actions": replay["actions"],
                    "td_target": self._td_targets(replay, online),
                }
                metrics_acc.append(self.learner_group.update_minibatch(batch))
        if (self.iteration + 1) % cfg.target_update_freq == 0:
            self.target_params = self.learner_group.get_weights_numpy()
        self.env_runner_group.sync_weights(
            self.learner_group.get_weights_numpy()
        )
        result: Dict[str, Any] = {
            k: float(np.mean([m[k] for m in metrics_acc]))
            for k in (metrics_acc[0] if metrics_acc else {})
        }
        result["num_env_steps_sampled"] = steps
        result["replay_buffer_size"] = len(self.buffer)
        self._track_episode_metrics(
            self.env_runner_group.pop_metrics(), result
        )
        return result

    def get_state(self) -> Dict[str, Any]:
        return {
            "learner": self.learner_group.get_state(),
            "connector": self.env_runner_group.connector_state(),
            "target_params": self.target_params,
            "recent_returns": list(self._recent_returns),
            "iteration": self.iteration,
        }

    def set_state(self, state: Dict[str, Any]):
        self.learner_group.set_state(state["learner"])
        self.env_runner_group.restore_connector_state(
            state.get("connector")
        )
        self.target_params = state["target_params"]
        self._recent_returns = list(state.get("recent_returns", []))
        self.iteration = state.get("iteration", self.iteration)
        self.env_runner_group.sync_weights(
            self.learner_group.get_weights_numpy()
        )

    def stop(self):
        self.env_runner_group.stop()
        self.learner_group.stop()
