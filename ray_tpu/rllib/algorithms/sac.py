"""SAC for discrete AND continuous action spaces (new API stack).

Reference: `rllib/algorithms/sac/` (`sac.py`, `sac_learner.py`).
Both variants share the recipe: twin Q networks with a polyak-free
periodic target sync, automatically-tuned entropy temperature
(log_alpha is a learned parameter in the same pytree, so the single
compiled learner update covers actor + critics + alpha).  Discrete
envs get the standard discrete-SAC variant (expected Q under the full
softmax policy); continuous envs (`VectorEnv.continuous`) get the
original SAC: tanh-squashed reparameterized Gaussian actor and
Q(s, a) critics (`ContinuousSACModule`).

TD targets are computed OUTSIDE the learner with jitted target-network
forwards (the DQN pattern here): the compiled update depends only on
(obs, actions, td_target), keeping Learner/LearnerGroup unchanged.
"""

from __future__ import annotations

from typing import Any, Dict, List, Tuple

import numpy as np

from ray_tpu.rllib.algorithms.algorithm import Algorithm
from ray_tpu.rllib.algorithms.algorithm_config import AlgorithmConfig
from ray_tpu.rllib.algorithms.dqn import ReplayBuffer, _transitions
from ray_tpu.rllib.core.learner import LearnerGroup
from ray_tpu.rllib.core.rl_module import MLPModule, require_flat_obs


class SACModule(MLPModule):
    """pi tower = policy logits; twin critics q1/q2 (one Q per action);
    log_alpha rides the pytree so one optimizer updates everything."""

    def init_params(self, rng) -> Dict[str, Any]:
        import jax
        import jax.numpy as jnp

        k_pi, k_q1, k_q2 = jax.random.split(rng, 3)  # independent keys
        return {
            "pi": self.init_tower(k_pi, self.num_actions),
            "q1": self.init_tower(k_q1, self.num_actions),
            "q2": self.init_tower(k_q2, self.num_actions),
            "log_alpha": jnp.zeros(()),
        }

    def forward_train(self, params, obs):
        import jax.numpy as jnp

        from ray_tpu.rllib.core.rl_module import tower_jax

        return tower_jax(params["pi"], obs), jnp.zeros(obs.shape[0])

    def q_values(self, params, obs):
        from ray_tpu.rllib.core.rl_module import tower_jax

        return tower_jax(params["q1"], obs), tower_jax(params["q2"], obs)

    def forward_numpy(self, params_np, obs: np.ndarray):
        from ray_tpu.rllib.core.rl_module import tower_numpy

        return (tower_numpy(params_np["pi"], obs),
                np.zeros(obs.shape[0], np.float32))


LOG_STD_MIN, LOG_STD_MAX = -5.0, 2.0


class ContinuousSACModule(MLPModule):
    """Squashed-Gaussian actor + twin state-action critics (reference:
    `rllib/algorithms/sac/sac_learner.py` continuous path, matching the
    original SAC: tanh-squashed reparameterized policy, Q(s, a) MLPs).

    Actions live in [-1, 1]^A at the module boundary; the EnvRunner
    rescales to the env's bounds.  The pi tower outputs (mu, log_std);
    q towers take concat(obs, action).
    """

    def __init__(self, observation_size: int, action_dim: int,
                 hidden=(64, 64)):
        # num_actions doubles as the pi tower's output size (mu+logstd)
        super().__init__(observation_size, 2 * action_dim, hidden=hidden)
        self.action_dim = action_dim

    def init_params(self, rng) -> Dict[str, Any]:
        import jax
        import jax.numpy as jnp

        k_pi, k_q1, k_q2 = jax.random.split(rng, 3)
        q_in = self.observation_size + self.action_dim
        q_tower = MLPModule(q_in, 1, hidden=self.hidden)
        return {
            "pi": self.init_tower(k_pi, 2 * self.action_dim),
            "q1": q_tower.init_tower(k_q1, 1),
            "q2": q_tower.init_tower(k_q2, 1),
            "log_alpha": jnp.zeros(()),
        }

    # -- jax -----------------------------------------------------------
    def actor(self, params, obs):
        import jax.numpy as jnp

        from ray_tpu.rllib.core.rl_module import tower_jax

        out = tower_jax(params["pi"], obs)
        mu, log_std = jnp.split(out, 2, axis=-1)
        return mu, jnp.clip(log_std, LOG_STD_MIN, LOG_STD_MAX)

    def q_values(self, params, obs, actions):
        import jax.numpy as jnp

        from ray_tpu.rllib.core.rl_module import tower_jax

        sa = jnp.concatenate([obs, actions], axis=-1)
        return (tower_jax(params["q1"], sa)[..., 0],
                tower_jax(params["q2"], sa)[..., 0])

    def sample_squashed(self, params, obs, noise):
        """Reparameterized tanh-Gaussian sample + its log-prob (the
        noise is standard normal, drawn OUTSIDE the jitted loss so the
        compiled update stays a pure function of the batch)."""
        import jax.numpy as jnp

        mu, log_std = self.actor(params, obs)
        std = jnp.exp(log_std)
        pre = mu + std * noise
        a = jnp.tanh(pre)
        logp = jnp.sum(
            -0.5 * noise**2 - log_std - 0.5 * jnp.log(2 * jnp.pi)
            - jnp.log(1.0 - a**2 + 1e-6),
            axis=-1,
        )
        return a, logp

    def forward_train(self, params, obs):
        import jax.numpy as jnp

        mu, _ = self.actor(params, obs)
        return mu, jnp.zeros(obs.shape[0])

    # -- numpy (env runners) ------------------------------------------
    def select_actions_numpy(self, params_np, obs, rng, explore):
        from ray_tpu.rllib.core.rl_module import tower_numpy

        out = tower_numpy(params_np["pi"], obs)
        mu, log_std = np.split(out, 2, axis=-1)
        if explore is False:
            a = np.tanh(mu)
            logp = np.zeros(a.shape[0], np.float32)
        else:
            log_std = np.clip(log_std, LOG_STD_MIN, LOG_STD_MAX)
            std = np.exp(log_std)
            noise = rng.standard_normal(mu.shape).astype(np.float32)
            pre = mu + std * noise
            a = np.tanh(pre)
            logp = np.sum(
                -0.5 * noise**2 - log_std - 0.5 * np.log(2 * np.pi)
                - np.log(1.0 - a**2 + 1e-6),
                axis=-1,
            ).astype(np.float32)
        return (a.astype(np.float32), logp,
                np.zeros(a.shape[0], np.float32))

    def forward_numpy(self, params_np, obs: np.ndarray):
        from ray_tpu.rllib.core.rl_module import tower_numpy

        out = tower_numpy(params_np["pi"], obs)
        mu, _ = np.split(out, 2, axis=-1)
        return mu, np.zeros(obs.shape[0], np.float32)


def make_continuous_sac_loss(target_entropy: float):
    """Joint actor + twin-critic + temperature loss, continuous SAC.
    `batch["noise"]` carries the reparameterization draw."""

    def sac_loss(module, params, batch):
        import jax
        import jax.numpy as jnp

        obs = batch["obs"]
        alpha = jnp.exp(params["log_alpha"])

        # critics toward externally computed TD targets
        q1_a, q2_a = module.q_values(params, obs, batch["actions"])
        y = batch["td_target"]
        critic_loss = jnp.mean((q1_a - y) ** 2) + jnp.mean((q2_a - y) ** 2)

        # actor: reparameterized sample, critics detached
        a_pi, logp = module.sample_squashed(params, obs, batch["noise"])
        q1_pi, q2_pi = module.q_values(
            jax.lax.stop_gradient(params), obs, a_pi
        )
        min_q = jnp.minimum(q1_pi, q2_pi)
        actor_loss = jnp.mean(
            jax.lax.stop_gradient(alpha) * logp - min_q
        )

        # temperature toward the entropy target (policy detached)
        logp_sg = jax.lax.stop_gradient(logp)
        alpha_loss = jnp.mean(
            params["log_alpha"] * (-logp_sg - target_entropy)
        )

        total = critic_loss + actor_loss + alpha_loss
        return total, {
            "critic_loss": critic_loss,
            "actor_loss": actor_loss,
            "alpha": alpha,
            "entropy": -jnp.mean(logp_sg),
        }

    return sac_loss


class SACConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.lr = 3e-3
        self.buffer_size: int = 50_000
        self.learn_batch_size: int = 128
        self.num_updates_per_iter: int = 32
        self.target_update_freq: int = 1
        #: None -> auto: 0.5 * log(num_actions) (discrete-SAC default)
        self.target_entropy: float = None  # type: ignore[assignment]
        self.num_env_runners = 1
        self.rollout_fragment_length = 32

    @property
    def algo_class(self):
        return SAC


def make_sac_loss(target_entropy: float):
    """Joint actor + twin-critic + temperature loss (discrete SAC)."""

    def sac_loss(module, params, batch):
        import jax
        import jax.numpy as jnp

        obs = batch["obs"]
        actions = batch["actions"].astype(jnp.int32)
        logits, _ = module.forward_train(params, obs)
        logp_all = jax.nn.log_softmax(logits, axis=-1)
        probs = jnp.exp(logp_all)
        alpha = jnp.exp(params["log_alpha"])

        q1, q2 = module.q_values(params, obs)
        q1_a = jnp.take_along_axis(q1, actions[:, None], axis=-1)[:, 0]
        q2_a = jnp.take_along_axis(q2, actions[:, None], axis=-1)[:, 0]
        y = batch["td_target"]
        critic_loss = jnp.mean((q1_a - y) ** 2) + jnp.mean((q2_a - y) ** 2)

        # actor: minimize E_pi[alpha*logpi - minQ] (critics detached)
        min_q = jax.lax.stop_gradient(jnp.minimum(q1, q2))
        actor_loss = jnp.mean(jnp.sum(
            probs * (jax.lax.stop_gradient(alpha) * logp_all - min_q),
            axis=-1,
        ))

        # temperature: entropy toward the target (policy detached)
        entropy = -jnp.sum(
            jax.lax.stop_gradient(probs * logp_all), axis=-1
        )
        alpha_loss = jnp.mean(
            params["log_alpha"] * (entropy - target_entropy)
        )

        total = critic_loss + actor_loss + alpha_loss
        return total, {
            "critic_loss": critic_loss,
            "actor_loss": actor_loss,
            "alpha": alpha,
            "entropy": jnp.mean(entropy),
        }

    return sac_loss


class SAC(Algorithm):
    def setup_components(self):
        import jax

        from ray_tpu.rllib.env.env_runner_group import EnvRunnerGroup

        cfg = self.config
        self.env_runner_group = EnvRunnerGroup(
            cfg.env, cfg.num_env_runners, cfg.num_envs_per_env_runner,
            cfg.rollout_fragment_length, seed=cfg.seed,
            env_kwargs=cfg.env_kwargs,
            connector=cfg.env_to_module_connector,
        )
        spec = self.env_runner_group.env_spec()
        require_flat_obs(spec, "SAC")
        self._continuous = spec["continuous"]
        hidden = tuple(cfg.model.get("hidden", (64, 64)))
        if self._continuous:
            action_dim = spec["action_dim"]
            self.module = ContinuousSACModule(
                spec["observation_size"], action_dim, hidden=hidden
            )
            if cfg.target_entropy is None:
                # the continuous-SAC convention: -|A|
                cfg.target_entropy = -float(action_dim)
            loss = make_continuous_sac_loss(cfg.target_entropy)
            self.buffer = ReplayBuffer(
                cfg.buffer_size, spec["observation_size"],
                action_shape=(action_dim,), action_dtype=np.float32,
            )
        else:
            self.module = SACModule(
                spec["observation_size"], spec["num_actions"],
                hidden=hidden,
            )
            if cfg.target_entropy is None:
                cfg.target_entropy = 0.5 * float(
                    np.log(spec["num_actions"])
                )
            loss = make_sac_loss(cfg.target_entropy)
            self.buffer = ReplayBuffer(
                cfg.buffer_size, spec["observation_size"]
            )
        self.learner_group = LearnerGroup(
            self.module, loss,
            num_learners=cfg.num_learners, lr=cfg.lr,
            grad_clip=cfg.grad_clip, seed=cfg.seed, mesh=cfg.mesh,
        )
        self.target_params = self.learner_group.get_weights_numpy()
        self._rng = np.random.default_rng(cfg.seed)

        if self._continuous:
            def _target_terms(target_p, online_p, next_obs, noise):
                import jax.numpy as jnp

                a2, logp2 = self.module.sample_squashed(
                    online_p, next_obs, noise
                )
                tq1, tq2 = self.module.q_values(target_p, next_obs, a2)
                alpha = jnp.exp(online_p["log_alpha"])
                return jnp.minimum(tq1, tq2) - alpha * logp2
        else:
            def _target_terms(target_p, online_p, next_obs):
                import jax.numpy as jnp

                logits, _ = self.module.forward_train(online_p, next_obs)
                logp_all = jax.nn.log_softmax(logits, axis=-1)
                probs = jnp.exp(logp_all)
                tq1, tq2 = self.module.q_values(target_p, next_obs)
                min_q = jnp.minimum(tq1, tq2)
                alpha = jnp.exp(online_p["log_alpha"])
                return jnp.sum(probs * (min_q - alpha * logp_all), axis=-1)

        self._target_terms = jax.jit(_target_terms)
        self.env_runner_group.sync_weights(
            self.learner_group.get_weights_numpy()
        )

    def _td_targets(self, replay, online) -> np.ndarray:
        cfg = self.config
        if self._continuous:
            noise = self._rng.standard_normal(
                replay["actions"].shape
            ).astype(np.float32)
            v_next = np.asarray(self._target_terms(
                self.target_params, online, replay["next_obs"], noise
            ))
        else:
            v_next = np.asarray(self._target_terms(
                self.target_params, online, replay["next_obs"]
            ))
        nonterminal = 1.0 - replay["terminated"].astype(np.float32)
        return (replay["rewards"] + cfg.gamma * v_next * nonterminal).astype(
            np.float32
        )

    def training_step(self) -> Dict[str, Any]:
        cfg = self.config
        samples = self.env_runner_group.sample(self.module)
        steps = 0
        for s in samples:
            obs, actions, rewards, next_obs, done = _transitions(s)
            self.buffer.add_batch(obs, actions, rewards, next_obs, done)
            steps += len(actions)

        metrics_acc: List[Dict[str, float]] = []
        if len(self.buffer) >= cfg.learn_batch_size:
            online = self.learner_group.get_weights_numpy()
            for _ in range(cfg.num_updates_per_iter):
                replay = self.buffer.sample(cfg.learn_batch_size, self._rng)
                batch = {
                    "obs": replay["obs"],
                    "actions": replay["actions"],
                    "td_target": self._td_targets(replay, online),
                }
                if self._continuous:
                    batch["noise"] = self._rng.standard_normal(
                        replay["actions"].shape
                    ).astype(np.float32)
                metrics_acc.append(self.learner_group.update_minibatch(batch))
        if (self.iteration + 1) % cfg.target_update_freq == 0:
            self.target_params = self.learner_group.get_weights_numpy()
        self.env_runner_group.sync_weights(
            self.learner_group.get_weights_numpy()
        )
        result: Dict[str, Any] = {
            k: float(np.mean([m[k] for m in metrics_acc]))
            for k in (metrics_acc[0] if metrics_acc else {})
        }
        result["num_env_steps_sampled"] = steps
        result["replay_buffer_size"] = len(self.buffer)
        self._track_episode_metrics(
            self.env_runner_group.pop_metrics(), result
        )
        return result

    def get_state(self) -> Dict[str, Any]:
        return {
            "learner": self.learner_group.get_state(),
            "connector": self.env_runner_group.connector_state(),
            "target_params": self.target_params,
            "recent_returns": list(self._recent_returns),
            "iteration": self.iteration,
        }

    def set_state(self, state: Dict[str, Any]):
        self.learner_group.set_state(state["learner"])
        self.env_runner_group.restore_connector_state(
            state.get("connector")
        )
        self.target_params = state["target_params"]
        self._recent_returns = list(state.get("recent_returns", []))
        self.iteration = state.get("iteration", self.iteration)
        self.env_runner_group.sync_weights(
            self.learner_group.get_weights_numpy()
        )

    def stop(self):
        self.env_runner_group.stop()
        self.learner_group.stop()
