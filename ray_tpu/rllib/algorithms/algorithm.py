"""Algorithm: the outer training loop object.

Reference: `rllib/algorithms/algorithm.py` (`step():881`) — an Algorithm
is a Tune Trainable whose step() runs one training iteration (sample →
learn → sync), and which checkpoints its learner + config state
(reference: `Checkpointable`, `rllib/utils/checkpoints.py`).
"""

from __future__ import annotations

import json
import os
import pickle
import time
from typing import Any, Dict, List, Optional

import numpy as np

from ray_tpu.rllib.algorithms.algorithm_config import AlgorithmConfig
from ray_tpu.tune.trainable import Trainable


class Algorithm(Trainable):
    """Subclasses implement setup_components() and training_step()."""

    config: AlgorithmConfig

    def __init__(self, config: AlgorithmConfig, trial_dir: str = ""):
        self._algo_config = config
        self._recent_returns: List[float] = []
        # Trainable.__init__ calls self.setup(...)
        super().__init__({}, trial_dir or "/tmp/ray_tpu_rllib")

    def setup(self, _config: Dict[str, Any]):
        self.config = self._algo_config
        self.setup_components()

    def setup_components(self):
        raise NotImplementedError

    def training_step(self) -> Dict[str, Any]:
        raise NotImplementedError

    # -- Trainable contract -------------------------------------------
    def step(self) -> Dict[str, Any]:
        t0 = time.time()
        result = self.training_step()
        result.setdefault("time_this_iter_s", time.time() - t0)
        return result

    def train(self) -> Dict[str, Any]:
        return super().train()

    def _track_episode_metrics(self, episodes: List[Dict[str, float]],
                               result: Dict[str, Any]):
        for ep in episodes:
            self._recent_returns.append(ep["episode_return"])
        self._recent_returns = self._recent_returns[-100:]
        if self._recent_returns:
            result["episode_return_mean"] = float(
                np.mean(self._recent_returns)
            )
            result["num_episodes"] = len(episodes)

    # -- checkpointing (reference: Checkpointable mixin) ---------------
    def save_checkpoint(self, checkpoint_dir: str) -> Optional[Dict]:
        state = self.get_state()
        with open(os.path.join(checkpoint_dir, "algorithm_state.pkl"), "wb") as f:
            pickle.dump(state, f)
        return None

    def load_checkpoint(self, checkpoint) -> None:
        path = checkpoint if isinstance(checkpoint, str) else None
        if path is None:
            return
        from ray_tpu.core import serialization

        with open(os.path.join(path, "algorithm_state.pkl"), "rb") as f:
            # local checkpoint, but decode still routes through the
            # audited unpickle chokepoint
            self.set_state(serialization.loads(f.read()))

    def get_state(self) -> Dict[str, Any]:
        raise NotImplementedError

    def set_state(self, state: Dict[str, Any]):
        raise NotImplementedError

    def stop(self):
        pass

    def cleanup(self):
        self.stop()
