"""DQN on the new API stack (off-policy, replay buffer, target network).

Reference: `rllib/algorithms/dqn/` (`dqn.py`, `dqn_rainbow_learner.py`)
— reduced to the double-DQN core: epsilon-greedy rollouts feed a uniform
replay buffer; each training iteration runs K gradient steps on replayed
minibatches against a periodically-synced target network.

TD targets are computed OUTSIDE the learner with a jitted target-network
forward: the learner's compiled update then depends only on
(obs, actions, td_target), which keeps the same Learner/LearnerGroup
machinery as PPO working unchanged (including DDP sharding — targets
are per-row data, not parameters).
"""

from __future__ import annotations

from typing import Any, Dict, List, Tuple

import numpy as np

from ray_tpu.rllib.algorithms.algorithm import Algorithm
from ray_tpu.rllib.algorithms.algorithm_config import AlgorithmConfig
from ray_tpu.rllib.core.learner import LearnerGroup
from ray_tpu.rllib.core.rl_module import (
    MLPModule, require_discrete_actions, require_flat_obs,
)


class QMLPModule(MLPModule):
    """Q-network: the 'pi' tower outputs Q-values per action (the value
    tower is unused).  Epsilon-greedy exploration lives here so env
    runners stay generic (env_runner.py select_actions_numpy hook)."""

    def select_actions_numpy(self, params_np, obs: np.ndarray, rng,
                             explore) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        q, _ = self.forward_numpy(params_np, obs)
        greedy = q.argmax(axis=-1)
        eps = float(explore or 0.0)
        B = obs.shape[0]
        random_a = rng.integers(0, self.num_actions, B)
        take_random = rng.random(B) < eps
        actions = np.where(take_random, random_a, greedy)
        zeros = np.zeros(B, np.float32)
        return actions, zeros, zeros


class DQNConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.lr = 1e-3
        self.buffer_size: int = 50_000
        self.learn_batch_size: int = 64
        self.num_updates_per_iter: int = 32
        self.target_update_freq: int = 2  # iterations between target syncs
        self.epsilon_start: float = 1.0
        self.epsilon_end: float = 0.05
        self.epsilon_decay_iters: int = 30
        self.double_q: bool = True
        self.num_env_runners = 1
        self.rollout_fragment_length = 32

    @property
    def algo_class(self):
        return DQN


def make_dqn_loss():
    """Huber TD loss against precomputed targets."""

    def dqn_loss(module, params, batch):
        import jax.numpy as jnp

        q, _ = module.forward_train(params, batch["obs"])
        qa = jnp.take_along_axis(
            q, batch["actions"].astype(jnp.int32)[:, None], axis=-1
        )[:, 0]
        err = qa - batch["td_target"]
        huber = jnp.where(
            jnp.abs(err) <= 1.0, 0.5 * err**2, jnp.abs(err) - 0.5
        )
        loss = jnp.mean(huber)
        return loss, {"td_error_mean": jnp.mean(jnp.abs(err)),
                      "q_mean": jnp.mean(qa)}

    return dqn_loss


class ReplayBuffer:
    """Uniform ring buffer of transitions (reference:
    `rllib/utils/replay_buffers/`)."""

    def __init__(self, capacity: int, obs_dim: int, *,
                 action_shape: tuple = (), action_dtype=np.int32):
        self.capacity = capacity
        self.obs = np.zeros((capacity, obs_dim), np.float32)
        self.next_obs = np.zeros((capacity, obs_dim), np.float32)
        self.actions = np.zeros((capacity, *action_shape), action_dtype)
        self.rewards = np.zeros(capacity, np.float32)
        self.terminated = np.zeros(capacity, np.bool_)
        self._next = 0
        self._size = 0

    def __len__(self):
        return self._size

    def add_batch(self, obs, actions, rewards, next_obs, terminated):
        n = obs.shape[0]
        idx = (self._next + np.arange(n)) % self.capacity
        self.obs[idx] = obs
        self.next_obs[idx] = next_obs
        self.actions[idx] = actions
        self.rewards[idx] = rewards
        self.terminated[idx] = terminated
        self._next = int((self._next + n) % self.capacity)
        self._size = int(min(self._size + n, self.capacity))

    def sample(self, n: int, rng) -> Dict[str, np.ndarray]:
        idx = rng.integers(0, self._size, n)
        return {
            "obs": self.obs[idx],
            "actions": self.actions[idx],
            "rewards": self.rewards[idx],
            "next_obs": self.next_obs[idx],
            "terminated": self.terminated[idx],
        }


def _transitions(sample: Dict[str, np.ndarray]):
    """Rollout [T, B] arrays -> flat (s, a, r, s', term) transitions.
    s' at the rollout edge comes from final_obs; transitions that ended
    in auto-reset still carry terminated correctly (s' unused when
    terminal).  Truncated steps are treated as terminal (standard DQN
    simplification; the Q bootstrap error is bounded by gamma*Qmax)."""
    T, B = sample["actions"].shape[:2]  # [T,B] or [T,B,A] (continuous)
    obs = sample["obs"]
    next_obs = np.concatenate(
        [obs[1:], sample["final_obs"][None]], axis=0
    )
    done = sample["terminated"] | sample["truncated"]
    flat = lambda x: x.reshape(T * B, *x.shape[2:])
    return (
        flat(obs), flat(sample["actions"]), flat(sample["rewards"]),
        flat(next_obs), flat(done),
    )


class DQN(Algorithm):
    def setup_components(self):
        import jax

        from ray_tpu.rllib.env.env_runner_group import EnvRunnerGroup

        cfg = self.config
        self.env_runner_group = EnvRunnerGroup(
            cfg.env, cfg.num_env_runners, cfg.num_envs_per_env_runner,
            cfg.rollout_fragment_length, seed=cfg.seed,
            env_kwargs=cfg.env_kwargs,
            connector=cfg.env_to_module_connector,
        )
        spec = self.env_runner_group.env_spec()
        require_flat_obs(spec, "DQN")
        require_discrete_actions(spec, "DQN")
        self.module = QMLPModule(
            spec["observation_size"], spec["num_actions"],
            hidden=tuple(cfg.model.get("hidden", (64, 64))),
        )
        self.learner_group = LearnerGroup(
            self.module, make_dqn_loss(), num_learners=cfg.num_learners,
            lr=cfg.lr, grad_clip=cfg.grad_clip, seed=cfg.seed, mesh=cfg.mesh,
        )
        self.buffer = ReplayBuffer(cfg.buffer_size, spec["observation_size"])
        self.target_params = self.learner_group.get_weights_numpy()
        self._rng = np.random.default_rng(cfg.seed)
        self._target_q = jax.jit(
            lambda p, o: self.module.forward_train(p, o)[0]
        )
        self.env_runner_group.sync_weights(
            self.learner_group.get_weights_numpy()
        )

    def _epsilon(self) -> float:
        cfg = self.config
        frac = min(1.0, self.iteration / max(1, cfg.epsilon_decay_iters))
        return cfg.epsilon_start + frac * (cfg.epsilon_end - cfg.epsilon_start)

    def _td_targets(self, batch: Dict[str, np.ndarray],
                    online_params=None) -> np.ndarray:
        cfg = self.config
        q_next_target = np.asarray(
            self._target_q(self.target_params, batch["next_obs"])
        )
        if cfg.double_q:
            online = (
                online_params
                if online_params is not None
                else self.learner_group.get_weights_numpy()
            )
            q_next_online = np.asarray(
                self._target_q(online, batch["next_obs"])
            )
            best = q_next_online.argmax(axis=-1)
            q_next = np.take_along_axis(
                q_next_target, best[:, None], axis=-1
            )[:, 0]
        else:
            q_next = q_next_target.max(axis=-1)
        nonterminal = 1.0 - batch["terminated"].astype(np.float32)
        return (batch["rewards"] + cfg.gamma * q_next * nonterminal).astype(
            np.float32
        )

    def training_step(self) -> Dict[str, Any]:
        cfg = self.config
        eps = self._epsilon()
        samples = self.env_runner_group.sample(self.module, explore=eps)
        steps = 0
        for s in samples:
            obs, actions, rewards, next_obs, done = _transitions(s)
            self.buffer.add_batch(obs, actions, rewards, next_obs, done)
            steps += len(actions)

        metrics_acc: List[Dict[str, float]] = []
        if len(self.buffer) >= cfg.learn_batch_size:
            # one online-weights fetch per iteration: double-Q argmax
            # tolerates that staleness (same as the runner sync), and
            # per-minibatch fetches would serialize full-weight
            # transfers in the DDP path
            online = self.learner_group.get_weights_numpy()
            for _ in range(cfg.num_updates_per_iter):
                replay = self.buffer.sample(cfg.learn_batch_size, self._rng)
                batch = {
                    "obs": replay["obs"],
                    "actions": replay["actions"],
                    "td_target": self._td_targets(replay, online),
                }
                metrics_acc.append(self.learner_group.update_minibatch(batch))
        if (self.iteration + 1) % cfg.target_update_freq == 0:
            self.target_params = self.learner_group.get_weights_numpy()
        self.env_runner_group.sync_weights(
            self.learner_group.get_weights_numpy()
        )
        result: Dict[str, Any] = {
            k: float(np.mean([m[k] for m in metrics_acc]))
            for k in (metrics_acc[0] if metrics_acc else {})
        }
        result["epsilon"] = eps
        result["num_env_steps_sampled"] = steps
        result["replay_buffer_size"] = len(self.buffer)
        self._track_episode_metrics(
            self.env_runner_group.pop_metrics(), result
        )
        return result

    def get_state(self) -> Dict[str, Any]:
        return {
            "learner": self.learner_group.get_state(),
            "connector": self.env_runner_group.connector_state(),
            "target_params": self.target_params,
            "buffer": self.buffer,
            "rng": self._rng,
            "recent_returns": list(self._recent_returns),
            "iteration": self.iteration,
        }

    def set_state(self, state: Dict[str, Any]):
        self.learner_group.set_state(state["learner"])
        self.env_runner_group.restore_connector_state(
            state.get("connector")
        )
        self.target_params = state["target_params"]
        if "buffer" in state:
            self.buffer = state["buffer"]
        if "rng" in state:
            self._rng = state["rng"]
        self._recent_returns = list(state.get("recent_returns", []))
        self.iteration = state.get("iteration", self.iteration)
        self.env_runner_group.sync_weights(
            self.learner_group.get_weights_numpy()
        )

    def stop(self):
        self.env_runner_group.stop()
        self.learner_group.stop()
