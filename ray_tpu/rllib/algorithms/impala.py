"""IMPALA: importance-weighted actor-learner architecture.

Reference: `rllib/algorithms/impala/impala.py` (+ the V-trace math in
its learner, Espeholt et al. 2018).  The architectural point — and what
separates this from the sync PPO/APPO loops here — is ASYNC sampling:
env runners sample continuously with pipelined in-flight rollouts; the
learner consumes whatever batches are ready and never blocks on the
slowest runner.  Weight broadcasts are non-blocking, so rollouts are
systematically stale — V-trace's clipped importance weighting is what
makes learning from them sound.

TPU-native split as elsewhere: rollouts are numpy on CPU actors; the
update is one compiled jax program (LearnerGroup: SPMD mesh or DDP
actors).
"""

from __future__ import annotations

from typing import Any, Dict, List

import numpy as np

from ray_tpu.rllib.algorithms.algorithm import Algorithm
from ray_tpu.rllib.algorithms.algorithm_config import AlgorithmConfig
from ray_tpu.rllib.algorithms.appo import compute_vtrace, _logsumexp
from ray_tpu.rllib.core.learner import LearnerGroup
from ray_tpu.rllib.core.rl_module import make_default_module
from ray_tpu.rllib.env.env_runner_group import EnvRunnerGroup


class IMPALAConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.lr = 5e-4
        self.vf_loss_coeff: float = 0.5
        self.entropy_coeff: float = 0.01
        self.minibatch_size = 256
        self.vtrace_clip_rho_threshold: float = 1.0
        self.vtrace_clip_c_threshold: float = 1.0
        # inflight_rollouts_per_runner comes from the base config
        #: max ready batches consumed per training_step
        self.max_batches_per_step: int = 4

    @property
    def algo_class(self):
        return IMPALA


def make_impala_loss(vf_loss_coeff: float, entropy_coeff: float):
    """Canonical IMPALA loss: plain policy gradient against V-trace
    advantages (no ratio clip — rho clipping already happened inside
    the V-trace targets), baseline MSE, entropy bonus (reference:
    the IMPALA learner's pg/baseline/entropy triple)."""

    def impala_loss(module, params, batch):
        import jax
        import jax.numpy as jnp

        logits, values = module.forward_train(params, batch["obs"])
        logp_all = jax.nn.log_softmax(logits, axis=-1)
        actions = batch["actions"].astype(jnp.int32)
        logp = jnp.take_along_axis(logp_all, actions[:, None], axis=-1)[:, 0]
        policy_loss = -jnp.mean(logp * batch["advantages"])
        vf_loss = jnp.mean((values - batch["value_targets"]) ** 2)
        entropy = -jnp.mean(jnp.sum(jnp.exp(logp_all) * logp_all, axis=-1))
        total = policy_loss + vf_loss_coeff * vf_loss - entropy_coeff * entropy
        return total, {
            "policy_loss": policy_loss,
            "vf_loss": vf_loss,
            "entropy": entropy,
        }

    return impala_loss


class IMPALA(Algorithm):
    def setup_components(self):
        cfg = self.config
        self.env_runner_group = EnvRunnerGroup(
            cfg.env, cfg.num_env_runners, cfg.num_envs_per_env_runner,
            cfg.rollout_fragment_length, seed=cfg.seed,
            env_kwargs=cfg.env_kwargs,
            connector=cfg.env_to_module_connector,
        )
        spec = self.env_runner_group.env_spec()
        self.module = make_default_module(spec, cfg.model)
        loss = make_impala_loss(cfg.vf_loss_coeff, cfg.entropy_coeff)
        self.learner_group = LearnerGroup(
            self.module, loss, num_learners=cfg.num_learners,
            lr=cfg.lr, grad_clip=cfg.grad_clip, seed=cfg.seed, mesh=cfg.mesh,
        )
        self.env_runner_group.sync_weights(
            self.learner_group.get_weights_numpy()
        )
        self._sampling_started = False

    def _vtrace_batch(self, samples: List[Dict[str, np.ndarray]],
                      weights) -> Dict[str, np.ndarray]:
        obs_l, act_l, adv_l, tgt_l = [], [], [], []
        for s in samples:
            T, B = s["actions"].shape
            flat = s["obs"].reshape(T * B, *s["obs"].shape[2:])
            logits, values = self.module.forward_numpy(weights, flat)
            logits = logits.reshape(T, B, -1)
            values = values.reshape(T, B).astype(np.float32)
            logp_all = logits - _logsumexp(logits)
            tgt_logp = np.take_along_axis(
                logp_all, s["actions"][..., None].astype(np.int64), axis=-1
            )[..., 0]
            _, final_v = self.module.forward_numpy(weights, s["final_obs"])
            pg_adv, vs = compute_vtrace(
                behavior_logp=s["logp"],
                target_logp=tgt_logp,
                rewards=s["rewards"],
                values=values,
                final_value=final_v.astype(np.float32),
                terminated=s["terminated"],
                truncated=s["truncated"],
                bootstrap_values=s["bootstrap_values"],
                gamma=self.config.gamma,
                clip_rho=self.config.vtrace_clip_rho_threshold,
                clip_c=self.config.vtrace_clip_c_threshold,
            )
            obs_l.append(s["obs"].reshape(T * B, *s["obs"].shape[2:]))
            act_l.append(s["actions"].reshape(-1))
            adv_l.append(pg_adv.reshape(-1))
            tgt_l.append(vs.reshape(-1))
        adv = np.concatenate(adv_l)
        adv = (adv - adv.mean()) / (adv.std() + 1e-8)
        return {
            "obs": np.concatenate(obs_l),
            "actions": np.concatenate(act_l),
            "advantages": adv,
            "value_targets": np.concatenate(tgt_l),
        }

    def training_step(self) -> Dict[str, Any]:
        cfg = self.config
        if not self._sampling_started:
            self.env_runner_group.start_async_sampling(
                self.module,
                inflight_per_runner=cfg.inflight_rollouts_per_runner,
            )
            self._sampling_started = True
        samples = self.env_runner_group.get_ready_samples(
            max_batches=cfg.max_batches_per_step
        )
        if not samples:
            return {"num_env_steps_sampled": 0}
        weights = self.learner_group.get_weights_numpy()
        batch = self._vtrace_batch(samples, weights)

        n = batch["obs"].shape[0]
        mb = min(cfg.minibatch_size, n)
        n_even = (n // mb) * mb
        rng = np.random.default_rng(cfg.seed + self.iteration)
        perm = rng.permutation(n)[:n_even]
        metrics_acc: List[Dict[str, float]] = []
        for start in range(0, n_even, mb):
            idx = perm[start:start + mb]
            metrics_acc.append(self.learner_group.update_minibatch({
                k: v[idx] for k, v in batch.items()
            }))

        # non-blocking broadcast: in-flight rollouts stay stale by
        # design; V-trace corrects them
        self.env_runner_group.sync_weights_async(
            self.learner_group.get_weights_numpy()
        )
        result: Dict[str, Any] = {
            k: float(np.mean([m[k] for m in metrics_acc]))
            for k in (metrics_acc[0] if metrics_acc else {})
        }
        result["num_env_steps_sampled"] = n
        result["num_async_batches"] = len(samples)
        self._track_episode_metrics(
            self.env_runner_group.pop_metrics(), result
        )
        return result

    def get_state(self) -> Dict[str, Any]:
        return {
            "learner": self.learner_group.get_state(),
            "connector": self.env_runner_group.connector_state(),
            "recent_returns": list(self._recent_returns),
            "iteration": self.iteration,
        }

    def set_state(self, state: Dict[str, Any]):
        self.learner_group.set_state(state["learner"])
        self.env_runner_group.restore_connector_state(
            state.get("connector")
        )
        self._recent_returns = list(state.get("recent_returns", []))
        self.iteration = state.get("iteration", self.iteration)
        self.env_runner_group.sync_weights(
            self.learner_group.get_weights_numpy()
        )

    def stop(self):
        self.env_runner_group.stop()
        self.learner_group.stop()
