"""Multi-node-without-a-cluster test harness.

Reference: `python/ray/cluster_utils.py:135` `Cluster` — starts multiple
node daemons **as separate processes on one host** (`add_node:201`,
`remove_node:282`); the workhorse for distributed core tests (node
death, actor restart across nodes, multi-node scheduling) without
hardware.
"""

from __future__ import annotations

import os
import signal
import subprocess
import time
from typing import Any, Dict, List, Optional

from ray_tpu import exceptions as exc


class NodeHandle:
    def __init__(self, proc: subprocess.Popen, session_dir: str,
                 ready: Dict[str, Any], is_head: bool):
        self.proc = proc
        self.session_dir = session_dir
        self.node_id: str = ready["node_id"]
        self.ready = ready
        self.is_head = is_head

    @property
    def alive(self) -> bool:
        return self.proc.poll() is None

    def __repr__(self):
        return f"NodeHandle({self.node_id[:8]}, head={self.is_head})"


class Cluster:
    """Reference: `cluster_utils.Cluster` — `add_node` spawns a node
    daemon; the first one is the head (hosts the controller)."""

    def __init__(self, initialize_head: bool = False, head_node_args:
                 Optional[Dict] = None):
        self._base = os.path.join(
            os.environ.get("RT_TMPDIR", "/tmp/ray_tpu"),
            f"cluster_{int(time.time() * 1000):x}_{os.getpid()}",
        )
        os.makedirs(self._base, exist_ok=True)
        self._nodes: List[NodeHandle] = []
        self._next_idx = 0
        self._connected = False
        if initialize_head:
            self.add_node(**(head_node_args or {}))

    @property
    def head_node(self) -> Optional[NodeHandle]:
        for n in self._nodes:
            if n.is_head and n.alive:
                return n
        return None

    @property
    def address(self) -> Optional[str]:
        """Head ready-file path — pass to `ray_tpu.init(address=...)`."""
        head = self.head_node
        return os.path.join(head.session_dir, "ready.json") if head else None

    def add_node(self, *, num_cpus: float = 4, num_tpus: Optional[float] = None,
                 resources: Optional[Dict[str, float]] = None,
                 labels: Optional[Dict[str, str]] = None,
                 num_workers: int = 2, wait: bool = True) -> NodeHandle:
        """Reference: `cluster_utils.py:201` add_node."""
        from ray_tpu.core.node_launcher import launch_noded

        idx = self._next_idx
        self._next_idx += 1
        is_head = not any(n.is_head for n in self._nodes)
        session_dir = os.path.join(self._base, f"node_{idx}")
        controller_addr = None
        if not is_head:
            head = self.head_node
            if head is None:
                raise exc.RayTpuError("head node died; cannot add workers")
            controller_addr = tuple(head.ready["controller_addr"])
        proc, ready = launch_noded(
            session_dir,
            head=is_head,
            controller_addr=controller_addr,
            num_cpus=num_cpus,
            num_tpus=num_tpus,
            resources=resources,
            labels=labels,
            num_workers=num_workers,
        )
        node = NodeHandle(proc, session_dir, ready, is_head)
        self._nodes.append(node)
        if wait and self._connected:
            self.wait_for_nodes()
        return node

    def remove_node(self, node: NodeHandle, *, graceful: bool = True,
                    allow_graceful: Optional[bool] = None):
        """Reference: `cluster_utils.py:282` remove_node.  graceful=False
        is the node-failure injection path (SIGKILL, no cleanup)."""
        if allow_graceful is not None:
            graceful = allow_graceful
        if node.alive:
            node.proc.send_signal(
                signal.SIGTERM if graceful else signal.SIGKILL
            )
            try:
                node.proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                node.proc.kill()
                node.proc.wait(timeout=5)
        # a SIGKILL'd (or kill-injected) daemon never unlinks its shm
        # store; /dev/shm is a shared host resource, so reap it here —
        # tmpfs segments leaked per killed node otherwise accumulate
        # across test runs until the host's shm fills
        shm_name = (node.ready or {}).get("shm_name")
        if shm_name:
            try:
                from ray_tpu.shm import ShmStore

                ShmStore.unlink(shm_name)
            except Exception:
                pass
        self._nodes = [n for n in self._nodes if n is not node]

    def connect(self, **init_kwargs):
        """ray_tpu.init against this cluster's head."""
        import ray_tpu as rt

        if self.address is None:
            raise exc.RayTpuError("no live head node")
        info = rt.init(address=self.address, **init_kwargs)
        self._connected = True
        return info

    def wait_for_nodes(self, timeout: float = 30.0):
        """Block until the controller sees every live daemon as ALIVE."""
        import ray_tpu as rt

        want = {n.node_id for n in self._nodes if n.alive}
        alive: set = set()
        deadline = time.time() + timeout
        while time.time() < deadline:
            alive = {n["node_id"] for n in rt.nodes() if n["alive"]}
            if want <= alive:
                return
            time.sleep(0.1)
        raise exc.RayTpuError(
            f"nodes never became ALIVE: {want - alive}"
        )

    def shutdown(self):
        import ray_tpu as rt

        if self._connected:
            try:
                rt.shutdown()
            except Exception:
                pass
            self._connected = False
        for n in list(self._nodes):
            self.remove_node(n, graceful=True)
