"""Workflow public API + executor.

Reference surface: `ray.workflow.run/run_async/resume/get_output/
get_status/list_all/delete` (`python/ray/workflow/api.py`).

Execution model (reference: `workflow_executor.py`): topological walk of
the FunctionNode DAG; ready tasks run as ordinary remote tasks, results
are durably written (atomic rename) before dependents are released, and
a workflow-level status file tracks RUNNING/SUCCESSFUL/FAILED.  Resume
reloads the pickled DAG from storage and skips every task with a
persisted result — user code is not needed to resume.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import cloudpickle

import ray_tpu as rt
from ray_tpu.dag.dag_node import DAGNode, FunctionNode

_storage_dir: Optional[str] = None
_lock = threading.Lock()


class WorkflowStatus:
    RUNNING = "RUNNING"
    SUCCESSFUL = "SUCCESSFUL"
    FAILED = "FAILED"
    RESUMABLE = "RESUMABLE"


def init_storage(path: str):
    """Set the workflow store root (reference: `workflow.init`)."""
    global _storage_dir
    _storage_dir = path
    os.makedirs(path, exist_ok=True)


def _store() -> str:
    global _storage_dir
    if _storage_dir is None:
        _storage_dir = os.environ.get(
            "RAY_TPU_WORKFLOW_STORAGE", "/tmp/ray_tpu/workflows"
        )
        os.makedirs(_storage_dir, exist_ok=True)
    return _storage_dir


def _wf_dir(workflow_id: str) -> str:
    return os.path.join(_store(), workflow_id)


def _atomic_write(path: str, data: bytes):
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(data)
    os.replace(tmp, path)


def _write_status(workflow_id: str, status: str, error: str = ""):
    _atomic_write(
        os.path.join(_wf_dir(workflow_id), "status.json"),
        json.dumps({"status": status, "error": error,
                    "ts": time.time()}).encode(),
    )


# ----------------------------------------------------------------------
# executor
# ----------------------------------------------------------------------
def _topo(root: FunctionNode) -> List[FunctionNode]:
    order: List[FunctionNode] = []
    seen = set()

    def visit(n: DAGNode):
        if n._id in seen:
            return
        seen.add(n._id)
        for u in n._upstream():
            visit(u)
        if isinstance(n, FunctionNode):
            order.append(n)

    visit(root)
    return order


def _task_key(idx: int, node: FunctionNode) -> str:
    name = getattr(node.remote_fn, "__name__", "task")
    return f"{idx:04d}_{name}"


def _execute_dag(workflow_id: str, root: FunctionNode) -> Any:
    wf = _wf_dir(workflow_id)
    tasks_dir = os.path.join(wf, "tasks")
    os.makedirs(tasks_dir, exist_ok=True)
    order = _topo(root)
    keys = {n._id: _task_key(i, n) for i, n in enumerate(order)}
    results: Dict[int, Any] = {}

    # load already-persisted results (resume path)
    for n in order:
        path = os.path.join(tasks_dir, keys[n._id] + ".pkl")
        if os.path.exists(path):
            with open(path, "rb") as f:
                results[n._id] = cloudpickle.load(f)

    def resolve(v):
        if isinstance(v, FunctionNode):
            return results[v._id]
        return v

    for n in order:
        if n._id in results:
            continue  # durably completed in a previous run
        args = [resolve(a) for a in n.args]
        kwargs = {k: resolve(v) for k, v in n.kwargs.items()}
        value = rt.get(n.remote_fn.remote(*args, **kwargs))
        _atomic_write(
            os.path.join(tasks_dir, keys[n._id] + ".pkl"),
            cloudpickle.dumps(value),
        )
        results[n._id] = value
    return results[root._id]


def _run_to_completion(workflow_id: str, root: FunctionNode) -> Any:
    _write_status(workflow_id, WorkflowStatus.RUNNING)
    # liveness marker: lets get_status distinguish RUNNING (executor
    # alive) from RESUMABLE (interrupted) — reference keeps this in the
    # cluster's workflow manager actor
    _atomic_write(
        os.path.join(_wf_dir(workflow_id), "executor.json"),
        json.dumps({"pid": os.getpid()}).encode(),
    )
    try:
        out = _execute_dag(workflow_id, root)
    except BaseException as e:
        _write_status(workflow_id, WorkflowStatus.FAILED, error=repr(e))
        raise
    _atomic_write(
        os.path.join(_wf_dir(workflow_id), "output.pkl"),
        cloudpickle.dumps(out),
    )
    _write_status(workflow_id, WorkflowStatus.SUCCESSFUL)
    return out


# ----------------------------------------------------------------------
# public API
# ----------------------------------------------------------------------
def run(dag: FunctionNode, *, workflow_id: Optional[str] = None) -> Any:
    """Execute a bound task DAG durably; returns the final output
    (reference: `workflow.run`)."""
    if not isinstance(dag, FunctionNode):
        raise TypeError("workflow.run expects fn.bind(...) (a FunctionNode)")
    workflow_id = workflow_id or f"wf_{int(time.time() * 1000):x}"
    wf = _wf_dir(workflow_id)
    os.makedirs(wf, exist_ok=True)
    # persist the DAG so resume() works without user code
    _atomic_write(os.path.join(wf, "dag.pkl"), cloudpickle.dumps(dag))
    return _run_to_completion(workflow_id, dag)


_async_executor = None


def run_async(dag: FunctionNode, *, workflow_id: Optional[str] = None):
    """Submit and return a concurrent.futures.Future."""
    import concurrent.futures

    global _async_executor
    with _lock:
        if _async_executor is None:
            _async_executor = concurrent.futures.ThreadPoolExecutor(
                max_workers=8, thread_name_prefix="workflow"
            )
    return _async_executor.submit(run, dag, workflow_id=workflow_id)


def resume(workflow_id: str) -> Any:
    """Re-run an interrupted workflow; completed tasks are skipped
    (reference: `workflow.resume` + `workflow_state_from_storage.py`)."""
    wf = _wf_dir(workflow_id)
    dag_path = os.path.join(wf, "dag.pkl")
    if not os.path.exists(dag_path):
        raise ValueError(f"no workflow {workflow_id!r} in storage")
    out_path = os.path.join(wf, "output.pkl")
    if os.path.exists(out_path):
        with open(out_path, "rb") as f:
            return cloudpickle.load(f)
    with open(dag_path, "rb") as f:
        dag = cloudpickle.load(f)
    return _run_to_completion(workflow_id, dag)


def get_output(workflow_id: str) -> Any:
    out_path = os.path.join(_wf_dir(workflow_id), "output.pkl")
    if not os.path.exists(out_path):
        raise ValueError(f"workflow {workflow_id!r} has no output yet")
    with open(out_path, "rb") as f:
        return cloudpickle.load(f)


def get_status(workflow_id: str) -> str:
    path = os.path.join(_wf_dir(workflow_id), "status.json")
    if not os.path.exists(path):
        raise ValueError(f"no workflow {workflow_id!r}")
    with open(path) as f:
        status = json.load(f)["status"]
    if status == WorkflowStatus.RUNNING:
        # RUNNING with a live executor process stays RUNNING; without
        # one the run was interrupted and is RESUMABLE (reference:
        # WorkflowStatus.RESUMABLE)
        exec_path = os.path.join(_wf_dir(workflow_id), "executor.json")
        try:
            with open(exec_path) as f:
                pid = json.load(f)["pid"]
            os.kill(pid, 0)
            return WorkflowStatus.RUNNING
        except (OSError, ValueError, KeyError):
            return WorkflowStatus.RESUMABLE
    return status


def list_all(status_filter: Optional[str] = None) -> List[Tuple[str, str]]:
    out = []
    root = _store()
    for wid in sorted(os.listdir(root)):
        try:
            s = get_status(wid)
        except ValueError:
            continue
        if status_filter is None or s == status_filter:
            out.append((wid, s))
    return out


def delete(workflow_id: str):
    import shutil

    shutil.rmtree(_wf_dir(workflow_id), ignore_errors=True)
