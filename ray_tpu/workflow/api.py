"""Workflow public API + executor.

Reference surface: `ray.workflow.run/run_async/resume/get_output/
get_status/list_all/delete` (`python/ray/workflow/api.py`).

Execution model (reference: `workflow_executor.py`): topological walk of
the FunctionNode DAG; ready tasks run as ordinary remote tasks, results
are durably written (atomic rename) before dependents are released, and
a workflow-level status file tracks RUNNING/SUCCESSFUL/FAILED.  Resume
reloads the pickled DAG from storage and skips every task with a
persisted result — user code is not needed to resume.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import cloudpickle

import ray_tpu as rt
from ray_tpu.dag.dag_node import DAGNode, FunctionNode

_storage_dir: Optional[str] = None
_lock = threading.Lock()


class WorkflowStatus:
    RUNNING = "RUNNING"
    SUCCESSFUL = "SUCCESSFUL"
    FAILED = "FAILED"
    RESUMABLE = "RESUMABLE"


class Continuation:
    """Returned BY a workflow task to dynamically extend the workflow
    (reference: `workflow.continuation` — a task returning a DAG makes
    the executor run it durably and use its output as the task's own
    result).  Continuations nest: a continuation task may itself return
    another Continuation."""

    def __init__(self, dag: "FunctionNode"):
        if not isinstance(dag, FunctionNode):
            raise TypeError(
                "workflow.continuation expects fn.bind(...) "
                "(a FunctionNode)"
            )
        self.dag = dag


def continuation(dag: "FunctionNode") -> Continuation:
    """Wrap a bound DAG as a task's dynamic continuation."""
    return Continuation(dag)


class EventNode(FunctionNode):
    """A durable wait-point in the DAG (reference:
    `workflow.wait_for_event` + the event listener protocol): the
    executor blocks this step until `send_event(workflow_id, name)`
    writes the payload into storage; once written, the event is durable
    — resumes see it immediately."""

    def __init__(self, name: str, timeout_s: Optional[float] = None):
        def _event_placeholder():  # pragma: no cover — never executed
            raise RuntimeError("EventNode executes via the event path")

        _event_placeholder.__name__ = f"event_{name}"
        super().__init__(_event_placeholder, (), {})
        self.event_name = name
        self.timeout_s = timeout_s


def wait_for_event(name: str,
                   timeout_s: Optional[float] = None) -> EventNode:
    """A DAG node resolving to the payload of a named workflow event."""
    return EventNode(name, timeout_s)


def send_event(workflow_id: str, name: str, payload: Any = None):
    """Deliver an event to a (possibly running, possibly interrupted)
    workflow; durable once written.  Raises for an unknown workflow id
    so a typo'd id can't silently swallow the event."""
    wf = _wf_dir(workflow_id)
    if not os.path.isdir(wf):
        raise ValueError(f"no workflow {workflow_id!r} in storage")
    events = os.path.join(wf, "events")
    os.makedirs(events, exist_ok=True)
    _atomic_write(os.path.join(events, f"{name}.pkl"),
                  cloudpickle.dumps(payload))


def init_storage(path: str):
    """Set the workflow store root (reference: `workflow.init`)."""
    global _storage_dir
    _storage_dir = path
    os.makedirs(path, exist_ok=True)


def _store() -> str:
    global _storage_dir
    if _storage_dir is None:
        _storage_dir = os.environ.get(
            "RAY_TPU_WORKFLOW_STORAGE", "/tmp/ray_tpu/workflows"
        )
        os.makedirs(_storage_dir, exist_ok=True)
    return _storage_dir


def _wf_dir(workflow_id: str) -> str:
    return os.path.join(_store(), workflow_id)


def _atomic_write(path: str, data: bytes):
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(data)
    os.replace(tmp, path)


def _write_status(workflow_id: str, status: str, error: str = ""):
    _atomic_write(
        os.path.join(_wf_dir(workflow_id), "status.json"),
        json.dumps({"status": status, "error": error,
                    "ts": time.time()}).encode(),
    )


# ----------------------------------------------------------------------
# executor
# ----------------------------------------------------------------------
def _topo(root: FunctionNode) -> List[FunctionNode]:
    order: List[FunctionNode] = []
    seen = set()

    def visit(n: DAGNode):
        if n._id in seen:
            return
        seen.add(n._id)
        for u in n._upstream():
            visit(u)
        if isinstance(n, FunctionNode):
            order.append(n)

    visit(root)
    return order


def _task_key(idx: int, node: FunctionNode) -> str:
    name = getattr(node.remote_fn, "__name__", "task")
    return f"{idx:04d}_{name}"


def _write_meta(tasks_dir: str, key: str, **fields):
    """Per-step durable metadata (reference: workflow step metadata in
    storage — `workflow.get_metadata`): merged, atomic."""
    path = os.path.join(tasks_dir, key + ".meta.json")
    meta = {}
    if os.path.exists(path):
        try:
            with open(path) as f:
                meta = json.load(f)
        except Exception:
            meta = {}
    meta.update(fields)
    _atomic_write(path, json.dumps(meta).encode())


def _wait_event(workflow_id: str, node: EventNode) -> Any:
    events_dir = os.path.join(_wf_dir(workflow_id), "events")
    path = os.path.join(events_dir, f"{node.event_name}.pkl")
    deadline = (
        time.monotonic() + node.timeout_s
        if node.timeout_s is not None else None
    )
    while not os.path.exists(path):
        if deadline is not None and time.monotonic() > deadline:
            raise TimeoutError(
                f"workflow event {node.event_name!r} not delivered "
                f"within {node.timeout_s}s"
            )
        time.sleep(0.05)
    with open(path, "rb") as f:
        return cloudpickle.load(f)


def _execute_dag(workflow_id: str, root: FunctionNode,
                 tasks_dir: Optional[str] = None) -> Any:
    """Topological durable execution.  Dynamic workflows: a task that
    returns `workflow.continuation(dag)` extends the run — the
    continuation DAG is persisted BEFORE it executes (a kill-restart
    resumes the continuation without re-running the task that produced
    it) and runs in its own nested task directory; its output becomes
    the task's result.  EventNodes block durably on `send_event`."""
    wf = _wf_dir(workflow_id)
    if tasks_dir is None:
        tasks_dir = os.path.join(wf, "tasks")
    os.makedirs(tasks_dir, exist_ok=True)
    order = _topo(root)
    keys = {n._id: _task_key(i, n) for i, n in enumerate(order)}
    results: Dict[int, Any] = {}

    # load already-persisted results (resume path)
    for n in order:
        path = os.path.join(tasks_dir, keys[n._id] + ".pkl")
        if os.path.exists(path):
            with open(path, "rb") as f:
                results[n._id] = cloudpickle.load(f)

    def resolve(v):
        if isinstance(v, FunctionNode):
            return results[v._id]
        return v

    def run_continuation(key: str, cont_dag: FunctionNode) -> Any:
        sub_dir = os.path.join(tasks_dir, key + "_cont")
        value = _execute_dag(workflow_id, cont_dag, tasks_dir=sub_dir)
        if isinstance(value, Continuation):
            # the continuation's own root returned a continuation: the
            # nested DAG was persisted by the recursive call; unwrap
            # happens there, so this is unreachable — guard anyway
            raise RuntimeError("unresolved nested continuation")
        return value

    for n in order:
        if n._id in results:
            continue  # durably completed in a previous run
        key = keys[n._id]
        cont_path = os.path.join(tasks_dir, key + ".cont.pkl")
        if os.path.exists(cont_path):
            # interrupted mid-continuation: resume the persisted
            # continuation DAG, do NOT re-run the producing task
            with open(cont_path, "rb") as f:
                cont_dag = cloudpickle.load(f)
            value = run_continuation(key, cont_dag)
            _write_meta(tasks_dir, key, end_ts=time.time(),
                        status="SUCCESSFUL")
        elif isinstance(n, EventNode):
            _write_meta(tasks_dir, key, name=n.event_name, kind="event",
                        start_ts=time.time(), status="WAITING")
            try:
                value = _wait_event(workflow_id, n)
            except BaseException as e:
                _write_meta(tasks_dir, key, end_ts=time.time(),
                            status="FAILED", error=repr(e))
                raise
            _write_meta(tasks_dir, key, end_ts=time.time(),
                        status="SUCCESSFUL")
        else:
            args = [resolve(a) for a in n.args]
            kwargs = {k: resolve(v) for k, v in n.kwargs.items()}
            _write_meta(
                tasks_dir, key,
                name=getattr(n.remote_fn, "__name__", "task"),
                kind="task", start_ts=time.time(), status="RUNNING",
            )
            try:
                value = rt.get(n.remote_fn.remote(*args, **kwargs))
            except BaseException as e:
                _write_meta(tasks_dir, key, end_ts=time.time(),
                            status="FAILED", error=repr(e))
                raise
            if isinstance(value, Continuation):
                # durable-first: persist the continuation DAG before
                # executing it, then run it as a nested sub-workflow
                _atomic_write(cont_path, cloudpickle.dumps(value.dag))
                _write_meta(tasks_dir, key, continuation=True)
                value = run_continuation(key, value.dag)
            _write_meta(tasks_dir, key, end_ts=time.time(),
                        status="SUCCESSFUL")
        _atomic_write(
            os.path.join(tasks_dir, key + ".pkl"),
            cloudpickle.dumps(value),
        )
        results[n._id] = value
    return results[root._id]


def _run_to_completion(workflow_id: str, root: FunctionNode) -> Any:
    _write_status(workflow_id, WorkflowStatus.RUNNING)
    # liveness marker: lets get_status distinguish RUNNING (executor
    # alive) from RESUMABLE (interrupted) — reference keeps this in the
    # cluster's workflow manager actor
    _atomic_write(
        os.path.join(_wf_dir(workflow_id), "executor.json"),
        json.dumps({"pid": os.getpid()}).encode(),
    )
    try:
        out = _execute_dag(workflow_id, root)
    except BaseException as e:
        _write_status(workflow_id, WorkflowStatus.FAILED, error=repr(e))
        raise
    _atomic_write(
        os.path.join(_wf_dir(workflow_id), "output.pkl"),
        cloudpickle.dumps(out),
    )
    _write_status(workflow_id, WorkflowStatus.SUCCESSFUL)
    return out


# ----------------------------------------------------------------------
# public API
# ----------------------------------------------------------------------
def run(dag: FunctionNode, *, workflow_id: Optional[str] = None) -> Any:
    """Execute a bound task DAG durably; returns the final output
    (reference: `workflow.run`)."""
    if not isinstance(dag, FunctionNode):
        raise TypeError("workflow.run expects fn.bind(...) (a FunctionNode)")
    workflow_id = workflow_id or f"wf_{int(time.time() * 1000):x}"
    wf = _wf_dir(workflow_id)
    os.makedirs(wf, exist_ok=True)
    # persist the DAG so resume() works without user code
    _atomic_write(os.path.join(wf, "dag.pkl"), cloudpickle.dumps(dag))
    return _run_to_completion(workflow_id, dag)


_async_executor = None


def run_async(dag: FunctionNode, *, workflow_id: Optional[str] = None):
    """Submit and return a concurrent.futures.Future."""
    import concurrent.futures

    global _async_executor
    with _lock:
        if _async_executor is None:
            _async_executor = concurrent.futures.ThreadPoolExecutor(
                max_workers=8, thread_name_prefix="workflow"
            )
    return _async_executor.submit(run, dag, workflow_id=workflow_id)


def resume(workflow_id: str) -> Any:
    """Re-run an interrupted workflow; completed tasks are skipped
    (reference: `workflow.resume` + `workflow_state_from_storage.py`)."""
    wf = _wf_dir(workflow_id)
    dag_path = os.path.join(wf, "dag.pkl")
    if not os.path.exists(dag_path):
        raise ValueError(f"no workflow {workflow_id!r} in storage")
    out_path = os.path.join(wf, "output.pkl")
    if os.path.exists(out_path):
        with open(out_path, "rb") as f:
            return cloudpickle.load(f)
    with open(dag_path, "rb") as f:
        dag = cloudpickle.load(f)
    return _run_to_completion(workflow_id, dag)


def get_output(workflow_id: str) -> Any:
    out_path = os.path.join(_wf_dir(workflow_id), "output.pkl")
    if not os.path.exists(out_path):
        raise ValueError(f"workflow {workflow_id!r} has no output yet")
    with open(out_path, "rb") as f:
        return cloudpickle.load(f)


def get_status(workflow_id: str) -> str:
    path = os.path.join(_wf_dir(workflow_id), "status.json")
    if not os.path.exists(path):
        raise ValueError(f"no workflow {workflow_id!r}")
    with open(path) as f:
        status = json.load(f)["status"]
    if status == WorkflowStatus.RUNNING:
        # RUNNING with a live executor process stays RUNNING; without
        # one the run was interrupted and is RESUMABLE (reference:
        # WorkflowStatus.RESUMABLE)
        exec_path = os.path.join(_wf_dir(workflow_id), "executor.json")
        try:
            with open(exec_path) as f:
                pid = json.load(f)["pid"]
            os.kill(pid, 0)
            return WorkflowStatus.RUNNING
        except (OSError, ValueError, KeyError):
            return WorkflowStatus.RESUMABLE
    return status


def list_all(status_filter: Optional[str] = None) -> List[Tuple[str, str]]:
    out = []
    root = _store()
    for wid in sorted(os.listdir(root)):
        try:
            s = get_status(wid)
        except ValueError:
            continue
        if status_filter is None or s == status_filter:
            out.append((wid, s))
    return out


def get_metadata(workflow_id: str) -> Dict[str, Any]:
    """Workflow + per-step durable metadata (reference:
    `workflow.get_metadata`): status, and for each step its name, kind
    (task/event), timestamps, status, and whether it spawned a
    continuation.  Nested continuation steps appear under their parent
    step's key with a '/'-joined path."""
    wf = _wf_dir(workflow_id)
    status_path = os.path.join(wf, "status.json")
    if not os.path.exists(status_path):
        raise ValueError(f"no workflow {workflow_id!r}")
    with open(status_path) as f:
        info = json.load(f)
    steps: Dict[str, Any] = {}

    def scan(tasks_dir: str, prefix: str):
        if not os.path.isdir(tasks_dir):
            return
        for fn in sorted(os.listdir(tasks_dir)):
            full = os.path.join(tasks_dir, fn)
            if fn.endswith(".meta.json"):
                key = prefix + fn[: -len(".meta.json")]
                try:
                    with open(full) as f:
                        steps[key] = json.load(f)
                except Exception:
                    continue
            elif fn.endswith("_cont") and os.path.isdir(full):
                scan(full, prefix + fn[: -len("_cont")] + "/")

    scan(os.path.join(wf, "tasks"), "")
    return {"workflow_id": workflow_id, "status": info.get("status"),
            "error": info.get("error", ""), "steps": steps}


def delete(workflow_id: str):
    import shutil

    shutil.rmtree(_wf_dir(workflow_id), ignore_errors=True)
