"""Durable workflows: task DAGs with storage-backed resume.

Reference: `python/ray/workflow/` — `workflow_executor.py` (DAG
execution), `workflow_storage.py` (every task result durably logged),
`workflow_state_from_storage.py` (resume skips completed tasks) — the
same contract on a directory-per-workflow store: the bound DAG is
persisted at submission, each task's result is written before the
workflow advances, and `resume()` replays only what never finished.
"""

from ray_tpu.workflow.api import (
    Continuation,
    WorkflowStatus,
    continuation,
    delete,
    get_metadata,
    get_output,
    get_status,
    init_storage,
    list_all,
    resume,
    run,
    run_async,
    send_event,
    wait_for_event,
)

__all__ = [
    "Continuation",
    "WorkflowStatus",
    "continuation",
    "delete",
    "get_metadata",
    "send_event",
    "wait_for_event",
    "get_output",
    "get_status",
    "init_storage",
    "list_all",
    "resume",
    "run",
    "run_async",
]
