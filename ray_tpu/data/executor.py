"""Streaming executor: runs a LogicalPlan as a pipelined stream of
remote tasks over block refs.

Reference: `data/_internal/execution/streaming_executor.py:48` — a
pull-based operator pipeline with bounded in-flight work per stage
(backpressure) instead of stage-by-stage materialization.  Here each
stage is a generator over (block_ref, meta_ref) pairs; map stages keep
a sliding window of submitted tasks, so at any moment at most
`window` tasks per stage are in flight and blocks stream through the
object plane without ever being gathered on the driver.  Every task
returns (block, metadata) as two objects, so the driver reads row
counts without fetching payloads (the reference's Block/BlockMetadata
split, `data/block.py`).
"""

from __future__ import annotations

from collections import deque
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

import ray_tpu as rt
from ray_tpu.data import block as B
from ray_tpu.data.plan import AllToAllOp, LimitOp, LogicalPlan, MapOp, ReadOp

# (block_ref, meta_ref-or-value)
RefPair = Tuple[Any, Any]


def _run_read_task(read_task: Callable[[], List[B.Block]]):
    blocks = read_task()
    out = B.concat(blocks) if len(blocks) != 1 else blocks[0]
    return out, {"num_rows": B.num_rows(out), "size_bytes": B.size_bytes(out)}


def _run_map_task(fn: Callable[[B.Block], List[B.Block]], blk: B.Block):
    outs = fn(blk)
    out = B.concat(outs) if len(outs) != 1 else outs[0]
    return out, {"num_rows": B.num_rows(out), "size_bytes": B.size_bytes(out)}


def _run_alltoall_task(fn: Callable[[List[B.Block]], List[B.Block]], *blocks):
    outs = fn(list(blocks))
    pairs = []
    for b in outs:
        ref = rt.put(b)
        pairs.append((ref, {"num_rows": B.num_rows(b), "size_bytes": B.size_bytes(b)}))
    return pairs


def _slice_task(blk: B.Block, end: int):
    out = B.slice_block(blk, 0, end)
    return out, {"num_rows": B.num_rows(out), "size_bytes": B.size_bytes(out)}


class StreamingExecutor:
    def __init__(self, plan: LogicalPlan, *, window: int = 8,
                 num_cpus: float = 1.0):
        self.plan = plan.optimized()
        self.window = window
        self._remote_opts = {"num_cpus": num_cpus, "num_returns": 2}
        self.stats: Dict[str, Any] = {"stages": self.plan.describe(), "tasks": 0}

    # -- stage generators ---------------------------------------------
    def _read_stream(self, op: ReadOp) -> Iterator[RefPair]:
        read_remote = rt.remote(_run_read_task).options(**self._remote_opts)
        inflight: deque = deque()
        for task in op.read_tasks:
            while len(inflight) >= self.window:
                yield inflight.popleft()
            inflight.append(tuple(read_remote.remote(task)))
            self.stats["tasks"] += 1
        while inflight:
            yield inflight.popleft()

    def _map_stream(self, stream: Iterator[RefPair], op: MapOp) -> Iterator[RefPair]:
        map_remote = rt.remote(_run_map_task).options(**self._remote_opts)
        inflight: deque = deque()
        for block_ref, _meta in stream:
            while len(inflight) >= self.window:
                yield inflight.popleft()
            inflight.append(tuple(map_remote.remote(op.fn, block_ref)))
            self.stats["tasks"] += 1
        while inflight:
            yield inflight.popleft()

    def _alltoall_stream(self, stream: Iterator[RefPair],
                         op: AllToAllOp) -> Iterator[RefPair]:
        pairs = list(stream)  # barrier
        refs = [p[0] for p in pairs]
        a2a_remote = rt.remote(_run_alltoall_task).options(
            num_cpus=self._remote_opts["num_cpus"]
        )
        self.stats["tasks"] += 1
        out_pairs = rt.get(a2a_remote.remote(op.fn, *refs))
        yield from out_pairs

    def _limit_stream(self, stream: Iterator[RefPair], op: LimitOp) -> Iterator[RefPair]:
        remaining = op.limit
        slice_remote = rt.remote(_slice_task).options(**self._remote_opts)
        for block_ref, meta in stream:
            if remaining <= 0:
                break
            n = self._meta(meta)["num_rows"]
            if n <= remaining:
                remaining -= n
                yield block_ref, meta
            else:
                self.stats["tasks"] += 1
                yield tuple(slice_remote.remote(block_ref, remaining))
                remaining = 0

    @staticmethod
    def _meta(meta) -> Dict[str, Any]:
        if isinstance(meta, dict):
            return meta
        return rt.get(meta)

    # -- public --------------------------------------------------------
    def execute(self) -> Iterator[RefPair]:
        ops = self.plan.ops
        if not ops or not isinstance(ops[0], ReadOp):
            raise ValueError(f"plan must start with a ReadOp: {self.plan.describe()}")
        stream: Iterator[RefPair] = self._read_stream(ops[0])
        for op in ops[1:]:
            if isinstance(op, MapOp):
                stream = self._map_stream(stream, op)
            elif isinstance(op, AllToAllOp):
                stream = self._alltoall_stream(stream, op)
            elif isinstance(op, LimitOp):
                stream = self._limit_stream(stream, op)
            else:
                raise TypeError(f"unknown op: {op}")
        return stream

    def execute_to_refs(self) -> List[RefPair]:
        return list(self.execute())
