"""Streaming executor: runs a LogicalPlan as a pipelined stream of
remote tasks over block refs.

Reference: `data/_internal/execution/streaming_executor.py:48` — a
pull-based operator pipeline with bounded in-flight work per stage
(backpressure) instead of stage-by-stage materialization.  Here each
stage is a generator over (block_ref, meta_ref) pairs; map stages keep
a sliding window of submitted tasks, so at any moment at most
`window` tasks per stage are in flight and blocks stream through the
object plane without ever being gathered on the driver.  Every task
returns (block, metadata) as two objects, so the driver reads row
counts without fetching payloads (the reference's Block/BlockMetadata
split, `data/block.py`).

Fault model: every data-plane task is submitted with
`DataContext.data_task_max_retries`, so a worker SIGKILLed mid-epoch
retries through the core worker-died path, and a block evicted/lost
AFTER its task completed re-derives via lineage reconstruction when a
consumer pulls it — the epoch keeps streaming either way.
Unrecoverable losses (retries exhausted, lineage gone) surface as the
core plane's typed errors (`WorkerCrashedError`, `ObjectLostError`,
`ObjectReconstructionFailedError`) at the consuming `rt.get`, never as
a hang.  Shuffles run as a distributed map/reduce exchange
(`data/shuffle.py`), not a single gather task.
"""

from __future__ import annotations

import logging
from collections import deque
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

import ray_tpu as rt
from ray_tpu.data import block as B
from ray_tpu.data.context import DataContext
from ray_tpu.data.plan import (
    ActorMapOp,
    LimitOp,
    LogicalPlan,
    MapOp,
    ReadOp,
    ShuffleOp,
)

logger = logging.getLogger(__name__)

# (block_ref, meta_ref-or-value)
RefPair = Tuple[Any, Any]


def resolve_metas(metas: List[Any]) -> List[Dict[str, Any]]:
    """Materialize a list of metadata entries with ONE batched
    `rt.get` for the unresolved refs (dicts pass through).  The old
    per-block blocking `rt.get` serialized the whole stream on
    driver-side metadata fetches; batching lets metadata reads ride
    the pipeline."""
    refs, slots = [], []
    out: List[Any] = list(metas)
    for i, m in enumerate(out):
        if not isinstance(m, dict):
            refs.append(m)
            slots.append(i)
    if refs:
        for i, v in zip(slots, rt.get(refs)):
            out[i] = v
    return out


def resolve_pairs(pairs: List[RefPair]) -> List[RefPair]:
    """(ref, meta_ref) pairs -> (ref, meta_dict) pairs, metadata
    fetched in one batch."""
    metas = resolve_metas([m for _, m in pairs])
    return [(ref, m) for (ref, _), m in zip(pairs, metas)]


def _run_read_task(read_task: Callable[[], List[B.Block]]):
    blocks = read_task()
    out = B.concat(blocks) if len(blocks) != 1 else blocks[0]
    return out, {"num_rows": B.num_rows(out), "size_bytes": B.size_bytes(out)}


def _run_map_task(fn: Callable[[B.Block], List[B.Block]], blk: B.Block):
    outs = fn(blk)
    out = B.concat(outs) if len(outs) != 1 else outs[0]
    return out, {"num_rows": B.num_rows(out), "size_bytes": B.size_bytes(out)}


class _BatchMapWorker:
    """Pool actor for ActorMapOp: constructs the UDF once, maps blocks
    batch-by-batch (reference: `actor_pool_map_operator.py` worker)."""

    def __init__(self, cls, args, kwargs, batch_size, batch_format):
        self._udf = cls(*args, **kwargs)
        self._batch_size = batch_size
        self._fmt = batch_format

    def map_block(self, blk: B.Block):
        from ray_tpu.data.dataset import _coerce_batch

        out: List[B.Block] = []
        n = B.num_rows(blk)
        size = self._batch_size or n or 1
        for s in range(0, max(n, 1), size):
            piece = B.slice_block(blk, s, min(s + size, n))
            res = self._udf(B.format_batch(piece, self._fmt))
            out.append(_coerce_batch(res))
        merged = B.concat(out) if len(out) != 1 else out[0]
        return merged, {
            "num_rows": B.num_rows(merged),
            "size_bytes": B.size_bytes(merged),
        }


def _slice_task(blk: B.Block, end: int):
    out = B.slice_block(blk, 0, end)
    return out, {"num_rows": B.num_rows(out), "size_bytes": B.size_bytes(out)}


class StreamingExecutor:
    def __init__(self, plan: LogicalPlan, *, window: Optional[int] = None,
                 num_cpus: float = 1.0):
        ctx = DataContext.get_current()
        self.ctx = ctx
        self.plan = plan.optimized()
        self.window = window if window is not None else ctx.window
        self.max_stage_bytes = ctx.max_stage_inflight_bytes
        # budget in-flight bytes against the node's object store: a
        # running task PINS its inputs and outputs, and pinned bytes
        # can neither spill nor evict — unbounded in-flight pins on a
        # small store wedge every create.  (The 2x in the shuffle
        # admission below accounts input + output per task.)
        cap = self._store_capacity()
        if cap > 0:
            self.max_stage_bytes = min(
                self.max_stage_bytes,
                max(1, int(cap * ctx.store_memory_fraction)),
            )
        self._actor_depth = ctx.actor_pool_pipeline_depth
        self.task_num_cpus = num_cpus
        self._remote_opts = {
            "num_cpus": num_cpus,
            "num_returns": 2,
            # worker death mid-epoch retries instead of killing the
            # stream; lineage reconstruction rides the same budget
            "max_retries": ctx.data_task_max_retries,
        }
        self._meta_sizes: Dict[bytes, int] = {}
        self.stats: Dict[str, Any] = {"stages": self.plan.describe(), "tasks": 0}

    @staticmethod
    def _store_capacity() -> int:
        try:
            from ray_tpu.core.runtime import get_runtime, is_initialized

            if is_initialized():
                return int(getattr(get_runtime().store, "capacity", 0) or 0)
        except Exception as e:
            logger.debug("object-store capacity probe failed: %s", e)
        return 0

    # -- metadata ------------------------------------------------------
    def resolve_metas(self, metas: List[Any]) -> List[Dict[str, Any]]:
        return resolve_metas(metas)

    def resolve_pairs(self, pairs: List[RefPair]) -> List[RefPair]:
        return resolve_pairs(pairs)

    def _resolved_meta_stream(self, stream: Iterator[RefPair]
                              ) -> Iterator[RefPair]:
        """Stream adapter: yields (ref, meta_dict) with metadata
        resolved in window-sized batches — a bounded lookahead instead
        of one blocking driver get per block."""
        buf: List[RefPair] = []
        for pair in stream:
            buf.append(pair)
            if len(buf) >= self.window:
                yield from self.resolve_pairs(buf)
                buf = []
        if buf:
            yield from self.resolve_pairs(buf)

    # -- stage generators ---------------------------------------------
    def _read_stream(self, op: ReadOp) -> Iterator[RefPair]:
        read_remote = rt.remote(_run_read_task).options(**self._remote_opts)
        inflight: deque = deque()
        for task in op.read_tasks:
            while len(inflight) >= self.window:
                yield inflight.popleft()
            inflight.append(tuple(read_remote.remote(task)))
            self.stats["tasks"] += 1
        while inflight:
            yield inflight.popleft()

    def _input_size(self, meta) -> int:
        """Estimated bytes of an input block, WITHOUT stalling the
        pipeline: metadata is consulted only when already materialized
        (a dict, or a completed task's ready ref) — else 0 (unknown,
        count-based pressure still applies).  Resolved sizes are cached
        by ref so multi-stage pipelines probe the runtime once per
        block, not once per stage (the per-block probe the round-2
        review flagged)."""
        if isinstance(meta, dict):
            return int(meta.get("size_bytes", 0))
        cache = self._meta_sizes
        try:
            key = meta.binary()
        except AttributeError:
            key = None  # plain value, not a ref — no cache slot
        if key is not None and key in cache:
            return cache[key]
        try:
            done, _ = rt.wait([meta], timeout=0)
            if done:
                size = int(rt.get(meta).get("size_bytes", 0))
                if key is not None:
                    if len(cache) > 4096:
                        cache.clear()
                    cache[key] = size
                return size
        except Exception as e:
            # best-effort probe: fall through to "unknown size" but
            # keep the cause visible for the next incident
            logger.debug("in-flight size probe failed: %s", e)
        return 0

    def _map_stream(self, stream: Iterator[RefPair], op: MapOp) -> Iterator[RefPair]:
        """Task-based map with count- AND byte-based backpressure
        (reference: ConcurrencyCapBackpressurePolicy + the resource
        manager's per-operator memory budgets)."""
        map_remote = rt.remote(_run_map_task).options(**self._remote_opts)
        inflight: deque = deque()  # (pair, est_bytes)
        inflight_bytes = 0
        for block_ref, meta in stream:
            sz = self._input_size(meta)
            while len(inflight) >= self.window or (
                inflight and inflight_bytes + sz > self.max_stage_bytes
            ):
                pair, psz = inflight.popleft()
                inflight_bytes -= psz
                yield pair
            inflight.append(
                (tuple(map_remote.remote(op.fn, block_ref)), sz)
            )
            inflight_bytes += sz
            self.stats["tasks"] += 1
        while inflight:
            yield inflight.popleft()[0]

    def _actor_map_stream(self, stream: Iterator[RefPair],
                          op: ActorMapOp) -> Iterator[RefPair]:
        """Actor-pool map (reference: `actor_pool_map_operator.py` +
        pool autoscaler): blocks route to the least-loaded actor with
        `actor_pool_pipeline_depth` pipelining; the pool grows toward
        strategy.max_size while saturated and is torn down when the
        stream ends."""
        strat = op.strategy
        Worker = rt.remote(num_cpus=self._remote_opts["num_cpus"])(
            _BatchMapWorker
        )

        def spawn():
            return Worker.remote(op.cls, op.args, op.kwargs,
                                 op.batch_size, op.batch_format)

        actors = [spawn() for _ in range(strat.min_size)]
        load = [0] * len(actors)
        outstanding: Dict[Any, int] = {}  # meta_ref -> actor index
        inflight: deque = deque()  # pairs in submission order

        def reap(block: bool):
            if not outstanding:
                return
            done, _ = rt.wait(
                list(outstanding),
                num_returns=1 if block else len(outstanding),
                timeout=None if block else 0,
            )
            for m in done:
                load[outstanding.pop(m)] -= 1

        try:
            for block_ref, _meta in stream:
                reap(block=False)
                while True:
                    i = min(range(len(actors)), key=load.__getitem__)
                    if load[i] < self._actor_depth:
                        break
                    if len(actors) < strat.max_size:
                        actors.append(spawn())
                        load.append(0)
                        i = len(actors) - 1
                        break
                    # saturated at max_size: hand completed work
                    # downstream, then wait for a slot
                    if inflight:
                        yield inflight.popleft()
                    reap(block=True)
                method = actors[i].map_block.options(num_returns=2)
                b, m = method.remote(block_ref)
                load[i] += 1
                outstanding[m] = i
                inflight.append((b, m))
                self.stats["tasks"] += 1
            while inflight:
                yield inflight.popleft()
        finally:
            for a in actors:
                try:
                    rt.kill(a)
                except Exception as e:
                    # pool teardown is best-effort: the actor may
                    # already be gone (its worker died mid-stream)
                    logger.debug("actor pool teardown kill failed: %s", e)

    def _shuffle_stream(self, stream: Iterator[RefPair],
                        op: ShuffleOp) -> Iterator[RefPair]:
        """Distributed map-partition -> reduce-partition exchange; the
        single-task AllToAll gather barrier this replaced is gone —
        see `data/shuffle.py` for the fault/memory model."""
        from ray_tpu.data import shuffle as _shuffle

        yield from _shuffle.run_shuffle(self, stream, op)

    def _limit_stream(self, stream: Iterator[RefPair], op: LimitOp) -> Iterator[RefPair]:
        remaining = op.limit
        slice_remote = rt.remote(_slice_task).options(**self._remote_opts)
        # metadata resolves in window-sized batches (bounded lookahead)
        # so the row-count reads ride the pipeline instead of issuing
        # one blocking driver-side get per block
        for block_ref, meta in self._resolved_meta_stream(stream):
            if remaining <= 0:
                break
            n = meta["num_rows"]
            if n <= remaining:
                remaining -= n
                yield block_ref, meta
            else:
                self.stats["tasks"] += 1
                yield tuple(slice_remote.remote(block_ref, remaining))
                remaining = 0

    # -- public --------------------------------------------------------
    def execute(self) -> Iterator[RefPair]:
        ops = self.plan.ops
        if not ops or not isinstance(ops[0], ReadOp):
            raise ValueError(f"plan must start with a ReadOp: {self.plan.describe()}")
        stream: Iterator[RefPair] = self._read_stream(ops[0])
        for op in ops[1:]:
            if isinstance(op, MapOp):
                stream = self._map_stream(stream, op)
            elif isinstance(op, ActorMapOp):
                stream = self._actor_map_stream(stream, op)
            elif isinstance(op, ShuffleOp):
                stream = self._shuffle_stream(stream, op)
            elif isinstance(op, LimitOp):
                stream = self._limit_stream(stream, op)
            else:
                raise TypeError(f"unknown op: {op}")
        return stream

    def execute_to_refs(self) -> List[RefPair]:
        return list(self.execute())
