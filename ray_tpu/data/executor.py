"""Streaming executor: runs a LogicalPlan as a pipelined stream of
remote tasks over block refs.

Reference: `data/_internal/execution/streaming_executor.py:48` — a
pull-based operator pipeline with bounded in-flight work per stage
(backpressure) instead of stage-by-stage materialization.  Here each
stage is a generator over (block_ref, meta_ref) pairs; map stages keep
a sliding window of submitted tasks, so at any moment at most
`window` tasks per stage are in flight and blocks stream through the
object plane without ever being gathered on the driver.  Every task
returns (block, metadata) as two objects, so the driver reads row
counts without fetching payloads (the reference's Block/BlockMetadata
split, `data/block.py`).
"""

from __future__ import annotations

from collections import deque
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

import ray_tpu as rt
from ray_tpu.data import block as B
from ray_tpu.data.context import DataContext
from ray_tpu.data.plan import (
    ActorMapOp,
    AllToAllOp,
    LimitOp,
    LogicalPlan,
    MapOp,
    ReadOp,
)

# (block_ref, meta_ref-or-value)
RefPair = Tuple[Any, Any]


def _run_read_task(read_task: Callable[[], List[B.Block]]):
    blocks = read_task()
    out = B.concat(blocks) if len(blocks) != 1 else blocks[0]
    return out, {"num_rows": B.num_rows(out), "size_bytes": B.size_bytes(out)}


def _run_map_task(fn: Callable[[B.Block], List[B.Block]], blk: B.Block):
    outs = fn(blk)
    out = B.concat(outs) if len(outs) != 1 else outs[0]
    return out, {"num_rows": B.num_rows(out), "size_bytes": B.size_bytes(out)}


def _run_alltoall_task(fn: Callable[[List[B.Block]], List[B.Block]], *blocks):
    outs = fn(list(blocks))
    pairs = []
    for b in outs:
        ref = rt.put(b)
        pairs.append((ref, {"num_rows": B.num_rows(b), "size_bytes": B.size_bytes(b)}))
    return pairs


class _BatchMapWorker:
    """Pool actor for ActorMapOp: constructs the UDF once, maps blocks
    batch-by-batch (reference: `actor_pool_map_operator.py` worker)."""

    def __init__(self, cls, args, kwargs, batch_size, batch_format):
        self._udf = cls(*args, **kwargs)
        self._batch_size = batch_size
        self._fmt = batch_format

    def map_block(self, blk: B.Block):
        from ray_tpu.data.dataset import _coerce_batch

        out: List[B.Block] = []
        n = B.num_rows(blk)
        size = self._batch_size or n or 1
        for s in range(0, max(n, 1), size):
            piece = B.slice_block(blk, s, min(s + size, n))
            res = self._udf(B.format_batch(piece, self._fmt))
            out.append(_coerce_batch(res))
        merged = B.concat(out) if len(out) != 1 else out[0]
        return merged, {
            "num_rows": B.num_rows(merged),
            "size_bytes": B.size_bytes(merged),
        }


def _slice_task(blk: B.Block, end: int):
    out = B.slice_block(blk, 0, end)
    return out, {"num_rows": B.num_rows(out), "size_bytes": B.size_bytes(out)}


class StreamingExecutor:
    def __init__(self, plan: LogicalPlan, *, window: Optional[int] = None,
                 num_cpus: float = 1.0):
        ctx = DataContext.get_current()
        self.plan = plan.optimized()
        self.window = window if window is not None else ctx.window
        self.max_stage_bytes = ctx.max_stage_inflight_bytes
        self._actor_depth = ctx.actor_pool_pipeline_depth
        self._remote_opts = {"num_cpus": num_cpus, "num_returns": 2}
        self._meta_sizes: Dict[bytes, int] = {}
        self.stats: Dict[str, Any] = {"stages": self.plan.describe(), "tasks": 0}

    # -- stage generators ---------------------------------------------
    def _read_stream(self, op: ReadOp) -> Iterator[RefPair]:
        read_remote = rt.remote(_run_read_task).options(**self._remote_opts)
        inflight: deque = deque()
        for task in op.read_tasks:
            while len(inflight) >= self.window:
                yield inflight.popleft()
            inflight.append(tuple(read_remote.remote(task)))
            self.stats["tasks"] += 1
        while inflight:
            yield inflight.popleft()

    def _input_size(self, meta) -> int:
        """Estimated bytes of an input block, WITHOUT stalling the
        pipeline: metadata is consulted only when already materialized
        (a dict, or a completed task's ready ref) — else 0 (unknown,
        count-based pressure still applies).  Resolved sizes are cached
        by ref so multi-stage pipelines probe the runtime once per
        block, not once per stage (the per-block probe the round-2
        review flagged)."""
        if isinstance(meta, dict):
            return int(meta.get("size_bytes", 0))
        cache = self._meta_sizes
        try:
            key = meta.binary()
        except Exception:
            key = None
        if key is not None and key in cache:
            return cache[key]
        try:
            done, _ = rt.wait([meta], timeout=0)
            if done:
                size = int(rt.get(meta).get("size_bytes", 0))
                if key is not None:
                    if len(cache) > 4096:
                        cache.clear()
                    cache[key] = size
                return size
        except Exception:
            pass
        return 0

    def _map_stream(self, stream: Iterator[RefPair], op: MapOp) -> Iterator[RefPair]:
        """Task-based map with count- AND byte-based backpressure
        (reference: ConcurrencyCapBackpressurePolicy + the resource
        manager's per-operator memory budgets)."""
        map_remote = rt.remote(_run_map_task).options(**self._remote_opts)
        inflight: deque = deque()  # (pair, est_bytes)
        inflight_bytes = 0
        for block_ref, meta in stream:
            sz = self._input_size(meta)
            while len(inflight) >= self.window or (
                inflight and inflight_bytes + sz > self.max_stage_bytes
            ):
                pair, psz = inflight.popleft()
                inflight_bytes -= psz
                yield pair
            inflight.append(
                (tuple(map_remote.remote(op.fn, block_ref)), sz)
            )
            inflight_bytes += sz
            self.stats["tasks"] += 1
        while inflight:
            yield inflight.popleft()[0]

    def _actor_map_stream(self, stream: Iterator[RefPair],
                          op: ActorMapOp) -> Iterator[RefPair]:
        """Actor-pool map (reference: `actor_pool_map_operator.py` +
        pool autoscaler): blocks route to the least-loaded actor with
        `actor_pool_pipeline_depth` pipelining; the pool grows toward
        strategy.max_size while saturated and is torn down when the
        stream ends."""
        strat = op.strategy
        Worker = rt.remote(num_cpus=self._remote_opts["num_cpus"])(
            _BatchMapWorker
        )

        def spawn():
            return Worker.remote(op.cls, op.args, op.kwargs,
                                 op.batch_size, op.batch_format)

        actors = [spawn() for _ in range(strat.min_size)]
        load = [0] * len(actors)
        outstanding: Dict[Any, int] = {}  # meta_ref -> actor index
        inflight: deque = deque()  # pairs in submission order

        def reap(block: bool):
            if not outstanding:
                return
            done, _ = rt.wait(
                list(outstanding),
                num_returns=1 if block else len(outstanding),
                timeout=None if block else 0,
            )
            for m in done:
                load[outstanding.pop(m)] -= 1

        try:
            for block_ref, _meta in stream:
                reap(block=False)
                while True:
                    i = min(range(len(actors)), key=load.__getitem__)
                    if load[i] < self._actor_depth:
                        break
                    if len(actors) < strat.max_size:
                        actors.append(spawn())
                        load.append(0)
                        i = len(actors) - 1
                        break
                    # saturated at max_size: hand completed work
                    # downstream, then wait for a slot
                    if inflight:
                        yield inflight.popleft()
                    reap(block=True)
                method = actors[i].map_block.options(num_returns=2)
                b, m = method.remote(block_ref)
                load[i] += 1
                outstanding[m] = i
                inflight.append((b, m))
                self.stats["tasks"] += 1
            while inflight:
                yield inflight.popleft()
        finally:
            for a in actors:
                try:
                    rt.kill(a)
                except Exception:
                    pass

    def _alltoall_stream(self, stream: Iterator[RefPair],
                         op: AllToAllOp) -> Iterator[RefPair]:
        pairs = list(stream)  # barrier
        refs = [p[0] for p in pairs]
        a2a_remote = rt.remote(_run_alltoall_task).options(
            num_cpus=self._remote_opts["num_cpus"]
        )
        self.stats["tasks"] += 1
        out_pairs = rt.get(a2a_remote.remote(op.fn, *refs))
        yield from out_pairs

    def _limit_stream(self, stream: Iterator[RefPair], op: LimitOp) -> Iterator[RefPair]:
        remaining = op.limit
        slice_remote = rt.remote(_slice_task).options(**self._remote_opts)
        for block_ref, meta in stream:
            if remaining <= 0:
                break
            n = self._meta(meta)["num_rows"]
            if n <= remaining:
                remaining -= n
                yield block_ref, meta
            else:
                self.stats["tasks"] += 1
                yield tuple(slice_remote.remote(block_ref, remaining))
                remaining = 0

    @staticmethod
    def _meta(meta) -> Dict[str, Any]:
        if isinstance(meta, dict):
            return meta
        return rt.get(meta)

    # -- public --------------------------------------------------------
    def execute(self) -> Iterator[RefPair]:
        ops = self.plan.ops
        if not ops or not isinstance(ops[0], ReadOp):
            raise ValueError(f"plan must start with a ReadOp: {self.plan.describe()}")
        stream: Iterator[RefPair] = self._read_stream(ops[0])
        for op in ops[1:]:
            if isinstance(op, MapOp):
                stream = self._map_stream(stream, op)
            elif isinstance(op, ActorMapOp):
                stream = self._actor_map_stream(stream, op)
            elif isinstance(op, AllToAllOp):
                stream = self._alltoall_stream(stream, op)
            elif isinstance(op, LimitOp):
                stream = self._limit_stream(stream, op)
            else:
                raise TypeError(f"unknown op: {op}")
        return stream

    def execute_to_refs(self) -> List[RefPair]:
        return list(self.execute())
