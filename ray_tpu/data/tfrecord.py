"""TFRecord IO without a TensorFlow dependency.

Reference: `python/ray/data/_internal/datasource/tfrecords_datasource.py`
(which imports TensorFlow for both the record framing and the
`tf.Example` proto).  TFRecord is *the* canonical TPU training input
format, so this framework ships a native implementation of both layers:

- **record framing**: `<u64 length><u32 masked-crc32c(length)>
  <data><u32 masked-crc32c(data)>` per record;
- **tf.Example**: a tiny protobuf wire-format codec for the fixed
  Example/Features/Feature schema (bytes_list / float_list /
  int64_list) — the schema is frozen upstream, so a general proto
  runtime is unnecessary.
"""

from __future__ import annotations

import struct
from typing import Any, Dict, Iterator, List, Union

import numpy as np

# ---------------------------------------------------------------------------
# crc32c (Castagnoli), masked per the TFRecord spec.  The native
# `google_crc32c` extension is used when importable (it ships with the
# google-cloud stack); the fallback is a slice-by-8 table walk in plain
# python ints — a per-byte numpy-scalar loop would make checksum
# verification slower than the file IO it protects.
# ---------------------------------------------------------------------------
try:
    import google_crc32c as _gcrc
except ImportError:  # pragma: no cover - present in the image
    _gcrc = None

_CRC_TABLES = None


def _crc_tables():
    global _CRC_TABLES
    if _CRC_TABLES is None:
        poly = 0x82F63B78
        t0 = []
        for i in range(256):
            c = i
            for _ in range(8):
                c = (c >> 1) ^ poly if c & 1 else c >> 1
            t0.append(c)
        tables = [t0]
        for k in range(1, 8):
            prev = tables[k - 1]
            tables.append([t0[v & 0xFF] ^ (v >> 8) for v in prev])
        _CRC_TABLES = tables
    return _CRC_TABLES


def crc32c(data: bytes) -> int:
    if _gcrc is not None:
        return int(_gcrc.value(bytes(data)))
    t = _crc_tables()
    crc = 0xFFFFFFFF
    n = len(data)
    i = 0
    while n - i >= 8:
        low = crc ^ int.from_bytes(data[i:i + 4], "little")
        hi = int.from_bytes(data[i + 4:i + 8], "little")
        crc = (
            t[7][low & 0xFF] ^ t[6][(low >> 8) & 0xFF]
            ^ t[5][(low >> 16) & 0xFF] ^ t[4][low >> 24]
            ^ t[3][hi & 0xFF] ^ t[2][(hi >> 8) & 0xFF]
            ^ t[1][(hi >> 16) & 0xFF] ^ t[0][hi >> 24]
        )
        i += 8
    t0 = t[0]
    for b in data[i:]:
        crc = t0[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


def _masked_crc(data: bytes) -> int:
    crc = crc32c(data)
    return ((crc >> 15 | crc << 17) + 0xA282EAD8) & 0xFFFFFFFF


# ---------------------------------------------------------------------------
# record framing
# ---------------------------------------------------------------------------
def write_records(path: str, records: List[bytes]) -> None:
    with open(path, "wb") as f:
        for rec in records:
            header = struct.pack("<Q", len(rec))
            f.write(header)
            f.write(struct.pack("<I", _masked_crc(header)))
            f.write(rec)
            f.write(struct.pack("<I", _masked_crc(rec)))


def read_records(path: str, *, verify: bool = True) -> Iterator[bytes]:
    with open(path, "rb") as f:
        while True:
            header = f.read(8)
            if not header:
                return
            if len(header) != 8:
                raise ValueError(f"truncated TFRecord header in {path}")
            (length,) = struct.unpack("<Q", header)
            hcrc_raw = f.read(4)
            if len(hcrc_raw) != 4:
                raise ValueError(f"truncated TFRecord header crc in {path}")
            (hcrc,) = struct.unpack("<I", hcrc_raw)
            data = f.read(length)
            if len(data) != length:
                raise ValueError(f"truncated TFRecord data in {path}")
            dcrc_raw = f.read(4)
            if len(dcrc_raw) != 4:
                raise ValueError(f"truncated TFRecord data crc in {path}")
            (dcrc,) = struct.unpack("<I", dcrc_raw)
            if verify:
                if _masked_crc(header) != hcrc:
                    raise ValueError(f"TFRecord length crc mismatch in {path}")
                if _masked_crc(data) != dcrc:
                    raise ValueError(f"TFRecord data crc mismatch in {path}")
            yield data


# ---------------------------------------------------------------------------
# minimal protobuf wire codec for tf.Example
#
# message Example { Features features = 1; }
# message Features { map<string, Feature> feature = 1; }
# message Feature { oneof kind {
#     BytesList bytes_list = 1; FloatList float_list = 2;
#     Int64List int64_list = 3; } }
# message BytesList { repeated bytes value = 1; }
# message FloatList { repeated float value = 1 [packed=true]; }
# message Int64List { repeated int64 value = 1 [packed=true]; }
# ---------------------------------------------------------------------------
def _varint(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _read_varint(buf: memoryview, pos: int):
    shift = 0
    result = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7


def _len_delimited(field: int, payload: bytes) -> bytes:
    return _varint(field << 3 | 2) + _varint(len(payload)) + payload


def _encode_feature(values) -> bytes:
    if isinstance(values, (bytes, str)):
        values = [values]
    elif isinstance(values, np.ndarray):
        values = values.tolist()
    elif not isinstance(values, (list, tuple)):
        values = [values]
    if not values:
        return _len_delimited(1, b"")  # empty bytes_list
    v0 = values[0]
    if isinstance(v0, (bytes, str)):
        inner = b"".join(
            _len_delimited(1, v.encode() if isinstance(v, str) else v)
            for v in values
        )
        return _len_delimited(1, inner)  # bytes_list
    if isinstance(v0, (float, np.floating)):
        packed = struct.pack(f"<{len(values)}f", *[float(v) for v in values])
        return _len_delimited(2, _len_delimited(1, packed))
    if isinstance(v0, (int, np.integer)):
        packed = b"".join(_varint(int(v) & (1 << 64) - 1) for v in values)
        return _len_delimited(3, _len_delimited(1, packed))
    raise TypeError(f"unsupported feature value type {type(v0).__name__}")


def encode_example(features: Dict[str, Any]) -> bytes:
    """{name: bytes|str|int|float|list-thereof} -> serialized Example."""
    feats = bytearray()
    for name, values in features.items():
        key = _len_delimited(1, name.encode())
        val = _len_delimited(2, _encode_feature(values))
        feats += _len_delimited(1, key + val)
    return _len_delimited(1, bytes(feats))


def _decode_feature(buf: memoryview):
    """Feature message -> python list (bytes / float / int)."""
    pos = 0
    while pos < len(buf):
        tag, pos = _read_varint(buf, pos)
        field, wire = tag >> 3, tag & 7
        if wire != 2:
            raise ValueError(f"unexpected wire type {wire} in Feature")
        ln, pos = _read_varint(buf, pos)
        inner = buf[pos:pos + ln]
        pos += ln
        if field == 1:  # BytesList
            out: List[Any] = []
            ip = 0
            while ip < len(inner):
                t, ip = _read_varint(inner, ip)
                if t != (1 << 3 | 2):
                    raise ValueError("bad BytesList")
                n, ip = _read_varint(inner, ip)
                out.append(bytes(inner[ip:ip + n]))
                ip += n
            return out
        if field == 2:  # FloatList (packed or repeated)
            out = []
            ip = 0
            while ip < len(inner):
                t, ip = _read_varint(inner, ip)
                if t == (1 << 3 | 2):  # packed
                    n, ip = _read_varint(inner, ip)
                    out.extend(struct.unpack(f"<{n // 4}f",
                                             bytes(inner[ip:ip + n])))
                    ip += n
                elif t == (1 << 3 | 5):  # single fixed32
                    out.extend(struct.unpack("<f", bytes(inner[ip:ip + 4])))
                    ip += 4
                else:
                    raise ValueError("bad FloatList")
            return [float(v) for v in out]
        if field == 3:  # Int64List (packed or repeated varint)
            out = []
            ip = 0
            while ip < len(inner):
                t, ip = _read_varint(inner, ip)
                if t == (1 << 3 | 2):  # packed
                    n, ip = _read_varint(inner, ip)
                    end = ip + n
                    while ip < end:
                        v, ip = _read_varint(inner, ip)
                        out.append(v - (1 << 64) if v >= 1 << 63 else v)
                elif t == (1 << 3 | 0):
                    v, ip = _read_varint(inner, ip)
                    out.append(v - (1 << 64) if v >= 1 << 63 else v)
                else:
                    raise ValueError("bad Int64List")
            return out
    return []


def decode_example(data: Union[bytes, memoryview]) -> Dict[str, Any]:
    """Serialized Example -> {name: list of bytes/float/int}."""
    buf = memoryview(data)
    pos = 0
    out: Dict[str, Any] = {}
    while pos < len(buf):
        tag, pos = _read_varint(buf, pos)
        if tag != (1 << 3 | 2):  # Example.features
            raise ValueError("not a tf.Example")
        ln, pos = _read_varint(buf, pos)
        feats = buf[pos:pos + ln]
        pos += ln
        fp = 0
        while fp < len(feats):
            t, fp = _read_varint(feats, fp)
            if t != (1 << 3 | 2):  # Features.feature entry
                raise ValueError("bad Features map")
            n, fp = _read_varint(feats, fp)
            entry = feats[fp:fp + n]
            fp += n
            ep = 0
            name = None
            value: Any = []
            while ep < len(entry):
                et, ep = _read_varint(entry, ep)
                en, ep = _read_varint(entry, ep)
                payload = entry[ep:ep + en]
                ep += en
                if et == (1 << 3 | 2):  # key
                    name = bytes(payload).decode()
                elif et == (2 << 3 | 2):  # value: Feature
                    value = _decode_feature(payload)
            if name is not None:
                out[name] = value
    return out


def _scalarize(values):
    """Single-element feature lists become scalars (the shape users
    expect from row-oriented reads)."""
    return values[0] if isinstance(values, list) and len(values) == 1 else values


def read_tfrecords_rows(path: str, *, parse_example: bool = True,
                        verify: bool = True) -> List[Dict[str, Any]]:
    rows = []
    for rec in read_records(path, verify=verify):
        if parse_example:
            try:
                rows.append({
                    k: _scalarize(v) for k, v in decode_example(rec).items()
                })
                continue
            except (ValueError, IndexError, struct.error):
                # not an Example (truncated varints surface as
                # IndexError, bad packed floats as struct.error):
                # surface the raw record instead
                pass
        rows.append({"data": rec})
    return rows
